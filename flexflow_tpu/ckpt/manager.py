"""CheckpointManager: async cadence, retain-N GC, resume, goodput.

The training loop's one checkpoint object (``fit(checkpoint_dir=...)``
builds it; the multihost dryrun drives it directly). Split of labor per
save:

* on the training thread: ``snapshot()`` — the device→host copy of this
  host's shards. This is the ONLY blocking cost the hot loop pays
  (observed as ``<run>/ckpt_save_stall_s``); it must finish before the
  next step's dispatch because the jitted step donates the very buffers
  being read.
* on the writer thread: serialization, checksums, the tmp+rename file
  writes, the manifest commit barrier, and retain-N garbage collection
  (``<run>/ckpt_async_write_s``, ``<run>/ckpt_bytes_written``).

Saves are serialized (a new save joins the previous writer first), and
writer errors are re-raised on the training thread at the next
``save``/``finalize`` — a checkpoint that silently failed to commit is
worse than a loud crash.

Goodput accounting: ``finalize`` publishes ``<run>/goodput_effective``
= productive time / (wall + restart-lost time + supervisor downtime),
where checkpoint stalls count against the numerator, the steps lost to
the last preemption (restored iteration vs the rank-0 PROGRESS
heartbeat) are priced at the run's own mean step time, and the restart
backoff a ``scripts/supervise.py`` session spent (SUPERVISOR.json's
``downtime_s``) lands in the denominator. This is the ratchet
coordinate for the elastic-training direction.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

from flexflow_tpu.ckpt import manifest as mf
from flexflow_tpu.ckpt import sharded
from flexflow_tpu.obs.registry import get_registry

_PROGRESS_INTERVAL_S = 0.5


class CheckpointManager:
    def __init__(self, ffmodel, directory: str, every: int = 0,
                 retain: int = 3, async_write: bool = True,
                 run_name: str = "fit", fs_timeout: float = 120.0,
                 heartbeat=None, state_provider=None):
        if not directory:
            raise ValueError("CheckpointManager needs a checkpoint directory")
        self.ff = ffmodel
        self.directory = str(directory)
        self.every = int(every)
        self.retain = max(1, int(retain))
        self.async_write = bool(async_write)
        self.run_name = run_name
        self.fs_timeout = float(fs_timeout)
        # watchdog feed (flexflow_tpu/runtime_health.py): writer-thread
        # progress marks — a long commit is progress, not a hang
        self.heartbeat = heartbeat
        # JSON-able client state recorded in every manifest (the
        # dataloader cursor travels here; fit_loader sets it)
        self.state_provider = state_provider
        self.restart_lost_steps = 0
        self._last_saved_iter = -1
        self._stall_total_s = 0.0
        self._pending: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        self._last_progress = 0.0
        import jax
        self._rank = jax.process_index()
        os.makedirs(self.directory, exist_ok=True)

    # ---- resume ------------------------------------------------------------
    def resume(self, require: bool = False) -> int:
        """Restore the newest complete checkpoint, if any.

        Returns the restored iteration (0 when the directory holds no
        checkpoint at all — a fresh launch under the same command line).
        A directory that has step dirs but NO complete checkpoint, or a
        corrupt one, raises on every rank; ``require=True`` also makes
        an empty directory an error."""
        t0 = time.perf_counter()
        has_steps = bool(mf.list_steps(self.directory))
        import jax
        if jax.process_count() > 1:
            # the fresh-start decision is derived from per-host
            # filesystem state, so it must be agreed across ranks
            # BEFORE anyone diverges into training vs load collectives
            # (the same ADVICE r5 class load_sharded guards): if ANY
            # rank sees steps, every rank takes the load path — whose
            # own gather then fails fast on the ranks that cannot.
            from flexflow_tpu import distributed
            seen, _ = distributed.ranks_agree(1 if has_steps else 0)
            has_steps = any(seen)
        if not has_steps and not require:
            return 0  # fresh start (every rank sees an empty directory)
        # missing/partial fails fast on every rank (load_sharded gathers)
        it = sharded.load_sharded(self.directory, self.ff)
        self._last_saved_iter = it
        reg = get_registry()
        reg.gauge(f"{self.run_name}/ckpt_restore_s",
                  time.perf_counter() - t0)
        progress = mf.read_progress(self.directory)
        if progress > it:
            self.restart_lost_steps = progress - it
            reg.gauge(f"{self.run_name}/ckpt_restart_lost_steps",
                      self.restart_lost_steps)
        return it

    # ---- cadence -----------------------------------------------------------
    def should_save(self, iteration: int) -> bool:
        return (self.every > 0 and iteration > self._last_saved_iter
                and iteration % self.every == 0)

    def note_step(self, iteration: int) -> None:
        """Rank-0 progress heartbeat (time-gated atomic write) so a
        resume can price the steps the preemption threw away."""
        if self._rank != 0:
            return
        now = time.monotonic()
        if now - self._last_progress < _PROGRESS_INTERVAL_S:
            return
        self._last_progress = now
        try:
            mf.note_progress(self.directory, iteration)
        except OSError as e:
            print(f"[ckpt] progress heartbeat failed: {e!r}",
                  file=sys.stderr)

    # ---- save --------------------------------------------------------------
    def save(self, iteration: Optional[int] = None) -> None:
        """Snapshot on the calling thread, commit async (or inline when
        ``async_write=False``). Raises a previous writer error here
        rather than losing it. The stall gauge starts BEFORE the join
        with the previous writer: when the writer is slower than the
        save cadence, that join blocks the hot loop and must show up in
        ``ckpt_save_stall_s``/goodput — the exact regime the metric
        exists to expose."""
        t0 = time.perf_counter()
        self._join_pending()
        client_state = None
        if self.state_provider is not None:
            try:
                client_state = self.state_provider()
            except Exception as e:
                print(f"[ckpt] state_provider failed (manifest will carry "
                      f"no client_state): {e!r}", file=sys.stderr)
        snap = sharded.snapshot(self.ff, step=iteration,
                                client_state=client_state)
        self._last_saved_iter = snap.step
        if self.async_write:
            self._pending = threading.Thread(
                target=self._commit, args=(snap,), daemon=True,
                name=f"ckpt-writer-step{snap.step}")
            self._pending.start()
        else:
            # inline commit blocks the training thread — that cost
            # belongs in the stall too
            self._commit(snap)
        stall = time.perf_counter() - t0
        self._stall_total_s += stall
        get_registry().observe(f"{self.run_name}/ckpt_save_stall_s", stall)
        if not self.async_write:
            self._raise_writer_error()
        self.note_step(snap.step)

    def _commit(self, snap) -> None:
        t0 = time.perf_counter()
        try:
            if self.heartbeat is not None:
                self.heartbeat(f"ckpt commit start step {snap.step}")
            nbytes = sharded.write_snapshot(self.directory, snap,
                                            fs_timeout=self.fs_timeout,
                                            heartbeat=self.heartbeat)
            reg = get_registry()
            reg.observe(f"{self.run_name}/ckpt_async_write_s",
                        time.perf_counter() - t0)
            reg.inc(f"{self.run_name}/ckpt_saves")
            reg.inc(f"{self.run_name}/ckpt_bytes_written", nbytes)
            if self._rank == 0:
                mf.collect_garbage(self.directory, self.retain)
        except BaseException as e:  # surfaces at next save()/finalize()
            self._writer_error = e
            print(f"[ckpt] checkpoint write for step {snap.step} failed: "
                  f"{e!r}", file=sys.stderr)

    def _join_pending(self) -> None:
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        self._raise_writer_error()

    def _raise_writer_error(self) -> None:
        if self._writer_error is not None:
            e, self._writer_error = self._writer_error, None
            raise RuntimeError(
                f"asynchronous checkpoint write failed: {e!r}") from e

    # ---- durability barrier / teardown ------------------------------------
    def wait(self) -> None:
        """Durability barrier: returns only once every enqueued save is
        committed (manifest visible). Raises if the writer failed."""
        self._join_pending()

    def finalize(self, elapsed_s: Optional[float] = None,
                 steps: Optional[int] = None,
                 final_save: bool = True) -> None:
        """End-of-run: final checkpoint (when the last step isn't already
        saved), durability barrier, goodput gauge. The final save does
        NOT require a cadence: ``checkpoint_dir`` without
        ``checkpoint_every`` means "checkpoint once, at the end" — a
        configured directory that a whole run leaves empty would be a
        silent data-loss trap at the next ``--resume``."""
        if (final_save
                and self.ff._iter > max(self._last_saved_iter, 0)):
            self.save(self.ff._iter)
        self._join_pending()
        if elapsed_s and steps:
            productive = max(0.0, elapsed_s - self._stall_total_s)
            per_step = productive / max(1, steps)
            lost_s = self.restart_lost_steps * per_step
            # a run living under scripts/supervise.py also pays the
            # supervisor's restart backoff — that downtime belongs in
            # the goodput denominator, not hidden outside the metric
            reg = get_registry()
            sup_downtime = 0.0
            sup = mf.read_supervisor(self.directory)
            if sup:
                sup_downtime = float(sup.get("downtime_s") or 0.0)
                reg.gauge(f"{self.run_name}/supervisor_restarts",
                          float(sup.get("restarts") or 0))
                reg.gauge(f"{self.run_name}/supervisor_downtime_s",
                          sup_downtime)
            goodput = productive / max(elapsed_s + lost_s + sup_downtime,
                                       1e-12)
            reg.gauge(f"{self.run_name}/goodput_effective",
                      max(0.0, min(1.0, goodput)))

    @property
    def save_stall_s(self) -> float:
        return self._stall_total_s

"""Deterministic fault injection for the elastic-training harness.

``FFS_FAULT`` holds a comma-separated list of fault specs; each names
an injection seam the checkpoint/runtime code calls at well-defined
points, so a dryrun can kill a host mid-epoch, corrupt a shard on disk,
slow the writer, deliver a preemption signal, wedge the step loop, or
make checkpoint writes fail transiently — deterministically, without
patching internals:

* ``kill_host:<rank>@step:<n>`` — process ``rank`` exits hard (no
  cleanup, exit code ``KILL_EXIT``) right after finishing global step
  ``n`` — the hardware-loss simulation. Seam: ``step_hook(step)``.
* ``sigterm:<rank>@step:<n>`` — process ``rank`` sends ITSELF SIGTERM
  after finishing step ``n`` — the platform-preemption simulation the
  grace-window path (flexflow_tpu/runtime_health.py) must convert into
  a final checkpoint plus a ``PREEMPTED_EXIT``. Fires once. Seam:
  ``step_hook(step)``.
* ``hang:<rank>@step:<n>`` — process ``rank`` blocks the step loop
  after finishing step ``n`` (the stuck-collective simulation) until
  the watchdog ``os._exit``\\ s it with ``HUNG_EXIT``. Bounded at
  ``HANG_LIMIT_S`` so a missing watchdog turns into a loud error, not
  a silent CI hang. Seam: ``step_hook(step)``.
* ``corrupt_shard:<key_substr>@step:<n>`` — during the save of step
  ``n``, the serialized bytes of the first shard whose leaf path
  contains ``key_substr`` are bit-flipped AFTER its checksum was
  computed — the on-disk rot the integrity verifier must catch. Seam:
  ``corrupt_bytes(leaf_key, step, payload)``.
* ``slow_write:<ms>`` — every shard-file write sleeps ``ms``
  milliseconds first; exaggerates the writer latency so the async-path
  tests can prove the hot loop does not pay it. Seam: ``write_delay()``.
* ``io_error:<path_substr>:<count>`` — the next ``count`` atomic file
  writes whose destination path contains ``path_substr`` raise
  ``OSError(EIO)`` — the transient-filesystem blip the checkpoint
  writers must absorb with retry-with-backoff
  (flexflow_tpu/ckpt/sharded.py). Seam: ``io_check(path)`` inside
  ``manifest.atomic_replace``.

Parsing is cached per env-string so the per-step hook costs one dict
lookup when ``FFS_FAULT`` is unset.
"""

from __future__ import annotations

import errno
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

ENV = "FFS_FAULT"
KILL_EXIT = 77  # distinguishable from python tracebacks (1) and signals

# a hang fault without a watchdog must fail loudly, not wedge CI forever
HANG_LIMIT_S = 900.0


class FaultPlan:
    def __init__(self, kills: List[Tuple[int, int]],
                 corrupts: List[Tuple[str, int]],
                 slow_write_s: float,
                 sigterms: Optional[List[Tuple[int, int]]] = None,
                 hangs: Optional[List[Tuple[int, int]]] = None,
                 io_errors: Optional[List[List]] = None):
        self.kills = kills            # [(rank, step)]
        self.corrupts = corrupts      # [(key_substr, step)]
        self.slow_write_s = slow_write_s
        self.sigterms = sigterms or []  # [(rank, step)]
        self.hangs = hangs or []        # [(rank, step)]
        # [[path_substr, remaining_count], ...] — mutable: each injected
        # failure decrements its budget (the "transient" in transient
        # I/O error)
        self.io_errors = io_errors or []
        self._corrupted = set()       # fire each corrupt spec once
        self._sigtermed = set()       # fire each sigterm spec once

    def _rank(self) -> int:
        import jax
        return jax.process_index()

    def step_hook(self, step: int) -> None:
        if not (self.kills or self.sigterms or self.hangs):
            return
        rank = self._rank()
        for (r, s) in self.kills:
            if r == rank and s == step:
                print(f"[ffs_fault] kill_host: rank {rank} exiting at "
                      f"step {step}", file=sys.stderr, flush=True)
                os._exit(KILL_EXIT)
        for i, (r, s) in enumerate(self.sigterms):
            if r == rank and s == step and i not in self._sigtermed:
                self._sigtermed.add(i)
                print(f"[ffs_fault] sigterm: rank {rank} raising SIGTERM "
                      f"on itself at step {step}", file=sys.stderr,
                      flush=True)
                import signal
                os.kill(os.getpid(), signal.SIGTERM)
        for (r, s) in self.hangs:
            if r == rank and s == step:
                print(f"[ffs_fault] hang: rank {rank} wedging the step "
                      f"loop at step {step} (watchdog must reap this "
                      f"process)", file=sys.stderr, flush=True)
                deadline = time.monotonic() + HANG_LIMIT_S
                while time.monotonic() < deadline:
                    time.sleep(0.1)
                raise RuntimeError(
                    f"FFS_FAULT hang at step {step} expired after "
                    f"{HANG_LIMIT_S:.0f}s without a watchdog reaping the "
                    f"process — set --watchdog-timeout when injecting "
                    f"hang faults")

    def corrupt_bytes(self, leaf_key: str, step: int,
                      payload: bytes) -> bytes:
        for i, (sub, s) in enumerate(self.corrupts):
            if s == step and sub in leaf_key and i not in self._corrupted:
                self._corrupted.add(i)
                print(f"[ffs_fault] corrupt_shard: flipping a byte of "
                      f"'{leaf_key}' at step {step}", file=sys.stderr,
                      flush=True)
                b = bytearray(payload)
                b[len(b) // 2] ^= 0xFF
                return bytes(b)
        return payload

    def write_delay(self) -> None:
        if self.slow_write_s > 0:
            time.sleep(self.slow_write_s)

    def io_check(self, path: str) -> None:
        """Transient-write seam: raise EIO while a matching io_error
        spec still has failure budget (each raise spends one)."""
        for spec in self.io_errors:
            sub, remaining = spec
            if remaining > 0 and sub in path:
                spec[1] = remaining - 1
                print(f"[ffs_fault] io_error: failing write of "
                      f"'{os.path.basename(path)}' ({remaining - 1} "
                      f"failure(s) left for {sub!r})", file=sys.stderr,
                      flush=True)
                raise OSError(errno.EIO,
                              f"FFS_FAULT injected I/O error", path)


def _parse(spec: str) -> Optional[FaultPlan]:
    kills: List[Tuple[int, int]] = []
    corrupts: List[Tuple[str, int]] = []
    sigterms: List[Tuple[int, int]] = []
    hangs: List[Tuple[int, int]] = []
    io_errors: List[List] = []
    slow = 0.0
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            head, _, tail = part.partition("@")
            kind, _, arg = head.partition(":")
            if kind == "kill_host":
                kills.append((int(arg), _step_of(tail)))
            elif kind == "sigterm":
                sigterms.append((int(arg), _step_of(tail)))
            elif kind == "hang":
                hangs.append((int(arg), _step_of(tail)))
            elif kind == "corrupt_shard":
                corrupts.append((arg, _step_of(tail)))
            elif kind == "slow_write":
                slow = float(arg) / 1e3
            elif kind == "io_error":
                if tail:
                    raise ValueError("io_error takes no @step")
                sub, sep, cnt = arg.rpartition(":")
                if not sep or not sub:
                    raise ValueError(
                        "io_error needs <path_substr>:<count>")
                n = int(cnt)
                if n < 1:
                    raise ValueError(f"io_error count must be >= 1, "
                                     f"got {n}")
                io_errors.append([sub, n])
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"{ENV}={spec!r}: cannot parse fault {part!r} "
                f"(expected kill_host:<rank>@step:<n>, "
                f"sigterm:<rank>@step:<n>, hang:<rank>@step:<n>, "
                f"corrupt_shard:<key>@step:<n>, slow_write:<ms>, or "
                f"io_error:<path_substr>:<count>): {e}"
            ) from None
    if not (kills or corrupts or sigterms or hangs or io_errors or slow):
        return None
    return FaultPlan(kills, corrupts, slow, sigterms=sigterms,
                     hangs=hangs, io_errors=io_errors)


def _step_of(tail: str) -> int:
    kind, _, v = tail.partition(":")
    if kind != "step":
        raise ValueError(f"expected @step:<n>, got @{tail!r}")
    return int(v)


_CACHE: Dict[str, Optional[FaultPlan]] = {}


def get_plan() -> Optional[FaultPlan]:
    """The active fault plan (None when ``FFS_FAULT`` is unset/empty).
    Re-reads the env each call; parsing is memoized per spec string."""
    spec = os.environ.get(ENV, "")
    if not spec:
        return None
    if spec not in _CACHE:
        _CACHE[spec] = _parse(spec)
    return _CACHE[spec]


def step_hook(step: int) -> None:
    """Per-training-step seam (kill_host / sigterm / hang). No-op
    without ``FFS_FAULT``."""
    plan = get_plan()
    if plan is not None:
        plan.step_hook(step)


def io_check(path: str) -> None:
    """Per-atomic-write seam (io_error). No-op without ``FFS_FAULT``."""
    plan = get_plan()
    if plan is not None:
        plan.io_check(path)

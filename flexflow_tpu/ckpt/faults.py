"""Deterministic fault injection for the elastic-training harness.

``FFS_FAULT`` holds a comma-separated list of fault specs; each names
an injection seam the checkpoint/runtime code calls at well-defined
points, so a dryrun can kill a host mid-epoch, corrupt a shard on disk,
or slow the writer — deterministically, without patching internals:

* ``kill_host:<rank>@step:<n>`` — process ``rank`` exits hard (no
  cleanup, exit code ``KILL_EXIT``) right after finishing global step
  ``n`` — the preemption/hardware-loss simulation. The seam is
  ``step_hook(step)``, called once per training step.
* ``corrupt_shard:<key_substr>@step:<n>`` — during the save of step
  ``n``, the serialized bytes of the first shard whose leaf path
  contains ``key_substr`` are bit-flipped AFTER its checksum was
  computed — the on-disk rot the integrity verifier must catch. Seam:
  ``corrupt_bytes(leaf_key, step, payload)``.
* ``slow_write:<ms>`` — every shard-file write sleeps ``ms``
  milliseconds first; exaggerates the writer latency so the async-path
  tests can prove the hot loop does not pay it. Seam: ``write_delay()``.

Parsing is cached per env-string so the per-step hook costs one dict
lookup when ``FFS_FAULT`` is unset.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional, Tuple

ENV = "FFS_FAULT"
KILL_EXIT = 77  # distinguishable from python tracebacks (1) and signals


class FaultPlan:
    def __init__(self, kills: List[Tuple[int, int]],
                 corrupts: List[Tuple[str, int]],
                 slow_write_s: float):
        self.kills = kills            # [(rank, step)]
        self.corrupts = corrupts      # [(key_substr, step)]
        self.slow_write_s = slow_write_s
        self._corrupted = set()       # fire each corrupt spec once

    def step_hook(self, step: int) -> None:
        if not self.kills:
            return
        import jax
        rank = jax.process_index()
        for (r, s) in self.kills:
            if r == rank and s == step:
                print(f"[ffs_fault] kill_host: rank {rank} exiting at "
                      f"step {step}", file=sys.stderr, flush=True)
                os._exit(KILL_EXIT)

    def corrupt_bytes(self, leaf_key: str, step: int,
                      payload: bytes) -> bytes:
        for i, (sub, s) in enumerate(self.corrupts):
            if s == step and sub in leaf_key and i not in self._corrupted:
                self._corrupted.add(i)
                print(f"[ffs_fault] corrupt_shard: flipping a byte of "
                      f"'{leaf_key}' at step {step}", file=sys.stderr,
                      flush=True)
                b = bytearray(payload)
                b[len(b) // 2] ^= 0xFF
                return bytes(b)
        return payload

    def write_delay(self) -> None:
        if self.slow_write_s > 0:
            time.sleep(self.slow_write_s)


def _parse(spec: str) -> Optional[FaultPlan]:
    kills: List[Tuple[int, int]] = []
    corrupts: List[Tuple[str, int]] = []
    slow = 0.0
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            head, _, tail = part.partition("@")
            kind, _, arg = head.partition(":")
            if kind == "kill_host":
                kills.append((int(arg), _step_of(tail)))
            elif kind == "corrupt_shard":
                corrupts.append((arg, _step_of(tail)))
            elif kind == "slow_write":
                slow = float(arg) / 1e3
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"{ENV}={spec!r}: cannot parse fault {part!r} "
                f"(expected kill_host:<rank>@step:<n>, "
                f"corrupt_shard:<key>@step:<n>, or slow_write:<ms>): {e}"
            ) from None
    if not (kills or corrupts or slow):
        return None
    return FaultPlan(kills, corrupts, slow)


def _step_of(tail: str) -> int:
    kind, _, v = tail.partition(":")
    if kind != "step":
        raise ValueError(f"expected @step:<n>, got @{tail!r}")
    return int(v)


_CACHE: Dict[str, Optional[FaultPlan]] = {}


def get_plan() -> Optional[FaultPlan]:
    """The active fault plan (None when ``FFS_FAULT`` is unset/empty).
    Re-reads the env each call; parsing is memoized per spec string."""
    spec = os.environ.get(ENV, "")
    if not spec:
        return None
    if spec not in _CACHE:
        _CACHE[spec] = _parse(spec)
    return _CACHE[spec]


def step_hook(step: int) -> None:
    """Per-training-step seam (kill_host). No-op without ``FFS_FAULT``."""
    plan = get_plan()
    if plan is not None:
        plan.step_hook(step)

"""Per-shard checkpoint save/load (v2): no all-gather, no rank-0 funnel.

Each host serializes ONLY the shards its own devices hold (dedup by
``shard.replica_id == 0``, so replicated leaves are written exactly once
across the fleet) into a step-tagged directory, with per-shard CRC32
checksums and the manifest written last as the commit record
(flexflow_tpu/ckpt/manifest.py). Contrast with the legacy v1 path
(flexflow_tpu/checkpoint.py), which all-gathers every sharded leaf onto
every host and has rank 0 write one monolithic .npz — O(model) HBM+wire
traffic per host and a step-loop stall; here each host moves only its
addressable bytes and the file writes can run off the critical path
(flexflow_tpu/ckpt/manager.py).

Restore is elastic by construction: the loader reassembles each global
array from the shard index — written by however many hosts the SAVING
job had — and re-places it onto the LIVE model's NamedShardings,
whatever mesh/strategy the resuming job compiled (the re-search for the
surviving topology happens in ``FFModel.compile``; see
flexflow_tpu/ckpt/elastic.py for the planning helpers). bfloat16 leaves
are stored as uint16 bit-views with the true dtype in the manifest, so
restore is bit-exact.

Restore is also RANK-LOCAL in the common same-mesh case: for each leaf
the loader intersects the saved shard index with the live model's
addressable shard boxes and reads + CRC-verifies only the shards this
host actually needs — a saved box that exactly matches a needed box is
read, one that doesn't touch the needed region is skipped, and any
partial overlap (the mesh changed) falls back to the full scan for
that leaf. Cuts restore cost by ~the host count; the read/skip byte
split lands in the ``ckpt/restore_read_bytes`` /
``ckpt/restore_skipped_bytes`` obs counters.

Writes absorb transient filesystem blips with bounded
retry-with-backoff (``FFS_CKPT_IO_RETRIES`` retries, exponential from
``FFS_CKPT_IO_BACKOFF_S``; each retry bumps the ``ckpt/io_retries``
counter); a retry-exhausted error propagates with the underlying
``OSError`` intact so the manager can surface it at the next ``save``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.ckpt import faults
from flexflow_tpu.ckpt import manifest as mf
from flexflow_tpu.ckpt.tree import (flatten_tree, place_tree, rebuild_tree,
                                    tree_structure)


def _retry_io(what: str, fn, heartbeat=None):
    """Run ``fn`` (an atomic write), absorbing transient ``OSError``\\ s
    with bounded exponential backoff. ``FFS_CKPT_IO_RETRIES`` (default
    3) bounds the retries, ``FFS_CKPT_IO_BACKOFF_S`` (default 0.05)
    seeds the delay; each retry bumps ``ckpt/io_retries``. Exhausted
    retries re-raise the LAST ``OSError`` unchanged — the caller (the
    async writer) must surface the true cause, not a wrapper."""
    import sys

    retries = int(os.environ.get("FFS_CKPT_IO_RETRIES", "3"))
    backoff = float(os.environ.get("FFS_CKPT_IO_BACKOFF_S", "0.05"))
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            if attempt >= retries:
                raise
            delay = backoff * (2.0 ** attempt)
            attempt += 1
            from flexflow_tpu.obs.registry import get_registry
            get_registry().inc("ckpt/io_retries")
            print(f"[ckpt] transient I/O error writing {what}: {e!r} — "
                  f"retry {attempt}/{retries} in {delay * 1e3:.0f}ms",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
            if heartbeat is not None:
                heartbeat(f"ckpt io retry {attempt}")


#: shard payloads above this split into CRC'd chunks at write
#: (``FFS_CKPT_CHUNK_BYTES`` overrides; 0 disables chunking)
DEFAULT_CHUNK_BYTES = 128 << 20


def chunk_threshold_bytes() -> int:
    try:
        return int(os.environ.get("FFS_CKPT_CHUNK_BYTES",
                                  DEFAULT_CHUNK_BYTES))
    except ValueError:
        return DEFAULT_CHUNK_BYTES


def _crc_check(piece: Dict[str, Any], data: np.ndarray,
               what: str) -> None:
    """The ONE per-piece CRC32 check (whole shards and chunks alike) —
    load and verify can never disagree on what "intact" means."""
    crc = mf.crc32_bytes(data.tobytes())
    if crc != int(piece["crc32"]):
        raise ValueError(
            f"checksum mismatch on {what} '{piece['key']}' (stored "
            f"{int(piece['crc32']):#010x}, recomputed {crc:#010x})")


def verify_shard_row(npz, row: Dict[str, Any]) -> None:
    """CRC-verify one index row piece by piece WITHOUT reassembling —
    O(chunk) memory, the point of chunking on the verify path
    (``manifest.verify_step_dir``). Raises ValueError on corruption."""
    chunks = row.get("chunks")
    if not chunks:
        _crc_check(row, np.ascontiguousarray(npz[row["key"]]), "shard")
        return
    for ch in chunks:
        _crc_check(ch, np.ascontiguousarray(npz[ch["key"]]), "chunk")


def read_shard_row(npz, row: Dict[str, Any],
                   verify: bool = True) -> np.ndarray:
    """Read one index row's payload from an open npz — whole-shard or
    chunked — verifying CRC32s when ``verify``. Chunked rows reassemble
    by concatenating the 1-D chunk payloads and reshaping to the row's
    box shape; each read is capped at chunk size (the serving loader's
    per-request read bound). Raises ValueError on corruption."""
    chunks = row.get("chunks")
    if not chunks:
        data = np.ascontiguousarray(npz[row["key"]])
        if verify:
            _crc_check(row, data, "shard")
        return data
    parts = []
    for ch in chunks:
        part = np.ascontiguousarray(npz[ch["key"]])
        if verify:
            _crc_check(ch, part, "chunk")
        parts.append(part.reshape(-1))
    data = np.concatenate(parts)
    return data.reshape([max(0, b[1] - b[0])
                         for b in row.get("index", [])])


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _bit_view(arr: np.ndarray) -> Tuple[np.ndarray, str, str]:
    """(saved_array, true_dtype, saved_dtype): non-native dtypes
    (ml_dtypes bfloat16, float8) are stored as unsigned-int bit views —
    exact bits, loadable by plain numpy."""
    true = str(arr.dtype)
    if arr.dtype.kind not in "fiub":
        saved = arr.view(np.dtype(f"uint{8 * arr.dtype.itemsize}"))
        return saved, true, str(saved.dtype)
    return arr, true, true


def _box(index, shape) -> List[List[int]]:
    """Serialize a shard's tuple-of-slices index against the global
    shape as [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else dim
        out.append([int(start), int(stop)])
    return out


def _capture_state(ffmodel) -> Dict[str, Any]:
    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    return {
        "params": ffmodel.params,
        "opt_state": ffmodel.opt_state,
        # the bf16 working copy is derived from params — re-cast on load
        "op_state": {k: v for k, v in ffmodel.state.items()
                     if k != COMPUTE_PARAMS_KEY},
    }


class ShardSnapshot:
    """Host-side copy of this process's shards plus the manifest
    payload — everything the background writer needs, detached from
    the live (donated-per-step) device buffers.

    ``shards``: {leaf key: [(box, saved_np_array)]} — checksums are
    computed by ``write_snapshot`` on the writer thread, not here.
    """

    def __init__(self, step: int, process_index: int, process_count: int,
                 shards, leaves, structure, scalars, manifest_extra):
        self.step = step
        self.process_index = process_index
        self.process_count = process_count
        self.shards = shards
        self.leaves = leaves
        self.structure = structure
        self.scalars = scalars
        self.manifest_extra = manifest_extra
        self.payload_bytes = sum(
            a.nbytes for entries in shards.values() for _, a in entries)


def snapshot(ffmodel, step: Optional[int] = None,
             client_state: Optional[Dict[str, Any]] = None) -> ShardSnapshot:
    """Blocking device→host copy of this host's shards (the only part
    of a save that must run on the training thread — the next step's
    dispatch donates the buffers we are reading). ``client_state`` is
    an arbitrary JSON-able dict recorded verbatim in the manifest —
    the dataloader cursor (epoch/batch position) travels here so a
    resume can seek instead of skip-fetching."""
    import jax

    step = int(ffmodel._iter if step is None else step)
    state = _capture_state(ffmodel)
    flat = flatten_tree(state)
    pidx, pcnt = jax.process_index(), jax.process_count()
    shards: Dict[str, List[Tuple[List[List[int]], np.ndarray]]] = {}
    leaves: Dict[str, Dict[str, Any]] = {}
    scalars: Dict[str, Any] = {}
    for key, v in flat:
        if hasattr(v, "addressable_shards") and not (
                pcnt > 1 and all(d.process_index == pidx
                                 for d in v.sharding.device_set)):
            arr0 = None
            entries = []
            for sh in v.addressable_shards:
                if sh.replica_id != 0:
                    continue  # another device/host owns this shard
                data = np.ascontiguousarray(np.asarray(sh.data))
                saved, true, saved_dt = _bit_view(data)
                if arr0 is None:
                    arr0 = (true, saved_dt)
                entries.append((_box(sh.index, v.shape), saved))
            if entries:
                shards[key] = entries
            true, saved_dt = arr0 if arr0 is not None else _bit_view(
                np.zeros((), _np_dtype(str(v.dtype))))[1:]
            leaves[key] = dict(shape=[int(d) for d in v.shape],
                               dtype=str(v.dtype), saved_dtype=saved_dt)
        elif hasattr(v, "shape"):
            # host-resident leaf (plain numpy op state): replicated by
            # construction — process 0 owns it
            data = np.ascontiguousarray(np.asarray(v))
            saved, true, saved_dt = _bit_view(data)
            if pidx == 0:
                shards[key] = [(_box(tuple(slice(0, d) for d in data.shape),
                                     data.shape), saved)]
            leaves[key] = dict(shape=[int(d) for d in data.shape],
                               dtype=true, saved_dtype=saved_dt)
        else:
            scalars[key] = v

    # strategy + mesh + rng travel in the manifest: resume on a
    # different topology re-searches, resume on the same one can reuse
    # the recorded strategy verbatim (ckpt/elastic.py)
    from flexflow_tpu.search.unity import strategy_json
    mesh_axes = dict(zip(ffmodel.mesh.axis_names,
                         (int(d) for d in ffmodel.mesh.devices.shape)))
    extra = dict(
        iteration=int(ffmodel._iter),
        rng=[int(x) for x in np.asarray(ffmodel._rng).ravel()],
        mesh=mesh_axes,
        num_devices=int(np.prod(ffmodel.mesh.devices.shape)),
        strategy=strategy_json(mesh_axes, ffmodel.strategy or {},
                               ffmodel.executor.nodes,
                               objective=getattr(ffmodel,
                                                 "search_objective", None)),
        wall_unix=time.time(),
    )
    if client_state is not None:
        extra["client_state"] = client_state
    return ShardSnapshot(step, pidx, pcnt, shards, leaves,
                         tree_structure(state), scalars, extra)


def write_snapshot(directory: str, snap: ShardSnapshot,
                   fs_timeout: float = 120.0, heartbeat=None) -> int:
    """Write this host's shard + index files and run the commit
    protocol (rank 0 writes the manifest last after every host's index
    is visible; every rank returns only once the manifest exists — the
    durability barrier). Safe to run on a background thread: no JAX
    collectives, filesystem polling only. Transient write errors retry
    with backoff (``_retry_io``). ``heartbeat`` (when the run carries a
    watchdog) marks each completed file as writer progress — a long
    commit is not a hang. Returns this host's payload bytes."""
    step_dir = os.path.join(directory, mf.step_dir_name(snap.step))
    os.makedirs(step_dir, exist_ok=True)
    plan = faults.get_plan()
    chunk_bytes = chunk_threshold_bytes()

    arrays: Dict[str, np.ndarray] = {}
    index: Dict[str, List[Dict[str, Any]]] = {}
    for leaf_key, entries in snap.shards.items():
        rows = []
        for i, (box, arr) in enumerate(entries):
            npz_key = f"{leaf_key}::{i}"
            # checksums live on the writer thread (the training thread
            # pays only the device→host snapshot); the corrupt_shard
            # seam flips bytes AFTER the CRC so the verifier must catch
            # the rot
            payload = arr.tobytes()
            crc = mf.crc32_bytes(payload)
            # shard files above the chunk threshold split into CRC'd
            # chunks (ROADMAP elastic follow-on (b)): bounded write
            # units, and the serving loader's per-request reads are
            # capped at chunk size instead of whole-shard size. Chunk
            # CRCs are computed from the CLEAN payload, before the
            # corrupt_shard seam, so injected rot is caught per chunk.
            # the ONE slicing: (key, start, stop) per chunk, shared by
            # the clean-payload CRC pass and the (possibly corrupted)
            # storage pass below — they can never desynchronize
            slices = None
            if chunk_bytes and arr.nbytes > chunk_bytes and arr.size > 1:
                epc = max(1, chunk_bytes // max(1, arr.dtype.itemsize))
                slices = [(f"{npz_key}::c{j}", off,
                           min(off + epc, arr.size))
                          for j, off in enumerate(
                              range(0, arr.size, epc))]
            chunk_meta = None
            if slices is not None:
                flat = arr.reshape(-1)
                chunk_meta = [dict(
                    key=ck,
                    crc32=int(mf.crc32_bytes(flat[o:e].tobytes())),
                    bytes=int(flat[o:e].nbytes)) for ck, o, e in slices]
            if plan is not None:
                hurt = plan.corrupt_bytes(leaf_key, snap.step, payload)
                if hurt is not payload:
                    arr = np.frombuffer(hurt, dtype=arr.dtype).reshape(
                        arr.shape)
            row = dict(key=npz_key, index=box, crc32=int(crc),
                       bytes=int(arr.nbytes))
            if slices is not None:
                flat = arr.reshape(-1)
                for ck, o, e in slices:
                    arrays[ck] = flat[o:e]
                row["chunks"] = chunk_meta
                from flexflow_tpu.obs.registry import get_registry
                get_registry().inc("ckpt/chunked_shards")
            else:
                arrays[npz_key] = arr
            rows.append(row)
        index[leaf_key] = rows

    shards_file = mf.shards_name(snap.process_index)
    spath = os.path.join(step_dir, shards_file)

    def _write_shards():
        with mf.atomic_replace(spath) as f:
            if plan is not None:
                plan.write_delay()
            np.savez(f, **arrays)

    _retry_io(shards_file, _write_shards, heartbeat=heartbeat)
    if heartbeat is not None:
        heartbeat(f"ckpt shards step {snap.step}")
    # index AFTER the shard data it references is durable
    index_path = os.path.join(step_dir, mf.index_name(snap.process_index))
    _retry_io(mf.index_name(snap.process_index),
              lambda: mf.atomic_write_json(
                  index_path,
                  dict(version=mf.CKPT_VERSION, step=snap.step,
                       host=snap.process_index, shards_file=shards_file,
                       shards=index)),
              heartbeat=heartbeat)
    if heartbeat is not None:
        heartbeat(f"ckpt index step {snap.step}")

    index_files = [mf.index_name(h) for h in range(snap.process_count)]
    if snap.process_index == 0:
        # the cross-host barrier: every host's index must be visible
        # before the commit record claims the checkpoint is whole
        mf.wait_for_files([os.path.join(step_dir, n) for n in index_files],
                          fs_timeout, "every host's shard index")
        manifest = dict(
            version=mf.CKPT_VERSION,
            step=snap.step,
            structure=snap.structure,
            scalars=snap.scalars,
            leaves=snap.leaves,
            index_files=index_files,
            num_hosts=snap.process_count,
            **snap.manifest_extra,
        )
        _retry_io(mf.MANIFEST_NAME,
                  lambda: mf.atomic_write_json(
                      os.path.join(step_dir, mf.MANIFEST_NAME), manifest),
                  heartbeat=heartbeat)
    # durability barrier: no rank observes the save as complete before
    # the commit record exists
    mf.wait_for_files([os.path.join(step_dir, mf.MANIFEST_NAME)],
                      fs_timeout, "the checkpoint manifest")
    return snap.payload_bytes


def save_sharded(directory: str, ffmodel, step: Optional[int] = None,
                 fs_timeout: float = 120.0) -> str:
    """Synchronous per-shard save (snapshot + commit on the calling
    thread). Returns the committed step directory. The async path goes
    through ``CheckpointManager``."""
    snap = snapshot(ffmodel, step=step)
    write_snapshot(directory, snap, fs_timeout=fs_timeout)
    return os.path.join(directory, mf.step_dir_name(snap.step))


# ---------------------------------------------------------------------------
# load


def _box_volume(box, shape=None) -> int:
    """Elements inside a serialized shard box ([] = a 0-d scalar)."""
    if not box:
        return int(np.prod(shape)) if shape else 1
    return int(np.prod([max(0, b[1] - b[0]) for b in box]))


def _boxes_intersect(a, b) -> bool:
    for (s1, e1), (s2, e2) in zip(a, b):
        if min(e1, e2) <= max(s1, s2):
            return False
    return True


def _live_boxes(ffmodel) -> Dict[str, Optional[List[List[List[int]]]]]:
    """Per-leaf deduplicated addressable shard boxes of the LIVE
    model's arrays — the regions THIS host must restore. ``None``
    marks a leaf the planner cannot reason about (host-resident numpy
    op state) — those take the full scan."""
    out: Dict[str, Optional[List[List[List[int]]]]] = {}
    for key, v in flatten_tree(_capture_state(ffmodel)):
        boxes = None
        if hasattr(v, "addressable_shards") and hasattr(v, "sharding"):
            try:
                boxes, seen = [], set()
                for sh in v.addressable_shards:
                    box = _box(sh.index, v.shape)
                    t = tuple(map(tuple, box))
                    if t not in seen:
                        seen.add(t)
                        boxes.append(box)
            except Exception:
                boxes = None
        out[key] = boxes
    return out


def _select_rows(entries, needed):
    """The rank-local read plan for one leaf.

    ``entries`` are (shards_file, row) pairs from every host's index;
    ``needed`` the live addressable boxes (None = unknowable). Returns
    ``(selected, skipped, want_elements, rank_local)``. Rank-local mode
    engages only when every saved box either EXACTLY matches a needed
    box or misses the needed region entirely — the same-mesh case. Any
    partial overlap means the mesh changed; that leaf falls back to the
    full scan (``want_elements=None`` → caller uses the global count),
    which reassembles the whole array exactly as before."""
    if needed is None:
        return entries, [], None, False
    needed_keys = {tuple(map(tuple, b)) for b in needed}
    selected, skipped = [], []
    for ent in entries:
        box = ent[1]["index"]
        t = tuple(map(tuple, box))
        if t in needed_keys:
            selected.append(ent)
        elif any(_boxes_intersect(box, nb) for nb in needed):
            # boxes changed (elastic resume onto a different mesh):
            # correctness over savings — read everything for this leaf
            return entries, [], None, False
        else:
            skipped.append(ent)
    want = sum(_box_volume(nb) for nb in needed)
    return selected, skipped, want, True


def _gather_agree(value: int, what: str) -> int:
    """Fail-fast cross-rank agreement: every rank must see the same
    non-negative value or EVERY rank raises the same actionable error
    (the ADVICE r5 fix — a missing checkpoint on one host must never
    become a silent collective deadlock)."""
    import jax

    if jax.process_count() <= 1:
        if value < 0:
            raise FileNotFoundError(what)
        return value
    from flexflow_tpu import distributed
    seen, agree = distributed.ranks_agree(value)
    if all(v < 0 for v in seen):
        # unanimous absence is a wrong path / never-saved directory,
        # NOT a filesystem-sharing problem — don't send the operator
        # off to debug a working shared mount
        raise FileNotFoundError(what)
    if any(v < 0 for v in seen) or not agree:
        bad = [r for r, v in enumerate(seen) if v < 0]
        raise FileNotFoundError(
            f"{what} (per-rank view: {seen}"
            + (f"; ranks {bad} cannot see it — the checkpoint directory "
               f"must be on a filesystem shared by every host" if bad
               else "; hosts disagree on the newest complete checkpoint")
            + ")")
    return seen[0]


def load_sharded(path: str, ffmodel, verify: bool = True,
                 rank_local: bool = True,
                 include_opt_state: bool = True) -> int:
    """Restore a v2 per-shard checkpoint onto the live model.

    ``path`` is a checkpoint root (newest complete step is taken) or a
    specific ``step_*`` directory. Works across mesh shapes and host
    counts: each global array is reassembled from the shard index and
    re-placed onto the live strategy's NamedShardings. Missing or
    partial checkpoints raise on EVERY rank. ``rank_local`` (default)
    reads + CRC-verifies only the shards whose boxes this host's live
    arrays actually cover, falling back per-leaf to the full scan when
    the saved boxes don't line up with the live ones (mesh changed).
    ``include_opt_state=False`` skips the optimizer-state leaves
    entirely — no reads, no reassembly — for forward-only consumers
    (the serving loader restores a training checkpoint into an
    INFERENCE-compiled model, which allocates no optimizer state).
    Returns the restored iteration counter."""
    from flexflow_tpu.obs.registry import get_registry

    def _wanted(leaf_key: str) -> bool:
        return include_opt_state or not (
            leaf_key == "opt_state" or leaf_key.startswith("opt_state/"))

    step_dir = mf.resolve_step_dir(path)
    local = -1 if step_dir is None else _read_step(step_dir)
    step = _gather_agree(
        local,
        f"no complete checkpoint under '{path}' — a checkpoint is only "
        f"complete once its {mf.MANIFEST_NAME} commit record exists "
        f"(a save interrupted mid-write leaves none)")
    if step_dir is None or _read_step(step_dir) != step:
        # unreachable single-process; cross-host disagreement raised above
        raise FileNotFoundError(f"checkpoint step mismatch under {path}")
    manifest = mf.read_json(os.path.join(step_dir, mf.MANIFEST_NAME))

    flat: Dict[str, Any] = dict(manifest.get("scalars", {}))
    pending: Dict[str, np.ndarray] = {}
    filled: Dict[str, int] = {}
    want: Dict[str, int] = {}
    local_mode: Dict[str, bool] = {}
    for leaf_key, meta in manifest["leaves"].items():
        if not _wanted(leaf_key):
            continue
        pending[leaf_key] = np.empty([int(d) for d in meta["shape"]],
                                     dtype=_np_dtype(meta["saved_dtype"]))
        filled[leaf_key] = 0
        want[leaf_key] = (int(np.prod(meta["shape"]))
                          if meta["shape"] else 1)
        local_mode[leaf_key] = False

    # gather every host's index rows BEFORE reading any shard bytes, so
    # the rank-local planner sees each leaf's complete saved shard set
    rows_by_leaf: Dict[str, List] = {k: [] for k in pending}
    for idx_file in manifest["index_files"]:
        index = mf.read_json(os.path.join(step_dir, idx_file))
        if index is None:
            raise FileNotFoundError(
                f"checkpoint {step_dir} is incomplete: shard index "
                f"{idx_file} is missing/unreadable despite a manifest — "
                f"refusing a partial restore")
        for leaf_key, rows in index["shards"].items():
            if not _wanted(leaf_key):
                continue
            rows_by_leaf.setdefault(leaf_key, []).extend(
                (index["shards_file"], row) for row in rows)

    live = _live_boxes(ffmodel) if rank_local else {}
    reg = get_registry()
    read_bytes = skipped_bytes = 0
    # plan per-leaf first (the rank-local selection needs each leaf's
    # complete shard set), then read FILE-major: a full-scan restore of
    # an N-host checkpoint must hold at most ONE host's npz (and file
    # descriptor) open at a time
    reads_by_file: Dict[str, List] = {}
    for leaf_key, entries in rows_by_leaf.items():
        selected, skipped, leaf_want, is_local = _select_rows(
            entries, live.get(leaf_key))
        if is_local:
            want[leaf_key] = leaf_want
            local_mode[leaf_key] = True
            skipped_bytes += sum(int(row.get("bytes", 0))
                                 for _, row in skipped)
        for shards_file, row in selected:
            reads_by_file.setdefault(shards_file, []).append(
                (leaf_key, row))
    for shards_file, rows in reads_by_file.items():
        npz = np.load(os.path.join(step_dir, shards_file))
        try:
            for leaf_key, row in rows:
                dest = pending[leaf_key]
                try:
                    data = read_shard_row(npz, row, verify=verify)
                except ValueError as e:  # stored-CRC mismatch
                    raise ValueError(
                        f"checkpoint {step_dir}: {e} on '{leaf_key}' — "
                        f"on-disk corruption; refusing to restore") from e
                except Exception as e:  # zip-level CRC / truncation
                    raise ValueError(
                        f"checkpoint {step_dir}: shard '{row['key']}' of "
                        f"'{leaf_key}' is unreadable ({e}) — on-disk "
                        f"corruption; refusing to restore") from e
                read_bytes += int(row.get("bytes", data.nbytes))
                box = row["index"]
                if box:
                    sl = tuple(slice(b[0], b[1]) for b in box)
                    dest[sl] = data
                    filled[leaf_key] += int(
                        np.prod([b[1] - b[0] for b in box]))
                else:
                    dest[...] = data
                    filled[leaf_key] += 1
        finally:
            npz.close()
    reg.inc("ckpt/restore_read_bytes", read_bytes)
    reg.inc("ckpt/restore_skipped_bytes", skipped_bytes)
    for leaf_key, meta in manifest["leaves"].items():
        if leaf_key not in pending:
            continue  # opt-state leaf skipped by include_opt_state=False
        if filled[leaf_key] != want[leaf_key]:
            scope = ("this host's live shard boxes"
                     if local_mode[leaf_key] else "the global shape")
            raise ValueError(
                f"checkpoint {step_dir}: leaf '{leaf_key}' reassembled "
                f"{filled[leaf_key]}/{want[leaf_key]} elements of "
                f"{scope} — incomplete shard set; refusing a partial "
                f"restore")
        true = _np_dtype(meta["dtype"])
        if pending[leaf_key].dtype != true:
            pending[leaf_key] = pending[leaf_key].view(true)
        flat[leaf_key] = pending[leaf_key]

    if include_opt_state:
        state = rebuild_tree(manifest["structure"], flat)
    else:
        # rebuild only the forward-relevant subtrees; the optimizer
        # leaves were never read
        items = manifest["structure"]["items"]
        state = {
            "params": rebuild_tree(items["params"], flat, "params/"),
            "op_state": rebuild_tree(items["op_state"], flat, "op_state/"),
        }
    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    live_op_state = {k: v for k, v in ffmodel.state.items()
                     if k != COMPUTE_PARAMS_KEY}
    ffmodel.params = place_tree(ffmodel.params, state["params"])
    if include_opt_state:
        ffmodel.opt_state = place_tree(ffmodel.opt_state,
                                       state["opt_state"])
    ffmodel.state = place_tree(live_op_state, state["op_state"])
    ffmodel._compute_params_dirty = True
    ffmodel._refresh_compute_params()
    ffmodel._iter = int(manifest["iteration"])
    if manifest.get("rng"):
        import jax.numpy as jnp
        ffmodel._rng = jnp.asarray(np.asarray(manifest["rng"],
                                              dtype=np.uint32))
    return ffmodel._iter


def _read_step(step_dir: str) -> int:
    m = mf.read_json(os.path.join(step_dir, mf.MANIFEST_NAME))
    return int(m["step"]) if m and "step" in m else -1

"""Numerically-exact post-import graph transforms.

``fold_conv_batchnorm`` plays the role of the reference's Conv+BN fold
family in inference graph optimization — but as an EXPLICIT pass over a
compiled model with live weights, not an automatic search rewrite:
rewrites re-initialize replaced ops' parameters (their weights arrive
only after compile), and the fold is only interesting for PRETRAINED
inference, so the automatic form would silently produce wrong numerics.
Here the fold computes

    k' = k * (gamma / sqrt(var + eps))        per output channel
    b' = beta + (b - mean) * gamma / sqrt(var + eps)

from the model's live BN parameters and running stats, removes the BN
layer (folding its fused relu into the conv's activation), recompiles,
and installs the folded weights — bit-for-bit the same function with one
op fewer.
"""

from __future__ import annotations

import numpy as np

from flexflow_tpu.ffconst import ActiMode, CompMode, OperatorType


def fold_conv_batchnorm(ff) -> int:
    """Fold every Conv2D -> BatchNorm pair in a compiled INFERENCE model.
    Returns the number of folds performed. The model is recompiled; all
    other weights are carried over."""
    if ff.config.computation_mode != CompMode.INFERENCE:
        raise ValueError(
            "fold_conv_batchnorm requires CompMode.INFERENCE: under "
            "training the BN statistics are batch-dependent and cannot "
            "fold into constants")

    # tensor guid -> consumer layers
    consumers = {}
    for layer in ff.layers:
        for t in layer.inputs:
            consumers.setdefault(t.guid, []).append(layer)

    pairs = []
    for bn in ff.layers:
        if bn.op_type != OperatorType.BATCHNORM:
            continue
        src = bn.inputs[0].owner_layer
        if (src is not None and src.op_type == OperatorType.CONV2D
                and src.properties.get("activation",
                                       ActiMode.AC_MODE_NONE)
                in (ActiMode.AC_MODE_NONE, None)
                and len(consumers.get(bn.inputs[0].guid, [])) == 1):
            pairs.append((src, bn))
    if not pairs:
        return 0

    # live weights BEFORE the graph changes
    folded = {}
    for conv, bn in pairs:
        k = ff.get_parameter(conv.name, "kernel")          # [O, I, KH, KW]
        b = (ff.get_parameter(conv.name, "bias")
             if conv.properties.get("use_bias", True)
             else np.zeros((k.shape[0],), np.float32))
        gamma = ff.get_parameter(bn.name, "scale")
        beta = ff.get_parameter(bn.name, "bias")
        st = ff.state.get(bn.name, {})
        mean = np.asarray(st.get("mean", np.zeros_like(gamma)))
        var = np.asarray(st.get("var", np.ones_like(gamma)))
        eps = bn.properties.get("eps", 1e-5)
        g = gamma / np.sqrt(var + eps)
        folded[conv.name] = (
            k * g[:, None, None, None],
            beta + (b - mean) * g,
            bool(bn.properties.get("relu", True)),
        )

    # graph surgery: drop BN layers, rewire their consumers to the conv
    # output, upgrade the conv (bias + folded relu)
    bn_names = {bn.name for _, bn in pairs}
    others = []  # non-conv/bn params to carry over
    for lname, sub in ff.params.items():
        if lname not in folded and lname not in bn_names:
            others.append((lname, {p: np.asarray(v)
                                   for p, v in sub.items()}))
    # op state (e.g. running stats of BNs the fold did NOT touch) must
    # survive the recompile too — compile() reassigns ff.state
    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    state_save = {
        lname: {k: np.asarray(v) for k, v in sub.items()}
        for lname, sub in ff.state.items()
        if lname not in bn_names and lname != COMPUTE_PARAMS_KEY
        and isinstance(sub, dict)
    }
    remap = {bn.outputs[0].guid: conv.outputs[0] for conv, bn in pairs}
    ff.layers = [l for l in ff.layers if l.name not in bn_names]
    for layer in ff.layers:
        layer.inputs = [remap.get(t.guid, t) for t in layer.inputs]
    for conv, bn in pairs:
        conv.properties["use_bias"] = True
        if folded[conv.name][2]:
            conv.properties["activation"] = ActiMode.AC_MODE_RELU
    if getattr(ff, "outputs", None) is not None:
        out = ff.outputs
        if out is not None and out.guid in remap:
            ff.outputs = remap[out.guid]

    metric_types = list(ff.metrics.metrics)
    ff.compile(ff.optimizer, ff.loss_type, metric_types,
               comp_mode=CompMode.INFERENCE,
               machine_spec=ff.machine_spec, mesh=ff.mesh)

    # the recompiled graph is the same graph minus the folded BNs, so
    # every carried-over parameter must restore cleanly; a failure means
    # the fold corrupted the graph and the pass's bit-exactness contract
    # is already broken — surface it instead of training on re-inits
    failed = []
    for lname, sub in others:
        for pname, value in sub.items():
            try:
                ff.set_parameter(lname, value, pname)
            except (KeyError, ValueError) as e:
                failed.append((lname, pname, str(e)))
    if failed:
        raise RuntimeError(
            "fold_conv_batchnorm: failed to restore carried-over weights "
            f"after recompile: {failed}")
    import jax
    import jax.numpy as jnp
    for lname, sub in state_save.items():
        live = ff.state.get(lname)
        if not isinstance(live, dict):
            continue
        for k, value in sub.items():
            old = live.get(k)
            if old is not None and tuple(old.shape) == tuple(value.shape):
                live[k] = jax.device_put(jnp.asarray(value, old.dtype),
                                         old.sharding)
    for conv, _bn in pairs:
        k, b, _relu = folded[conv.name]
        ff.set_parameter(conv.name, np.asarray(k, np.float32), "kernel")
        ff.set_parameter(conv.name, np.asarray(b, np.float32), "bias")
    return len(pairs)

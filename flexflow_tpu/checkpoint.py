"""Training-state checkpoint / resume.

The reference has **no** model-state checkpointing (SURVEY §5.4 — only
weight get/set and strategy files); this is deliberate new scope for the
TPU framework: full (params, optimizer state, op state, iteration) capture
to a single .npz plus a JSON manifest, restoring onto the live shardings.

Format: flattened pytree with '/'-joined key paths. Works for any nesting
of dict/list/tuple with array leaves, so SGD momentum and Adam (m, v, t)
states round-trip unchanged.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Tuple

import numpy as np
import jax


def _flatten(tree, prefix="") -> List[Tuple[str, Any]]:
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out += _flatten(tree[k], f"{prefix}{k}/")
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for i, v in enumerate(tree):
            out += _flatten(v, f"{prefix}{i}/")
        return out
    return [(prefix[:-1], tree)]


def _structure(tree):
    """JSON-able skeleton used to rebuild nesting on load."""
    if isinstance(tree, dict):
        return {"__kind__": "dict",
                "items": {k: _structure(v) for k, v in tree.items()}}
    if isinstance(tree, tuple):
        return {"__kind__": "tuple", "items": [_structure(v) for v in tree]}
    if isinstance(tree, list):
        return {"__kind__": "list", "items": [_structure(v) for v in tree]}
    return {"__kind__": "leaf"}


def _rebuild(skel, flat: Dict[str, Any], prefix=""):
    kind = skel["__kind__"]
    if kind == "dict":
        return {k: _rebuild(v, flat, f"{prefix}{k}/")
                for k, v in skel["items"].items()}
    if kind in ("list", "tuple"):
        seq = [_rebuild(v, flat, f"{prefix}{i}/")
               for i, v in enumerate(skel["items"])]
        return tuple(seq) if kind == "tuple" else seq
    return flat[prefix[:-1]]


def save_checkpoint(path: str, ffmodel) -> None:
    """Write params + optimizer state + op state + iteration counter.

    Multi-host: every process participates in gathering sharded leaves
    (a collective), only process 0 writes the files, and every process
    returns only after the files are durable (barrier) — the standard
    multi-controller checkpoint discipline."""
    from flexflow_tpu import distributed
    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    state = {
        "params": ffmodel.params,
        "opt_state": ffmodel.opt_state,
        # the bf16 working copy is derived from params — re-cast on load
        # instead of doubling the checkpoint's parameter payload
        "op_state": {k: v for k, v in ffmodel.state.items()
                     if k != COMPUTE_PARAMS_KEY},
    }
    flat = _flatten(state)
    multihost = jax.process_count() > 1
    arrays = {}
    scalars = {}
    for k, v in flat:
        if hasattr(v, "shape"):
            # cross-host shards are not host-readable directly — gather
            # (no-op single-process)
            arr = (distributed.all_gather_host(v) if multihost
                   else np.asarray(v))
            if arr.dtype.kind not in "fiub":
                # np.savez writes non-native dtypes (ml_dtypes bfloat16)
                # as raw void bytes that cannot load back — store as f32;
                # load re-casts to the live leaf's dtype
                arr = arr.astype(np.float32)
            arrays[k] = arr
        else:
            scalars[k] = v
    if not multihost or distributed.process_index() == 0:
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        np.savez(path if path.endswith(".npz") else path + ".npz", **arrays)
        manifest = {
            "version": 1,
            "iteration": ffmodel._iter,
            "structure": _structure(state),
            "scalars": scalars,
            "array_keys": sorted(arrays),
        }
        with open(_manifest_path(path), "w") as f:
            json.dump(manifest, f)
    if multihost:
        # no rank may observe save_checkpoint as complete before the
        # files are durable (a preemption handler or an immediate load
        # on another rank must find a whole checkpoint)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ffs_checkpoint_written")


def _manifest_path(path: str) -> str:
    base = path[:-4] if path.endswith(".npz") else path
    return base + ".manifest.json"


def load_checkpoint(path: str, ffmodel) -> int:
    """Restore state saved by save_checkpoint onto the live shardings.

    Returns the saved iteration counter. Shapes must match the compiled
    model (same graph); shardings may differ — arrays are re-placed with
    the current strategy's NamedShardings.
    """
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    data = np.load(npz_path)
    flat = {k: data[k] for k in manifest["array_keys"]}
    flat.update(manifest["scalars"])
    state = _rebuild(manifest["structure"], flat)

    # re-place arrays on the shardings of the live values
    def place(live, new):
        if isinstance(live, dict):
            if not isinstance(new, dict) or set(new) != set(live):
                raise ValueError(
                    f"checkpoint structure mismatch: expected keys "
                    f"{sorted(live)}, found "
                    f"{sorted(new) if isinstance(new, dict) else type(new)}")
            return {k: place(live[k], new[k]) for k in live}
        if isinstance(live, (list, tuple)):
            if not isinstance(new, (list, tuple)) or len(new) != len(live):
                raise ValueError(
                    f"checkpoint structure mismatch: expected sequence of "
                    f"{len(live)}, found {new!r:.80}")
            rebuilt = [place(l, n) for l, n in zip(live, new)]
            return type(live)(rebuilt) if isinstance(live, tuple) else rebuilt
        if hasattr(live, "sharding") and hasattr(new, "shape"):
            if tuple(live.shape) != tuple(np.shape(new)):
                raise ValueError(
                    f"checkpoint shape {np.shape(new)} != live {live.shape}")
            # cast to the live dtype (bf16 opt state is saved as f32)
            import jax.numpy as jnp
            if jax.process_count() > 1:
                # every host loads the full array; each places only its
                # addressable shards of the (possibly cross-host)
                # sharding. The callback returns numpy so JAX places
                # each shard directly on its device (ml_dtypes covers
                # bf16), with no default-device detour
                arr = np.asarray(new)
                dtype = np.dtype(live.dtype)
                return jax.make_array_from_callback(
                    tuple(live.shape), live.sharding,
                    lambda idx: arr[idx].astype(dtype))
            return jax.device_put(jnp.asarray(new, live.dtype), live.sharding)
        return new

    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    live_op_state = {k: v for k, v in ffmodel.state.items()
                     if k != COMPUTE_PARAMS_KEY}
    ffmodel.params = place(ffmodel.params, state["params"])
    ffmodel.opt_state = place(ffmodel.opt_state, state["opt_state"])
    ffmodel.state = place(live_op_state, state["op_state"])
    ffmodel._compute_params_dirty = True
    ffmodel._refresh_compute_params()
    ffmodel._iter = int(manifest["iteration"])
    return ffmodel._iter

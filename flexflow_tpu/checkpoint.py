"""Training-state checkpoint / resume — legacy v1 single-file format.

The reference has **no** model-state checkpointing (SURVEY §5.4 — only
weight get/set and strategy files); this v1 format was the first new
scope: full (params, optimizer state, op state, iteration) capture to a
single .npz plus a JSON manifest, restoring onto the live shardings.
It all-gathers every sharded leaf onto every host and funnels the write
through rank 0 — fine for one host, a step-loop stall and a
shared-filesystem trap at scale. New runs should use the v2 per-shard
package (flexflow_tpu/ckpt): each host writes only its addressable
shards, asynchronously, with a manifest-last commit record.
``load_checkpoint`` auto-detects both formats, so v1 checkpoints remain
a supported migration path.

v1 hardening (ISSUE 10 satellites):

* crash-atomic: the .npz and the manifest are written tmp+``os.replace``
  with the manifest LAST — a save preempted mid-write can no longer
  shadow the previous good checkpoint with a corrupt half-file;
* bf16-exact: ml_dtypes bfloat16 leaves are stored as uint16 bit views
  with the true dtype recorded in the manifest (older checkpoints that
  took the f32 widening detour still load);
* fail-fast: on multi-host, every rank checks visibility of the files
  and the ranks AGREE before anyone touches a collective — a
  non-shared filesystem yields one actionable error on every rank
  instead of FileNotFoundError-then-deadlock (ADVICE r5).

Format: flattened pytree with '/'-joined key paths. Works for any
nesting of dict/list/tuple with array leaves, so SGD momentum and Adam
(m, v, t) states round-trip unchanged.
"""

from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np
import jax

from flexflow_tpu.ckpt.tree import (flatten_tree, place_tree, rebuild_tree,
                                    tree_structure)


def save_checkpoint(path: str, ffmodel) -> None:
    """Write params + optimizer state + op state + iteration counter.

    Multi-host: every process participates in gathering sharded leaves
    (a collective), only process 0 writes the files, and every process
    returns only after the files are durable (barrier) — the standard
    multi-controller checkpoint discipline."""
    from flexflow_tpu import distributed
    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    state = {
        "params": ffmodel.params,
        "opt_state": ffmodel.opt_state,
        # the bf16 working copy is derived from params — re-cast on load
        # instead of doubling the checkpoint's parameter payload
        "op_state": {k: v for k, v in ffmodel.state.items()
                     if k != COMPUTE_PARAMS_KEY},
    }
    flat = flatten_tree(state)
    multihost = jax.process_count() > 1
    arrays = {}
    scalars = {}
    dtypes: Dict[str, str] = {}
    from flexflow_tpu.ckpt.sharded import _bit_view
    for k, v in flat:
        if hasattr(v, "shape"):
            # cross-host shards are not host-readable directly — gather
            # (no-op single-process)
            arr = (distributed.all_gather_host(v) if multihost
                   else np.asarray(v))
            # np.savez writes non-native dtypes (ml_dtypes bfloat16) as
            # raw void bytes that cannot load back — store the exact
            # bits as an unsigned-int view (shared codec with the v2
            # format), true dtype recorded in the manifest
            saved, true, saved_dt = _bit_view(arr)
            if saved_dt != true:
                dtypes[k] = true
            arrays[k] = saved
        else:
            scalars[k] = v
    if not multihost or distributed.process_index() == 0:
        npz_path = path if path.endswith(".npz") else path + ".npz"
        # crash-atomic: .npz first, manifest LAST — the manifest is the
        # commit record, so a preemption mid-save leaves the previous
        # (path, manifest) pair intact or fully replaces both
        from flexflow_tpu.ckpt.manifest import atomic_replace, \
            atomic_write_json
        with atomic_replace(npz_path) as f:
            np.savez(f, **arrays)
        manifest = {
            "version": 1,
            "iteration": ffmodel._iter,
            "rng": [int(x) for x in np.asarray(ffmodel._rng).ravel()],
            "structure": tree_structure(state),
            "scalars": scalars,
            "array_keys": sorted(arrays),
            # true dtypes of bit-view-stored leaves (bf16-exact satellite)
            "dtypes": dtypes,
        }
        atomic_write_json(_manifest_path(path), manifest)
    if multihost:
        # no rank may observe save_checkpoint as complete before the
        # files are durable (a preemption handler or an immediate load
        # on another rank must find a whole checkpoint)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ffs_checkpoint_written")


def _manifest_path(path: str) -> str:
    base = path[:-4] if path.endswith(".npz") else path
    return base + ".manifest.json"


def _check_visible(path: str) -> None:
    """ADVICE r5 fix: agreement on file visibility BEFORE any rank
    enters the collectives a cross-host load performs. A checkpoint
    rank 0 wrote to a non-shared filesystem used to be a
    FileNotFoundError on the other ranks followed by rank 0 hanging in
    its gather — now every rank raises the same actionable error."""
    npz_path = path if path.endswith(".npz") else path + ".npz"
    visible = (os.path.exists(npz_path)
               and os.path.exists(_manifest_path(path)))
    if jax.process_count() <= 1:
        if not visible:
            raise FileNotFoundError(
                f"no checkpoint at '{path}' (expected {npz_path} + "
                f"{_manifest_path(path)})")
        return
    from flexflow_tpu import distributed
    seen, _ = distributed.ranks_agree(1 if visible else 0)
    if not all(seen):
        bad = [r for r, v in enumerate(seen) if not v]
        raise FileNotFoundError(
            f"checkpoint '{path}' is not visible on rank(s) {bad} "
            f"(per-rank visibility: {seen}). Multi-host load requires "
            f"the checkpoint on a filesystem shared by every host "
            f"(GCS/NFS) — or use the v2 per-shard format "
            f"(flexflow_tpu/ckpt), which each host writes/reads "
            f"through the same shared directory without a rank-0 "
            f"funnel.")


def load_checkpoint(path: str, ffmodel) -> int:
    """Restore a checkpoint onto the live shardings (v1 or v2).

    ``path`` may be a v1 file stem (``<stem>.npz`` + manifest) or a v2
    per-shard checkpoint directory (a root of ``step_*`` dirs, or one
    step dir) — the format is auto-detected, so resume tooling needs
    one entry point for both. Returns the saved iteration counter.
    Shapes must match the compiled model (same graph); shardings may
    differ — arrays are re-placed with the current strategy's
    NamedShardings, including onto a different mesh (elastic resume).
    Missing or partial checkpoints fail fast on every rank.
    """
    # the FORMAT decision itself is per-host filesystem state, so it
    # must be agreed before ranks diverge into different loaders (each
    # with its own collective): a v2 root visible only on some ranks
    # would otherwise pair a step-number gather on one rank with a
    # visibility-flag gather on another — mixed-meaning values in one
    # collective, the ADVICE r5 class all over again
    is_dir = os.path.isdir(path)
    if jax.process_count() > 1:
        from flexflow_tpu import distributed
        seen, agree = distributed.ranks_agree(1 if is_dir else 0)
        if not agree:
            bad = [r for r, v in enumerate(seen) if not v]
            raise FileNotFoundError(
                f"checkpoint path '{path}' is a v2 directory on some "
                f"ranks but not on rank(s) {bad} (per-rank view: "
                f"{seen}) — the checkpoint must be on a filesystem "
                f"shared by every host (GCS/NFS)")
    if is_dir:
        from flexflow_tpu.ckpt import load_sharded
        return load_sharded(path, ffmodel)
    _check_visible(path)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    data = np.load(npz_path)
    dtypes = manifest.get("dtypes", {})

    def _restore(k):
        arr = data[k]
        if k in dtypes:
            from flexflow_tpu.ckpt.sharded import _np_dtype
            arr = arr.view(_np_dtype(dtypes[k]))
        return arr

    flat = {k: _restore(k) for k in manifest["array_keys"]}
    flat.update(manifest["scalars"])
    state = rebuild_tree(manifest["structure"], flat)

    from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
    live_op_state = {k: v for k, v in ffmodel.state.items()
                     if k != COMPUTE_PARAMS_KEY}
    ffmodel.params = place_tree(ffmodel.params, state["params"])
    ffmodel.opt_state = place_tree(ffmodel.opt_state, state["opt_state"])
    ffmodel.state = place_tree(live_op_state, state["op_state"])
    ffmodel._compute_params_dirty = True
    ffmodel._refresh_compute_params()
    ffmodel._iter = int(manifest["iteration"])
    if manifest.get("rng"):
        import jax.numpy as jnp
        ffmodel._rng = jnp.asarray(np.asarray(manifest["rng"],
                                              dtype=np.uint32))
    return ffmodel._iter

"""Compiled-step inspector: XLA cost/memory analysis + collective census.

The single owner of HLO-text parsing for collectives (the priced-vs-
emitted validator in ``flexflow_tpu/search/validate.py`` builds its
byte totals on this census). ``inspect_model_step`` lowers + compiles
the model's jitted train step on the live mesh and reports what the
program ACTUALLY is: FLOPs and HBM bytes accessed (XLA cost analysis),
per-device argument/temp/peak bytes (XLA memory analysis), and the
per-step collective census — all-reduce / all-gather / reduce-scatter /
all-to-all / collective-permute counts and payload byte volumes.
"""

from __future__ import annotations

import re
from collections import defaultdict
from typing import Any, Dict, Optional

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s32": 4, "u32": 4,
    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

# HLO collective opcodes the census recognizes (async -start/-done pairs
# count once via the -start op)
COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "all-to-all", "collective-permute")

# The payload threshold below which the search's simulator does not
# price a collective (scalar loss/metric reductions). The validator
# (search/validate.py) filters its census with this; the observability
# summary deliberately does NOT — it reports every collective the step
# runs — and records the threshold it used as ``collectives_min_bytes``.
PRICED_MIN_BYTES = float(1 << 12)

_COLLECTIVE_RE = re.compile(
    r"\b(" + "|".join(COLLECTIVE_KINDS) + r")(-start|-done)?(\.\d+)?\(")


def shape_bytes(shape_str: str) -> float:
    """Total bytes of an HLO shape string like ``f32[128,256]`` or a
    variadic tuple ``(f32[8,4], f32[8,4])``."""
    total = 0.0
    for m in _SHAPE_RE.finditer(shape_str):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def collective_census(hlo_text: str, min_bytes: float = 0.0
                      ) -> Dict[str, Dict[str, float]]:
    """HLO opcode -> {count, bytes} over the optimized (SPMD) module.

    Byte volume is each op's OUTPUT shape — per-partition bytes in an
    SPMD module, i.e. what one device moves per step. The default
    ``min_bytes=0`` keeps every collective, scalar loss/metric
    reductions included; pass ``PRICED_MIN_BYTES`` to drop the ones the
    search's simulator deliberately does not price (as the validator
    does). HLO lines read ``%name = SHAPE opcode(operands)``; splitting
    at the first `` = `` keeps LHS names like ``%all-reduce.58`` from
    matching.
    """
    out: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        line = line.strip()
        if " = " not in line:
            continue
        rhs = line.split(" = ", 1)[1]
        m = _COLLECTIVE_RE.search(rhs)
        if not m or m.group(2) == "-done":
            continue
        b = shape_bytes(rhs[:m.start()])
        if b < min_bytes:
            continue
        kind = m.group(1)
        e = out.setdefault(kind, dict(count=0, bytes=0.0))
        e["count"] += 1
        e["bytes"] += b
    return out


def census_totals(census: Dict[str, Dict[str, float]]) -> Dict[str, float]:
    return dict(
        count=sum(e["count"] for e in census.values()),
        bytes=sum(e["bytes"] for e in census.values()),
    )


_RG_RE = re.compile(
    # explicit groups {{0,1},{2,3}} or the iota form [G,S]<=[dims]T(perm)
    r"replica_groups=(\{\{[\d, {}]*\}\}|\[[\d,]+\]<=\[[\d,]+\]"
    r"(?:T\([\d,]+\))?)")
_RG_IOTA_RE = re.compile(
    r"^\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?$")


def parse_replica_groups(attr: str):
    """Device-id groups of one collective's ``replica_groups`` HLO
    attribute. Handles the explicit form ``{{0,1},{2,3}}`` and the iota
    form ``[G,S]<=[dims]`` / ``[G,S]<=[dims]T(perm)`` (reshape
    iota(prod(dims)) to dims, transpose by perm, reshape to G x S).
    None when the attribute doesn't parse."""
    import numpy as np
    if attr.startswith("{{"):
        return [[int(x) for x in grp.split(",") if x.strip()]
                for grp in re.findall(r"\{([\d, ]*)\}", attr[1:-1])]
    m = _RG_IOTA_RE.match(attr)
    if not m:
        return None
    g, s = int(m.group(1)), int(m.group(2))
    dims = [int(x) for x in m.group(3).split(",")]
    arr = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(4):
        arr = arr.transpose([int(x) for x in m.group(4).split(",")])
    return arr.reshape(g, s).tolist()


def collective_census_by_fabric(hlo_text: str, chips_per_slice: int,
                                min_bytes: float = 0.0
                                ) -> Dict[str, Dict[str, float]]:
    """The census split by fabric tier: ``{"ici": {count, bytes},
    "dcn": {count, bytes}}`` over the optimized SPMD module.

    A collective rides DCN when any of its replica groups contains
    devices from more than one slice (device id // chips_per_slice, the
    slice-major order ``model.compile`` lays the ('slice', ...) mesh
    out in). A collective with no / unparseable replica_groups engages
    every participant — on a multi-slice mesh that spans, so it counts
    as DCN (conservative: the methodology BENCH_NOTES documents).

    Byte attribution is DECOMPOSED (ISSUE 20 r16): XLA lowers a
    spanning all-reduce hierarchically — intra-slice reduce-scatter,
    inter-slice exchange on the 1/d-sized shard each chip then holds
    (d = the group's largest single-slice membership), intra-slice
    all-gather — so only ``bytes/d`` of the payload crosses DCN; the
    remaining ``bytes*(1-1/d)`` moves on ICI and is charged there. A
    group with one chip per slice (d = 1) has no intra-slice stage and
    charges its full payload to DCN. Counts keep the old whole-fabric
    attribution: a spanning collective counts once, under "dcn"."""
    out = {"ici": dict(count=0, bytes=0.0), "dcn": dict(count=0, bytes=0.0)}
    cps = max(1, int(chips_per_slice))
    for line in hlo_text.splitlines():
        line = line.strip()
        if " = " not in line:
            continue
        rhs = line.split(" = ", 1)[1]
        m = _COLLECTIVE_RE.search(rhs)
        if not m or m.group(2) == "-done":
            continue
        b = shape_bytes(rhs[:m.start()])
        if b < min_bytes:
            continue
        rg = _RG_RE.search(rhs)
        groups = parse_replica_groups(rg.group(1)) if rg else None
        intra = 0  # largest single-slice membership over spanning groups
        if groups:
            spans = False
            for g in groups:
                if not g or len({d // cps for d in g}) <= 1:
                    continue
                spans = True
                per_slice: Dict[int, int] = {}
                for d in g:
                    per_slice[d // cps] = per_slice.get(d // cps, 0) + 1
                intra = max(intra, max(per_slice.values()))
        else:
            spans = True  # flat/implicit group: all participants
            intra = cps
        if spans:
            out["dcn"]["count"] += 1
            dcn_b = b / max(1, intra)
            out["dcn"]["bytes"] += dcn_b
            out["ici"]["bytes"] += b - dcn_b  # intra-slice stages
        else:
            out["ici"]["count"] += 1
            out["ici"]["bytes"] += b
    return out


_FUSION_RE = re.compile(r"=\s+\S+\s+fusion(\.\d+)?\(")
_CUSTOM_CALL_RE = re.compile(r"=\s+\S+\s+custom-call(\.\d+)?\(")


def fusion_census(hlo_text: str,
                  census: Optional[Dict[str, Dict[str, float]]] = None
                  ) -> Dict[str, int]:
    """Dispatch-count proxy over the optimized module: how many kernel
    launches the step is (fusion regions + custom calls + collectives).
    The coordinate the kernel-search dimension moves (ISSUE 15: a fused
    optimizer update collapses three regions into one), tracked by the
    bench's downward ``dispatch_count`` ratchet the way
    ``collective_bytes`` tracks the census. ``census``: a
    collective_census already computed for the same text (avoids a
    second full-module scan)."""
    fusions = len(_FUSION_RE.findall(hlo_text))
    custom = len(_CUSTOM_CALL_RE.findall(hlo_text))
    if census is None:
        census = collective_census(hlo_text)
    colls = sum(e["count"] for e in census.values())
    return dict(fusions=fusions, custom_calls=custom,
                collectives=int(colls),
                dispatches=fusions + custom + int(colls))


def inspect_compiled(compiled) -> Dict[str, Any]:
    """Cost + memory analysis + collective census of one jax ``Compiled``.

    Robust to backend gaps: any analysis a backend does not implement
    reports as None rather than raising (the CPU backend implements all
    three as of jax 0.4.x).
    """
    out: Dict[str, Any] = dict(flops=None, bytes_accessed=None,
                               transcendentals=None)
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["flops"] = float(ca.get("flops", 0.0)) or None
            out["bytes_accessed"] = (
                float(ca.get("bytes accessed", 0.0)) or None)
            t = ca.get("transcendentals")
            out["transcendentals"] = float(t) if t else None
    except Exception:
        pass
    mem: Optional[Dict[str, float]] = None
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = float(getattr(ma, "argument_size_in_bytes", 0))
            tmp = float(getattr(ma, "temp_size_in_bytes", 0))
            mem = dict(
                argument_bytes=arg,
                output_bytes=float(getattr(ma, "output_size_in_bytes", 0)),
                temp_bytes=tmp,
                generated_code_bytes=float(
                    getattr(ma, "generated_code_size_in_bytes", 0)),
                # the per-device peak an HBM budget must cover: live
                # arguments (params/opt state/batch) + XLA temp — same
                # definition as search/validate.compiled_footprint_bytes
                peak_bytes=arg + tmp,
            )
    except Exception:
        pass
    out["memory"] = mem
    census: Dict[str, Dict[str, float]] = {}
    fusions: Optional[Dict[str, int]] = None
    try:
        text = compiled.as_text()
        census = collective_census(text)
        fusions = fusion_census(text, census=census)
    except Exception:
        pass
    out["collectives"] = census
    out["collectives_total"] = census_totals(census)
    out["collectives_min_bytes"] = 0.0
    out["fusions"] = fusions
    return out


def export_step_summary(ff, tracer) -> Dict[str, Any]:
    """Inspect the compiled train step and write the ``.summary.json``
    artifact next to the tracer's other files (the one emission path
    shared by ``FFModel._finalize_trace`` and ``bench.py``). Returns
    the summary dict."""
    import os

    from flexflow_tpu.obs.artifacts import write_artifact

    summary = inspect_model_step(ff)
    path = os.path.join(tracer.trace_dir, tracer.file_stem + ".summary.json")
    write_artifact(path, summary, host_id=tracer.host_id,
                   kind="step_summary",
                   header_extra=dict(run_name=tracer.run_name,
                                     run_seq=tracer.run_seq))
    return summary


def model_context(ff) -> Dict[str, Any]:
    """Graph/mesh context the raw XLA numbers need to be interpreted —
    the ONE definition shared by the trace header (FFModel._make_tracer)
    and the step summary, so the two artifacts can never desync."""
    return dict(
        num_ops=len(ff.executor.nodes),
        mesh_axes=dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape)),
        batch_size=(ff.input_tensors[0].shape[0]
                    if ff.input_tensors else None),
    )


def inspect_model_step(ff) -> Dict[str, Any]:
    """Inspect the compiled TRAIN step of a compiled FFModel: lowers the
    jitted step on the live mesh with representative inputs and runs
    ``inspect_compiled`` on it (a fresh lower+compile — AOT inspection
    cannot reuse the executor's cached executable)."""
    from flexflow_tpu.search.validate import compiled_train_step

    compiled = compiled_train_step(ff)
    out = inspect_compiled(compiled)
    out.update(model_context(ff))
    # multi-slice fabric attribution: on a ('slice', ...) mesh, split the
    # census by fabric tier — the cross-slice (DCN) byte volume is the
    # coordinate bench.py records as dcn_bytes
    try:
        axis_names = tuple(getattr(ff.mesh, "axis_names", ()) or ())
        if "slice" in axis_names:
            axes = dict(zip(axis_names, ff.mesh.devices.shape))
            cps = int(ff.mesh.devices.size) // int(axes["slice"])
            out["collectives_by_fabric"] = collective_census_by_fabric(
                compiled.as_text(), cps)
    except Exception:
        pass
    return out

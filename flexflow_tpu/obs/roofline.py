"""Per-layer roofline attribution for a compiled model.

The evidence channel behind conv-family optimization decisions (ISSUE 2):
each materialized op is slope-timed standalone on the live device (the
BENCH_NOTES methodology — two loop lengths cancel dispatch overhead and
tunnel round-trip, search/profile.measure_op), its analytic FLOPs and HBM
bytes give an arithmetic intensity, and comparing against the chip's
peaks names the op compute-bound or bandwidth-bound. The per-class
aggregates (conv family vs matmul family) are what
``MachineSpec.conv_efficiency`` / ``machine_to_json`` feed back into the
native cost model, so predicted conv times track measured ones.

Emitted as JSON rows (machine-readable, scripts/roofline.py commits them)
plus a markdown table for BENCH_NOTES.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from flexflow_tpu.ffconst import OperatorType

# op-class buckets for the per-class efficiency aggregates
CONV_FAMILY = {OperatorType.CONV2D, OperatorType.POOL2D,
               OperatorType.BATCHNORM, OperatorType.GROUPNORM}
MATMUL_FAMILY = {OperatorType.LINEAR, OperatorType.BATCHMATMUL,
                 OperatorType.MULTIHEAD_ATTENTION, OperatorType.EXPERTS,
                 OperatorType.EINSUM}


def _op_class(op) -> str:
    if op.op_type in CONV_FAMILY:
        return "conv"
    if op.op_type in MATMUL_FAMILY:
        return "matmul"
    return "other"


def roofline_report(nodes, machine_spec, repeats: int = 3, warmup: int = 1,
                    dtype_size: float = 4.0,
                    include_bwd: bool = True) -> Dict[str, Any]:
    """Time every op in an OpNode list and attribute it on the roofline.

    Returns ``{"rows": [...], "classes": {...}, "machine": {...}}``.
    Each row: op name/type/class, shapes, flops, bytes, intensity
    (flop/byte), measured fwd/bwd seconds, achieved FLOP/s and bytes/s,
    MFU (fraction of chip peak), and ``bound`` — which roofline wall the
    op sits under at the machine's ridge point. Ops whose standalone
    forward cannot run are reported with ``error`` instead of numbers.
    """
    from flexflow_tpu.search.profile import measure_op, op_io_bytes

    peak_flops = float(machine_spec.flops)
    hbm_bw = float(machine_spec.hbm_bw)
    ridge = peak_flops / hbm_bw  # flop/byte where the two walls meet
    rows: List[Dict[str, Any]] = []
    for node in nodes:
        op = node.op
        row: Dict[str, Any] = dict(
            name=op.name,
            type=op.op_type.name,
            op_class=_op_class(op),
            layout=getattr(op, "exec_layout", "NCHW"),
            input_shapes=[list(s) for s in op.input_shapes],
            output_shapes=[list(s) for s in op.output_shapes],
        )
        flops = float(op.flops())
        bytes_ = op_io_bytes(op, dtype_size)
        row["flops"] = flops
        row["bytes"] = bytes_
        row["intensity"] = flops / bytes_ if bytes_ else None
        # which wall the op sits under *analytically*, independent of how
        # well the kernel runs: under the ridge point it cannot beat HBM
        row["bound"] = ("compute" if bytes_ and flops / bytes_ >= ridge
                        else "bandwidth")
        try:
            fwd_s, bwd_s = measure_op(op, repeats=repeats, warmup=warmup,
                                      hbm_bw=hbm_bw,
                                      include_bwd=include_bwd)
        except Exception as e:  # standalone-unrunnable op: keep the row
            row["error"] = f"{type(e).__name__}: {e}"
            rows.append(row)
            continue
        row["fwd_s"] = fwd_s
        if include_bwd:
            row["bwd_s"] = bwd_s
        row["achieved_flops"] = flops / fwd_s if fwd_s else None
        row["achieved_bw"] = bytes_ / fwd_s if fwd_s else None
        row["mfu"] = flops / fwd_s / peak_flops if fwd_s else None
        row["hbm_frac"] = bytes_ / fwd_s / hbm_bw if fwd_s else None
        rows.append(row)
    return dict(rows=rows, classes=class_aggregates(rows),
                machine=dict(chip=machine_spec.chip, peak_flops=peak_flops,
                             hbm_bw=hbm_bw, ridge_intensity=ridge))


def class_aggregates(rows) -> Dict[str, Dict[str, float]]:
    """Per-op-class totals: the conv-vs-matmul efficiency evidence. The
    ``efficiency`` figure (class FLOPs / class measured time / peak) is
    the number to feed ``MachineSpec.conv_efficiency``."""
    agg: Dict[str, Dict[str, float]] = {}
    for r in rows:
        if "fwd_s" not in r:
            continue
        a = agg.setdefault(r["op_class"],
                           dict(ops=0, flops=0.0, bytes=0.0, fwd_s=0.0))
        a["ops"] += 1
        a["flops"] += r["flops"]
        a["bytes"] += r["bytes"]
        a["fwd_s"] += r["fwd_s"]
    return agg


def finish_aggregates(agg, peak_flops: float) -> None:
    """Attach achieved-FLOP/s and efficiency to class aggregates in
    place (separate from collection so callers can merge reports)."""
    for a in agg.values():
        t = a.get("fwd_s") or 0.0
        a["achieved_flops"] = a["flops"] / t if t else None
        a["efficiency"] = a["flops"] / t / peak_flops if t else None


def format_markdown(report, top: Optional[int] = 20) -> str:
    """Markdown roofline table, heaviest ops first (by measured fwd
    time), plus the per-class aggregate block."""
    rows = [r for r in report["rows"] if "fwd_s" in r]
    rows.sort(key=lambda r: -r["fwd_s"])
    skipped = len(report["rows"]) - len(rows)
    lines = [
        "| op | class | layout | fwd us | GFLOP/s | GB/s | MFU | bound |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows[:top]:
        lines.append(
            f"| {r['name']} | {r['op_class']} | {r['layout']} "
            f"| {r['fwd_s'] * 1e6:.1f} "
            f"| {(r['achieved_flops'] or 0) / 1e9:.1f} "
            f"| {(r['achieved_bw'] or 0) / 1e9:.1f} "
            f"| {(r['mfu'] or 0) * 100:.2f}% | {r['bound']} |")
    if top and len(rows) > top:
        lines.append(f"| ... ({len(rows) - top} more ops) | | | | | | | |")
    if skipped:
        lines.append(f"\n({skipped} ops unmeasurable standalone — see the "
                     f"JSON rows' `error` fields)")
    agg = dict(report["classes"])
    finish_aggregates(agg, report["machine"]["peak_flops"])
    lines.append("\nPer-class aggregates (feed `efficiency` of the conv "
                 "class to `MachineSpec.conv_efficiency`):\n")
    lines.append("| class | ops | total fwd ms | GFLOP/s | efficiency |")
    lines.append("|---|---|---|---|---|")
    for name, a in sorted(agg.items()):
        lines.append(
            f"| {name} | {a['ops']} | {a['fwd_s'] * 1e3:.2f} "
            f"| {(a['achieved_flops'] or 0) / 1e9:.1f} "
            f"| {(a['efficiency'] or 0) * 100:.2f}% |")
    bw_bound = sum(1 for r in rows if r["bound"] == "bandwidth")
    lines.append(f"\n{bw_bound}/{len(rows)} measured ops are "
                 f"bandwidth-bound at the machine ridge point "
                 f"({report['machine']['ridge_intensity']:.1f} flop/byte).")
    return "\n".join(lines)

"""Device-trace attribution: where a step's time goes ON THE DEVICE.

The StepTracer records host-side wall time; this module closes the gap
ROADMAP item (d) names — a windowed ``jax.profiler`` capture around a
range of training steps, plus a stdlib-only parser that classifies the
emitted Chrome-trace device spans into compute / collective / host-stall
buckets and runs interval arithmetic per step:

- ``compute_time``        union of device compute spans inside the step
- ``comms_time``          union of collective spans (all-reduce,
                          all-gather, reduce-scatter, collective-permute,
                          all-to-all)
- ``overlapped_comms``    comms time hidden under compute
- ``exposed_comms``       comms the step actually waits on — the number
                          the comms-compute-overlap direction ratchets

Capture is windowed (``fit(profile_steps="A:B")`` / ``--profile-steps``)
because a whole-run profile of a long job is gigabytes; a 2-4 step
window is the steady-state sample. The CPU backend emits the same
Chrome-trace JSON (``plugins/profile/*/*.trace.json.gz``) with per-op
``args.hlo_op`` spans, so the whole pipeline runs devicelessly in
tier-1. Steps are located inside the profile via
``jax.profiler.StepTraceAnnotation`` markers the capture wraps around
each step, which also give the host-clock correlation used to rebase
device lanes onto the StepTracer timeline for the merged Perfetto view.

Cf. "A Learned Performance Model for TPUs" (PAPERS.md 2008.01040): the
per-collective measured times this produces are exactly the calibration
signal the analytic simulator lacks — ``obs/drift.py`` joins them
against the census-priced predictions and ``scripts/calibrate.py
--ingest-drift`` folds the ratios into CALIBRATION.json.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from flexflow_tpu.obs.inspect import COLLECTIVE_KINDS

# the step marker the capture wraps around each training step; the
# parser finds these annotations inside the profile to window device
# spans per step (args.step_num carries the global step index)
STEP_ANNOTATION = "ff_step"

# HLO op-name prefixes bucketed as host stalls: device time spent
# waiting on the host feed or cross-program transfers, not computing
HOST_OP_PREFIXES = ("infeed", "outfeed", "send", "recv", "host-call")

_KIND_RE = re.compile(
    r"^(" + "|".join(COLLECTIVE_KINDS) + r"|collective-broadcast)"
    r"(-start|-done)?(\.\d+)?$")

# Perfetto lane tids for device events injected into the StepTracer
# trace (tid 0 is the host train_loop): one lane per bucket, shared by
# all local devices — the union semantics below treat them as one
# device-time resource per host.
TID_COMPUTE, TID_COMMS, TID_HOST = 64, 65, 66
LANE_THREADS = {TID_COMPUTE: "device:compute", TID_COMMS: "device:comms",
                TID_HOST: "device:host"}


def parse_profile_steps(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``"A:B"`` -> capture steps A..B-1 (half-open, python-slice
    convention); bare ``"N"`` -> just step N. None/"" -> no capture."""
    if not spec:
        return None
    s = str(spec).strip()
    try:
        if ":" in s:
            a, b = s.split(":", 1)
            start, stop = int(a), int(b)
        else:
            start, stop = int(s), int(s) + 1
    except ValueError:
        raise ValueError(
            f"--profile-steps expects 'A:B' or 'N', got {spec!r}")
    if start < 0 or stop <= start:
        raise ValueError(
            f"--profile-steps window must satisfy 0 <= A < B, got {spec!r}")
    return start, stop


# ---------------------------------------------------------------------------
# classification + interval arithmetic (stdlib only)


def classify_hlo_op(name: str) -> Tuple[str, Optional[str]]:
    """Bucket one device HLO op-name: ``("collective", kind)``,
    ``("host", None)``, or ``("compute", None)``."""
    m = _KIND_RE.match(name)
    if m:
        return "collective", m.group(1)
    for p in HOST_OP_PREFIXES:
        if name.startswith(p):
            return "host", None
    return "compute", None


def merge_intervals(iv: List[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Union of half-open intervals, sorted and coalesced."""
    out: List[Tuple[float, float]] = []
    for s, e in sorted(iv):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def interval_total(merged: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in merged)


def intersect_total(a: List[Tuple[float, float]],
                    b: List[Tuple[float, float]]) -> float:
    """Total overlap between two MERGED interval lists (two-pointer)."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


# ---------------------------------------------------------------------------
# Chrome-trace parsing


def load_chrome_trace(path: str) -> Dict[str, Any]:
    """Load a Chrome-trace JSON, gzipped (``*.trace.json.gz``, what
    ``jax.profiler`` emits) or plain."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def locate_profile_traces(profile_dir: str) -> List[str]:
    """The Chrome-trace files a ``jax.profiler`` session left under its
    log dir (``plugins/profile/<session>/<host>.trace.json.gz``). When
    repeated sessions share the dir, only the NEWEST session's files are
    returned."""
    sessions = sorted(glob.glob(os.path.join(profile_dir, "plugins",
                                             "profile", "*")))
    if not sessions:
        return []
    return sorted(glob.glob(os.path.join(sessions[-1], "*.trace.json*")))


def extract_device_events(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Device op spans from a profiler Chrome trace.

    An event is a device op when its args carry ``hlo_op``/``hlo_module``
    (the CPU thunk executor stamps these) or when it sits under a
    ``/device:`` process (real TPU lanes). Python-tracer frames
    (``$``-prefixed) and runtime bookkeeping spans carry neither and are
    dropped. Returns rows ``{name, ts, dur, bucket, kind}`` (µs)."""
    device_pids = set()
    for e in trace.get("traceEvents", []):
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and str((e.get("args") or {}).get("name", ""))
                .startswith("/device:")):
            device_pids.add(e.get("pid"))
    out: List[Dict[str, Any]] = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        name = e.get("name") or ""
        args = e.get("args") or {}
        if not (args.get("hlo_op") or args.get("hlo_module")
                or e.get("pid") in device_pids):
            continue
        if name.startswith("$"):
            continue
        bucket, kind = classify_hlo_op(name)
        out.append(dict(name=name, ts=float(e.get("ts", 0.0)),
                        dur=float(e.get("dur", 0.0)),
                        bucket=bucket, kind=kind))
    return out


def extract_step_windows(trace: Dict[str, Any],
                         annotation: str = STEP_ANNOTATION
                         ) -> Dict[int, Tuple[float, float]]:
    """``{step_index: (ts, end)}`` (µs, profiler timebase) from the
    StepTraceAnnotation markers the capture wrapped around each step."""
    out: Dict[int, Tuple[float, float]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("name") != annotation:
            continue
        args = e.get("args") or {}
        try:
            step = int(args.get("step_num"))
        except (TypeError, ValueError):
            continue
        t0 = float(e.get("ts", 0.0))
        t1 = t0 + float(e.get("dur", 0.0))
        if step in out:  # same step re-entered: span the union
            t0 = min(t0, out[step][0])
            t1 = max(t1, out[step][1])
        out[step] = (t0, t1)
    return out


def attribute_steps(device_events: List[Dict[str, Any]],
                    step_windows: Dict[int, Tuple[float, float]]
                    ) -> List[Dict[str, Any]]:
    """Per-step interval accounting over the device spans.

    All local devices share one timeline per bucket (union semantics):
    ``compute_s`` is wall time during which ANY device computes,
    ``overlapped_comms_s`` is collective time hidden under that compute,
    and ``exposed_comms_s = comms_s - overlapped_comms_s`` is what the
    step waits on. Times in seconds."""
    rows: List[Dict[str, Any]] = []
    for step in sorted(step_windows):
        t0, t1 = step_windows[step]
        compute_iv: List[Tuple[float, float]] = []
        comms_iv: List[Tuple[float, float]] = []
        host_iv: List[Tuple[float, float]] = []
        kind_iv: Dict[str, List[Tuple[float, float]]] = {}
        kind_count: Dict[str, int] = {}
        for ev in device_events:
            s = max(ev["ts"], t0)
            e = min(ev["ts"] + ev["dur"], t1)
            if e <= s:
                continue
            if ev["bucket"] == "collective":
                comms_iv.append((s, e))
                kind_iv.setdefault(ev["kind"], []).append((s, e))
                kind_count[ev["kind"]] = kind_count.get(ev["kind"], 0) + 1
            elif ev["bucket"] == "host":
                host_iv.append((s, e))
            else:
                compute_iv.append((s, e))
        compute_u = merge_intervals(compute_iv)
        comms_u = merge_intervals(comms_iv)
        compute_s = interval_total(compute_u) / 1e6
        comms_s = interval_total(comms_u) / 1e6
        overlapped_s = intersect_total(comms_u, compute_u) / 1e6
        host_s = interval_total(merge_intervals(host_iv)) / 1e6
        busy_s = interval_total(
            merge_intervals(compute_iv + comms_iv + host_iv)) / 1e6
        wall_s = (t1 - t0) / 1e6
        rows.append(dict(
            step=step,
            wall_s=wall_s,
            compute_s=compute_s,
            comms_s=comms_s,
            overlapped_comms_s=overlapped_s,
            exposed_comms_s=comms_s - overlapped_s,
            host_s=host_s,
            idle_s=max(wall_s - busy_s, 0.0),
            # per-kind hidden/exposed split (ISSUE 9): a kind's hidden
            # seconds are its intervals under the compute union — the
            # measured counterpart of the simulator's per-choice hidden
            # term, so the merged report can show WHERE overlap lands
            per_kind={k: _kind_entry(v, kind_count[k], compute_u)
                      for k, v in kind_iv.items()},
        ))
    return rows


def _kind_entry(iv, count, compute_u):
    u = merge_intervals(iv)
    t = interval_total(u) / 1e6
    hidden = intersect_total(u, compute_u) / 1e6
    return dict(time_s=t, count=count, overlapped_s=hidden,
                exposed_s=t - hidden)


def aggregate_attribution(per_step: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll per-step attribution rows up into run totals plus a
    per-collective-kind summary (``{kind: {time_s, count, per_step_s}}``
    — the measured half of the measured-vs-priced drift join)."""
    n = len(per_step)
    totals = dict(compute_s=0.0, comms_s=0.0, overlapped_comms_s=0.0,
                  exposed_comms_s=0.0, host_s=0.0, idle_s=0.0, wall_s=0.0)
    coll: Dict[str, Dict[str, float]] = {}
    for row in per_step:
        for k in totals:
            totals[k] += row[k]
        for kind, e in row["per_kind"].items():
            c = coll.setdefault(kind, dict(time_s=0.0, count=0,
                                           overlapped_s=0.0, exposed_s=0.0))
            c["time_s"] += e["time_s"]
            c["count"] += e["count"]
            c["overlapped_s"] += e.get("overlapped_s", 0.0)
            c["exposed_s"] += e.get("exposed_s", e["time_s"])
    for c in coll.values():
        c["per_step_s"] = c["time_s"] / n if n else 0.0
        c["exposed_per_step_s"] = c["exposed_s"] / n if n else 0.0
        c["overlapped_per_step_s"] = c["overlapped_s"] / n if n else 0.0
    return dict(steps=n, totals=totals, collectives=coll)


def _parse_traces(trace_paths: List[str],
                  annotation: str = STEP_ANNOTATION):
    """(device_events, step_windows) pooled over a capture's
    Chrome-trace files (unreadable files are skipped — a half-written
    profile must not kill the report)."""
    events: List[Dict[str, Any]] = []
    windows: Dict[int, Tuple[float, float]] = {}
    for p in trace_paths:
        try:
            trace = load_chrome_trace(p)
        except (OSError, ValueError):
            continue
        events += extract_device_events(trace)
        windows.update(extract_step_windows(trace, annotation))
    return events, windows


def attribution_report(trace_paths: List[str],
                       annotation: str = STEP_ANNOTATION) -> Dict[str, Any]:
    """Parse + attribute one capture's Chrome-trace files.

    Returns ``{per_step, steps, totals, collectives, device_events}``."""
    events, windows = _parse_traces(trace_paths, annotation)
    per_step = attribute_steps(events, windows)
    return dict(per_step=per_step, device_events=len(events),
                **aggregate_attribution(per_step))


# ---------------------------------------------------------------------------
# capture


class NullCapture:
    """Inert capture: the no-profile-window fast path."""

    active = False
    captured = False
    _NULL = contextlib.nullcontext()

    def step(self, step_index: int):
        return self._NULL

    def finalize(self, ff, tracer):
        return None


NULL_CAPTURE = NullCapture()


class _CaptureStep:
    """Per-step context: starts the profiler session when the window
    opens, wraps the step in a StepTraceAnnotation while capturing, and
    stops the session when the window closes — recording the host
    perf_counter bracket of every annotated step for the clock
    correlation the Perfetto lane merge needs."""

    __slots__ = ("cap", "idx", "_ann", "_t0")

    def __init__(self, cap, idx):
        self.cap = cap
        self.idx = idx
        self._ann = None

    def __enter__(self):
        cap = self.cap
        if cap.state == "idle" and self.idx >= cap.window[0]:
            cap._start()
        if cap.state == "capturing":
            try:
                import jax
                self._ann = jax.profiler.StepTraceAnnotation(
                    STEP_ANNOTATION, step_num=self.idx)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        cap = self.cap
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
            cap.host_steps[self.idx] = (self._t0, t1)
        if cap.state == "capturing" and self.idx + 1 >= cap.window[1]:
            cap._stop()
        return False


class DeviceTraceCapture:
    """One windowed ``jax.profiler`` session around a step range.

    Wrap each training step in ``capture.step(i)``; the session starts
    when step ``window[0]`` begins and stops after step ``window[1]-1``
    completes. ``finalize`` parses the emitted trace, writes the
    ``.devtrace.json`` attribution artifact, feeds the counter registry,
    and injects rebased device lanes + per-step attribution counter
    tracks into the StepTracer's Perfetto output. Every profiler
    interaction degrades to a warning — observability must never kill
    the run it watches."""

    active = True

    def __init__(self, tracer, window: Tuple[int, int]):
        self.tracer = tracer
        self.window = window
        self.profile_dir = os.path.join(tracer.trace_dir,
                                        tracer.file_stem + ".jaxprof")
        self.state = "idle"  # -> capturing -> done | failed
        self.host_steps: Dict[int, Tuple[float, float]] = {}
        self.trace_paths: List[str] = []

    @property
    def captured(self) -> bool:
        return self.state == "done" and bool(self.trace_paths)

    def step(self, step_index: int):
        if self.state in ("done", "failed"):
            return NullCapture._NULL
        return _CaptureStep(self, step_index)

    def _start(self) -> None:
        try:
            import jax
            jax.profiler.start_trace(self.profile_dir)
            self.state = "capturing"
        except Exception as e:
            import sys
            print(f"[obs] device-trace capture failed to start ({e!r}); "
                  "profiling disabled for this run", file=sys.stderr)
            self.state = "failed"

    def _stop(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
            self.state = "done"
            self.trace_paths = locate_profile_traces(self.profile_dir)
            if not self.trace_paths:
                import sys
                print(f"[obs] profiler session left no Chrome trace under "
                      f"{self.profile_dir}", file=sys.stderr)
        except Exception as e:
            import sys
            print(f"[obs] device-trace capture failed to stop ({e!r})",
                  file=sys.stderr)
            self.state = "failed"

    # ---- post-run ----------------------------------------------------------
    def _clock_shift_us(self, step_windows) -> float:
        """Profiler-timebase -> tracer-timeline shift, averaged over
        every step seen by both clocks (the host perf_counter bracket
        recorded around each annotation vs the annotation's own span in
        the profile)."""
        origin = getattr(self.tracer, "_origin", None)
        if origin is None:
            return 0.0
        shifts = [
            (t0 - origin) * 1e6 - step_windows[idx][0]
            for idx, (t0, _) in self.host_steps.items()
            if idx in step_windows]
        return sum(shifts) / len(shifts) if shifts else 0.0

    def finalize(self, ff, tracer) -> Optional[Dict[str, Any]]:
        """Parse + attribute, emit the artifact, merge Perfetto lanes.
        Returns the attribution report (None when nothing was captured).
        Must run BEFORE ``tracer.export()`` so the device lanes land in
        the exported trace."""
        if self.state == "capturing":  # run ended inside the window
            self._stop()
        if not self.captured:
            return None
        events, windows = _parse_traces(self.trace_paths)
        per_step = attribute_steps(events, windows)
        report = dict(
            window=list(self.window),
            profile_dir=self.profile_dir,
            trace_files=[os.path.relpath(p, tracer.trace_dir)
                         for p in self.trace_paths],
            per_step=per_step,
            device_events=len(events),
            **aggregate_attribution(per_step),
        )
        # registry: exposed-comms / compute distributions survive into
        # the counters snapshot (bounded reservoir, registry.observe)
        from flexflow_tpu.obs.registry import get_registry
        reg = get_registry()
        run = tracer.run_name
        for row in per_step:
            reg.observe(f"{run}/devtrace_compute_s", row["compute_s"])
            reg.observe(f"{run}/devtrace_exposed_comms_s",
                        row["exposed_comms_s"])
        tot = report["totals"]
        if tot["wall_s"] > 0:
            reg.gauge(f"{run}/devtrace_exposed_comms_frac",
                      tot["exposed_comms_s"] / tot["wall_s"])
            reg.gauge(f"{run}/devtrace_compute_frac",
                      tot["compute_s"] / tot["wall_s"])
        # Perfetto lanes: device spans + per-step attribution counters,
        # rebased from the profiler timebase onto the tracer timeline
        shift = self._clock_shift_us(windows)
        lane_events: List[Dict[str, Any]] = []
        tid_of = {"compute": TID_COMPUTE, "collective": TID_COMMS,
                  "host": TID_HOST}
        for ev in events:
            ce = dict(name=ev["name"], ph="X", tid=tid_of[ev["bucket"]],
                      ts=round(ev["ts"] + shift, 3),
                      dur=round(ev["dur"], 3), cat="devtrace")
            if ev["kind"]:
                ce["args"] = dict(kind=ev["kind"])
            lane_events.append(ce)
        for row in per_step:
            t0 = windows[row["step"]][0] + shift
            lane_events.append(dict(
                name="step_attribution", ph="C", tid=0,
                ts=round(t0, 3), cat="devtrace",
                args=dict(compute_ms=round(row["compute_s"] * 1e3, 4),
                          overlapped_comms_ms=round(
                              row["overlapped_comms_s"] * 1e3, 4),
                          exposed_comms_ms=round(
                              row["exposed_comms_s"] * 1e3, 4))))
        tracer.add_trace_events(lane_events, dict(LANE_THREADS))
        from flexflow_tpu.obs.artifacts import write_artifact
        stem = os.path.join(tracer.trace_dir, tracer.file_stem)
        write_artifact(stem + ".devtrace.json", report,
                       host_id=tracer.host_id, kind="devtrace",
                       header_extra=dict(run_name=tracer.run_name,
                                         run_seq=tracer.run_seq))
        return report


def make_capture(tracer, profile_steps: Optional[str]):
    """A DeviceTraceCapture over the parsed window, or the shared no-op.

    Needs an ACTIVE tracer (the artifacts land in its trace dir and the
    lanes merge into its Perfetto output): a profile window without a
    trace dir warns and degrades rather than raising mid-fit."""
    window = parse_profile_steps(profile_steps)
    if window is None:
        return NULL_CAPTURE
    if not getattr(tracer, "active", False):
        import sys
        print("[obs] --profile-steps needs --trace-dir (device-trace "
              "artifacts land in the trace dir); profiling skipped",
              file=sys.stderr)
        return NULL_CAPTURE
    return DeviceTraceCapture(tracer, window)


# ---------------------------------------------------------------------------
# goodput / MFU step metrics (registry + drift report surface)


def train_step_flops(ff) -> float:
    """Model FLOPs of one training step: analytic per-op forward FLOPs
    (the roofline machinery's ``op.flops()``) x3 for fwd+bwd — the same
    fwd:bwd convention the drift predictor uses. Global (whole-batch)
    FLOPs; divide by chip count for per-chip."""
    return 3.0 * sum(float(n.op.flops()) for n in ff.executor.nodes)


def record_step_metrics(ff, tracer, registry=None) -> Dict[str, Any]:
    """Step-time histogram + goodput + MFU into the counter registry.

    - ``<run>/step_time_s`` observations (p50/p99 survive into the
      counters snapshot via the registry's bounded reservoir)
    - ``<run>/goodput`` gauge: productive-step time / run wall time —
      what fraction of the traced run the device spent inside steps
    - ``<run>/mfu`` gauge: model FLOPs per step / chips / median step
      time / chip peak FLOPs (meaningful on TPU; on cpu-sim it is
      relative to the synthetic 1 TFLOP/s peak)
    Returns the same numbers as a dict for the drift report."""
    from flexflow_tpu.obs.registry import get_registry, percentile
    if registry is None:
        registry = get_registry()
    run = tracer.run_name
    ds = tracer.step_durations_s()
    # step 0 carries the jit compile: record it SEPARATELY and never let
    # it into the percentile reservoir — a single-step run used to
    # observe its compile step, which is how OBS_REPORT once showed a
    # 17 s p99 against an 18 ms p50 (ISSUE 8 satellite). With one step
    # there is no steady-state sample, so nothing is observed.
    steady = ds[1:]
    out: Dict[str, Any] = dict(steps=len(ds))
    if ds:
        out["compile_time_s"] = ds[0]
        registry.gauge(f"{run}/compile_time_s", ds[0])
    for d in steady:
        registry.observe(f"{run}/step_time_s", d)
    if steady:
        s = sorted(steady)
        out["step_time_p50"] = percentile(s, 0.50)
        out["step_time_p99"] = percentile(s, 0.99)
    wall = tracer.run_wall_s()
    if wall and ds:
        out["goodput"] = min(sum(ds) / wall, 1.0)
        registry.gauge(f"{run}/goodput", out["goodput"])
    spec = getattr(ff, "machine_spec", None)
    step_s = out.get("step_time_p50")
    if spec is not None and step_s:
        n_chips = int(ff.mesh.devices.size)
        flops = train_step_flops(ff)
        out["model_flops_per_step"] = flops
        out["mfu"] = flops / n_chips / step_s / float(spec.flops)
        registry.gauge(f"{run}/mfu", out["mfu"])
    return out

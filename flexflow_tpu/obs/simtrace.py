"""Simulated-schedule observability: the search's predicted timeline.

The native simulator already produces a full task schedule for the
strategy it ranked best — per-task ``start``/``finish`` seconds on the
{compute, ICI} streams (``ffs_sim.hpp`` list scheduler, returned by
``ffs_simulate``). Until now that schedule existed only inside the cost
model; this module renders it as Perfetto lanes (``sim:compute`` /
``sim:comms``) on the SAME lane layout as the measured device lanes the
devtrace capture injects (``device:compute`` / ``device:comms``,
obs/devtrace.py), so the predicted and the measured step sit side by
side in one merged timeline — the SCALE-Sim-style simulator validation
view (PAPERS.md): if the simulator believes the right schedule, the two
lane groups should look alike; where they diverge is exactly the
calibration signal.

Also emits the ``.simtrace.json`` artifact: the predicted step
breakdown plus per-op priced rows joined against measured per-op
seconds where a profile table exists — the (op class x shape x sharding
-> priced terms, measured seconds) corpus rows the learned-TPU-cost-
model direction trains on ("A Learned Performance Model for TPUs",
PAPERS.md 2008.01040).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# Perfetto lane tids for the predicted schedule, disjoint from the
# devtrace lanes (64-66) and below the merge tid-block size (256), so
# sim lanes keep their own rows in both per-host and merged traces.
SIM_TID_COMPUTE, SIM_TID_COMMS = 72, 73
SIM_LANE_THREADS = {SIM_TID_COMPUTE: "sim:compute",
                    SIM_TID_COMMS: "sim:comms"}

# SimTask kind -> lane (mirrors the simulator's two-stream scheduler:
# comm/gradsync ride the ICI stream, everything else the compute
# stream). Public: explain.py's timeline rendering uses the same map.
SIM_COMMS_KINDS = ("comm", "gradsync")


def sim_lane_events(tasks: List[Dict[str, Any]],
                    name_of: Dict[int, str],
                    t0_us: float = 0.0) -> List[Dict[str, Any]]:
    """Chrome-trace ``X`` events for a simulated task schedule.

    ``tasks``: ``ffs_simulate`` response rows ({kind, node, start,
    finish, collective?, bytes?}, seconds). Zero-duration rows (the
    census records pipe simulation emits) are skipped — they carry
    bytes, not time. ``name_of`` maps node INDEX -> op name. ``t0_us``
    places the schedule on the host timeline (e.g. at a measured step's
    start) so predicted and measured lanes share a clock base."""
    events: List[Dict[str, Any]] = []
    for t in tasks:
        start = float(t.get("start", 0.0))
        finish = float(t.get("finish", 0.0))
        if finish <= start:
            continue
        kind = str(t.get("kind", ""))
        tid = SIM_TID_COMMS if kind in SIM_COMMS_KINDS else SIM_TID_COMPUTE
        node = t.get("node", -1)
        label = name_of.get(node, "step")
        args: Dict[str, Any] = dict(kind=kind)
        if t.get("collective"):
            args["collective"] = t["collective"]
            args["bytes"] = t.get("bytes", 0)
        if t.get("hidden_s"):
            # predicted-hidden interval (ISSUE 9): seconds of this comm
            # task the simulator scheduled under busy compute — in the
            # merged view, compare against the devtrace lanes' measured
            # overlapped_comms_s to check the hiding actually landed
            args["hidden_s"] = round(float(t["hidden_s"]), 9)
        events.append(dict(
            name=f"{label}:{kind}", ph="X", tid=tid,
            ts=round(t0_us + start * 1e6, 3),
            dur=round((finish - start) * 1e6, 3),
            cat="simtrace", args=args))
    return events


def per_op_predicted(tasks: List[Dict[str, Any]]
                     ) -> Dict[int, Dict[str, float]]:
    """Node index -> priced seconds per term, aggregated from the
    simulated schedule (fwd_s / bwd_s / comm_s / gradsync_s). Collective
    census bytes accumulate under ``collective_bytes``."""
    out: Dict[int, Dict[str, float]] = {}
    for t in tasks:
        node = t.get("node", -1)
        if node is None or node < 0:
            continue
        row = out.setdefault(int(node), dict(
            fwd_s=0.0, bwd_s=0.0, comm_s=0.0, gradsync_s=0.0,
            hidden_s=0.0, collective_bytes=0.0))
        dur = max(0.0, float(t.get("finish", 0.0))
                  - float(t.get("start", 0.0)))
        kind = str(t.get("kind", ""))
        if kind in ("fwd", "bwd"):
            row[f"{kind}_s"] += dur
        elif kind == "comm":
            row["comm_s"] += dur
        elif kind == "gradsync":
            row["gradsync_s"] += dur
        row["hidden_s"] += float(t.get("hidden_s", 0.0))
        if t.get("collective"):
            row["collective_bytes"] += float(t.get("bytes", 0.0))
    return out


def corpus_rows(ff, resp: Dict[str, Any],
                measured: Optional[Dict[str, float]] = None
                ) -> List[Dict[str, Any]]:
    """Learned-cost-model corpus rows: one per op, joining the op's
    identity (class, shape, sharding choice) -> the simulator's priced
    terms -> measured per-op seconds where a profile table has them
    (``ff.op_profile`` from ``--profiling`` / ``--search-measure-ops``,
    or an explicit ``measured`` table). ``measured.source`` records
    whether the measured half is real ("measured") or absent (None) so
    a training-set builder can filter."""
    from flexflow_tpu.obs.drift import work_division

    measured = measured if measured is not None else (ff.op_profile or {})
    priced = per_op_predicted(resp.get("tasks") or [])
    rows: List[Dict[str, Any]] = []
    for idx, node in enumerate(ff.executor.nodes):
        op = node.op
        st = (ff.strategy or {}).get(op.guid)
        p = priced.get(idx, dict(fwd_s=0.0, bwd_s=0.0, comm_s=0.0,
                                 gradsync_s=0.0, collective_bytes=0.0))
        mf = measured.get(f"{op.guid}:fwd")
        mb = measured.get(f"{op.guid}:bwd")
        rows.append(dict(
            guid=op.guid,
            name=op.name,
            type=op.op_type.name,
            out_shape=list(op.output_shapes[0]) if op.output_shapes else [],
            choice=getattr(st, "choice", None),
            # priced terms are PER-CHIP SHARDED schedule durations;
            # measured fwd/bwd are WHOLE-OP unsharded profile seconds —
            # work_div is the strategy's split so consumers can compare
            # measured/work_div against priced fwd+bwd (compute only)
            work_div=work_division(node, ff.mesh),
            priced=dict(p),
            measured=dict(
                fwd_s=mf, bwd_s=mb,
                source="measured" if mf is not None else None),
        ))
    return rows


def simtrace_report(ff, resp: Dict[str, Any],
                    measured: Optional[Dict[str, float]] = None
                    ) -> Dict[str, Any]:
    """The ``.simtrace.json`` payload: predicted step breakdown + the
    per-op corpus rows + the mesh the prediction assumed."""
    return dict(
        predicted=dict(
            step_s=resp.get("iteration_time"),
            fwd_s=resp.get("fwd_time"),
            bwd_s=resp.get("bwd_time"),
            comm_s=resp.get("comm_time"),
            gradsync_s=resp.get("gradsync_time"),
            # predicted comm seconds hidden under compute (the schedule's
            # overlapped intervals + the '_ovl'/pipeline analytic hidden
            # terms) — the predicted twin of the devtrace's measured
            # overlapped_comms_s
            hidden_comm_s=resp.get("hidden_comm_time"),
            memory_bytes=resp.get("memory"),
        ),
        search_predicted_s=(ff.search_info or {}).get("predicted_time")
        if isinstance(ff.search_info, dict) else None,
        mesh_axes=dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape)),
        tasks=sum(1 for t in (resp.get("tasks") or [])
                  if float(t.get("finish", 0.0))
                  > float(t.get("start", 0.0))),
        per_op=corpus_rows(ff, resp, measured=measured),
    )


def write_simtrace(ff, tracer, align_ts_us: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
    """Replay the compiled strategy through the native simulator, write
    the ``.simtrace.json`` artifact, and inject the predicted schedule
    as ``sim:`` Perfetto lanes into the tracer's export (must run BEFORE
    ``tracer.export()``).

    ``align_ts_us``: where on the tracer timeline the simulated step
    begins. Defaults to the start of the LAST traced step (steady state
    — never the compile-carrying first step) so the predicted lanes
    overlay a measured step in the merged view. Returns the simtrace
    report, or None when the tracer is inactive."""
    if not getattr(tracer, "active", False):
        return None
    from flexflow_tpu.obs.artifacts import write_artifact
    from flexflow_tpu.search.validate import simulate_strategy
    import os

    resp = simulate_strategy(ff)
    report = simtrace_report(ff, resp)
    if align_ts_us is None:
        align_ts_us = tracer.last_step_start_us() or 0.0
    name_of = {i: n.op.name for i, n in enumerate(ff.executor.nodes)}
    events = sim_lane_events(resp.get("tasks") or [], name_of,
                             t0_us=align_ts_us)
    if events:
        tracer.add_trace_events(events, dict(SIM_LANE_THREADS))
    stem = os.path.join(tracer.trace_dir, tracer.file_stem)
    write_artifact(stem + ".simtrace.json", report,
                   host_id=tracer.host_id, kind="simtrace",
                   header_extra=dict(run_name=tracer.run_name,
                                     run_seq=tracer.run_seq))
    return report

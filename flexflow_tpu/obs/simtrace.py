"""Simulated-schedule observability: the search's predicted timeline.

The native simulator already produces a full task schedule for the
strategy it ranked best — per-task ``start``/``finish`` seconds on the
{compute, ICI} streams (``ffs_sim.hpp`` list scheduler, returned by
``ffs_simulate``). Until now that schedule existed only inside the cost
model; this module renders it as Perfetto lanes (``sim:compute`` /
``sim:comms``) on the SAME lane layout as the measured device lanes the
devtrace capture injects (``device:compute`` / ``device:comms``,
obs/devtrace.py), so the predicted and the measured step sit side by
side in one merged timeline — the SCALE-Sim-style simulator validation
view (PAPERS.md): if the simulator believes the right schedule, the two
lane groups should look alike; where they diverge is exactly the
calibration signal.

Also emits the ``.simtrace.json`` artifact: the predicted step
breakdown plus per-op priced rows joined against measured per-op
seconds where a profile table exists — the (op class x shape x sharding
-> priced terms, measured seconds) corpus rows the learned-TPU-cost-
model direction trains on ("A Learned Performance Model for TPUs",
PAPERS.md 2008.01040).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

# Perfetto lane tids for the predicted schedule, disjoint from the
# devtrace lanes (64-66) and below the merge tid-block size (256), so
# sim lanes keep their own rows in both per-host and merged traces.
SIM_TID_COMPUTE, SIM_TID_COMMS = 72, 73
SIM_LANE_THREADS = {SIM_TID_COMPUTE: "sim:compute",
                    SIM_TID_COMMS: "sim:comms"}

# SimTask kind -> lane (mirrors the simulator's two-stream scheduler:
# comm/gradsync ride the ICI stream, everything else the compute
# stream). Public: explain.py's timeline rendering uses the same map.
SIM_COMMS_KINDS = ("comm", "gradsync")

# Corpus-row schema version of the ``per_op`` rows below. v2 added the
# featurization fields the learned cost model trains on (flops,
# io_bytes, param_bytes, dtype_size, mesh degrees, ring sizes); v3 adds
# the ``impl`` column — WHICH KERNEL ran the op (einsum/flash/ring/
# conv/conv_bn_fused/triad/fused, the searched ``_k:`` dimension) — so
# ``scripts/costmodel.py train`` learns per-impl coefficients
# ("TYPE:impl" classes) instead of blending two lowerings into one
# regression. The costmodel corpus loader
# (flexflow_tpu/costmodel/corpus.py) refuses rows NEWER than what it
# understands, so a schema drift here fails the CI costmodel stage
# loudly instead of silently training on garbage; v2 rows stay
# trainable (impl derived from the choice suffix).
CORPUS_SCHEMA_VERSION = 3


def sim_lane_events(tasks: List[Dict[str, Any]],
                    name_of: Dict[int, str],
                    t0_us: float = 0.0) -> List[Dict[str, Any]]:
    """Chrome-trace ``X`` events for a simulated task schedule.

    ``tasks``: ``ffs_simulate`` response rows ({kind, node, start,
    finish, collective?, bytes?}, seconds). Zero-duration rows (the
    census records pipe simulation emits) are skipped — they carry
    bytes, not time. ``name_of`` maps node INDEX -> op name. ``t0_us``
    places the schedule on the host timeline (e.g. at a measured step's
    start) so predicted and measured lanes share a clock base."""
    events: List[Dict[str, Any]] = []
    for t in tasks:
        start = float(t.get("start", 0.0))
        finish = float(t.get("finish", 0.0))
        if finish <= start:
            continue
        kind = str(t.get("kind", ""))
        tid = SIM_TID_COMMS if kind in SIM_COMMS_KINDS else SIM_TID_COMPUTE
        node = t.get("node", -1)
        label = name_of.get(node, "step")
        args: Dict[str, Any] = dict(kind=kind)
        if t.get("collective"):
            args["collective"] = t["collective"]
            args["bytes"] = t.get("bytes", 0)
        if t.get("hidden_s"):
            # predicted-hidden interval (ISSUE 9): seconds of this comm
            # task the simulator scheduled under busy compute — in the
            # merged view, compare against the devtrace lanes' measured
            # overlapped_comms_s to check the hiding actually landed
            args["hidden_s"] = round(float(t["hidden_s"]), 9)
        events.append(dict(
            name=f"{label}:{kind}", ph="X", tid=tid,
            ts=round(t0_us + start * 1e6, 3),
            dur=round((finish - start) * 1e6, 3),
            cat="simtrace", args=args))
    return events


def per_op_predicted(tasks: List[Dict[str, Any]]
                     ) -> Dict[int, Dict[str, float]]:
    """Node index -> priced seconds per term, aggregated from the
    simulated schedule (fwd_s / bwd_s / comm_s / gradsync_s). Collective
    census bytes accumulate under ``collective_bytes``."""
    out: Dict[int, Dict[str, float]] = {}
    for t in tasks:
        node = t.get("node", -1)
        if node is None or node < 0:
            continue
        row = out.setdefault(int(node), dict(
            fwd_s=0.0, bwd_s=0.0, comm_s=0.0, gradsync_s=0.0,
            hidden_s=0.0, collective_bytes=0.0))
        dur = max(0.0, float(t.get("finish", 0.0))
                  - float(t.get("start", 0.0)))
        kind = str(t.get("kind", ""))
        if kind in ("fwd", "bwd"):
            row[f"{kind}_s"] += dur
        elif kind == "comm":
            row["comm_s"] += dur
        elif kind == "gradsync":
            row["gradsync_s"] += dur
        row["hidden_s"] += float(t.get("hidden_s", 0.0))
        if t.get("collective"):
            row["collective_bytes"] += float(t.get("bytes", 0.0))
    return out


def _row_impl(ff, op, choice: Optional[str]) -> Optional[str]:
    """Kernel impl of one corpus row: the ``_k:`` choice suffix when the
    search picked one, else the executor's recorded kernel choice, else
    (attention only) the impl ``forward`` dispatches on this platform.
    None for ops with no registered kernel alternatives."""
    from flexflow_tpu.search.unity import kernel_choice_of
    k = kernel_choice_of(choice)
    if k is not None:
        return k
    kc = getattr(ff.executor, "kernel_choices", None) or {}
    if op.name in kc:
        return kc[op.name]
    if hasattr(op, "selected_impl"):
        try:
            mesh_axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
            return op.selected_impl(mesh_axes, training=True)
        except Exception:
            return None
    return None


def corpus_rows(ff, resp: Dict[str, Any],
                measured: Optional[Dict[str, float]] = None
                ) -> List[Dict[str, Any]]:
    """Learned-cost-model corpus rows: one per op, joining the op's
    identity (class, shape, sharding choice) -> the simulator's priced
    terms -> measured per-op seconds where a profile table has them
    (``ff.op_profile`` from ``--profiling`` / ``--search-measure-ops``,
    or an explicit ``measured`` table). ``measured.source`` records
    whether the measured half is real ("measured") or absent (None) so
    a training-set builder can filter."""
    from flexflow_tpu.obs.drift import work_division

    measured = measured if measured is not None else (ff.op_profile or {})
    priced = per_op_predicted(resp.get("tasks") or [])
    # which model priced each node's compute (analytic roofline vs
    # learned regression vs measured profile) — ffs_simulate reports it
    # per guid when the machine carried a learned table
    sources = resp.get("cost_sources") or {}
    mesh_axes = dict(zip(ff.mesh.axis_names,
                         (int(d) for d in ff.mesh.devices.shape)))
    rows: List[Dict[str, Any]] = []
    for idx, node in enumerate(ff.executor.nodes):
        op = node.op
        st = (ff.strategy or {}).get(op.guid)
        p = priced.get(idx, dict(fwd_s=0.0, bwd_s=0.0, comm_s=0.0,
                                 gradsync_s=0.0, collective_bytes=0.0))
        mf = measured.get(f"{op.guid}:fwd")
        mb = measured.get(f"{op.guid}:bwd")
        dts = op.dtype.size
        # native total_io_bytes convention (ffs_graph.hpp): params +
        # every input + every output at the op's dtype width — the
        # byte half of the learned model's featurization
        io_bytes = float(op.params_elems()) * dts
        for s in op.input_shapes:
            io_bytes += float(math.prod(s)) * dts
        for s in op.output_shapes:
            io_bytes += float(math.prod(s)) * dts
        choice = getattr(st, "choice", None)
        rows.append(dict(
            schema=CORPUS_SCHEMA_VERSION,
            guid=op.guid,
            name=op.name,
            type=op.op_type.name,
            out_shape=list(op.output_shapes[0]) if op.output_shapes else [],
            choice=choice,
            # which kernel implementation executed the op (the searched
            # "_k:" dimension, ISSUE 15): the executor's recorded choice
            # wins; attention ops without one report the impl forward
            # actually dispatches (ring/flash/einsum)
            impl=_row_impl(ff, op, choice),
            # priced terms are PER-CHIP SHARDED schedule durations;
            # measured fwd/bwd are WHOLE-OP unsharded profile seconds —
            # work_div is the strategy's split so consumers can compare
            # measured/work_div against priced fwd+bwd (compute only)
            work_div=work_division(node, ff.mesh),
            # featurization fields (op class x shape x choice x mesh):
            # whole-op analytic FLOPs/bytes; the trainer shards them by
            # work_div to match the per-chip pricing the DP queries
            flops=float(op.flops()),
            io_bytes=io_bytes,
            param_bytes=float(op.params_elems()) * dts,
            dtype_size=dts,
            mesh_axes=mesh_axes,
            priced=dict(p, source=sources.get(str(op.guid), "analytic")),
            measured=dict(
                fwd_s=mf, bwd_s=mb,
                source="measured" if mf is not None else None),
        ))
    return rows


def simtrace_report(ff, resp: Dict[str, Any],
                    measured: Optional[Dict[str, float]] = None,
                    resp_analytic: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """The ``.simtrace.json`` payload: predicted step breakdown + the
    per-op corpus rows + the mesh the prediction assumed.

    ``resp_analytic``: a second simulation of the same strategy with the
    learned cost table disabled — when the active prediction used
    learned per-op costs, the analytic twin rides along so the obs
    report can show simulator accuracy analytic-vs-learned side by side
    (the SCALE-Sim-style tracked metric)."""
    rows = corpus_rows(ff, resp, measured=measured)
    src_census: Dict[str, int] = {}
    for r in rows:
        s = (r.get("priced") or {}).get("source") or "analytic"
        src_census[s] = src_census.get(s, 0) + 1
    report = dict(
        corpus_schema=CORPUS_SCHEMA_VERSION,
        predicted=dict(
            step_s=resp.get("iteration_time"),
            fwd_s=resp.get("fwd_time"),
            bwd_s=resp.get("bwd_time"),
            comm_s=resp.get("comm_time"),
            gradsync_s=resp.get("gradsync_time"),
            # predicted comm seconds hidden under compute (the schedule's
            # overlapped intervals + the '_ovl'/pipeline analytic hidden
            # terms) — the predicted twin of the devtrace's measured
            # overlapped_comms_s
            hidden_comm_s=resp.get("hidden_comm_time"),
            memory_bytes=resp.get("memory"),
        ),
        search_predicted_s=(ff.search_info or {}).get("predicted_time")
        if isinstance(ff.search_info, dict) else None,
        mesh_axes=dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape)),
        tasks=sum(1 for t in (resp.get("tasks") or [])
                  if float(t.get("finish", 0.0))
                  > float(t.get("start", 0.0))),
        # which model priced the compute terms, per op (the learned
        # cost model's engagement census: all-analytic when no trained
        # table is loaded / FFS_NO_LEARNED_COSTS is set)
        cost_sources=src_census,
        per_op=rows,
    )
    if resp_analytic is not None:
        report["predicted_analytic"] = dict(
            step_s=resp_analytic.get("iteration_time"),
            fwd_s=resp_analytic.get("fwd_time"),
            bwd_s=resp_analytic.get("bwd_time"),
            comm_s=resp_analytic.get("comm_time"),
            gradsync_s=resp_analytic.get("gradsync_time"),
        )
    return report


def write_simtrace(ff, tracer, align_ts_us: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
    """Replay the compiled strategy through the native simulator, write
    the ``.simtrace.json`` artifact, and inject the predicted schedule
    as ``sim:`` Perfetto lanes into the tracer's export (must run BEFORE
    ``tracer.export()``).

    ``align_ts_us``: where on the tracer timeline the simulated step
    begins. Defaults to the start of the LAST traced step (steady state
    — never the compile-carrying first step) so the predicted lanes
    overlay a measured step in the merged view. Returns the simtrace
    report, or None when the tracer is inactive."""
    if not getattr(tracer, "active", False):
        return None
    from flexflow_tpu.obs.artifacts import write_artifact
    from flexflow_tpu.search.validate import simulate_strategy
    import os

    resp = simulate_strategy(ff)
    resp_analytic = None
    if any(v == "learned" for v in (resp.get("cost_sources") or {}).values()):
        # the prediction used learned per-op costs: simulate the same
        # strategy once more with the table disabled so the artifact
        # carries analytic-vs-learned accuracy side by side
        try:
            resp_analytic = simulate_strategy(ff, learned=False)
        except Exception:
            resp_analytic = None
    report = simtrace_report(ff, resp, resp_analytic=resp_analytic)
    if align_ts_us is None:
        align_ts_us = tracer.last_step_start_us() or 0.0
    name_of = {i: n.op.name for i, n in enumerate(ff.executor.nodes)}
    events = sim_lane_events(resp.get("tasks") or [], name_of,
                             t0_us=align_ts_us)
    if events:
        tracer.add_trace_events(events, dict(SIM_LANE_THREADS))
    stem = os.path.join(tracer.trace_dir, tracer.file_stem)
    write_artifact(stem + ".simtrace.json", report,
                   host_id=tracer.host_id, kind="simtrace",
                   header_extra=dict(run_name=tracer.run_name,
                                     run_seq=tracer.run_seq))
    return report

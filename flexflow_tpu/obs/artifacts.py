"""Artifact conventions shared by every obs writer.

Each JSON artifact carries a ``header`` stamped with the framework
version (ISSUE satellite: traces must be attributable to the build that
produced them), the JAX platform, host identity, and a wall-clock
timestamp. Writes are atomic (write-temp-then-rename) so a crashed run
never leaves a half-written trace for the next tool to choke on.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, Optional


def artifact_header(host_id: Optional[int] = None,
                    kind: Optional[str] = None) -> Dict[str, Any]:
    """Provenance header every trace/census/drift artifact embeds."""
    from flexflow_tpu.version import __version__

    try:
        import jax
        platform = jax.devices()[0].platform
        device = getattr(jax.devices()[0], "device_kind", platform)
        if host_id is None:
            host_id = jax.process_index()
    except Exception:  # pre-backend-init callers (pure unit tests)
        platform, device = "unknown", "unknown"
        host_id = host_id or 0
    header = dict(
        flexflow_tpu_version=__version__,
        created_unix=time.time(),
        platform=platform,
        device=device,
        host_id=int(host_id),
    )
    if kind:
        header["kind"] = kind
    return header


def atomic_write_text(path: str, text: str) -> None:
    """Write-temp-then-rename in the destination directory (same fs).

    The temp name is dot-prefixed AND ``.tmp``-suffixed so a temp left
    behind by a killed process can never match a consumer's artifact
    pattern (``*.trace.json`` etc.), glob dotfile semantics or not."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_",
                               suffix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_artifact(path: str, payload: Dict[str, Any],
                   host_id: Optional[int] = None,
                   kind: Optional[str] = None,
                   header_extra: Optional[Dict[str, Any]] = None) -> str:
    """Stamp ``payload`` with the provenance header (plus any
    ``header_extra`` fields, e.g. the tracer's run_name) and write it
    atomically. Returns ``path``."""
    body = dict(payload)
    if "header" not in body:
        header = artifact_header(host_id=host_id, kind=kind)
        header.update(header_extra or {})
        body["header"] = header
    atomic_write_text(path, json.dumps(body, indent=1, default=_json_safe))
    return path


def _json_safe(o):
    """Best-effort JSON coercion for numpy scalars and odd leaves."""
    try:
        import numpy as np
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
    except Exception:
        pass
    return str(o)

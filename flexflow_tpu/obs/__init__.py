"""Runtime observability: step tracing, compiled-step inspection, drift.

The feedback channel the search stack lacked: costs flow INTO the native
search (flexflow_tpu/search/profile.py measured tables, machine.py
analytic comms), and this package makes what the jitted step actually
does flow back OUT — per-step phase spans (Chrome-trace/Perfetto JSON +
a JSONL event stream), XLA cost/memory analysis and a collective census
of the optimized HLO, and a drift report comparing the search's
predicted step time against the measured one (consumable by
scripts/calibrate.py). The devtrace layer (``--profile-steps``) adds a
windowed ``jax.profiler`` capture attributing each step's DEVICE time
into compute / collective / exposed-comms buckets, merged into the same
Perfetto timeline and joined against the census-priced collectives for
per-kind calibration. Cf. "A Learned Performance Model for TPUs" /
SCALE-Sim (PAPERS.md): a calibrated performance model is only as good
as its feedback loop.

Everything is inert unless a trace dir is set: ``make_tracer(None)``
returns the shared ``NULL_TRACER`` whose methods are no-ops, so the
training hot path pays nothing when observability is off.
"""

from flexflow_tpu.obs.artifacts import artifact_header, write_artifact
from flexflow_tpu.obs.devtrace import (
    NULL_CAPTURE,
    DeviceTraceCapture,
    attribution_report,
    make_capture,
    parse_profile_steps,
    record_step_metrics,
)
from flexflow_tpu.obs.drift import collective_drift, drift_report
from flexflow_tpu.obs.inspect import (
    collective_census,
    export_step_summary,
    inspect_compiled,
    inspect_model_step,
    model_context,
)
from flexflow_tpu.obs.registry import CounterRegistry, get_registry
from flexflow_tpu.obs.simtrace import (
    corpus_rows,
    sim_lane_events,
    simtrace_report,
    write_simtrace,
)
from flexflow_tpu.obs.roofline import (
    class_aggregates,
    finish_aggregates,
    format_markdown,
    roofline_report,
)
from flexflow_tpu.obs.tracer import (
    NULL_TRACER,
    NullTracer,
    StepTracer,
    make_tracer,
    merge_host_traces,
)

__all__ = [
    "artifact_header",
    "write_artifact",
    "NULL_CAPTURE",
    "DeviceTraceCapture",
    "attribution_report",
    "make_capture",
    "parse_profile_steps",
    "record_step_metrics",
    "collective_drift",
    "drift_report",
    "collective_census",
    "export_step_summary",
    "inspect_compiled",
    "inspect_model_step",
    "model_context",
    "CounterRegistry",
    "get_registry",
    "corpus_rows",
    "sim_lane_events",
    "simtrace_report",
    "write_simtrace",
    "class_aggregates",
    "finish_aggregates",
    "format_markdown",
    "roofline_report",
    "NULL_TRACER",
    "NullTracer",
    "StepTracer",
    "make_tracer",
    "merge_host_traces",
]

"""Search-drift calibration: predicted vs measured step time.

Closes the loop the native search never had: its cost model predicts an
iteration time from per-op costs (measured microbenchmarks when
``--search-measure-ops`` ran, analytic FLOP/byte roofline otherwise)
divided by each op's sharding work division, plus machine-model
collective costs — and nothing ever checked that prediction against
the step the chip actually ran. ``drift_report`` rebuilds the same
prediction in Python (profile.py measured table scaled by the
strategy's work division + machine.py analytic comms priced from the
REAL collective census) and compares it with the tracer's measured
step time. The report is consumable by ``scripts/calibrate.py
--ingest-drift``, which folds the ratios into CALIBRATION.json — the
same file the memory-aware search already reads its correction factor
from.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def work_division(node, mesh) -> int:
    """How many ways the strategy splits this op's work: the product of
    the mesh-axis extents its primary output is sharded over (the analog
    of the reference scaling measured op cost by the MachineView degree)."""
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    spec = node.output_specs[0] if node.output_specs else None
    if spec is None:
        return 1
    div = 1
    for entry in spec:
        if entry is None:
            continue
        for axis in (entry if isinstance(entry, tuple) else (entry,)):
            div *= axis_sizes.get(axis, 1)
    return max(div, 1)


def _analytic_op_cost(op, machine_spec) -> float:
    """Roofline forward-pass estimate when no measured table exists:
    max(FLOP time at MXU efficiency, HBM time for in+out+params),
    floored at the per-kernel dispatch overhead."""
    import numpy as np

    flop_s = op.flops() / (machine_spec.flops
                           * getattr(machine_spec, "mxu_efficiency", 0.55))
    bytes_ = 4.0 * (sum(float(np.prod(s)) for s in op.input_shapes)
                    + sum(float(np.prod(s)) for s in op.output_shapes)
                    + float(op.params_elems()))
    mem_s = bytes_ / machine_spec.hbm_bw
    return max(flop_s, mem_s, getattr(machine_spec, "min_op_time", 5e-7))


def predicted_step_time(ff, measured: Optional[Dict[str, float]] = None
                        ) -> Dict[str, Any]:
    """Per-op + comms prediction of one training-step wall time.

    ``measured``: profile.py's ``{"<guid>:fwd": s, "<guid>:bwd": s}``
    table (defaults to ``ff.op_profile`` when ``--profiling`` or
    ``--search-measure-ops`` populated it). Ops absent from the table
    fall back to the analytic roofline — per-op rows record which
    source priced them.
    """
    measured = measured if measured is not None else (ff.op_profile or {})
    mesh = ff.mesh
    spec = ff.machine_spec
    per_op: List[Dict[str, Any]] = []
    compute_s = 0.0
    for node in ff.executor.nodes:
        op = node.op
        fwd = measured.get(f"{op.guid}:fwd")
        bwd = measured.get(f"{op.guid}:bwd")
        source = "measured"
        if fwd is None:
            fwd = _analytic_op_cost(op, spec)
            bwd = 2.0 * fwd
            source = "analytic"
        elif bwd is None:
            bwd = 2.0 * fwd
        div = work_division(node, mesh)
        op_s = (fwd + bwd) / div
        compute_s += op_s
        per_op.append(dict(name=op.name, guid=op.guid,
                           type=op.op_type.name, fwd_s=fwd, bwd_s=bwd,
                           work_div=div, sharded_s=op_s, source=source))
    overhead_s = float(measured.get("__step_overhead__", 0.0))
    return dict(compute_s=compute_s, step_overhead_s=overhead_s,
                per_op=per_op,
                measured_ops=sum(1 for r in per_op
                                 if r["source"] == "measured"))


def predicted_comm_time(ff, census: Dict[str, Dict[str, float]]
                        ) -> Dict[str, Any]:
    """Price the REAL collective census (per-partition bytes from the
    compiled HLO) through the machine model's analytic collective costs
    — the comms half of the prediction, fed by actual emissions instead
    of the simulator's guess at which collectives GSPMD inserts.

    The census is unfiltered (includes the scalar loss/metric
    reductions the validator's ``PRICED_MIN_BYTES`` drops): pricing is
    per-kind on aggregate bytes — latency paid once per kind, not per
    op — so the scalars perturb predicted_s at noise level while the
    report stays a complete account of what the step moves."""
    n_chips = int(ff.mesh.devices.size)
    spec = ff.machine_spec
    corr = getattr(spec, "collective_corrections", None) or {}
    per_kind: Dict[str, Dict[str, float]] = {}
    total = 0.0
    for kind, entry in (census or {}).items():
        t = spec.collective_time(kind, entry["bytes"], n_chips)
        row = dict(entry, predicted_s=t)
        # when a measured correction is already applied to this spec,
        # also record the raw analytic time: the per-kind drift ratio
        # must be measured / UNCALIBRATED so re-ingesting a corrected
        # run derives the same absolute factor (replace converges)
        # instead of the residual ~1.0 (which would un-calibrate it)
        f = corr.get(kind)
        if f:
            row["predicted_uncorrected_s"] = t / f
        per_kind[kind] = row
        total += t
    return dict(comm_s=total, per_kind=per_kind)


def collective_drift(per_kind_predicted: Dict[str, Dict[str, Any]],
                     measured_collectives: Dict[str, Dict[str, float]],
                     platform: Optional[str] = None
                     ) -> Dict[str, Dict[str, Any]]:
    """Join measured per-collective device time (obs/devtrace.py
    attribution, ``{kind: {per_step_s, ...}}``) against the simulator-
    priced census (``predicted_comm_time``'s per-kind rows). Each kind
    gets ``measured_s`` / ``predicted_s`` / ``ratio`` — the per-kind
    correction signal ``scripts/calibrate.py --ingest-drift`` folds into
    CALIBRATION.json ``collective_corrections`` (the measured hook the
    machine model's wus_rs/ag_time terms calibrate against).

    ``ratio`` is measured / UNCORRECTED-analytic
    (``predicted_uncorrected_s`` when the pricing spec already carried a
    correction, else ``predicted_s``): the derived factor is absolute,
    so re-ingesting a run priced with corrections applied replaces the
    stored factor with the same value instead of its ~1.0 residual.

    ``platform`` (when known) stamps each row ``ingestable``: a drift
    ratio measured on the CPU thunk executor compares host-CPU wall time
    against analytic ICI pricing — 400-600x "drift" that is backend
    mismatch, not calibration signal — so CPU-platform rows are marked
    ``ingestable: false`` and ``calibrate.py --ingest-drift`` skips
    them instead of deriving corrections (ISSUE 8 satellite)."""
    out: Dict[str, Dict[str, Any]] = {}
    for kind in sorted(set(per_kind_predicted) | set(measured_collectives)):
        prow = per_kind_predicted.get(kind) or {}
        pred = prow.get("predicted_s")
        base = prow.get("predicted_uncorrected_s", pred)
        meas = (measured_collectives.get(kind) or {}).get("per_step_s")
        row: Dict[str, Any] = dict(predicted_s=pred, measured_s=meas)
        if base and meas and base > 0:
            row["ratio"] = meas / base
        if platform is not None:
            row["ingestable"] = platform != "cpu"
        out[kind] = row
    return out


def drift_report(ff, measured_step_s: Optional[float],
                 census: Optional[Dict[str, Dict[str, float]]] = None,
                 measured: Optional[Dict[str, float]] = None,
                 phase_summary: Optional[Dict[str, Any]] = None,
                 measured_collectives: Optional[
                     Dict[str, Dict[str, float]]] = None,
                 step_metrics: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """The calibration report: predicted-vs-measured step-time ratio.

    ``measured_step_s``: steady-state step wall time (tracer median).
    ``census``: collective census from the compiled step (inspector);
    None prices zero comms. Also carries the native search's own
    prediction (``search_info["predicted_time"]``) when one exists, so
    drift of the REAL search — not just this reconstruction — is
    visible.

    ``measured_collectives``: per-kind measured device time from the
    device-trace attribution (``{kind: {per_step_s, ...}}``); when
    present the report gains a ``collective_drift`` section joining it
    against the census-priced prediction. ``step_metrics``: the
    goodput/MFU/step-percentile dict from
    ``obs.devtrace.record_step_metrics``, carried along for the run
    report.
    """
    pred = predicted_step_time(ff, measured=measured)
    comm = predicted_comm_time(ff, census or {})
    total = pred["compute_s"] + pred["step_overhead_s"] + comm["comm_s"]
    ratio = (total / measured_step_s
             if measured_step_s and measured_step_s > 0 else None)
    search_pred = None
    if isinstance(ff.search_info, dict):
        search_pred = ff.search_info.get("predicted_time")
    search_ratio = (search_pred / measured_step_s
                    if search_pred and measured_step_s else None)
    report = dict(
        predicted=dict(total_s=total,
                       compute_s=pred["compute_s"],
                       comm_s=comm["comm_s"],
                       step_overhead_s=pred["step_overhead_s"],
                       measured_ops=pred["measured_ops"],
                       num_ops=len(pred["per_op"])),
        measured=dict(step_s=measured_step_s),
        ratio=ratio,
        search_predicted_s=search_pred,
        search_ratio=search_ratio,
        per_op=pred["per_op"],
        comm=comm["per_kind"],
        mesh_axes=dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape)),
    )
    if phase_summary:
        report["phases"] = phase_summary
    if measured_collectives is not None:
        try:
            import jax
            platform = jax.devices()[0].platform
        except Exception:
            platform = None
        report["collective_drift"] = collective_drift(
            comm["per_kind"], measured_collectives, platform=platform)
    if step_metrics:
        report["step_metrics"] = step_metrics
    return report

"""Step tracer: per-step wall time + phase spans, Perfetto-viewable.

Records a span tree per training step — data_load (host slicing),
device_put (host->device staging), step (jitted dispatch), metrics_sync
(the host fetch that fences the device) — and exports two artifacts:

- ``<run>_hostNN.trace.json``: Chrome-trace/Perfetto ``traceEvents``
  JSON (load in ui.perfetto.dev or chrome://tracing). One ``pid`` per
  host, so multi-host traces merge into one timeline
  (``merge_host_traces``).
- ``<run>_hostNN.events.jsonl``: the same events as a line-delimited
  stream (first line = provenance header) for programmatic consumers.

``make_tracer(None)`` returns the shared ``NULL_TRACER``: every method
is a no-op returning a preallocated context manager, so untraced runs
pay only an attribute lookup per step.
"""

from __future__ import annotations

import contextlib
import glob
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

from flexflow_tpu.obs.artifacts import artifact_header, atomic_write_text

# distinguishes repeated fit()/evaluate() calls sharing one trace_dir
_RUN_SEQ = itertools.count()


class NullTracer:
    """Inert tracer: the no-trace_dir fast path."""

    active = False
    _NULL = contextlib.nullcontext()

    def step(self):
        return self._NULL

    def phase(self, name, **args):
        return self._NULL

    def instant(self, name, **args):
        pass

    def set_meta(self, **meta):
        pass

    def add_trace_events(self, events, threads=None):
        pass

    def step_time_s(self):
        return None

    def last_step_start_us(self):
        return None

    def run_wall_s(self):
        return None

    def export(self):
        return {}


NULL_TRACER = NullTracer()


def _clock_pair(samples: int = 5):
    """A (perf_counter, unix-wall) pair sampled with minimal skew: each
    wall read is bracketed by two perf_counter reads and the tightest
    bracket wins. The pair is the shared epoch ``merge_host_traces``
    uses to line up per-host lanes, so its uncertainty (the bracket
    width) is stamped into the trace header."""
    best = None
    for _ in range(samples):
        p0 = time.perf_counter()
        w = time.time()
        p1 = time.perf_counter()
        if best is None or (p1 - p0) < best[2]:
            best = ((p0 + p1) / 2, w, p1 - p0)
    return best


class _Span:
    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer, name, args):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self.tracer._record(self.name, self.t0, t1, self.args)
        return False


class _StepSpan(_Span):
    """The whole-step span: flags the tracer so phase events recorded
    inside it carry the step index."""

    __slots__ = ()

    def __enter__(self):
        self.tracer._in_step = True
        return _Span.__enter__(self)

    def __exit__(self, *exc):
        r = _Span.__exit__(self, *exc)
        self.tracer._in_step = False
        return r


class StepTracer:
    """Records phase spans and exports Chrome-trace JSON + JSONL."""

    active = True

    # events kept in memory before export; ~5 spans/step so the default
    # covers ~100k steps. Past the cap, spans are counted but not stored
    # (dropped_events lands in the header) — a week-long traced run must
    # degrade to a truncated trace, not an OOM.
    MAX_EVENTS = 500_000

    def __init__(self, trace_dir: str, host_id: Optional[int] = None,
                 run_name: str = "fit", max_events: Optional[int] = None):
        if host_id is None:
            try:
                import jax
                host_id = jax.process_index()
            except Exception:
                host_id = 0
        self.trace_dir = trace_dir
        self.host_id = int(host_id)
        self.run_name = run_name
        self.run_seq = next(_RUN_SEQ)
        self.max_events = (self.MAX_EVENTS if max_events is None
                           else max_events)
        self._dropped = 0
        self.meta: Dict[str, Any] = {}
        self._events: List[Dict[str, Any]] = []
        self._extra_events: List[Dict[str, Any]] = []
        self._extra_threads: Dict[int, str] = {}
        # shared wall-clock epoch: a tight (perf_counter, unix) pairing
        # so merge_host_traces can shift every host onto one timeline
        self._origin, self._wall_origin, pair_spread = _clock_pair()
        self._clock_pair_spread_us = pair_spread * 1e6
        self._step_index = -1
        self._in_step = False
        os.makedirs(trace_dir, exist_ok=True)

    # ---- recording --------------------------------------------------------
    def _record(self, name: str, t0: float, t1: float,
                args: Optional[Dict[str, Any]]) -> None:
        if len(self._events) >= self.max_events:
            self._dropped += 1
            return
        ev = dict(name=name,
                  ts=(t0 - self._origin) * 1e6,
                  dur=(t1 - t0) * 1e6)
        if self._in_step or name == "step":
            ev["step"] = self._step_index
        if args:
            ev["args"] = args
        self._events.append(ev)

    def step(self):
        """Span wrapping one whole training step (phases nest inside)."""
        self._step_index += 1
        return _StepSpan(self, "step", None)

    def phase(self, name: str, **args):
        """Span for one phase (data_load / device_put / step_dispatch /
        metrics_sync / ...) — nests under the current step span."""
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        if len(self._events) >= self.max_events:
            self._dropped += 1
            return
        t = time.perf_counter()
        ev = dict(name=name, ts=(t - self._origin) * 1e6, dur=0.0,
                  instant=True)
        if args:
            ev["args"] = args
        self._events.append(ev)

    def set_meta(self, **meta) -> None:
        self.meta.update(meta)

    def add_trace_events(self, events: List[Dict[str, Any]],
                         threads: Optional[Dict[int, str]] = None) -> None:
        """Attach externally-sourced Chrome-trace events (the devtrace
        capture's device lanes + attribution counter tracks) to this
        host's export. ``events`` are complete Chrome dicts except
        ``pid`` (stamped at export with this host's pid); ``threads``
        maps each lane tid to its Perfetto row label. Extra events land
        in the ``.trace.json`` only — the ``.events.jsonl`` stream stays
        the host-phase record (device spans have their own
        ``.devtrace.json`` artifact)."""
        self._extra_events.extend(events)
        self._extra_threads.update(threads or {})

    # ---- summaries --------------------------------------------------------
    def step_durations_s(self) -> List[float]:
        return [e["dur"] / 1e6 for e in self._events if e["name"] == "step"
                and not e.get("instant")]

    def step_time_s(self) -> Optional[float]:
        """Median steady-state step wall time. The first step carries jit
        compilation, so it is dropped whenever more than one step exists."""
        ds = self.step_durations_s()
        if not ds:
            return None
        if len(ds) > 1:
            ds = ds[1:]
        ds = sorted(ds)
        return ds[len(ds) // 2]

    def last_step_start_us(self) -> Optional[float]:
        """Timeline timestamp (µs, tracer origin) where the LAST traced
        step began — the steady-state anchor the simtrace lanes align
        to (never the compile-carrying first step when more than one
        step ran)."""
        starts = [e["ts"] for e in self._events
                  if e["name"] == "step" and not e.get("instant")]
        return starts[-1] if starts else None

    def run_wall_s(self) -> Optional[float]:
        """Wall span the recorded events cover (first event start to
        last event end) — the denominator of the goodput gauge."""
        spans = [(e["ts"], e["ts"] + e.get("dur", 0.0))
                 for e in self._events]
        if not spans:
            return None
        return (max(e for _, e in spans) - min(s for s, _ in spans)) / 1e6

    def phase_summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for e in self._events:
            if e.get("instant"):
                continue
            s = out.setdefault(e["name"],
                               dict(count=0.0, total_s=0.0, max_s=0.0))
            d = e["dur"] / 1e6
            s["count"] += 1
            s["total_s"] += d
            s["max_s"] = max(s["max_s"], d)
        return out

    # ---- export -----------------------------------------------------------
    @property
    def file_stem(self) -> str:
        return (f"{self.run_name}_r{self.run_seq:02d}"
                f"_host{self.host_id:02d}")

    def export(self) -> Dict[str, str]:
        """Write the Chrome-trace JSON + JSONL stream; returns paths."""
        header = artifact_header(host_id=self.host_id, kind="trace")
        header.update(run_name=self.run_name, run_seq=self.run_seq,
                      wall_origin_unix=self._wall_origin,
                      clock_pair_spread_us=round(
                          self._clock_pair_spread_us, 3),
                      **self.meta)
        if self._dropped:
            header["dropped_events"] = self._dropped
        trace_events = [
            dict(name="process_name", ph="M", pid=self.host_id, tid=0,
                 args=dict(name=f"host{self.host_id}:{self.run_name}")),
            dict(name="thread_name", ph="M", pid=self.host_id, tid=0,
                 args=dict(name="train_loop")),
        ]
        for tid, label in sorted(self._extra_threads.items()):
            trace_events.append(dict(name="thread_name", ph="M",
                                     pid=self.host_id, tid=tid,
                                     args=dict(name=label)))
        for e in self._events:
            ev = dict(name=e["name"], pid=self.host_id, tid=0,
                      ts=round(e["ts"], 3), cat="flexflow_tpu")
            if e.get("instant"):
                ev.update(ph="i", s="t")
            else:
                ev.update(ph="X", dur=round(e["dur"], 3))
            args = dict(e.get("args") or {})
            if "step" in e:
                args["step"] = e["step"]
            if args:
                ev["args"] = args
            trace_events.append(ev)
        for ev in self._extra_events:  # devtrace lanes, pre-rebased
            trace_events.append(dict(ev, pid=self.host_id))
        trace_path = os.path.join(self.trace_dir,
                                  self.file_stem + ".trace.json")
        atomic_write_text(trace_path, json.dumps(
            dict(traceEvents=trace_events, displayTimeUnit="ms",
                 metadata=header)))
        jsonl_path = os.path.join(self.trace_dir,
                                  self.file_stem + ".events.jsonl")
        lines = [json.dumps(dict(header, record="header"))]
        lines += [json.dumps(e) for e in self._events]
        atomic_write_text(jsonl_path, "\n".join(lines) + "\n")
        return dict(trace=trace_path, events=jsonl_path)


def make_tracer(trace_dir: Optional[str], host_id: Optional[int] = None,
                run_name: str = "fit"):
    """StepTracer when ``trace_dir`` is set, else the shared no-op.

    An unusable trace dir (unwritable, path is a file, ...) degrades to
    the no-op with a warning: observability must never be the thing
    that kills the training run or bench it was asked to watch."""
    if not trace_dir:
        return NULL_TRACER
    try:
        return StepTracer(trace_dir, host_id=host_id, run_name=run_name)
    except OSError as e:
        import sys
        print(f"[obs] trace dir {trace_dir!r} unusable ({e}); "
              "tracing disabled for this run", file=sys.stderr)
        return NULL_TRACER


def merge_host_traces(trace_dir: str,
                      out_name: str = "merged.trace.json") -> Optional[str]:
    """Merge every per-host ``*.trace.json`` in ``trace_dir`` into one
    Chrome-trace file (events keep their per-host ``pid``, so Perfetto
    shows one track group per host). Per-host timestamps are relative
    to each tracer's own monotonic origin, so events are rebased onto a
    shared timeline using the ``wall_origin_unix`` every header records
    (earliest host = t0); hosts then align by real start time, not by
    per-worker startup skew. Returns the merged path, or None when
    there is nothing to merge."""
    paths = sorted(p for p in glob.glob(os.path.join(trace_dir,
                                                     "*.trace.json"))
                   if not p.endswith(out_name))
    if not paths:
        return None
    loaded: List[Dict[str, Any]] = []
    for p in paths:
        try:
            with open(p) as f:
                loaded.append(json.load(f))
        except (OSError, ValueError):
            continue
    origins = [(d.get("metadata") or {}).get("wall_origin_unix")
               for d in loaded]
    t0 = min((o for o in origins if o is not None), default=None)
    events: List[Dict[str, Any]] = []
    hosts: List[int] = []
    # One BLOCK of thread rows per source trace, keyed (run_name,
    # run_seq): a dir holding repeated fits, evaluate legs, or stale
    # traces from an earlier run merges into distinct row groups instead
    # of interleaving overlapping spans on one (pid, tid). Within a
    # block, each of the source trace's own tids (train_loop = 0 plus
    # any devtrace lanes) keeps its own row.
    BLOCK = 256  # > any per-trace tid (train_loop 0, devtrace lanes <128)
    blocks: Dict[Any, str] = {}  # (pid, block) -> label
    rows: Dict[Any, str] = {}  # (pid, out_tid) -> row label
    for data, origin in zip(loaded, origins):
        meta = data.get("metadata") or {}
        hid = meta.get("host_id")
        pid = int(hid) if hid is not None else 0
        run = str(meta.get("run_name", "run"))
        block = int(meta.get("run_seq", 0))
        label = f"{run}_r{block:02d}"
        while blocks.get((pid, block), label) != label:
            block += 1  # same (host, seq) from different runs: next block
        blocks[(pid, block)] = label
        lane_names: Dict[int, str] = {}
        for ev in data.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "thread_name":
                lane_names[int(ev.get("tid", 0))] = str(
                    (ev.get("args") or {}).get("name", ""))
        shift_us = ((origin - t0) * 1e6
                    if origin is not None and t0 is not None else 0.0)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue  # per-file metadata is re-synthesized below
            tid = int(ev.get("tid", 0)) % BLOCK
            out_tid = block * BLOCK + tid
            lane = lane_names.get(tid)
            rows[(pid, out_tid)] = (label if tid == 0 or not lane
                                    else f"{label}:{lane}")
            ev = dict(ev, pid=pid, tid=out_tid)
            if shift_us and "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift_us, 3)
            events.append(ev)
        if hid is not None:
            hosts.append(pid)
    if not events:
        return None
    meta_events: List[Dict[str, Any]] = []
    for pid in sorted({p for p, _ in rows}):
        meta_events.append(dict(name="process_name", ph="M", pid=pid,
                                tid=0, args=dict(name=f"host{pid}")))
    for (pid, tid), label in sorted(rows.items()):
        meta_events.append(dict(name="thread_name", ph="M", pid=pid,
                                tid=tid, args=dict(name=label)))
    events = meta_events + events
    header = artifact_header(kind="merged_trace")
    header["merged_hosts"] = sorted(set(hosts))
    header["merged_files"] = [os.path.basename(p) for p in paths]
    out = os.path.join(trace_dir, out_name)
    atomic_write_text(out, json.dumps(
        dict(traceEvents=events, displayTimeUnit="ms", metadata=header)))
    return out

"""Counter/gauge registry: cheap process-wide runtime counters.

The executor and tracer increment counters here (jit compiles, traced
steps, graph sizes); ``export()`` snapshots the registry as a
version-stamped JSON artifact. Deliberately tiny — dict bumps on paths
that already pay a jit dispatch, nothing that could show up in a
benchmark profile.

``observe()`` additionally keeps a bounded reservoir of samples per
series so p50/p99 survive into the snapshot without unbounded memory:
a week-long traced run's step-time distribution costs at most
``RESERVOIR_SIZE`` floats, and the reservoir is a uniform sample of the
whole stream (classic algorithm-R with a fixed seed, so snapshots are
reproducible for a given observation sequence).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

RESERVOIR_SIZE = 512


def percentile(sorted_samples: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted sample list — the
    ONE percentile definition shared by the registry snapshot, the
    traced-run step metrics (obs/devtrace.py) and bench.py, so a p50
    can never mean two different things depending on which artifact a
    report read it from."""
    n = len(sorted_samples)
    rank = max(1, -(-int(q * 100) * n // 100))  # ceil(q*n) via int math
    return sorted_samples[min(rank, n) - 1]


class CounterRegistry:
    """Monotonic counters + last-value gauges + observation summaries
    (count/sum/min/max plus reservoir-sampled p50/p99)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._observations: Dict[str, Dict[str, float]] = {}
        self._samples: Dict[str, List[float]] = {}
        self._rng = random.Random(0xFF5EED)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Streaming count/sum/min/max summary plus a bounded reservoir
        (RESERVOIR_SIZE samples max) for percentile estimates."""
        v = float(value)
        with self._lock:
            o = self._observations.get(name)
            if o is None:
                self._observations[name] = dict(count=1.0, sum=v, min=v,
                                                max=v)
                self._samples[name] = [v]
                return
            o["count"] += 1.0
            o["sum"] += v
            o["min"] = min(o["min"], v)
            o["max"] = max(o["max"], v)
            s = self._samples[name]
            if len(s) < RESERVOIR_SIZE:
                s.append(v)
            else:
                j = self._rng.randrange(int(o["count"]))
                if j < RESERVOIR_SIZE:
                    s[j] = v

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, default)

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            obs: Dict[str, Dict[str, float]] = {}
            for k, v in self._observations.items():
                e = dict(v)
                s = sorted(self._samples.get(k, ()))
                if s:
                    e["p50"] = percentile(s, 0.50)
                    e["p99"] = percentile(s, 0.99)
                obs[k] = e
            return dict(
                counters=dict(self._counters),
                gauges=dict(self._gauges),
                observations=obs,
            )

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._observations.clear()
            self._samples.clear()

    def export(self, path: str, host_id: Optional[int] = None) -> str:
        from flexflow_tpu.obs.artifacts import write_artifact
        return write_artifact(path, self.to_dict(), host_id=host_id,
                              kind="counters")


_REGISTRY = CounterRegistry()


def get_registry() -> CounterRegistry:
    """The process-wide default registry."""
    return _REGISTRY

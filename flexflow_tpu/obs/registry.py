"""Counter/gauge registry: cheap process-wide runtime counters.

The executor and tracer increment counters here (jit compiles, traced
steps, graph sizes); ``export()`` snapshots the registry as a
version-stamped JSON artifact. Deliberately tiny — dict bumps on paths
that already pay a jit dispatch, nothing that could show up in a
benchmark profile.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class CounterRegistry:
    """Monotonic counters + last-value gauges + min/max/sum observations."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._observations: Dict[str, Dict[str, float]] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Streaming count/sum/min/max summary (no per-sample storage)."""
        v = float(value)
        with self._lock:
            o = self._observations.get(name)
            if o is None:
                self._observations[name] = dict(count=1.0, sum=v, min=v,
                                                max=v)
            else:
                o["count"] += 1.0
                o["sum"] += v
                o["min"] = min(o["min"], v)
                o["max"] = max(o["max"], v)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, default)

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return dict(
                counters=dict(self._counters),
                gauges=dict(self._gauges),
                observations={k: dict(v)
                              for k, v in self._observations.items()},
            )

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._observations.clear()

    def export(self, path: str, host_id: Optional[int] = None) -> str:
        from flexflow_tpu.obs.artifacts import write_artifact
        return write_artifact(path, self.to_dict(), host_id=host_id,
                              kind="counters")


_REGISTRY = CounterRegistry()


def get_registry() -> CounterRegistry:
    """The process-wide default registry."""
    return _REGISTRY

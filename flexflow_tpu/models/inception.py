"""Inception-v3 (examples/cpp/InceptionV3/inception.cc).

Module structure per the reference: A (1x1 / 5x5 / double-3x3 / pool
branches, inception.cc:22-45), B (grid reduction :51-60), C (7x1/1x7
factorized :65-81), D (reduction :86-94), E (expanded 3x3/1x3/3x1 splits),
stem convs, avgpool head -> dense.
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel

RELU = ActiMode.AC_MODE_RELU


@dataclasses.dataclass
class InceptionConfig:
    batch_size: int = 64  # osdi22ae inception.sh batch
    image_size: int = 299
    num_classes: int = 1000
    # reduced=True keeps the stem + ONE module of each family (a/b/c/d/e)
    # — topology-representative but ~4x fewer convs, for CPU smoke runs
    reduced: bool = False


def _module_a(ff, x, pool_features, name):
    t1 = ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0, activation=RELU, name=f"{name}_b1")
    t2 = ff.conv2d(x, 48, 1, 1, 1, 1, 0, 0, activation=RELU)
    t2 = ff.conv2d(t2, 64, 5, 5, 1, 1, 2, 2, activation=RELU)
    t3 = ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0, activation=RELU)
    t3 = ff.conv2d(t3, 96, 3, 3, 1, 1, 1, 1, activation=RELU)
    t3 = ff.conv2d(t3, 96, 3, 3, 1, 1, 1, 1, activation=RELU)
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, pool_type=PoolType.POOL_AVG)
    t4 = ff.conv2d(t4, pool_features, 1, 1, 1, 1, 0, 0, activation=RELU)
    return ff.concat([t1, t2, t3, t4], axis=1)


def _module_b(ff, x, name):
    t1 = ff.conv2d(x, 384, 3, 3, 2, 2, 0, 0)
    t2 = ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2, 96, 3, 3, 1, 1, 1, 1)
    t2 = ff.conv2d(t2, 96, 3, 3, 2, 2, 0, 0)
    t3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    return ff.concat([t1, t2, t3], axis=1)


def _module_c(ff, x, channels, name):
    t1 = ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(x, channels, 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2, channels, 1, 7, 1, 1, 0, 3)
    t2 = ff.conv2d(t2, 192, 7, 1, 1, 1, 3, 0)
    t3 = ff.conv2d(x, channels, 1, 1, 1, 1, 0, 0)
    t3 = ff.conv2d(t3, channels, 7, 1, 1, 1, 3, 0)
    t3 = ff.conv2d(t3, channels, 1, 7, 1, 1, 0, 3)
    t3 = ff.conv2d(t3, channels, 7, 1, 1, 1, 3, 0)
    t3 = ff.conv2d(t3, 192, 1, 7, 1, 1, 0, 3)
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, pool_type=PoolType.POOL_AVG)
    t4 = ff.conv2d(t4, 192, 1, 1, 1, 1, 0, 0)
    return ff.concat([t1, t2, t3, t4], axis=1)


def _module_d(ff, x, name):
    t1 = ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0)
    t1 = ff.conv2d(t1, 320, 3, 3, 2, 2, 0, 0)
    t2 = ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2, 192, 1, 7, 1, 1, 0, 3)
    t2 = ff.conv2d(t2, 192, 7, 1, 1, 1, 3, 0)
    t2 = ff.conv2d(t2, 192, 3, 3, 2, 2, 0, 0)
    t3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    return ff.concat([t1, t2, t3], axis=1)


def _module_e(ff, x, name):
    t1 = ff.conv2d(x, 320, 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(x, 384, 1, 1, 1, 1, 0, 0)
    t2a = ff.conv2d(t2, 384, 1, 3, 1, 1, 0, 1)
    t2b = ff.conv2d(t2, 384, 3, 1, 1, 1, 1, 0)
    t3 = ff.conv2d(x, 448, 1, 1, 1, 1, 0, 0)
    t3 = ff.conv2d(t3, 384, 3, 3, 1, 1, 1, 1)
    t3a = ff.conv2d(t3, 384, 1, 3, 1, 1, 0, 1)
    t3b = ff.conv2d(t3, 384, 3, 1, 1, 1, 1, 0)
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, pool_type=PoolType.POOL_AVG)
    t4 = ff.conv2d(t4, 192, 1, 1, 1, 1, 0, 0)
    return ff.concat([t1, t2a, t2b, t3a, t3b, t4], axis=1)


def create_inception_v3(cfg: InceptionConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    x = ff.create_tensor((cfg.batch_size, 3, cfg.image_size, cfg.image_size),
                         name="input")
    x = ff.conv2d(x, 32, 3, 3, 2, 2, 0, 0, activation=RELU)
    x = ff.conv2d(x, 32, 3, 3, 1, 1, 0, 0, activation=RELU)
    x = ff.conv2d(x, 64, 3, 3, 1, 1, 1, 1, activation=RELU)
    x = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    x = ff.conv2d(x, 80, 1, 1, 1, 1, 0, 0, activation=RELU)
    x = ff.conv2d(x, 192, 3, 3, 1, 1, 0, 0, activation=RELU)
    x = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    x = _module_a(ff, x, 32, "a1")
    if not cfg.reduced:
        x = _module_a(ff, x, 64, "a2")
        x = _module_a(ff, x, 64, "a3")
    x = _module_b(ff, x, "b1")
    x = _module_c(ff, x, 128, "c1")
    if not cfg.reduced:
        x = _module_c(ff, x, 160, "c2")
        x = _module_c(ff, x, 160, "c3")
        x = _module_c(ff, x, 192, "c4")
    x = _module_d(ff, x, "d1")
    x = _module_e(ff, x, "e1")
    if not cfg.reduced:
        x = _module_e(ff, x, "e2")
    x = ff.pool2d(x, x.shape[2], x.shape[3], 1, 1, 0, 0,
                  pool_type=PoolType.POOL_AVG)
    x = ff.flat(x)
    x = ff.dense(x, cfg.num_classes, name="fc")
    x = ff.softmax(x)
    return ff

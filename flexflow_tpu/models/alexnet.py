"""AlexNet (examples/cpp/AlexNet/alexnet.cc): the reference's canonical
CNN example, CIFAR/ImageNet NCHW."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


def create_alexnet(batch_size: int = 64, num_classes: int = 10,
                   image_size: int = 224, batch_norm: bool = False,
                   ff_config: FFConfig = None) -> FFModel:
    """``batch_norm=True`` swaps the fused conv-ReLUs for conv→BN(+ReLU)
    pairs (the modern AlexNet-BN variant) — a zoo path exercising the
    Conv+BN fold the serving predict runs."""
    ff = FFModel(ff_config or FFConfig(batch_size=batch_size))

    def conv(t, ch, k, s, p, name):
        if batch_norm:
            t = ff.conv2d(t, ch, k, k, s, s, p, p, name=name)
            return ff.batch_norm(t, relu=True, name=f"{name}_bn")
        return ff.conv2d(t, ch, k, k, s, s, p, p,
                         activation=ActiMode.AC_MODE_RELU, name=name)

    t = ff.create_tensor((batch_size, 3, image_size, image_size))
    t = conv(t, 64, 11, 4, 2, "conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = conv(t, 192, 5, 1, 2, "conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = conv(t, 384, 3, 1, 1, "conv3")
    t = conv(t, 256, 3, 1, 1, "conv4")
    t = conv(t, 256, 3, 1, 1, "conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    # explicit names: checkpoint keys stay build-order-independent
    t = ff.dense(t, 4096, activation=ActiMode.AC_MODE_RELU, name="fc6")
    t = ff.dropout(t, 0.5)
    t = ff.dense(t, 4096, activation=ActiMode.AC_MODE_RELU, name="fc7")
    t = ff.dropout(t, 0.5)
    t = ff.dense(t, num_classes, name="fc8")
    t = ff.softmax(t)
    return ff

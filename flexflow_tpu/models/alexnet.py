"""AlexNet (examples/cpp/AlexNet/alexnet.cc): the reference's canonical
CNN example, CIFAR/ImageNet NCHW."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


def create_alexnet(batch_size: int = 64, num_classes: int = 10,
                   image_size: int = 224, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=batch_size))
    t = ff.create_tensor((batch_size, 3, image_size, image_size))
    t = ff.conv2d(t, 64, 11, 11, 4, 4, 2, 2, activation=ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation=ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4096, activation=ActiMode.AC_MODE_RELU)
    t = ff.dropout(t, 0.5)
    t = ff.dense(t, 4096, activation=ActiMode.AC_MODE_RELU)
    t = ff.dropout(t, 0.5)
    t = ff.dense(t, num_classes)
    t = ff.softmax(t)
    return ff

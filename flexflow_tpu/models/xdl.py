"""XDL click-through model (examples/cpp/XDL/xdl.cc).

N large embedding tables (reference default 4x 1M vocab, dim 64,
xdl.cc:26-31) looked up per sparse feature, concatenated (xdl.cc:79-82)
and fed to a dense MLP ending in a binary softmax. The embedding tables
are the parameter-parallel target, like DLRM.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class XDLConfig:
    batch_size: int = 64
    embedding_size: Sequence[int] = (1000000,) * 4
    sparse_feature_size: int = 64
    embedding_bag_size: int = 1
    mlp: Sequence[int] = (512, 256, 128, 2)


def create_xdl(cfg: XDLConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    embedded = []
    for i, vocab in enumerate(cfg.embedding_size):
        inp = ff.create_tensor((cfg.batch_size, cfg.embedding_bag_size),
                               dtype=DataType.INT32, name=f"sparse_{i}")
        e = ff.embedding(inp, vocab, cfg.sparse_feature_size,
                         aggr=AggrMode.AGGR_MODE_SUM, name=f"emb_{i}")
        embedded.append(e)
    t = ff.concat(embedded, axis=-1, name="concat_emb")
    for j, width in enumerate(cfg.mlp[:-1]):
        t = ff.dense(t, width, activation=ActiMode.AC_MODE_RELU,
                     name=f"mlp_d{j}")
    t = ff.dense(t, cfg.mlp[-1], name="mlp_out")
    t = ff.softmax(t)
    return ff

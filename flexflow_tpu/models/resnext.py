"""ResNeXt-50 32x4d (examples/cpp/resnext50/resnext.cc).

Block (resnext.cc:17-27): 1x1 relu -> grouped 3x3 relu (cardinality 32) ->
1x1 to 2x expansion; projection shortcut; stages [3,4,6,3]; head
avgpool -> flat -> dense(1000) (resnext.cc:84-86).
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class ResNeXtConfig:
    batch_size: int = 16  # osdi22ae resnext-50.sh batch
    image_size: int = 224
    num_classes: int = 1000
    cardinality: int = 32
    stages: tuple = (3, 4, 6, 3)


def _block(ff: FFModel, t, out_channels: int, stride: int, groups: int,
           name: str, has_residual: bool = False):
    """resnext.cc:14-31 — note the reference's has_residual defaults false
    and no call site enables it, so the benchmarked network has NO residual
    connections; we keep the same default for protocol parity."""
    inp = t
    t = ff.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0,
                  activation=ActiMode.AC_MODE_RELU, name=f"{name}_c1")
    t = ff.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1,
                  activation=ActiMode.AC_MODE_RELU, groups=groups,
                  name=f"{name}_c2")
    t = ff.conv2d(t, 2 * out_channels, 1, 1, 1, 1, 0, 0, name=f"{name}_c3")
    if has_residual and (stride > 1 or inp.shape[1] != 2 * out_channels):
        inp = ff.conv2d(inp, 2 * out_channels, 1, 1, stride, stride, 0, 0,
                        activation=ActiMode.AC_MODE_RELU, name=f"{name}_proj")
        t = ff.relu(ff.add(inp, t, name=f"{name}_add"), inplace=False)
    return t


def create_resnext50(cfg: ResNeXtConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    t = ff.create_tensor((cfg.batch_size, 3, cfg.image_size, cfg.image_size),
                         name="input")
    t = ff.conv2d(t, 64, 7, 7, 2, 2, 3, 3,
                  activation=ActiMode.AC_MODE_RELU, name="stem")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    widths = (128, 256, 512, 1024)
    for s, (n_blocks, w) in enumerate(zip(cfg.stages, widths)):
        for i in range(n_blocks):
            stride = 2 if (i == 0 and s > 0) else 1
            t = _block(ff, t, w, stride, cfg.cardinality, f"s{s}_b{i}")
    t = ff.pool2d(t, t.shape[2], t.shape[3], 1, 1, 0, 0,
                  pool_type=PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, cfg.num_classes, name="fc")
    t = ff.softmax(t)
    return ff

"""DLRM (examples/cpp/DLRM/dlrm.cc): sparse embedding tables + bottom/top
MLPs + pairwise feature interaction. The embedding tables are the
parameter-parallel showcase (shipped strategies
examples/cpp/DLRM/strategies/)."""

from __future__ import annotations

import dataclasses
from typing import Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, AggrMode
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class DLRMConfig:
    batch_size: int = 64
    num_sparse_features: int = 8
    vocab_size: int = 100000
    embedding_dim: int = 64
    indices_per_feature: int = 1
    dense_dim: int = 16
    bottom_mlp: Sequence[int] = (512, 256, 64)
    top_mlp: Sequence[int] = (512, 256, 1)


def create_dlrm(cfg: DLRMConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    from flexflow_tpu.ffconst import DataType

    # sparse features -> embedding bags (SUM aggregated)
    sparse_outs = []
    for i in range(cfg.num_sparse_features):
        ids = ff.create_tensor(
            (cfg.batch_size, cfg.indices_per_feature), DataType.INT32,
            name=f"sparse_{i}")
        e = ff.embedding(ids, cfg.vocab_size, cfg.embedding_dim,
                         aggr=AggrMode.AGGR_MODE_SUM, name=f"emb_{i}")
        sparse_outs.append(e)

    # dense features -> bottom MLP
    dense_in = ff.create_tensor((cfg.batch_size, cfg.dense_dim), name="dense")
    t = dense_in
    for j, h in enumerate(cfg.bottom_mlp):
        t = ff.dense(t, h, activation=ActiMode.AC_MODE_RELU, name=f"bot_{j}")

    # feature interaction: concat embeddings + bottom output (dlrm.cc
    # interact_features "cat" mode)
    z = ff.concat(sparse_outs + [t], axis=1, name="interact")

    for j, h in enumerate(cfg.top_mlp):
        act = ActiMode.AC_MODE_RELU if j < len(cfg.top_mlp) - 1 else ActiMode.AC_MODE_SIGMOID
        z = ff.dense(z, h, activation=act, name=f"top_{j}")
    return ff

"""MLP model (examples/cpp/MLP_Unify/mlp.cc): stacked dense layers."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.model import FFModel


def create_mlp(batch_size: int = 64, in_dim: int = 1024,
               hidden_dims: Sequence[int] = (4096, 4096, 4096),
               out_dim: int = 10, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=batch_size))
    t = ff.create_tensor((batch_size, in_dim))
    for i, h in enumerate(hidden_dims):
        t = ff.dense(t, h, activation=ActiMode.AC_MODE_RELU, name=f"mlp_{i}")
    t = ff.dense(t, out_dim, name="mlp_out")
    t = ff.softmax(t)
    return ff

"""Transformer / BERT-proxy model.

Analog of examples/cpp/Transformer/transformer.cc: the OSDI'22 Unity BERT
benchmark config is 12 layers, hidden 1024, 16 heads, seq 512, batch 8
(transformer.cc:79-84); each layer = MHA + residual + 2-layer FFN
(create_attention_encoder, transformer.cc:22-38; the reference omits
layernorm — we include the standard pre-LN encoder as the TPU flagship and
keep ``layer_norm=False`` parity mode for benchmark comparisons).
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, LossType, MetricsType
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class TransformerConfig:
    # reference defaults (transformer.cc:79-84)
    num_layers: int = 12
    hidden_size: int = 1024
    num_heads: int = 16
    seq_length: int = 512
    batch_size: int = 8
    ffn_mult: int = 4
    dropout: float = 0.0
    layer_norm: bool = True  # False = exact reference block structure
    causal: bool = False
    seq_parallel: str = None  # mesh axis for ring attention (e.g. "seq")


def create_transformer(cfg: TransformerConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    t = ff.create_tensor((cfg.batch_size, cfg.seq_length, cfg.hidden_size),
                         name="input")
    for i in range(cfg.num_layers):
        # attention sublayer (+ residual)
        a_in = ff.layer_norm(t, name=f"ln1_{i}") if cfg.layer_norm else t
        a = ff.multihead_attention(
            a_in, a_in, a_in, cfg.hidden_size, cfg.num_heads,
            dropout=cfg.dropout, causal=cfg.causal,
            seq_parallel=cfg.seq_parallel, name=f"attn_{i}")
        t = ff.add(t, a, name=f"res1_{i}")
        # FFN sublayer (dense_relu + dense, transformer.cc:31-35)
        f_in = ff.layer_norm(t, name=f"ln2_{i}") if cfg.layer_norm else t
        h = ff.dense(f_in, cfg.hidden_size * cfg.ffn_mult,
                     activation=ActiMode.AC_MODE_RELU, name=f"ffn1_{i}")
        h = ff.dense(h, cfg.hidden_size, name=f"ffn2_{i}")
        t = ff.add(t, h, name=f"res2_{i}")
    # classification head as in the reference (dense to 1 output per token
    # feature, transformer.cc:60-66 uses dense(hidden)->dense(1))
    t = ff.dense(t, 1, name="head")
    return ff


def compile_transformer(cfg: TransformerConfig, ff_config: FFConfig = None,
                        optimizer=None, mesh=None) -> FFModel:
    from flexflow_tpu.optimizers import SGDOptimizer

    ff = create_transformer(cfg, ff_config)
    ff.compile(optimizer or SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR], mesh=mesh)
    return ff

"""Mixture-of-Experts model (examples/cpp/mixture_of_experts/moe.cc).

Reference default (moe.cc:137-163): flattened input -> moe layer
(num_exp experts, top-k select, load-balance loss) -> softmax head; the
encoder variant stacks attention + MoE blocks (create_moe_encoder,
moe.cc:100-127). Dynamic expert rebalance via recompile_on_condition is
exercised in tests/test_aux_subsystems-style flows.
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class MoEConfig:
    batch_size: int = 32
    input_dim: int = 784  # reference uses MNIST-shaped input
    num_classes: int = 10
    num_exp: int = 4
    num_select: int = 2
    hidden_size: int = 64
    alpha: float = 2.0      # group_by capacity factor
    lambda_bal: float = 0.04  # load-balance loss weight
    # encoder variant
    num_encoder_layers: int = 0
    seq_length: int = 16
    num_attention_heads: int = 4


def create_moe(cfg: MoEConfig, ff_config: FFConfig = None) -> FFModel:
    """Flat MoE classifier (moe.cc:159-167)."""
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    t = ff.create_tensor((cfg.batch_size, cfg.input_dim), name="input")
    t = ff.moe(t, cfg.num_exp, cfg.num_select, cfg.hidden_size,
               cfg.alpha, cfg.lambda_bal, name="moe")
    t = ff.dense(t, cfg.num_classes, name="head")
    t = ff.softmax(t)
    return ff


def create_moe_encoder(cfg: MoEConfig, ff_config: FFConfig = None) -> FFModel:
    """Attention + MoE encoder stack (create_moe_encoder, moe.cc:100-127):
    each block is LN(x + attention(x)) then LN(x + moe(x))."""
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    x = ff.create_tensor((cfg.batch_size, cfg.seq_length, cfg.hidden_size),
                         name="input")
    for i in range(max(cfg.num_encoder_layers, 1)):
        a = ff.multihead_attention(x, x, x, cfg.hidden_size,
                                   cfg.num_attention_heads, name=f"attn_{i}")
        x = ff.layer_norm(ff.add(x, a, name=f"res1_{i}"), name=f"ln1_{i}")
        # token-level MoE: flatten tokens into the sample dim
        b, s, h = x.shape
        flat = ff.reshape(x, (b * s, h), name=f"flatten_{i}")
        m = ff.moe(flat, cfg.num_exp, cfg.num_select, cfg.hidden_size,
                   cfg.alpha, cfg.lambda_bal, name=f"moe_{i}")
        m = ff.reshape(m, (b, s, h), name=f"unflatten_{i}")
        x = ff.layer_norm(ff.add(x, m, name=f"res2_{i}"), name=f"ln2_{i}")
    x = ff.dense(x, cfg.num_classes, name="head")
    x = ff.softmax(x)
    return ff

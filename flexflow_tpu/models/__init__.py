"""Model zoo: the reference's example models rebuilt through the FFModel API.

Mirrors examples/cpp/{Transformer,AlexNet,ResNet,InceptionV3,DLRM,XDL,
mixture_of_experts,candle_uno,MLP_Unify,resnext50} with the same
architecture configs, so benchmark protocols carry over (SURVEY §6).
"""

from flexflow_tpu.models.transformer import create_transformer, TransformerConfig
from flexflow_tpu.models.mlp import create_mlp
from flexflow_tpu.models.alexnet import create_alexnet
from flexflow_tpu.models.dlrm import create_dlrm, DLRMConfig
from flexflow_tpu.models.resnet import create_resnet, ResNetConfig
from flexflow_tpu.models.resnext import create_resnext50, ResNeXtConfig
from flexflow_tpu.models.inception import create_inception_v3, InceptionConfig
from flexflow_tpu.models.candle_uno import create_candle_uno, CandleUnoConfig
from flexflow_tpu.models.xdl import create_xdl, XDLConfig
from flexflow_tpu.models.moe_model import create_moe, create_moe_encoder, MoEConfig
from flexflow_tpu.models.llama import (create_llama, import_hf_weights,
                                       LlamaModelConfig)

__all__ = [
    "create_transformer",
    "TransformerConfig",
    "create_mlp",
    "create_alexnet",
    "create_dlrm",
    "DLRMConfig",
    "create_resnet",
    "ResNetConfig",
    "create_resnext50",
    "ResNeXtConfig",
    "create_inception_v3",
    "InceptionConfig",
    "create_candle_uno",
    "CandleUnoConfig",
    "create_xdl",
    "XDLConfig",
    "create_moe",
    "create_moe_encoder",
    "MoEConfig",
    "create_llama", "import_hf_weights", "LlamaModelConfig",
]

"""Model zoo: the reference's example models rebuilt through the FFModel API.

Mirrors examples/cpp/{Transformer,AlexNet,ResNet,InceptionV3,DLRM,XDL,
mixture_of_experts,candle_uno,MLP_Unify,resnext50} with the same
architecture configs, so benchmark protocols carry over (SURVEY §6).
"""

from flexflow_tpu.models.transformer import create_transformer, TransformerConfig
from flexflow_tpu.models.mlp import create_mlp
from flexflow_tpu.models.alexnet import create_alexnet
from flexflow_tpu.models.dlrm import create_dlrm, DLRMConfig

__all__ = [
    "create_transformer",
    "TransformerConfig",
    "create_mlp",
    "create_alexnet",
    "create_dlrm",
    "DLRMConfig",
]

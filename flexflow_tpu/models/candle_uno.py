"""CANDLE-Uno (examples/cpp/candle_uno/candle_uno.cc).

Drug-response model: per-feature-type encoder towers (8x4192 dense, no
bias — candle_uno.cc:50-56), shared across inputs of the same feature kind
(dose / cell.rnaseq / drug.descriptors / drug.fingerprints,
candle_uno.cc:40-46), concatenated then a 4x4192 trunk to a single
regression output.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class CandleUnoConfig:
    batch_size: int = 64
    dense_layers: Sequence[int] = (4192,) * 4
    dense_feature_layers: Sequence[int] = (4192,) * 8
    # feature name -> (kind, input dim); kinds sharing an encoder tower
    # in the reference share structure (we keep separate weights per input,
    # as the reference's FFModel does — sharing happens at the shape level)
    input_features: Dict[str, int] = dataclasses.field(default_factory=lambda: {
        "dose1": 1, "dose2": 1, "cell_rnaseq": 942,
        "drug1_descriptors": 5270, "drug1_fingerprints": 2048,
        "drug2_descriptors": 5270, "drug2_fingerprints": 2048,
    })


def _feature_model(ff: FFModel, t, layers: Sequence[int], name: str):
    for i, width in enumerate(layers):
        t = ff.dense(t, width, activation=ActiMode.AC_MODE_RELU,
                     use_bias=False, name=f"{name}_d{i}")
    return t


def create_candle_uno(cfg: CandleUnoConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    encoded = []
    for fname, dim in cfg.input_features.items():
        t = ff.create_tensor((cfg.batch_size, dim), name=fname)
        encoded.append(_feature_model(ff, t, cfg.dense_feature_layers,
                                      f"enc_{fname}"))
    t = ff.concat(encoded, axis=-1, name="concat_features")
    for i, width in enumerate(cfg.dense_layers):
        t = ff.dense(t, width, activation=ActiMode.AC_MODE_RELU,
                     use_bias=False, name=f"trunk_d{i}")
    t = ff.dense(t, 1, name="out")  # growth-rate regression
    return ff

"""Llama-family decoder LM (BASELINE.md stretch target).

No counterpart exists in the reference's example zoo — this is new scope:
RMSNorm, rotary position embeddings, grouped-query attention, and SwiGLU
MLPs, built from the framework's own ops so the auto-parallelization
search sees a normal PCG (attention head axis shardable, seq axis
ring-shardable, batch data-parallel). ``import_hf_weights`` loads a
HuggingFace ``LlamaForCausalLM`` state dict for numerics parity
(tests/test_llama.py checks logits against the HF forward).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class LlamaModelConfig:
    # defaults are a test-size model; Llama-3-8B would be
    # hidden 4096 / inter 14336 / 32 layers / 32 heads / 8 kv heads /
    # vocab 128256 / theta 500000
    vocab_size: int = 256
    hidden_size: int = 64
    intermediate_size: int = 128
    num_hidden_layers: int = 2
    num_attention_heads: int = 4
    num_key_value_heads: int = 2
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    batch_size: int = 4
    seq_length: int = 16
    seq_parallel: Optional[str] = None  # 'seq' for ring attention


def create_llama(cfg: LlamaModelConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    from flexflow_tpu.ffconst import DataType

    ids = ff.create_tensor((cfg.batch_size, cfg.seq_length),
                           dtype=DataType.INT32, name="input_ids")
    t = ff.embedding(ids, cfg.vocab_size, cfg.hidden_size,
                     name="embed_tokens")
    for i in range(cfg.num_hidden_layers):
        # attention sublayer (pre-norm, causal, RoPE, GQA)
        h = ff.rms_norm(t, eps=cfg.rms_norm_eps, name=f"l{i}_input_ln")
        a = ff.multihead_attention(
            h, h, h, cfg.hidden_size, cfg.num_attention_heads,
            bias=False, causal=True,
            num_kv_heads=cfg.num_key_value_heads,
            rope=True, rope_theta=cfg.rope_theta,
            seq_parallel=cfg.seq_parallel,
            name=f"l{i}_attn")
        t = ff.add(t, a, name=f"l{i}_res1")
        # SwiGLU MLP: down(silu(gate(x)) * up(x))
        h = ff.rms_norm(t, eps=cfg.rms_norm_eps, name=f"l{i}_post_ln")
        gate = ff.dense(h, cfg.intermediate_size, use_bias=False,
                        name=f"l{i}_gate_proj")
        up = ff.dense(h, cfg.intermediate_size, use_bias=False,
                      name=f"l{i}_up_proj")
        silu = ff.multiply(gate, ff.sigmoid(gate, name=f"l{i}_sig"),
                           name=f"l{i}_silu")
        h = ff.multiply(silu, up, name=f"l{i}_swiglu")
        h = ff.dense(h, cfg.hidden_size, use_bias=False,
                     name=f"l{i}_down_proj")
        t = ff.add(t, h, name=f"l{i}_res2")
    t = ff.rms_norm(t, eps=cfg.rms_norm_eps, name="final_ln")
    t = ff.dense(t, cfg.vocab_size, use_bias=False, name="lm_head")
    return ff


def import_hf_weights(ff: FFModel, hf_model) -> int:
    """Copy a HuggingFace ``LlamaForCausalLM``'s weights into a compiled
    ``create_llama`` model. Returns the number of tensors copied."""
    import numpy as np

    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = hf_model.config
    h = cfg.num_attention_heads
    hk = getattr(cfg, "num_key_value_heads", h)
    e = cfg.hidden_size
    d = e // h

    def heads(w, nh):  # HF [nh*D, E] -> ours [nh, E, D]
        return w.reshape(nh, d, -1).transpose(0, 2, 1)

    copied = 0

    def put(layer, value, pname="kernel"):
        nonlocal copied
        ff.set_parameter(layer, np.ascontiguousarray(value, np.float32),
                         pname)
        copied += 1

    put("embed_tokens", sd["model.embed_tokens.weight"])
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        put(f"l{i}_input_ln", sd[p + "input_layernorm.weight"], "scale")
        put(f"l{i}_attn", heads(sd[p + "self_attn.q_proj.weight"], h), "wq")
        put(f"l{i}_attn", heads(sd[p + "self_attn.k_proj.weight"], hk), "wk")
        put(f"l{i}_attn", heads(sd[p + "self_attn.v_proj.weight"], hk), "wv")
        # o_proj [E, H*D] -> wo [H, D, E]
        put(f"l{i}_attn",
            sd[p + "self_attn.o_proj.weight"].transpose(1, 0).reshape(h, d, e),
            "wo")
        put(f"l{i}_post_ln",
            sd[p + "post_attention_layernorm.weight"], "scale")
        put(f"l{i}_gate_proj", sd[p + "mlp.gate_proj.weight"].T)
        put(f"l{i}_up_proj", sd[p + "mlp.up_proj.weight"].T)
        put(f"l{i}_down_proj", sd[p + "mlp.down_proj.weight"].T)
    put("final_ln", sd["model.norm.weight"], "scale")
    lm = sd.get("lm_head.weight")
    if lm is None:  # tied embeddings
        lm = sd["model.embed_tokens.weight"]
    put("lm_head", lm.T)
    return copied

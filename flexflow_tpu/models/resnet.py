"""ResNet-50 (examples/cpp/ResNet/resnet.cc).

Bottleneck: 1x1 conv -> 3x3 (stride) -> 1x1 to 4x expansion, projection
shortcut on stride/width change, ReLU join (resnet.cc:39-58); stem
7x7/s2 + 3x3 maxpool; stages [3,4,6,3]; avgpool -> flat -> dense(10)
(resnet.cc:91-112 — the reference's CIFAR-style 10-way head).
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


@dataclasses.dataclass
class ResNetConfig:
    batch_size: int = 64
    image_size: int = 224
    num_classes: int = 10  # reference uses 10 (resnet.cc:112)
    stages: tuple = (3, 4, 6, 3)
    # True = textbook ResNet (conv→BN→relu everywhere): the reference
    # example omits BN, so this is opt-in for parity with resnet.cc —
    # but it is the zoo's canonical Conv+BN-fold (serving predict) path
    batch_norm: bool = False


def _conv_bn(ff: FFModel, t, out_channels: int, kh: int, kw: int,
             stride: int, pad: int, name: str, bn: bool, relu: bool):
    if bn:
        t = ff.conv2d(t, out_channels, kh, kw, stride, stride, pad, pad,
                      name=name)
        return ff.batch_norm(t, relu=relu, name=f"{name}_bn")
    t = ff.conv2d(t, out_channels, kh, kw, stride, stride, pad, pad,
                  activation=ActiMode.AC_MODE_RELU if relu
                  else ActiMode.AC_MODE_NONE, name=name)
    return t


def _bottleneck(ff: FFModel, t, out_channels: int, stride: int, name: str,
                bn: bool = False):
    inp = t
    t = _conv_bn(ff, t, out_channels, 1, 1, 1, 0, f"{name}_c1", bn, False)
    t = ff.relu(t)
    t = _conv_bn(ff, t, out_channels, 3, 3, stride, 1, f"{name}_c2", bn,
                 False)
    t = ff.relu(t)
    t = _conv_bn(ff, t, 4 * out_channels, 1, 1, 1, 0, f"{name}_c3", bn,
                 False)
    if stride > 1 or inp.shape[1] != 4 * out_channels:
        # projection shortcut has no activation (resnet.cc:53, AC_MODE_NONE)
        inp = _conv_bn(ff, inp, 4 * out_channels, 1, 1, stride, 0,
                       f"{name}_proj", bn, False)
    t = ff.add(t, inp, name=f"{name}_add")
    return ff.relu(t, inplace=False)


def create_resnet(cfg: ResNetConfig, ff_config: FFConfig = None) -> FFModel:
    ff = FFModel(ff_config or FFConfig(batch_size=cfg.batch_size))
    bn = cfg.batch_norm
    t = ff.create_tensor((cfg.batch_size, 3, cfg.image_size, cfg.image_size),
                         name="input")
    t = _conv_bn(ff, t, 64, 7, 7, 2, 3, "stem", bn, bn)
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    for i in range(cfg.stages[0]):
        t = _bottleneck(ff, t, 64, 1, f"s1_b{i}", bn)
    for i in range(cfg.stages[1]):
        t = _bottleneck(ff, t, 128, 2 if i == 0 else 1, f"s2_b{i}", bn)
    for i in range(cfg.stages[2]):
        t = _bottleneck(ff, t, 256, 2 if i == 0 else 1, f"s3_b{i}", bn)
    for i in range(cfg.stages[3]):
        t = _bottleneck(ff, t, 512, 2 if i == 0 else 1, f"s4_b{i}", bn)
    t = ff.pool2d(t, t.shape[2], t.shape[3], 1, 1, 0, 0,
                  pool_type=PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, cfg.num_classes, name="fc")
    t = ff.softmax(t)
    return ff

"""Machine description: TPU chips, ICI/DCN topology, mesh construction.

Re-design of the reference's ``MachineView``/``MachineResource``
(include/flexflow/machine_view.h:14,51) and the machine models used by the
simulator (include/flexflow/simulator.h:212-515). On TPU the device grid is
a named ``jax.sharding.Mesh``; a MachineView names the sub-grid an op runs
on via (start, dims, strides) for search parity, and the machine spec
carries the analytic parameters (FLOP/s, HBM BW, ICI/DCN link BW) the cost
model needs (analog of machine_config_example:1-40).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class MachineView:
    """Device sub-grid assignment of one op (machine_view.h:14).

    ``dim[i]``/``stride[i]`` enumerate device ids
    ``start_device_id + sum_i k_i * stride_i`` for ``k_i < dim[i]`` — same
    encoding as the reference so strategy files round-trip.
    """

    start_device_id: int
    dim: Tuple[int, ...]
    stride: Tuple[int, ...]

    @property
    def ndims(self) -> int:
        return len(self.dim)

    def num_parts(self) -> int:
        return math.prod(self.dim) if self.dim else 1

    def device_ids(self) -> Tuple[int, ...]:
        ids = [self.start_device_id]
        for d, s in zip(self.dim, self.stride):
            ids = [i + k * s for i in ids for k in range(d)]
        return tuple(sorted(ids))

    def hash(self) -> int:
        h = hash((self.start_device_id, self.dim, self.stride))
        return h & 0x7FFFFFFFFFFFFFFF

    @classmethod
    def single_device(cls, device_id: int = 0) -> "MachineView":
        return cls(device_id, (1,), (1,))

    @classmethod
    def all_devices(cls, num_devices: int) -> "MachineView":
        return cls(0, (num_devices,), (1,))


# Analytic chip specs for the TPU generations we model. Numbers are public
# datasheet figures (bf16 peak FLOP/s, HBM bytes/s, HBM capacity, per-link
# ICI bytes/s each direction, links per chip).
CHIP_SPECS: Dict[str, Dict[str, float]] = {
    "tpu-v4": dict(flops=275e12, hbm_bw=1.23e12, hbm_cap=32e9, ici_bw=45e9, ici_links=6),
    "tpu-v5e": dict(flops=197e12, hbm_bw=0.82e12, hbm_cap=16e9, ici_bw=45e9, ici_links=4),
    "tpu-v5p": dict(flops=459e12, hbm_bw=2.77e12, hbm_cap=95e9, ici_bw=90e9, ici_links=6),
    "tpu-v6e": dict(flops=918e12, hbm_bw=1.64e12, hbm_cap=32e9, ici_bw=90e9, ici_links=4),
    "cpu-sim": dict(flops=1e12, hbm_bw=100e9, hbm_cap=16e9, ici_bw=10e9, ici_links=4),
}


def _factor_torus(n: int, dims: int) -> Tuple[int, ...]:
    """Near-equal `dims`-way factorization of a slice's chip count into
    torus extents, largest first (e.g. 32 chips, 3-D -> (4, 4, 2) — the
    real v4-32 topology). Falls back to fewer dims when n doesn't split."""
    if n <= 1:
        return (n,)
    out = []
    rem = n
    for i in range(dims, 1, -1):
        target = max(1, round(rem ** (1.0 / i)))
        f = max(d for d in range(1, target + 1) if rem % d == 0)
        if f > 1:
            out.append(f)
            rem //= f
    out.append(rem)
    return tuple(sorted((x for x in out if x > 1), reverse=True)) or (n,)


@dataclasses.dataclass
class MachineSpec:
    """One slice (ICI domain) of ``num_nodes`` DCN-connected slices.

    Replaces SimpleMachineModel/EnhancedMachineModel/NetworkedMachineModel
    (simulator.h:212,229,279,515): TPU topology is a torus, so instead of an
    adjacency matrix we carry per-axis torus extents and link bandwidths.
    """

    chip: str = "tpu-v5e"
    chips_per_slice: int = 1
    num_slices: int = 1
    torus: Optional[Tuple[int, ...]] = None  # e.g. (4, 4) for v5e-16
    dcn_bw: float = 25e9  # bytes/s per slice pair
    ici_latency: float = 1e-6
    dcn_latency: float = 10e-6
    mxu_efficiency: float = 0.55  # achieved fraction of peak on real shapes
    # conv-class asymptote: convs don't reach matmul-grade MXU utilization
    # even channels-last (im2col padding, halo reads, ragged spatial
    # extents) — the search priced them at mxu_efficiency and every conv
    # cost it produced was ~5x optimistic (inception_proxy measured ~7%
    # MFU, bench_history). Calibrate from scripts/roofline.py per-class
    # aggregates; measured per-op tables still override the analytic model.
    conv_efficiency: float = 0.35
    min_op_time: float = 5e-7     # per-kernel dispatch overhead (seconds)
    # per-bucket launch cost of an async (bucketed) collective: the
    # start/done pair XLA schedules around a hidden collective still
    # costs a dispatch plus the ring's first-hop latency — the '_ovl'
    # latency-hiding pricing charges it once per bucket
    collective_launch_overhead: float = 2e-6
    # Arbitrary inter-slice fabric (the reference NetworkedMachineModel's
    # role, simulator.h:515 + network.cc ECMP routing, re-expressed
    # TPU-first): explicit slice-pair links [(i, j, bytes_per_s), ...].
    # None = uniform all-to-all at dcn_bw. Cross-slice ring collectives
    # are bottleneck-bound, so the topology reduces to an effective
    # (bandwidth, latency) for the slice ring: per consecutive pair the
    # shortest path is routed (missing direct links hop through
    # intermediate slices), the pair's bandwidth is the min link on the
    # path, and the ring's effective bandwidth is the bottleneck pair.
    dcn_links: Optional[Sequence[Tuple[int, int, float]]] = None
    # measured per-collective-kind correction factors (kind ->
    # measured/predicted ratio) from CALIBRATION.json
    # ``collective_corrections`` — the device-trace attribution's
    # calibration of these analytic ring formulas
    # (scripts/calibrate.py --ingest-drift derives them; see
    # load_collective_corrections). None/{} = uncalibrated.
    collective_corrections: Optional[Dict[str, float]] = None

    def __post_init__(self):
        if self.torus is None:
            # default per-generation ICI topology: v4/v5p slices are 3-D
            # tori, v5e/v6e are 2-D meshes. A 1-tuple means "flat /
            # unspecified" — the native model prices all axes alike then.
            dims = 3 if self.chip in ("tpu-v4", "tpu-v5p") else 2
            self.torus = _factor_torus(self.chips_per_slice, dims)
        spec = CHIP_SPECS[self.chip]
        self.flops = spec["flops"]
        self.hbm_bw = spec["hbm_bw"]
        self.hbm_cap = spec["hbm_cap"]
        self.ici_bw = spec["ici_bw"]

    # keys a --machine-model-file may set, with unit conversions from the
    # reference's GB/s + ms conventions where they map
    _FILE_KEYS = {
        "chip": ("chip", str),
        "chips_per_slice": ("chips_per_slice", int),
        "num_slices": ("num_slices", int),
        "flops": ("flops", float),
        "hbm_bw": ("hbm_bw", float),
        "hbm_cap": ("hbm_cap", float),
        "ici_bw": ("ici_bw", float),
        "ici_latency": ("ici_latency", float),
        "dcn_bw": ("dcn_bw", float),
        "dcn_latency": ("dcn_latency", float),
        "mxu_efficiency": ("mxu_efficiency", float),
        "conv_efficiency": ("conv_efficiency", float),
        "min_op_time": ("min_op_time", float),
        "collective_launch_overhead": ("collective_launch_overhead", float),
        # per-slice ICI torus extents: JSON list or "4 2" in key=value form
        "torus": ("torus",
                  lambda v: tuple(int(x) for x in
                                  (v.split() if isinstance(v, str) else v))),
        # reference machine_config_example vocabulary (GB/s, ms):
        # nodes = DCN domains; nvlink = intra-node device link -> ICI;
        # nic = inter-node link -> DCN
        "num_nodes": ("num_slices", int),
        "nvlink_bandwidth": ("ici_bw", lambda v: float(v) * 1e9),
        "nvlink_latency": ("ici_latency", lambda v: float(v) * 1e-3),
        "nic_bandwidth": ("dcn_bw", lambda v: float(v) * 1e9),
        "nic_latency": ("dcn_latency", lambda v: float(v) * 1e-3),
        # arbitrary inter-slice fabric: [[i, j, bytes_per_s], ...]
        # (NetworkedMachineModel's adjacency-matrix role, simulator.h:515)
        "dcn_links": ("dcn_links",
                      lambda v: [(int(i), int(j), float(bw))
                                 for i, j, bw in v]),
    }

    @classmethod
    def from_file(cls, path: str) -> "MachineSpec":
        """Parse a --machine-model-file: JSON with this class's field
        names, or the reference's ``key = value`` format
        (machine_config_example) with its GPU-era keys mapped onto the
        TPU model (nvlink→ICI, nic→DCN, num_nodes→slices). Unknown keys
        are ignored, as the reference's parser does."""
        import json as _json

        with open(path) as f:
            text = f.read()
        values: Dict[str, object] = {}
        try:
            data = _json.loads(text)
            if isinstance(data, dict):
                values = data
        except ValueError:
            for line in text.splitlines():
                line = line.split("#", 1)[0].strip()
                if "=" not in line:
                    continue
                k, v = (s.strip() for s in line.split("=", 1))
                if k == "dcn_link":
                    # repeatable: "dcn_link = i j bytes_per_s"
                    i, j, bw = v.split()
                    values.setdefault("dcn_links", []).append(
                        [int(i), int(j), float(bw)])
                else:
                    values[k] = v
        init = {}
        overrides = {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        for key, raw in values.items():
            mapped = cls._FILE_KEYS.get(key)
            if mapped is None:
                continue
            name, conv = mapped
            val = conv(raw)
            if name in field_names:
                init[name] = val
            else:
                overrides[name] = val  # flops/hbm_bw/...: post-init attrs
        spec = cls(**init)
        for name, val in overrides.items():
            setattr(spec, name, val)
        return spec

    @property
    def num_devices(self) -> int:
        return self.chips_per_slice * self.num_slices

    def effective_dcn(self) -> Tuple[float, float]:
        """(bandwidth, latency) of the cross-slice ring under the
        explicit fabric, or the uniform defaults when none is given.

        For each consecutive ring pair (i, i+1 mod S): route the
        shortest path over the link graph (ECMP-role reduction:
        hop-count shortest, bottleneck bandwidth); the ring is paced by
        its slowest pair, and latency scales with the longest routed
        path. Unreachable pairs fall back to the uniform dcn_bw with a
        2-hop penalty (the fabric must be connected through a spine)."""
        if not self.dcn_links or self.num_slices <= 1:
            return self.dcn_bw, self.dcn_latency
        S = self.num_slices
        adj: Dict[int, Dict[int, float]] = {i: {} for i in range(S)}
        for i, j, bw in self.dcn_links:
            i, j, bw = int(i), int(j), float(bw)
            if i == j or i >= S or j >= S:
                continue
            adj[i][j] = max(adj[i].get(j, 0.0), bw)
            adj[j][i] = max(adj[j].get(i, 0.0), bw)

        def route(a: int, b: int) -> Tuple[int, float]:
            """(hops, bottleneck bw) of the hop-shortest (then
            widest-bottleneck) a->b path — Bellman-Ford relaxation."""
            best = {a: (0, float("inf"))}
            for _ in range(S):
                changed = False
                for u, (h, bw) in list(best.items()):
                    for v, link_bw in adj[u].items():
                        cand = (h + 1, min(bw, link_bw))
                        cur = best.get(v)
                        if cur is None or cand[0] < cur[0] or (
                                cand[0] == cur[0] and cand[1] > cur[1]):
                            best[v] = cand
                            changed = True
                if not changed:
                    break
            return best.get(b, (2, self.dcn_bw))

        worst_bw = float("inf")
        worst_hops = 1
        for i in range(S):
            hops, bw = route(i, (i + 1) % S)
            worst_bw = min(worst_bw, bw)
            worst_hops = max(worst_hops, hops)
        if not np.isfinite(worst_bw):
            worst_bw = self.dcn_bw
        return worst_bw, self.dcn_latency * worst_hops

    def ici_allreduce_time(self, bytes_: int, num_chips: int) -> float:
        """Bidirectional-ring allreduce cost over ICI: 2(n-1)/n * B / bw."""
        if num_chips <= 1:
            return 0.0
        eff_bw = self.ici_bw * 2  # bidirectional links
        return self.ici_latency * (num_chips - 1) + (
            2 * (num_chips - 1) / num_chips
        ) * bytes_ / eff_bw

    def ici_allgather_time(self, bytes_out: int, num_chips: int) -> float:
        if num_chips <= 1:
            return 0.0
        eff_bw = self.ici_bw * 2
        return self.ici_latency * (num_chips - 1) + (
            (num_chips - 1) / num_chips
        ) * bytes_out / eff_bw

    def ici_alltoall_time(self, bytes_: int, num_chips: int) -> float:
        if num_chips <= 1:
            return 0.0
        return self.ici_latency + bytes_ * (num_chips - 1) / num_chips / (
            self.ici_bw * 2
        )

    def slices_spanned(self, num_chips: int) -> int:
        """How many slices a ``num_chips`` collective group crosses.
        1 = fits inside one ICI domain (pure ICI pricing)."""
        if self.num_slices <= 1 or self.chips_per_slice <= 0:
            return 1
        if num_chips <= self.chips_per_slice:
            return 1
        return min(self.num_slices,
                   -(-num_chips // self.chips_per_slice))

    def dcn_collective_time(self, kind: str, bytes_: float,
                            slices: int) -> float:
        """Ring-collective cost over the cross-slice DCN fabric:
        ``slices`` participants (one leader chip per slice), paced by
        ``effective_dcn()``'s bottleneck (bandwidth, latency)."""
        k = int(slices)
        if k <= 1:
            return 0.0
        bw, lat = self.effective_dcn()
        if kind == "all-reduce":
            return lat * (k - 1) + (2 * (k - 1) / k) * bytes_ / bw
        if kind in ("reduce-scatter", "all-gather"):
            return lat * (k - 1) + ((k - 1) / k) * bytes_ / bw
        if kind == "all-to-all":
            return lat + bytes_ * (k - 1) / k / bw
        if kind == "collective-permute":
            return lat + bytes_ / bw
        return lat * (k - 1) + (2 * (k - 1) / k) * bytes_ / bw

    def hier_collective_time(self, kind: str, bytes_: float,
                             num_chips: int) -> float:
        """Two-level decomposition of a collective whose group spans
        slices — the multislice pricing rule (native twin:
        ``hier_allreduce_time`` in ffs_machine.hpp).

        Allreduce: intra-slice reduce-scatter at ICI + cross-slice
        allreduce of the 1/chips_per_slice shard at DCN + intra-slice
        all-gather at ICI. The other kinds decompose analogously: the
        intra-slice leg runs at ICI over ``chips_per_slice`` chips and
        the cross-slice leg moves the per-slice shard over the DCN
        ring. Bytes follow ``collective_time``'s census conventions
        (per-partition payloads; reduce-scatter counts per-shard OUTPUT
        bytes)."""
        inner = min(self.chips_per_slice, num_chips)
        k = self.slices_spanned(num_chips)
        if k <= 1:
            return self.collective_time(kind, bytes_, num_chips)
        if kind == "all-reduce":
            return (self.ici_allreduce_time(bytes_, inner) / 2
                    + self.dcn_collective_time(kind, bytes_ / inner, k)
                    + self.ici_allgather_time(bytes_, inner))
        if kind == "reduce-scatter":
            full = bytes_ * num_chips  # census counted per-shard output
            return (self.ici_allreduce_time(full, inner) / 2
                    + self.dcn_collective_time(kind, full / inner, k))
        if kind == "all-gather":
            return (self.dcn_collective_time(kind, bytes_ / inner, k)
                    + self.ici_allgather_time(bytes_, inner))
        if kind == "all-to-all":
            return (self.dcn_collective_time(kind, bytes_, k)
                    + self.ici_alltoall_time(bytes_, inner))
        if kind == "collective-permute":
            # the ring wrap hop crosses slices — DCN-paced
            return self.dcn_collective_time(kind, bytes_, k)
        return (self.ici_allreduce_time(bytes_, inner) / 2
                + self.dcn_collective_time("all-reduce", bytes_ / inner, k)
                + self.ici_allgather_time(bytes_, inner))

    def collective_time(self, kind: str, bytes_: float,
                        num_chips: int) -> float:
        """Analytic time for ``bytes_`` moved by one HLO collective kind
        (the census vocabulary of flexflow_tpu/obs/inspect.py) over an
        ``num_chips`` ICI ring. Used by the drift reporter to price the
        compiled step's REAL collective census through the same machine
        model the search's simulator uses. Census bytes are
        per-partition (SPMD module), which matches these formulas'
        per-chip payload convention.

        When the group spans slices (``num_chips > chips_per_slice`` on
        a multi-slice spec) the hierarchical ICI+DCN decomposition
        prices it instead — any collective that crosses the slice
        boundary pays DCN rates for the cross-slice leg.

        When ``collective_corrections`` carries a measured factor for
        ``kind`` (device-trace attribution calibration,
        ``scripts/calibrate.py --ingest-drift``), the analytic time is
        scaled by it — the wus_rs/ag_time measured hook (ROADMAP chip
        item (a))."""
        if num_chips <= 1:
            return 0.0
        if self.slices_spanned(num_chips) > 1:
            t = self.hier_collective_time(kind, bytes_, num_chips)
            if self.collective_corrections:
                t *= self.collective_corrections.get(kind, 1.0)
            return t
        if kind == "all-reduce":
            t = self.ici_allreduce_time(bytes_, num_chips)
        elif kind == "reduce-scatter":
            # first half of XLA's large-AR decomposition: half the AR
            # ring cost of the FULL payload. The census counted the op's
            # per-shard OUTPUT bytes (1/n of the reduced buffer), so
            # scale back up before applying the AR formula.
            t = self.ici_allreduce_time(bytes_ * num_chips,
                                        num_chips) / 2
        elif kind == "all-gather":
            t = self.ici_allgather_time(bytes_, num_chips)
        elif kind == "all-to-all":
            t = self.ici_alltoall_time(bytes_, num_chips)
        elif kind == "collective-permute":
            # one neighbor hop, full payload over a bidirectional link
            t = self.ici_latency + bytes_ / (self.ici_bw * 2)
        else:
            # unknown kind: price conservatively as an allreduce
            t = self.ici_allreduce_time(bytes_, num_chips)
        if self.collective_corrections:
            t *= self.collective_corrections.get(kind, 1.0)
        return t

    def dcn_allreduce_time(self, bytes_: int) -> float:
        if self.num_slices <= 1:
            return 0.0
        n = self.num_slices
        return self.dcn_latency * (n - 1) + (2 * (n - 1) / n) * bytes_ / self.dcn_bw

    def matmul_time(self, flops: int, dtype_size: int = 2) -> float:
        # MXU peak assumed for bf16; f32 halves throughput
        peak = self.flops if dtype_size <= 2 else self.flops / 2
        return flops / peak

    def memory_time(self, bytes_: int) -> float:
        return bytes_ / self.hbm_bw


def load_collective_corrections(platform: str,
                                path: Optional[str] = None
                                ) -> Dict[str, float]:
    """Measured per-collective-kind factors (kind -> measured/predicted
    ratio) from CALIBRATION.json ``collective_corrections`` for one
    PLATFORM bucket (the jax platform string that traced them, e.g.
    "tpu"). Empty dict when the file or bucket is absent — callers
    treat that as uncalibrated."""
    import json
    import os

    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "CALIBRATION.json")
    try:
        with open(path) as f:
            cal = json.load(f)
    except (OSError, ValueError):
        return {}
    bucket = (cal.get("collective_corrections") or {}).get(platform) or {}
    out: Dict[str, float] = {}
    for kind, e in bucket.items():
        try:
            out[kind] = float(e["factor"] if isinstance(e, dict) else e)
        except (KeyError, TypeError, ValueError):
            continue
    return out


def detect_machine_spec(num_devices: Optional[int] = None,
                        slices: int = 1) -> MachineSpec:
    """Build a MachineSpec from the live JAX backend (used at compile
    time). ``slices > 1`` splits the detected chips into that many
    DCN-connected slices (``FFConfig --slices``): chips_per_slice =
    n // slices, with the per-generation default ICI torus factored
    per SLICE rather than over the flat device count. On a real chip,
    measured per-collective calibration from CALIBRATION.json engages
    automatically (platform-gated like search/profile's op corrections;
    FFS_NO_DRIFT_CORRECTIONS opts out) — CPU runs never pick up chip
    factors or vice versa."""
    import os

    import jax

    devs = jax.devices()
    n = num_devices or len(devs)
    s = max(1, int(slices))
    if s > 1 and n % s != 0:
        raise ValueError(
            f"--slices {s} does not divide the {n} visible devices")
    kind = devs[0].device_kind.lower() if devs else "cpu"
    if "v5 lite" in kind or "v5e" in kind:
        chip = "tpu-v5e"
    elif "v5p" in kind or "v5" in kind:
        chip = "tpu-v5p"
    elif "v4" in kind:
        chip = "tpu-v4"
    elif "v6" in kind:
        chip = "tpu-v6e"
    else:
        chip = "cpu-sim"
    spec = MachineSpec(chip=chip, chips_per_slice=n // s, num_slices=s)
    platform = devs[0].platform if devs else "cpu"
    if platform != "cpu" and not os.environ.get("FFS_NO_DRIFT_CORRECTIONS"):
        corr = load_collective_corrections(platform)
        if corr:
            spec.collective_corrections = corr
    return spec


def make_mesh(num_devices: int, axes: Dict[str, int]):
    """Create a named ``jax.sharding.Mesh`` over the first ``num_devices``.

    ``axes`` maps axis name -> extent; product must equal num_devices.
    Canonical axis names: 'data' (sample dim), 'model' (parameter/attribute
    dims), 'seq' (sequence/context parallelism), 'expert' (MoE).
    """
    import jax
    from jax.sharding import Mesh

    sizes = tuple(axes.values())
    if math.prod(sizes) != num_devices:
        raise ValueError(f"mesh axes {axes} != {num_devices} devices")
    devs = np.array(jax.devices()[:num_devices]).reshape(sizes)
    return Mesh(devs, tuple(axes.keys()))

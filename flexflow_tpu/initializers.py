"""Parameter initializers.

Analog of include/flexflow/initializer.h:26-110 (Glorot/Zero/Uniform/
Normal/Constant); the reference runs them as Legion index tasks with
curand (initializer_kernel.cu) — here each is a pure function of a PRNG
key, executed sharded by GSPMD at init time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class Initializer:
    def __call__(self, rng: jax.Array, shape, dtype=jnp.float32) -> jax.Array:
        raise NotImplementedError


class ZeroInitializer(Initializer):
    def __call__(self, rng, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class ConstantInitializer(Initializer):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, rng, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class UniformInitializer(Initializer):
    def __init__(self, seed: int = 0, min_val: float = -0.05, max_val: float = 0.05):
        self.seed, self.min_val, self.max_val = seed, min_val, max_val

    def __call__(self, rng, shape, dtype=jnp.float32):
        return jax.random.uniform(rng, shape, dtype, self.min_val, self.max_val)


class NormInitializer(Initializer):
    def __init__(self, seed: int = 0, mean: float = 0.0, stddev: float = 1.0):
        self.seed, self.mean, self.stddev = seed, mean, stddev

    def __call__(self, rng, shape, dtype=jnp.float32):
        return self.mean + self.stddev * jax.random.normal(rng, shape, dtype)


class GlorotUniformInitializer(Initializer):
    """Glorot/Xavier uniform over (fan_in, fan_out) like the reference's
    GlorotUniform (initializer_kernel.cu glorot path)."""

    def __init__(self, seed: int = 0):
        self.seed = seed

    def __call__(self, rng, shape, dtype=jnp.float32):
        if len(shape) >= 2:
            fan_out = shape[-1]
            fan_in = int(np.prod(shape[:-1]))
        else:
            fan_in = fan_out = shape[0] if shape else 1
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        return jax.random.uniform(rng, shape, dtype, -limit, limit)


DefaultWeightInitializer = GlorotUniformInitializer
DefaultBiasInitializer = ZeroInitializer

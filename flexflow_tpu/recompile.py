"""Dynamic recompilation: mutate the model mid-training on a trigger.

Analog of the reference's RecompileState (include/flexflow/recompile.h:26)
and FFModel::recompile_on_condition (src/runtime/model.cc:2422-2426), used
there for MoE expert-capacity adaptation (examples/cpp/mixture_of_experts/
moe.cc:65-83). Under XLA "recompile" means: alter layer properties, rerun
``compile()`` (a fresh jitted step with new static shapes), and carry the
old parameters over where names+shapes still match.
"""

from __future__ import annotations

from typing import Callable, Optional


class RecompileState:
    """trigger_func() -> bool decides; alter_func(ff) mutates layer
    properties; both run between iterations (recompile.h:26 semantics)."""

    def __init__(self, trigger_func: Callable[[], bool],
                 alter_func: Callable[..., None], ffmodel=None):
        self.trigger_func = trigger_func
        self.alter_func = alter_func
        self.ffmodel = ffmodel
        self.recompilations = 0

    def trigger(self) -> bool:
        return bool(self.trigger_func())

    def alter(self) -> None:
        self.alter_func(self.ffmodel)
        self.recompilations += 1


def recompile_on_condition(ffmodel, state: RecompileState) -> bool:
    """If the trigger fires: snapshot params, alter, re-compile, restore
    matching params. Returns True when a recompile happened."""
    if not state.trigger():
        return False
    old_params = ffmodel.params
    optimizer = ffmodel.optimizer
    loss_type = ffmodel.loss_type
    metric_types = list(ffmodel.metrics.metrics)
    state.ffmodel = ffmodel
    state.alter()
    # re-derive tensor shapes through the altered layer list (alter_func
    # may have changed properties that move downstream shapes)
    from flexflow_tpu.ffconst import OperatorType
    from flexflow_tpu.ops import OpRegistry

    for layer in ffmodel.layers:
        if layer.op_type == OperatorType.INPUT:
            continue
        op = OpRegistry.create(layer, [t.shape for t in layer.inputs])
        for t, s in zip(layer.outputs, op.output_shapes):
            t.shape = tuple(s)
    iters_so_far = ffmodel._iter
    ffmodel.compile(optimizer, loss_type, metric_types,
                    comp_mode=ffmodel.config.computation_mode,
                    machine_spec=ffmodel.machine_spec,
                    mesh=ffmodel.mesh)  # keep the live mesh (and its axes)
    ffmodel._iter = iters_so_far  # compile() zeroes it; training continues
    # carry over parameters whose (name, shape) survived the alteration
    import numpy as np

    for lname, sub in old_params.items():
        if lname not in ffmodel.params:
            continue
        for pname, arr in sub.items():
            live = ffmodel.params[lname].get(pname)
            if live is not None and tuple(live.shape) == tuple(arr.shape):
                ffmodel.set_parameter(lname, np.asarray(arr), pname)
    return True

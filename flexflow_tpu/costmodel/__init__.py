"""Learned TPU cost model: measure -> learn -> search, closed.

The simulator's per-op compute pricing was a pile of hand-tuned
heuristics (the flat ``mxu_efficiency`` asymptote, the
``conv_efficiency`` conv-class scalar, the additive ``min_op_time``
dispatch floor) that CALIBRATION.json showed missing badly where
coverage is thin. Following "A Learned Performance Model for Tensor
Processing Units" (PAPERS.md, arXiv 2008.01040), this package trains a
small per-op-class regression on the accumulated measurement corpus —
the ``*.simtrace.json`` rows (op class x shape x sharding choice ->
priced terms -> measured seconds) the obs subsystem has been emitting
since PR 7, joined with roofline and devtrace-drift measurements — and
hands the trained table to the native search, which queries it where
coverage exists and falls back to the analytic terms elsewhere.

Layers:

- ``corpus``: trace-dir ingestion -> deduplicated, schema-versioned
  training corpus (``COSTMODEL_CORPUS.json``) + the featurization the
  native evaluator mirrors bit-for-bit.
- ``model``: numpy ridge regression in log space per op class, with a
  per-class feature hull; serialized to ``COSTMODEL.json`` with
  coverage counts and held-out error. ``predict`` returns
  ``(seconds, confidence)`` — low confidence outside the hull.
- discovery: ``load_native_table`` locates the trained model
  (``FFS_COSTMODEL_FILE`` or the repo-root ``COSTMODEL.json``), gates
  it per platform, and exports the native-evaluable coefficient table
  ``machine_to_json`` threads into ``libffsearch.so``.
  ``FFS_NO_LEARNED_COSTS=1`` opts out entirely (searches are then
  bit-identical to the pre-costmodel behavior).

Validation (SCALE-Sim TPU methodology, arXiv 2603.22535): simulator
accuracy is a *tracked metric* — ``scripts/costmodel.py report`` and
``scripts/obs_report.py`` render predicted-vs-measured step time
analytic-vs-learned side by side, ``bench.py`` records
``sim_accuracy_ratio`` per workload, and ``scripts/explain.py`` shows
when the two models rank a different winner for an op.
"""

from flexflow_tpu.costmodel.corpus import (CORPUS_SCHEMA_VERSION,
                                           FEATURE_NAMES, CorpusSchemaError,
                                           build_corpus, featurize,
                                           load_corpus, load_trace_dir,
                                           save_corpus)
from flexflow_tpu.costmodel.model import (MIN_CLASS_ROWS,
                                          MODEL_SCHEMA_VERSION, CostModel,
                                          default_model_path,
                                          load_model, load_native_table,
                                          train_model)

__all__ = [
    "CORPUS_SCHEMA_VERSION", "FEATURE_NAMES", "CorpusSchemaError",
    "build_corpus", "featurize", "load_corpus", "load_trace_dir",
    "save_corpus", "MIN_CLASS_ROWS", "MODEL_SCHEMA_VERSION", "CostModel",
    "default_model_path", "load_model", "load_native_table", "train_model",
]

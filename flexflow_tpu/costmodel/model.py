"""The learned per-op-class cost model (numpy ridge over log features).

One small regression per op class (LINEAR, CONV2D,
MULTIHEAD_ATTENTION, ...), trained on the corpus rows of
``costmodel/corpus.py``: features are the log-space sharded-work vector
(FEATURE_NAMES), targets are ``log(measured_seconds / work_div)`` for
the forward and backward passes separately — i.e. the model predicts
the PER-CHIP compute seconds the DP's ``node_cost`` needs. A ridge
model in log space is a learned roofline: it can express
``t ~ flops^a * bytes^b`` with per-class constants, which subsumes the
hand-tuned ``mxu_efficiency`` / ``conv_efficiency`` / ``min_op_time``
heuristics it retires (2008.01040's insight, scaled to this corpus).

Confidence comes from two terms: class coverage (rows seen) and the
feature hull — per-class min/max of every feature over the training
rows. A query outside the hull (plus margin) is an extrapolation the
model was never shown; ``predict`` returns low confidence and the
native evaluator falls back to the analytic terms (the per-op-class
gate the search relies on).

Serialized form: ``COSTMODEL.json`` — schema-versioned, carrying
per-class coefficients, hull, coverage counts, and held-out error so
both the native evaluator and the fflint staleness lint (FFL704) can
read trust directly off the artifact.
"""

from __future__ import annotations

import json
import math
import os
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.costmodel.corpus import (CORPUS_SCHEMA_VERSION,
                                           FEATURE_NAMES, featurize,
                                           row_class, row_key)

MODEL_SCHEMA_VERSION = 1

# Per-op-class coverage gate: below this many training rows the class
# is not exported to the native table at all (the DP keeps analytic
# pricing for it). 8 rows over a 4-feature model is the floor where
# the ridge solution stops being pure memorization.
MIN_CLASS_ROWS = 8

# Hull slack in log units (~2x in linear space): a query this far past
# the trained feature range still counts as covered; beyond it the
# native evaluator falls back to analytic pricing.
HULL_MARGIN = 0.7

RIDGE_LAMBDA = 1e-3

# Floor for targets/predictions (seconds) — keeps log() finite and
# matches the native min_op_time scale.
_T_FLOOR = 1e-9


def _split_test(rows: List[Dict[str, Any]], test_frac: float) -> np.ndarray:
    """Deterministic held-out mask: exactly floor(n * test_frac) rows,
    chosen by row-key CRC rank (stable across runs and row order — no
    RNG, so retraining on the same corpus yields the same split and the
    same held-out error, and tiny classes never lose most of their rows
    to a lopsided modulo split)."""
    mask = np.zeros(len(rows), dtype=bool)
    n_test = int(len(rows) * max(0.0, test_frac))
    if n_test <= 0:
        return mask
    ranked = sorted(range(len(rows)),
                    key=lambda i: (zlib.crc32(repr(row_key(rows[i]))
                                              .encode()), i))
    for i in ranked[:n_test]:
        mask[i] = True
    return mask


def _ridge(X: np.ndarray, y: np.ndarray, lam: float) -> np.ndarray:
    """Ridge solve with intercept (intercept unregularized)."""
    Xb = np.hstack([np.ones((X.shape[0], 1)), X])
    d = Xb.shape[1]
    reg = lam * np.eye(d)
    reg[0, 0] = 0.0
    return np.linalg.solve(Xb.T @ Xb + reg, Xb.T @ y)


def _err(coef: np.ndarray, X: np.ndarray, y: np.ndarray) -> float:
    """Median |log(pred/actual)| — robust multiplicative error."""
    if X.shape[0] == 0:
        return 0.0
    pred = np.hstack([np.ones((X.shape[0], 1)), X]) @ coef
    return float(np.median(np.abs(pred - y)))


class ClassModel:
    """Trained regression of one op class."""

    def __init__(self, coef_fwd, coef_bwd, fmin, fmax, n_train, n_test,
                 err_fwd, err_bwd):
        self.coef_fwd = np.asarray(coef_fwd, dtype=np.float64)
        self.coef_bwd = np.asarray(coef_bwd, dtype=np.float64)
        self.fmin = np.asarray(fmin, dtype=np.float64)
        self.fmax = np.asarray(fmax, dtype=np.float64)
        self.n_train = int(n_train)
        self.n_test = int(n_test)
        self.err_fwd = float(err_fwd)
        self.err_bwd = float(err_bwd)

    @property
    def err_factor(self) -> float:
        """Held-out multiplicative error as a factor (1.0 = perfect):
        exp(median |log(pred/actual)|) on the forward pass."""
        return float(math.exp(self.err_fwd))

    def hull_violation(self, f: np.ndarray) -> float:
        """Total log-units outside the trained feature range (0 inside)."""
        return float(np.sum(np.maximum(0.0, self.fmin - f)
                            + np.maximum(0.0, f - self.fmax)))

    def predict_log(self, f: np.ndarray, bwd: bool = False) -> float:
        coef = self.coef_bwd if bwd else self.coef_fwd
        return float(coef[0] + coef[1:] @ f)

    def to_json(self) -> Dict[str, Any]:
        return dict(
            coef_fwd=[round(float(v), 8) for v in self.coef_fwd],
            coef_bwd=[round(float(v), 8) for v in self.coef_bwd],
            fmin=[round(float(v), 6) for v in self.fmin],
            fmax=[round(float(v), 6) for v in self.fmax],
            n_train=self.n_train, n_test=self.n_test,
            err_fwd=round(self.err_fwd, 6), err_bwd=round(self.err_bwd, 6),
            err_factor=round(self.err_factor, 4),
        )

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "ClassModel":
        return cls(j["coef_fwd"], j["coef_bwd"], j["fmin"], j["fmax"],
                   j.get("n_train", 0), j.get("n_test", 0),
                   j.get("err_fwd", 0.0), j.get("err_bwd", 0.0))


class CostModel:
    """The trained table: per-op-class regressions + provenance."""

    def __init__(self, classes: Dict[str, ClassModel],
                 platform: str = "unknown",
                 corpus_rows: int = 0,
                 hull_margin: float = HULL_MARGIN):
        self.classes = classes
        self.platform = platform
        self.corpus_rows = int(corpus_rows)
        self.hull_margin = float(hull_margin)

    # ---- training ---------------------------------------------------------

    @classmethod
    def train(cls, corpus: Dict[str, Any], min_rows: int = MIN_CLASS_ROWS,
              test_frac: float = 0.25, lam: float = RIDGE_LAMBDA,
              platform: Optional[str] = None) -> "CostModel":
        """Trains on ONE platform's rows only: the model's coefficients
        must be as pure as the platform gate (``load_native_table``)
        claims they are, so a mixed cpu+tpu corpus contributes only its
        majority platform (or the explicit ``platform``) — the other
        rows are dropped, not blended into the regression."""
        all_rows = [r for r in corpus.get("rows") or []]
        platforms: Dict[str, int] = {}
        for r in all_rows:
            p = r.get("platform") or "unknown"
            platforms[p] = platforms.get(p, 0) + 1
        if platform is None:
            platform = max(platforms, key=platforms.get) if platforms \
                else "unknown"
        rows = [r for r in all_rows
                if (r.get("platform") or "unknown") == platform]
        # per-impl classes ("TYPE:impl" for compute-kernel impls — the
        # searched "_k:" dimension, ISSUE 15): flash rows never blend
        # into the einsum regression they'd otherwise bias
        by_class: Dict[str, List[Dict[str, Any]]] = {}
        for r in rows:
            by_class.setdefault(row_class(r), []).append(r)
        classes: Dict[str, ClassModel] = {}
        for cname, crows in sorted(by_class.items()):
            if len(crows) < min_rows:
                continue
            X = np.stack([featurize(r) for r in crows])
            div = np.array([max(1.0, float(r.get("work_div") or 1.0))
                            for r in crows])
            mfwd = np.array([float(r["measured"]["fwd_s"]) for r in crows])
            mbwd = np.array([float(r["measured"].get("bwd_s")
                                   or 2.0 * r["measured"]["fwd_s"])
                             for r in crows])
            yf = np.log(np.maximum(mfwd / div, _T_FLOOR))
            yb = np.log(np.maximum(mbwd / div, _T_FLOOR))
            test = _split_test(crows, test_frac)
            train = ~test
            coef_f = _ridge(X[train], yf[train], lam)
            coef_b = _ridge(X[train], yb[train], lam)
            # held-out error; with no test rows, train error (honest in
            # n_test=0 — FFL704 and report readers see the distinction)
            ef = _err(coef_f, X[test], yf[test]) if test.any() \
                else _err(coef_f, X[train], yf[train])
            eb = _err(coef_b, X[test], yb[test]) if test.any() \
                else _err(coef_b, X[train], yb[train])
            classes[cname] = ClassModel(
                coef_f, coef_b,
                X[train].min(axis=0), X[train].max(axis=0),
                int(train.sum()), int(test.sum()), ef, eb)
        return cls(classes, platform=platform, corpus_rows=len(rows))

    # ---- inference --------------------------------------------------------

    def predict(self, row: Dict[str, Any], bwd: bool = False
                ) -> Tuple[Optional[float], float]:
        """(seconds, confidence) for one corpus-row-shaped query.

        ``seconds`` is the predicted PER-CHIP compute time (already
        divided by the row's work_div, like the DP's node cost);
        ``None`` when the op class has no trained regression.
        Confidence = coverage term x hull term — outside the trained
        feature hull it decays toward 0 (extrapolation)."""
        cm = self.classes.get(row_class(row)) \
            or self.classes.get(row.get("type"))
        if cm is None:
            return None, 0.0
        f = featurize(row)
        t = max(math.exp(cm.predict_log(f, bwd=bwd)), _T_FLOOR)
        cov = min(1.0, cm.n_train / 16.0)
        v = cm.hull_violation(f)
        conf = cov * math.exp(-v / max(self.hull_margin, 1e-6))
        return t, float(conf)

    def in_hull(self, row: Dict[str, Any]) -> bool:
        cm = self.classes.get(row_class(row)) \
            or self.classes.get(row.get("type"))
        if cm is None:
            return False
        f = featurize(row)
        return bool(np.all(f >= cm.fmin - self.hull_margin)
                    and np.all(f <= cm.fmax + self.hull_margin))

    # ---- serialization ----------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        return dict(
            schema_version=MODEL_SCHEMA_VERSION,
            corpus_schema=CORPUS_SCHEMA_VERSION,
            platform=self.platform,
            feature_names=list(FEATURE_NAMES),
            hull_margin=self.hull_margin,
            corpus_rows=self.corpus_rows,
            classes={k: v.to_json() for k, v in sorted(self.classes.items())},
        )

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "CostModel":
        ver = int(j.get("schema_version", 0))
        if ver > MODEL_SCHEMA_VERSION:
            raise ValueError(
                f"COSTMODEL.json schema v{ver} is newer than this build "
                f"understands (<= v{MODEL_SCHEMA_VERSION})")
        return cls({k: ClassModel.from_json(v)
                    for k, v in (j.get("classes") or {}).items()},
                   platform=j.get("platform", "unknown"),
                   corpus_rows=j.get("corpus_rows", 0),
                   hull_margin=j.get("hull_margin", HULL_MARGIN))

    def save(self, path: str) -> None:
        from flexflow_tpu.obs.artifacts import atomic_write_text
        atomic_write_text(path, json.dumps(self.to_json(), indent=1))

    @classmethod
    def load(cls, path: str) -> "CostModel":
        with open(path) as f:
            return cls.from_json(json.load(f))

    # ---- native export ----------------------------------------------------

    def native_table(self) -> Dict[str, Any]:
        """The coefficient table ``machine_to_json`` embeds for the
        native evaluator (ffs_machine.hpp ``LearnedCostModel``): only
        classes that met the coverage gate exist here, so "class absent
        from the table" IS the native fallback-to-analytic signal."""
        return dict(
            feature_count=len(FEATURE_NAMES),
            hull_margin=self.hull_margin,
            classes={
                k: dict(wf=[float(v) for v in cm.coef_fwd],
                        wb=[float(v) for v in cm.coef_bwd],
                        fmin=[float(v) for v in cm.fmin],
                        fmax=[float(v) for v in cm.fmax],
                        n=cm.n_train, err=cm.err_fwd)
                for k, cm in sorted(self.classes.items())},
        )


def train_model(corpus: Dict[str, Any], **kw) -> CostModel:
    return CostModel.train(corpus, **kw)


def default_model_path() -> str:
    """``FFS_COSTMODEL_FILE`` override, else the repo-root
    ``COSTMODEL.json`` (where ``scripts/costmodel.py train`` writes)."""
    env = os.environ.get("FFS_COSTMODEL_FILE")
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "COSTMODEL.json")


def load_model(path: Optional[str] = None) -> Optional[CostModel]:
    """The trained model at ``path`` (default discovery), or None when
    absent/unreadable. Schema mismatches raise (a present-but-newer
    model must not silently degrade to analytic)."""
    path = path or default_model_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return CostModel.from_json(data)


def load_native_table(path: Optional[str] = None,
                      platform: Optional[str] = None
                      ) -> Optional[Dict[str, Any]]:
    """The native coefficient table for the current process, or None.

    None when: ``FFS_NO_LEARNED_COSTS`` is set (the opt-out — searches
    revert to pre-costmodel analytic pricing bit-for-bit), no trained
    model exists at the discovery path, the model covers no class, or
    the model was trained on a DIFFERENT platform than the live one
    (cpu-corpus coefficients must never price a TPU search and vice
    versa — same gating discipline as collective_corrections)."""
    if os.environ.get("FFS_NO_LEARNED_COSTS"):
        return None
    model = load_model(path)
    if model is None or not model.classes:
        return None
    if platform is None:
        try:
            import jax
            platform = jax.devices()[0].platform
        except Exception:
            platform = None
    if (platform is not None and model.platform != "unknown"
            and model.platform != platform):
        return None
    return model.native_table()

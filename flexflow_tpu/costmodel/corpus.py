"""Training-corpus store for the learned cost model.

Ingests the measurement artifacts the obs subsystem already emits into
one deduplicated, schema-versioned corpus:

- ``*.simtrace.json`` — the primary source: per-op rows carrying the
  op's identity (class, shape, sharding choice, mesh), the simulator's
  priced terms, the featurization fields (flops, io bytes, param
  bytes), and measured whole-op seconds where a profile table ran.
- ``*.drift.json`` — joined by run stem: a traced fit's measured
  per-op seconds fill the measured half of simtrace rows whose profile
  column is empty (the obs_report join, reused for training).
- ``roofline*.json`` — ``scripts/roofline.py`` standalone per-op
  measurements (always measured, work_div 1), which is where conv-class
  coverage comes from.

Rows are keyed by (platform, op class, shape, choice, mesh, work_div):
re-ingesting a directory replaces its rows in place; distinct shapes
and sharding choices accumulate. The corpus lands in
``COSTMODEL_CORPUS.json`` (``scripts/costmodel.py train``).

Featurization: log-space features over the *sharded* work — the native
evaluator (ffs_machine.hpp ``learned_predict``) computes the identical
vector from (Node, Choice), so a model trained here prices exactly what
the DP asks. Schema drift between the simtrace writer and this loader
fails loudly (``CorpusSchemaError``) — the CI costmodel stage asserts
that.
"""

from __future__ import annotations

import glob
import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Kept in lockstep with the simtrace writer: the loader understands
# rows up to THIS version and refuses newer ones.
from flexflow_tpu.obs.simtrace import CORPUS_SCHEMA_VERSION

# Oldest row schema still trainable: v2 introduced the featurization
# fields; v3 only ADDED the ``impl`` column (derivable from the choice
# suffix for v2 rows), so the committed v2 fixture corpus keeps
# training.
CORPUS_MIN_TRAINABLE = 2

# Kernel impls that change the COMPUTE lowering — these get their own
# learned class ("TYPE:impl", mirrored by the native evaluator's lookup
# in ffs_strategy.hpp learned_compute). Ring attention and the fused
# update keep the base class: ring's per-block compute IS the einsum
# (its ring comm is priced separately) and "fused" only moves the
# update term, not fwd/bwd compute.
_COMPUTE_IMPLS = frozenset({"flash", "conv_bn_fused"})


def row_impl(row: Dict[str, Any]) -> Optional[str]:
    """Kernel impl of a corpus row: the v3 ``impl`` column, else derived
    from the choice suffix (v2 rows)."""
    impl = row.get("impl")
    if impl:
        return str(impl)
    from flexflow_tpu.search.unity import kernel_choice_of
    ch = row.get("choice") or ""
    k = kernel_choice_of(ch)
    if k is not None:
        return k
    t = row.get("type")
    if t == "MULTIHEAD_ATTENTION":
        return "ring" if "_ring" in ch else "einsum"
    if t == "CONV2D":
        return "conv"
    return None


def row_class(row: Dict[str, Any]) -> str:
    """Learned-model class key of a row: the op type, suffixed
    ``:impl`` for compute-kernel impls so per-impl rows train per-impl
    coefficients instead of blending two lowerings into one
    regression."""
    impl = row_impl(row)
    if impl in _COMPUTE_IMPLS:
        return f"{row.get('type')}:{impl}"
    return str(row.get("type"))

# The featurization the regression trains over and the native evaluator
# mirrors (ffs_machine.hpp kLearnedFeatures — same order, same
# transforms). All log-space: per-op seconds span 6 orders of
# magnitude, and a linear model in log space is a learned roofline.
FEATURE_NAMES: Tuple[str, ...] = (
    "log_flops_sharded",   # log1p(analytic FLOPs / work_div)
    "log_bytes_sharded",   # log1p(io bytes (params+in+out) / work_div)
    "log_param_bytes",     # log1p(whole-op parameter bytes)
    "log_work_div",        # log(work division the choice applies)
)


class CorpusSchemaError(ValueError):
    """A trace artifact carries corpus rows NEWER than this loader
    understands — the simtrace schema drifted without updating the
    costmodel loader. Raised loudly (the CI costmodel stage fails)
    instead of silently training on misread rows."""


def featurize(row: Dict[str, Any]) -> np.ndarray:
    """Feature vector of one corpus row (FEATURE_NAMES order)."""
    div = max(1.0, float(row.get("work_div") or 1.0))
    flops = max(0.0, float(row.get("flops") or 0.0))
    io_bytes = max(0.0, float(row.get("io_bytes") or 0.0))
    pbytes = max(0.0, float(row.get("param_bytes") or 0.0))
    return np.array([
        math.log1p(flops / div),
        math.log1p(io_bytes / div),
        math.log1p(pbytes),
        math.log(div),
    ], dtype=np.float64)


def row_key(row: Dict[str, Any]) -> Tuple:
    """Dedup identity: op class x shape x choice x mesh x platform.
    Two measurements of the same configuration collapse (last wins) so
    re-ingesting a trace dir replaces rather than double-counts."""
    mesh = row.get("mesh_axes") or {}
    return (
        row.get("platform") or "unknown",
        row.get("type"),
        row_impl(row),
        tuple(row.get("out_shape") or ()),
        row.get("choice"),
        tuple(sorted((str(k), int(v)) for k, v in mesh.items())),
        int(row.get("work_div") or 1),
        round(float(row.get("flops") or 0.0), 3),
    )


def _check_schema(ver: Optional[int], path: str) -> None:
    if ver is not None and int(ver) > CORPUS_SCHEMA_VERSION:
        raise CorpusSchemaError(
            f"{os.path.basename(path)}: corpus rows are schema v{ver} but "
            f"this loader understands <= v{CORPUS_SCHEMA_VERSION} — the "
            f"simtrace corpus schema drifted; update "
            f"flexflow_tpu/costmodel/corpus.py in the same change as the "
            f"writer (obs/simtrace.py)")


def _trainable(row: Dict[str, Any]) -> bool:
    # zero-FLOP rows stay trainable on purpose: pooling/dropout/view
    # classes regress on their byte features alone
    m = row.get("measured") or {}
    return (m.get("source") == "measured" and m.get("fwd_s")
            and float(m["fwd_s"]) > 0 and (row.get("io_bytes") or 0) > 0)


def rows_from_simtrace(payload: Dict[str, Any], path: str,
                       drift: Optional[Dict[str, Any]] = None
                       ) -> Tuple[List[Dict[str, Any]], int]:
    """Corpus rows of one simtrace artifact; measured seconds joined
    from the stem's drift report where the profile column is empty.
    Returns (rows, skipped) — skipped counts per-op rows too old to
    carry the featurization fields (schema v1)."""
    _check_schema(payload.get("corpus_schema"), path)
    header = payload.get("header") or {}
    platform = header.get("platform") or "unknown"
    drift_ops = {r.get("guid"): r
                 for r in (drift or {}).get("per_op") or []
                 if r.get("source") == "measured"}
    out: List[Dict[str, Any]] = []
    skipped = 0
    for r in payload.get("per_op") or []:
        ver = r.get("schema", 1)
        _check_schema(ver, path)
        if int(ver) < CORPUS_MIN_TRAINABLE:
            skipped += 1  # pre-featurization row: nothing to train on
            continue
        row = dict(r)
        row.setdefault("mesh_axes", payload.get("mesh_axes") or {})
        row["platform"] = platform
        row["source_artifact"] = os.path.basename(path)
        m = dict(row.get("measured") or {})
        if m.get("source") != "measured":
            d = drift_ops.get(r.get("guid"))
            if d is not None and d.get("fwd_s"):
                m = dict(fwd_s=d["fwd_s"], bwd_s=d.get("bwd_s"),
                         source="measured")
        row["measured"] = m
        if _trainable(row):
            out.append(row)
        else:
            skipped += 1
    return out, skipped


def rows_from_roofline(payload: Dict[str, Any], path: str
                       ) -> List[Dict[str, Any]]:
    """Corpus rows from a ``scripts/roofline.py`` report: standalone
    per-op measurements, replicated layout (work_div 1). The roofline's
    ``bytes`` column is in+out+params at f32 — the same io convention."""
    platform = ((payload.get("meta") or {}).get("platform")
                or (payload.get("header") or {}).get("platform")
                or "unknown")
    out: List[Dict[str, Any]] = []
    for r in payload.get("rows") or []:
        if "fwd_s" not in r:
            continue
        oshape = (r.get("output_shapes") or [[]])[0]
        pbytes = max(0.0, float(r.get("bytes") or 0.0)
                     - 4.0 * sum(float(np.prod(s))
                                 for s in (r.get("input_shapes") or [])
                                 + (r.get("output_shapes") or [])))
        row = dict(
            schema=CORPUS_SCHEMA_VERSION,
            guid=None, name=r.get("name"), type=r.get("type"),
            out_shape=list(oshape), choice="rep", work_div=1,
            flops=float(r.get("flops") or 0.0),
            io_bytes=float(r.get("bytes") or 0.0),
            param_bytes=pbytes,
            dtype_size=4,
            mesh_axes={},
            platform=platform,
            source_artifact=os.path.basename(path),
            priced=dict(source="analytic"),
            measured=dict(fwd_s=r.get("fwd_s"), bwd_s=r.get("bwd_s"),
                          source="measured"),
        )
        if _trainable(row):
            out.append(row)
    return out


def load_trace_dir(trace_dir: str) -> Tuple[List[Dict[str, Any]],
                                            Dict[str, int]]:
    """All trainable corpus rows of one trace dir (simtrace joined with
    drift by run stem, plus roofline reports). Returns (rows, stats)."""
    rows: List[Dict[str, Any]] = []
    stats = dict(simtrace_files=0, roofline_files=0, rows=0, skipped=0)
    drifts: Dict[str, Dict[str, Any]] = {}
    for p in glob.glob(os.path.join(trace_dir, "*.drift.json")):
        stem = os.path.basename(p)[:-len(".drift.json")]
        try:
            with open(p) as f:
                drifts[stem] = json.load(f)
        except (OSError, ValueError):
            continue
    for p in sorted(glob.glob(os.path.join(trace_dir, "*.simtrace.json"))):
        stem = os.path.basename(p)[:-len(".simtrace.json")]
        try:
            with open(p) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        got, skipped = rows_from_simtrace(payload, p, drift=drifts.get(stem))
        rows.extend(got)
        stats["simtrace_files"] += 1
        stats["skipped"] += skipped
    for pattern in ("*.roofline.json", "roofline_*.json"):
        for p in sorted(glob.glob(os.path.join(trace_dir, pattern))):
            try:
                with open(p) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(payload, dict) or "rows" not in payload:
                continue
            rows.extend(rows_from_roofline(payload, p))
            stats["roofline_files"] += 1
    stats["rows"] = len(rows)
    return rows, stats


def build_corpus(trace_dirs: Sequence[str]) -> Dict[str, Any]:
    """Deduplicated training corpus over one or many trace dirs."""
    by_key: Dict[Tuple, Dict[str, Any]] = {}
    stats = dict(simtrace_files=0, roofline_files=0, skipped=0,
                 duplicates=0)
    for d in trace_dirs:
        rows, s = load_trace_dir(d)
        for k in ("simtrace_files", "roofline_files", "skipped"):
            stats[k] += s[k]
        for r in rows:
            k = row_key(r)
            if k in by_key:
                stats["duplicates"] += 1
            by_key[k] = r
    rows = list(by_key.values())
    classes: Dict[str, int] = {}
    for r in rows:
        c = row_class(r)
        classes[c] = classes.get(c, 0) + 1
    return dict(
        schema_version=1,
        corpus_schema=CORPUS_SCHEMA_VERSION,
        feature_names=list(FEATURE_NAMES),
        trace_dirs=[os.path.abspath(d) for d in trace_dirs],
        stats=stats,
        classes=classes,
        rows=rows,
    )


def save_corpus(path: str, corpus: Dict[str, Any]) -> None:
    from flexflow_tpu.obs.artifacts import atomic_write_text
    atomic_write_text(path, json.dumps(corpus, indent=1))


def load_corpus(path: str) -> Dict[str, Any]:
    with open(path) as f:
        corpus = json.load(f)
    _check_schema(corpus.get("corpus_schema"), path)
    for r in corpus.get("rows") or []:
        _check_schema(r.get("schema"), path)
    return corpus

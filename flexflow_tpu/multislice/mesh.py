"""Mesh and process-set plumbing for the ('slice', ...) outer axis.

The hierarchical strategy the search picks on a multi-slice machine is
"DP/WUS over DCN x searched hybrid within each slice": the cross-slice
axis only ever carries data parallelism (gradient sync), because any
tensor-parallel axis that crossed slices would put per-layer
collectives on the slow fabric — the ``inner_axes_cross_slice`` mesh
gate in ``ffs_search.cpp`` rejects those meshes outright. The runtime
mirror of that invariant lives here: split the searched 'data' extent
into an OUTER 'slice' axis times the within-slice remainder, and
extend every 'data'-sharded PartitionSpec entry across both. With
'slice' in the executor's ``data_axes``, the WUS bucketed-RS chaining
then prices/hides the slow DCN gradient sync exactly like any other
data axis — which is where bucketed async RS pays most.
"""

from __future__ import annotations

from typing import Dict, List

from jax.sharding import PartitionSpec as P


def slice_axes(axes: Dict[str, int], slices: int) -> Dict[str, int]:
    """Split a searched mesh's 'data' extent into ``{'slice': s,
    'data': dp // s, ...}`` with 'slice' OUTERMOST — so the flat
    device order lays consecutive chips within a slice (slice-major),
    matching how real multislice fleets enumerate devices.

    The slice count must divide the data extent: the cross-slice axis
    carries only data parallelism (see module docstring), so a search
    result whose dp the slice count does not divide cannot run on this
    fleet — that is a configuration error, not something to paper over.
    """
    s = max(1, int(slices))
    if s == 1:
        return dict(axes)
    dp = int(axes.get("data", 1))
    if dp % s != 0:
        raise ValueError(
            f"--slices {s} does not divide the searched data extent {dp} "
            f"(mesh {axes}); the cross-slice axis carries data parallelism "
            f"only, so slices must divide dp")
    out: Dict[str, int] = {"slice": s}
    for name, ext in axes.items():
        out[name] = dp // s if name == "data" else int(ext)
    if "data" not in out:
        out["data"] = 1
    return out


def _remap_entry(entry):
    """'data' -> ('slice', 'data') inside one PartitionSpec entry,
    flattening tuples (a dim sharded dp ways is now sharded s * dp/s
    ways across both axes)."""
    if entry is None:
        return None
    entries = entry if isinstance(entry, tuple) else (entry,)
    out: List[str] = []
    for a in entries:
        if a == "data":
            out.extend(("slice", "data"))
        else:
            out.append(a)
    return tuple(out) if len(out) > 1 else out[0]


def _remap_spec(spec):
    if spec is None:
        return None
    entries = [_remap_entry(e) for e in spec]
    return P(*entries)


def remap_strategy_for_slices(strategy) -> None:
    """In-place: every 'data' axis reference in a Strategy's
    PartitionSpecs becomes ('slice', 'data'). Run after the search
    (which saw the flat dp extent) and before ``apply_strategy`` on
    the slice-split mesh."""
    for st in strategy.values():
        st.output_specs = [_remap_spec(s) for s in st.output_specs]
        st.param_specs = {k: _remap_spec(v)
                          for k, v in st.param_specs.items()}


def slice_of_process(process_index: int, num_processes: int,
                     num_slices: int) -> int:
    """Slice index of a multihost process (contiguous blocks: processes
    [0, P/S) are slice 0, etc. — slice-major, matching ``slice_axes``'s
    device order)."""
    if num_slices <= 1:
        return 0
    if num_processes % num_slices != 0:
        raise ValueError(
            f"{num_slices} slices do not evenly divide {num_processes} "
            f"processes")
    return int(process_index) // (num_processes // num_slices)


def slice_process_groups(num_processes: int,
                         num_slices: int) -> List[List[int]]:
    """Process indices grouped by slice — the per-slice FFL5xx lint
    groups and the dryrun's process sets."""
    per = num_processes // max(1, num_slices)
    if num_slices >= 1 and num_processes % max(1, num_slices) != 0:
        raise ValueError(
            f"{num_slices} slices do not evenly divide {num_processes} "
            f"processes")
    return [list(range(s * per, (s + 1) * per))
            for s in range(max(1, num_slices))]

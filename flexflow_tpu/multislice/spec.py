"""MultiSliceSpec: N slices x per-slice ICI torus + the DCN between.

``machine.MachineSpec`` already carries the two-level fields
(``num_slices``, ``dcn_bw``, ``dcn_latency``, ``dcn_links``) because
``machine_to_json`` feeds them to the native search; this module gives
them a front door. A ``MultiSliceSpec`` is what a user (or
``FFConfig --slices``) states about the fleet — slice count, slice
shape, fabric — and ``to_machine_spec()`` produces the search-ready
``MachineSpec`` with the per-slice torus factored per SLICE (a flat
spec would factor the full chip count into one big torus that does
not exist).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from flexflow_tpu.machine import MachineSpec


@dataclasses.dataclass
class MultiSliceSpec:
    """A fleet of ``num_slices`` identical TPU slices.

    ``torus`` is the PER-SLICE ICI topology (e.g. ``(4, 4, 2)`` for a
    v4-32 slice); None lets ``MachineSpec`` factor the per-generation
    default. ``dcn_links`` optionally names an explicit slice-pair
    fabric ``[(i, j, bytes_per_s), ...]`` — absent, the DCN is uniform
    all-to-all at ``dcn_bw``.
    """

    num_slices: int = 2
    chips_per_slice: int = 4
    chip: str = "tpu-v4"
    torus: Optional[Tuple[int, ...]] = None
    dcn_bw: float = 25e9
    dcn_latency: float = 10e-6
    dcn_links: Optional[Sequence[Tuple[int, int, float]]] = None

    def __post_init__(self):
        if self.num_slices < 1:
            raise ValueError(f"num_slices must be >= 1, got {self.num_slices}")
        if self.chips_per_slice < 1:
            raise ValueError(
                f"chips_per_slice must be >= 1, got {self.chips_per_slice}")
        if self.dcn_bw <= 0:
            raise ValueError(f"dcn_bw must be > 0, got {self.dcn_bw}")
        if self.dcn_latency < 0:
            raise ValueError(
                f"dcn_latency must be >= 0, got {self.dcn_latency}")

    @property
    def num_devices(self) -> int:
        return self.num_slices * self.chips_per_slice

    def to_machine_spec(self, **overrides) -> MachineSpec:
        """The search-ready ``MachineSpec`` twin. Keyword overrides pass
        through to the MachineSpec constructor (e.g. calibration
        factors, mxu_efficiency)."""
        kw = dict(
            chip=self.chip,
            chips_per_slice=self.chips_per_slice,
            num_slices=self.num_slices,
            torus=self.torus,
            dcn_bw=self.dcn_bw,
            dcn_latency=self.dcn_latency,
            dcn_links=self.dcn_links,
        )
        kw.update(overrides)
        return MachineSpec(**kw)

    @classmethod
    def from_machine_spec(cls, spec: MachineSpec) -> "MultiSliceSpec":
        return cls(
            num_slices=spec.num_slices,
            chips_per_slice=spec.chips_per_slice,
            chip=spec.chip,
            torus=tuple(spec.torus) if spec.torus else None,
            dcn_bw=spec.dcn_bw,
            dcn_latency=spec.dcn_latency,
            dcn_links=spec.dcn_links,
        )

    def slice_of_device(self, device_index: int) -> int:
        """Slice index of a flat device index (slice-major order — the
        order ``model.compile`` lays the ('slice', ...) mesh out in)."""
        return int(device_index) // self.chips_per_slice

    def surviving(self, lost_slices: Sequence[int]) -> "MultiSliceSpec":
        """The spec after losing ``lost_slices`` — the topology class
        ``plan_resume`` re-searches for. Losing all slices is a crash,
        not a resume plan."""
        lost = {int(s) for s in lost_slices}
        left = self.num_slices - len(lost & set(range(self.num_slices)))
        if left < 1:
            raise ValueError("no surviving slices to resume on")
        links = None
        if self.dcn_links:
            # renumber the surviving slices densely; drop lost endpoints
            keep = [i for i in range(self.num_slices) if i not in lost]
            renum = {old: new for new, old in enumerate(keep)}
            links = [(renum[i], renum[j], bw) for i, j, bw in self.dcn_links
                     if i in renum and j in renum]
        return dataclasses.replace(self, num_slices=left,
                                   dcn_links=links or None)


def multislice_machine_spec(num_devices: int, slices: int,
                            chip: str = "cpu-sim",
                            **overrides) -> MachineSpec:
    """Convenience: the MachineSpec for ``num_devices`` chips split into
    ``slices`` DCN-connected slices (the ``--slices`` flag's path)."""
    s = max(1, int(slices))
    if num_devices % s != 0:
        raise ValueError(
            f"slices={s} does not divide num_devices={num_devices}")
    return MultiSliceSpec(num_slices=s, chips_per_slice=num_devices // s,
                          chip=chip).to_machine_spec(**overrides)

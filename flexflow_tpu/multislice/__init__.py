"""Multi-slice DCN hierarchy: the two-level machine model.

One TPU slice is an ICI domain — a torus of chips whose links the
per-axis ring pricing in ``native/ffs_machine.hpp`` models. Past one
slice, traffic crosses the data-center network (DCN): ~25 GB/s per
slice pair against 45-90 GB/s per ICI link, and 10 us latency against
1 us. The reference fork's ``NetworkedMachineModel``
(include/flexflow/simulator.h:515) made exactly this fabric split a
first-class pricing input; this package is the TPU-native
re-expression.

* ``MultiSliceSpec`` — the user-facing description (N slices x
  per-slice ICI torus, DCN bandwidth/latency/links), convertible to
  and from the ``machine.MachineSpec`` the search consumes;
* mesh helpers — split the searched data extent into an outer
  ``('slice', 'data', ...)`` axis pair and remap strategy
  PartitionSpecs so every ``'data'``-sharded dim extends across the
  slice axis (the runtime side of the hierarchical DP/WUS strategy);
* process-set helpers — map multihost process indices onto slices for
  the deviceless dryrun and the per-slice FFL5xx lint groups.
"""

from flexflow_tpu.multislice.spec import (MultiSliceSpec,
                                          multislice_machine_spec)
from flexflow_tpu.multislice.mesh import (remap_strategy_for_slices,
                                          slice_axes,
                                          slice_process_groups,
                                          slice_of_process)

__all__ = [
    "MultiSliceSpec",
    "multislice_machine_spec",
    "slice_axes",
    "remap_strategy_for_slices",
    "slice_process_groups",
    "slice_of_process",
]

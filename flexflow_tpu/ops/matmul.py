"""BatchMatmul.

Analog of src/ops/batch_matmul.cc (cuBLAS strided-batched GEMM). The
reference threads FFIterationConfig::seq_length through
a_seq_length_dim/b_seq_length_dim so short batches skip compute
(model.h:481-485); here ctx.seq_length slices the corresponding dim before
the einsum — under jit with a fixed seq_length this is a static slice.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


@register_op(OperatorType.BATCHMATMUL)
class BatchMatmul(Op):
    """a: [..., M, K] @ b: [..., K, N] -> [..., M, N]."""

    def __init__(self, layer, input_shapes):
        self.a_seq_length_dim = layer.get_property("a_seq_length_dim", -1)
        self.b_seq_length_dim = layer.get_property("b_seq_length_dim", -1)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        a, b = self.input_shapes
        assert a[-1] == b[-2], f"batch_matmul contraction mismatch {a} @ {b}"
        return [tuple(a[:-1]) + (b[-1],)]

    def forward(self, params, inputs, ctx: OpContext):
        a, b = inputs
        if ctx.seq_length is not None:
            if self.a_seq_length_dim >= 0:
                a = jnp.take(a, jnp.arange(ctx.seq_length), axis=self.a_seq_length_dim)
            if self.b_seq_length_dim >= 0:
                b = jnp.take(b, jnp.arange(ctx.seq_length), axis=self.b_seq_length_dim)
        cd = ctx.compute_dtype
        y = jnp.matmul(a.astype(cd), b.astype(cd), preferred_element_type=jnp.float32)
        return [y.astype(inputs[0].dtype)]

    def output_dim_roles(self):
        shp = self.output_shapes[0]
        return [tuple(DimRole.SAMPLE if i == 0 else DimRole.OTHER for i in range(len(shp)))]

    def flops(self):
        a, b = self.input_shapes
        batch = int(np.prod(a[:-2]))
        return 2 * batch * a[-2] * a[-1] * b[-1]

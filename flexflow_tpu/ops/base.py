"""Op base class and registry.

Analog of the reference's ``Op`` (include/flexflow/operator.h:51) with the
Legion task plumbing removed: an Op here is (a) a pure forward function
``forward(params, inputs, ctx)`` traced into the jitted step, (b) parameter
initialization, (c) cost metadata (flops / bytes) for the simulator, and
(d) dimension-role metadata that tells the search which dims are legal to
shard (the reference encodes this as is_valid_parallel_config +
substitution applicability).

The reference's per-op ``*Params`` structs (dedup/cache keys,
include/flexflow/ops/linear_params.h) map to ``Op.param_key()``.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.layer import Layer
from flexflow_tpu.tensor import Tensor


class DimRole(enum.Enum):
    """Role of an output dimension — drives the legal sharding axes."""

    SAMPLE = "sample"  # batch dim: data parallelism
    CHANNEL = "channel"  # feature dim: parameter (tensor) parallelism
    HEAD = "head"  # attention head dim: attribute parallelism
    SEQ = "seq"  # sequence dim: context parallelism
    EXPERT = "expert"  # MoE expert dim
    OTHER = "other"  # never sharded


class OpContext:
    """Per-call context threaded through forward: training flag, rng, policy."""

    def __init__(self, training: bool = False, rng: Optional[jax.Array] = None,
                 compute_dtype=jnp.float32, seq_length: Optional[int] = None,
                 mesh=None):
        self.training = training
        self.rng = rng
        self.compute_dtype = compute_dtype
        self.seq_length = seq_length
        self.mesh = mesh  # jax.sharding.Mesh for ops needing manual collectives

    def next_rng(self) -> jax.Array:
        if self.rng is None:
            raise ValueError("op needs rng but none provided")
        self.rng, sub = jax.random.split(self.rng)
        return sub


class Op:
    op_type: OperatorType = OperatorType.NOOP

    def __init__(self, layer: Layer, input_shapes: Sequence[Tuple[int, ...]]):
        self.layer = layer
        self.name = layer.name
        self.guid = layer.guid
        self.input_shapes: List[Tuple[int, ...]] = [tuple(s) for s in input_shapes]
        self.output_shapes: List[Tuple[int, ...]] = self.compute_output_shapes()
        self.dtype: DataType = layer.data_type

    # ---- graph-construction interface -------------------------------------
    def compute_output_shapes(self) -> List[Tuple[int, ...]]:
        raise NotImplementedError

    def init_params(self, rng: jax.Array) -> Dict[str, jax.Array]:
        """Initialize trainable parameters; {} for param-free ops."""
        return {}

    def forward(self, params: Dict[str, jax.Array], inputs: List[jax.Array],
                ctx: OpContext) -> List[jax.Array]:
        raise NotImplementedError

    # ---- search metadata ---------------------------------------------------
    def output_dim_roles(self) -> List[Tuple[DimRole, ...]]:
        """Per-output tuple of DimRoles; default: dim0=SAMPLE, rest OTHER."""
        roles = []
        for shp in self.output_shapes:
            roles.append(
                tuple(
                    DimRole.SAMPLE if i == 0 else DimRole.OTHER
                    for i in range(len(shp))
                )
            )
        return roles

    def flops(self) -> int:
        """Forward-pass FLOPs (global, unsharded). Backward ≈ 2x."""
        return 2 * sum(int(np.prod(s)) for s in self.output_shapes)

    def params_elems(self) -> int:
        return 0

    def param_key(self) -> Tuple:
        """Structural identity for node dedup / cost caching
        (analog of *Params hashing, model.h:677)."""
        return (
            self.op_type,
            tuple(self.input_shapes),
            tuple(sorted(
                (k, repr(v)) for k, v in self.layer.properties.items()
            )),
        )

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class OpRegistry:
    _by_type: Dict[OperatorType, Callable[..., Op]] = {}

    @classmethod
    def create(cls, layer: Layer, input_shapes) -> Op:
        if layer.op_type not in cls._by_type:
            raise NotImplementedError(f"no Op registered for {layer.op_type}")
        return cls._by_type[layer.op_type](layer, input_shapes)


def register_op(op_type: OperatorType):
    def deco(klass):
        klass.op_type = op_type
        OpRegistry._by_type[op_type] = klass
        return klass

    return deco

"""Parallel (resharding) operators — first-class PCG citizens.

TPU re-design of src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc (base ParallelOp, include/flexflow/parallel_ops/
parallel_op.h:17). In the reference these ops *re-partition Legion
regions*; under XLA they lower to ``with_sharding_constraint`` boundaries,
and GSPMD inserts the collective that realizes the movement:

* ``Repartition(dim, degree)`` → constrain output sharded on ``dim``
  (scatter / collective-permute in GSPMD terms);
* ``Combine(dim, degree)``      → constrain output unsharded on ``dim``
  (all-gather over ICI);
* ``Replicate(degree)``          → constrain fully replicated (broadcast);
* ``Reduction(degree)``          → sum partial replicas (psum /
  reduce-scatter). Under full-auto GSPMD partial-sum tensors never escape
  an op, so Reduction sums an explicit leading replica dim instead —
  semantically identical to the reference, where the replica dim is a real
  tensor dim (parallel_tensor.h:40);
* ``FusedParallelOp`` — a chain of the above collapsed to one constraint
  (analog of fuse_parallel_ops, src/runtime/substitution.cc:1925).

The mesh axis carrying each op's degree is resolved at strategy-application
time; these ops also serve as user-facing manual overrides exactly like the
reference's explicit API calls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


class ParallelOpBase(Op):
    """Common behavior: identity compute; sharding decided by strategy.

    ``preferred_spec_update(spec_entries)`` lets each parallel op rewrite
    the inherited PartitionSpec entries; the executor applies the result as
    a constraint after forward.
    """

    is_parallel_op = True

    def flops(self):
        return 0

    def output_dim_roles(self):
        return [
            tuple(DimRole.SAMPLE if i == 0 else DimRole.OTHER for i in range(len(s)))
            for s in self.output_shapes
        ]


@register_op(OperatorType.REPARTITION)
class Repartition(ParallelOpBase):
    """Split dim ``repartition_dim`` into ``repartition_degree`` shards
    (src/parallel_ops/partition.cc:132)."""

    def __init__(self, layer, input_shapes):
        self.repartition_dim = layer.get_property("dim", 0)
        self.repartition_degree = layer.get_property("degree", 1)
        self.axis = layer.get_property("axis", None)  # resolved mesh axis
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        shp = self.input_shapes[0]
        d = self.repartition_dim % len(shp)
        if shp[d] % self.repartition_degree:
            raise ValueError(
                f"repartition: dim {d} size {shp[d]} not divisible by "
                f"{self.repartition_degree}")
        return [tuple(shp)]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0]]

    def preferred_spec_update(self, entries):
        d = self.repartition_dim % len(self.output_shapes[0])
        entries = list(entries)
        # a Repartition RE-lays-out the tensor: if the producer already
        # used this mesh axis on another dim, that dim un-shards here
        # (GSPMD inserts the implied reshard) — the constraint owns the
        # axis, exactly like the reference's Repartition replacing the
        # ParallelTensor's layout (src/parallel_ops/partition.cc)
        for i, e in enumerate(entries):
            axes = e if isinstance(e, tuple) else (e,)
            if i != d and self.axis in axes:
                entries[i] = (tuple(a for a in axes if a != self.axis)
                              or None) if isinstance(e, tuple) else None
        entries[d] = self.axis
        return entries


@register_op(OperatorType.COMBINE)
class Combine(ParallelOpBase):
    """Gather shards of dim ``combine_dim`` back together — the all-gather
    boundary (src/parallel_ops/combine.cc:135)."""

    def __init__(self, layer, input_shapes):
        self.combine_dim = layer.get_property("dim", 0)
        self.combine_degree = layer.get_property("degree", 1)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [tuple(self.input_shapes[0])]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0]]

    def preferred_spec_update(self, entries):
        d = self.combine_dim % len(self.output_shapes[0])
        entries = list(entries)
        entries[d] = None
        return entries


@register_op(OperatorType.REPLICATE)
class Replicate(ParallelOpBase):
    """Broadcast to ``replicate_degree`` replicas
    (src/parallel_ops/replicate.cc). Output constrained fully replicated."""

    def __init__(self, layer, input_shapes):
        self.replicate_degree = layer.get_property("degree", 1)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [tuple(self.input_shapes[0])]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0]]

    def preferred_spec_update(self, entries):
        return [None] * len(entries)


@register_op(OperatorType.REDUCTION)
class Reduction(ParallelOpBase):
    """Sum ``reduction_degree`` partial replicas laid out along dim
    ``reduction_dim`` (src/parallel_ops/reduction.cc). The replica dim is
    explicit here (a real tensor dim, as in parallel_tensor.h:40): input
    shape (..., k*d, ...) reduces groups of k along that dim."""

    def __init__(self, layer, input_shapes):
        self.reduction_dim = layer.get_property("dim", 0)
        self.reduction_degree = layer.get_property("degree", 1)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        shp = list(self.input_shapes[0])
        d = self.reduction_dim % len(shp)
        if shp[d] % self.reduction_degree:
            raise ValueError("reduction: size not divisible by degree")
        shp[d] //= self.reduction_degree
        return [tuple(shp)]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        d = self.reduction_dim % x.ndim
        k = self.reduction_degree
        new_shape = x.shape[:d] + (k, x.shape[d] // k) + x.shape[d + 1:]
        return [jnp.sum(x.reshape(new_shape), axis=d)]


@register_op(OperatorType.FUSED_PARALLEL)
class FusedParallelOp(ParallelOpBase):
    """Chain of parallel-op descriptors applied as one boundary
    (include/flexflow/parallel_ops/fused_parallel_op.h:15). Property
    ``ops`` is a list of (op_type, dim, degree, axis) tuples."""

    def __init__(self, layer, input_shapes):
        self.fused_ops = [
            (OperatorType[k] if isinstance(k, str) else k, d, g, a)
            for (k, d, g, a) in layer.get_property("ops", [])
        ]
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        shp = list(self.input_shapes[0])
        for (kind, dim, degree, _axis) in self.fused_ops:
            if kind == OperatorType.REDUCTION:
                d = dim % len(shp)
                if shp[d] % degree:
                    raise ValueError("fused reduction: size not divisible")
                shp[d] //= degree
        return [tuple(shp)]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        for (kind, dim, degree, _axis) in self.fused_ops:
            if kind == OperatorType.REDUCTION:
                d = dim % x.ndim
                new_shape = x.shape[:d] + (degree, x.shape[d] // degree) + x.shape[d + 1:]
                x = jnp.sum(x.reshape(new_shape), axis=d)
        return [x]

    def preferred_spec_update(self, entries):
        entries = list(entries)
        for (kind, dim, degree, axis) in self.fused_ops:
            if kind == OperatorType.REPARTITION:
                entries[dim % len(entries)] = axis
            elif kind == OperatorType.COMBINE:
                entries[dim % len(entries)] = None
            elif kind == OperatorType.REPLICATE:
                entries = [None] * len(entries)
        return entries

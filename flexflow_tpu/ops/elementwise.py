"""ElementUnary / ElementBinary / scalar ops.

Analogs of src/ops/element_unary.cc/.cu and element_binary.cc (+ kernels):
exp/sin/cos/relu/gelu/sigmoid/tanh/elu/pow/rsqrt/identity/scalar_* and
add/sub/mul/div/max/min with numpy broadcast. Trivially XLA-fused.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op

_UNARY_FNS = {
    OperatorType.EXP: jnp.exp,
    OperatorType.SIN: jnp.sin,
    OperatorType.COS: jnp.cos,
    OperatorType.RELU: jax.nn.relu,
    OperatorType.GELU: jax.nn.gelu,
    OperatorType.SIGMOID: jax.nn.sigmoid,
    OperatorType.TANH: jnp.tanh,
    OperatorType.ELU: jax.nn.elu,
    OperatorType.RSQRT: jax.lax.rsqrt,
    OperatorType.LOG: jnp.log,
    OperatorType.IDENTITY: lambda x: x,
}

_BINARY_FNS = {
    OperatorType.EW_ADD: jnp.add,
    OperatorType.EW_SUB: jnp.subtract,
    OperatorType.EW_MUL: jnp.multiply,
    OperatorType.EW_DIV: jnp.divide,
    OperatorType.EW_MAX: jnp.maximum,
    OperatorType.EW_MIN: jnp.minimum,
}

_SCALAR_FNS = {
    OperatorType.SCALAR_MULTIPLY: lambda x, s: x * s,
    OperatorType.SCALAR_ADD: lambda x, s: x + s,
    OperatorType.SCALAR_SUB: lambda x, s: x - s,
    OperatorType.SCALAR_TRUE_DIV: lambda x, s: x / s,
    OperatorType.POW: lambda x, s: jnp.power(x, s),
}


class ElementUnary(Op):
    def __init__(self, layer, input_shapes):
        self.scalar = layer.get_property("scalar")
        self.inplace = layer.get_property("inplace", False)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        t = self.layer.op_type
        if t in _SCALAR_FNS:
            return [_SCALAR_FNS[t](x, self.scalar)]
        return [_UNARY_FNS[t](x)]

    def output_dim_roles(self):
        return [_elementwise_roles(self.output_shapes[0])]


class ElementBinary(Op):
    def compute_output_shapes(self):
        a, b = self.input_shapes
        return [tuple(np.broadcast_shapes(a, b))]

    def forward(self, params, inputs, ctx: OpContext):
        a, b = inputs
        return [_BINARY_FNS[self.layer.op_type](a, b)]

    def output_dim_roles(self):
        return [_elementwise_roles(self.output_shapes[0])]


def _elementwise_roles(shp):
    """dim0 sample; dim1 of a rank-3 tensor is a position dim the op treats
    independently — declared SEQ so context parallelism flows through.
    Rank-4 (NCHW image) activations keep dim1 = channel = OTHER."""
    roles = [DimRole.SAMPLE if i == 0 else DimRole.OTHER
             for i in range(len(shp))]
    if len(shp) == 3:
        roles[1] = DimRole.SEQ
    return tuple(roles)


for _t in list(_UNARY_FNS) + list(_SCALAR_FNS):
    register_op(_t)(type(f"ElementUnary_{_t.name}", (ElementUnary,), {}))
for _t in _BINARY_FNS:
    register_op(_t)(type(f"ElementBinary_{_t.name}", (ElementBinary,), {}))

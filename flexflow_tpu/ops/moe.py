"""Mixture-of-Experts ops: GroupBy, Aggregate, AggregateSpec, Cache.

Analogs of src/ops/{group_by,aggregate,aggregate_spec,cache}.cc/.cu.
TPU re-design: the reference scatters tokens into per-expert CUDA buffers
with dynamic counts; under XLA everything must be static-shape, so dispatch
is expressed GShard-style — one-hot dispatch/combine tensors with a fixed
per-expert capacity (capacity factor `alpha`, same knob as the reference's
Group_by alpha) — lowered to einsums on the MXU, and to all_to_all over the
'expert' mesh axis when experts are sharded (see parallel/expert.py).
"""

from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


def expert_capacity(batch: int, k: int, n_experts: int, alpha: float) -> int:
    return max(1, int(math.ceil(alpha * k * batch / n_experts)))


def load_balance_loss(assign, gate, n_experts: int, lambda_bal: float):
    """Switch/GShard auxiliary loss: lambda * E * <f, P> with f the token
    fraction per expert over ALL top-k slots (the reference's Aggregate
    backward loops every k slot, src/ops/aggregate.cu agg_backward_kernel)
    and P the mean router probability. assign [B,K] int, gate [B,E]."""
    f = jnp.mean(jax.nn.one_hot(assign, n_experts, dtype=jnp.float32),
                 axis=(0, 1))
    p_mean = jnp.mean(gate.astype(jnp.float32), axis=0)
    return lambda_bal * n_experts * jnp.sum(f * p_mean)


def make_dispatch_tensors(assign, gates, n_experts: int, capacity: int):
    """assign [B,K] int, gates [B,K] -> dispatch [B,K,E,C] bool-ish f32,
    combine [B,K,E,C] f32 (gate-weighted), overflow dropped."""
    b, k = assign.shape
    expert_onehot = jax.nn.one_hot(assign, n_experts, dtype=jnp.float32)  # [B,K,E]
    flat = expert_onehot.reshape(b * k, n_experts)
    # position of each (token, slot) within its expert, in flat order
    pos = jnp.cumsum(flat, axis=0) * flat - flat  # [B*K, E], 0-based
    pos = pos.reshape(b, k, n_experts)
    in_cap = pos < capacity
    pos_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = expert_onehot[..., None] * pos_onehot * in_cap[..., None]
    combine = dispatch * gates[..., None, None]
    return dispatch, combine


@register_op(OperatorType.GROUP_BY)
class GroupBy(Op):
    """inputs: (data [B,D], assign [B,K]) -> n_experts tensors [C, D].

    Reference Group_by (src/ops/group_by.cu) writes variable-count rows per
    expert buffer sized alpha*K*B/n; we produce fixed-capacity buffers via
    the dispatch einsum (overflowed tokens drop, as in the reference).
    """

    def __init__(self, layer, input_shapes):
        self.n_experts = layer.get_property("n")
        self.alpha = layer.get_property("alpha", 1.0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        data, assign = self.input_shapes
        b, k = assign
        cap = expert_capacity(b, k, self.n_experts, self.alpha)
        return [(cap, data[-1])] * self.n_experts

    def forward(self, params, inputs, ctx: OpContext):
        data, assign = inputs
        b, k = assign.shape
        cap = expert_capacity(b, k, self.n_experts, self.alpha)
        dispatch, _ = make_dispatch_tensors(
            assign, jnp.ones(assign.shape, jnp.float32), self.n_experts, cap
        )
        grouped = jnp.einsum("bd,bkec->ecd", data.astype(jnp.float32), dispatch)
        return [grouped[e].astype(data.dtype) for e in range(self.n_experts)]

    def output_dim_roles(self):
        return [(DimRole.OTHER, DimRole.CHANNEL)] * self.n_experts


@register_op(OperatorType.AGGREGATE)
class Aggregate(Op):
    """inputs: (gate_preds [B,K], gate_assign [B,K], true_gate_assign [B,K],
    gate_grads [B,K], expert_out_0 [C,D] ... expert_out_{n-1}) -> [B,D].

    Matches the reference's 4+n input signature (src/ops/aggregate.cc) —
    the two extra assign/grad inputs exist for the load-balance loss path;
    autodiff handles the gate gradient here so they are accepted and the
    lb loss is exposed via aggregate load stats.
    """

    def __init__(self, layer, input_shapes):
        self.n_experts = layer.get_property("n")
        self.lambda_bal = layer.get_property("lambda_bal", 0.0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        b, k = self.input_shapes[0]
        d = self.input_shapes[-1][-1]
        return [(b, d)]

    def forward(self, params, inputs, ctx: OpContext):
        gate_preds, gate_assign = inputs[0], inputs[1]
        expert_outs = inputs[-self.n_experts:]
        b, k = gate_assign.shape
        cap = expert_outs[0].shape[0]
        _, combine = make_dispatch_tensors(
            gate_assign, gate_preds.astype(jnp.float32), self.n_experts, cap
        )
        stacked = jnp.stack(expert_outs, axis=0).astype(jnp.float32)  # [E,C,D]
        out = jnp.einsum("bkec,ecd->bd", combine, stacked)
        if self.lambda_bal > 0.0 and len(inputs) >= 4 + self.n_experts:
            # inputs[3] is the full gate output [B, E] from the moe sugar
            self._aux_loss = load_balance_loss(
                gate_assign, inputs[3], self.n_experts, self.lambda_bal)
        return [out.astype(expert_outs[0].dtype)]

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.CHANNEL)]


@register_op(OperatorType.AGGREGATE_SPEC)
class AggregateSpec(Op):
    """Speculative aggregate (src/ops/aggregate_spec.cc): same combine but
    experts received *all* K assignments; output matches Aggregate."""

    def __init__(self, layer, input_shapes):
        self.n_experts = layer.get_property("n")
        self.lambda_bal = layer.get_property("lambda_bal", 0.0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        b, k = self.input_shapes[0]
        d = self.input_shapes[-1][-1]
        return [(b, d)]

    forward = Aggregate.forward
    output_dim_roles = Aggregate.output_dim_roles


@register_op(OperatorType.CACHE)
class Cache(Op):
    """Activation/score cache (src/ops/cache.cc): stores the input tensor
    across iterations; a user-provided score function decides whether the
    cached value is fresh enough to reuse. State lives in the model's
    non-trainable state collection; under jit the trigger works on
    materialized scores (host callback-free: score is returned as a metric).
    """

    def __init__(self, layer, input_shapes):
        self.num_batches = layer.get_property("num_batches", 1)
        self.score_fn = layer.get_property("score_fn")
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def init_state(self):
        return {
            "cached": jnp.zeros(self.input_shapes[0]),
            "score": jnp.zeros(()),
        }

    def forward(self, params, inputs, ctx: OpContext, state=None):
        (x,) = inputs
        if state is not None:
            score = (
                self.score_fn(state["cached"], x)
                if self.score_fn is not None
                else jnp.mean((state["cached"] - x) ** 2)
            )
            self._new_state = {"cached": x, "score": score}
        return [x]

"""Fused MoE Experts op: gate -> top-k dispatch -> expert FFN -> combine.

TPU-native fusion of the reference's MoE subgraph (topk + group_by +
per-expert Linear pairs + aggregate, model.h:507-512 `FFModel::moe` and
examples/cpp/mixture_of_experts/moe.cc:42-53): under SPMD the per-expert
ops cannot live on different devices, so the experts become one op with
stacked weights [E, ...] whose leading dim is sharded over the 'expert'
mesh axis — the placement the reference's search assigns per-op
(moe.cc:65-83) becomes a sharding choice on this node. Dispatch runs as
einsums on replicated routing tensors; with an expert axis the token
exchange is an explicit reduce-scatter/all-gather pair inside shard_map
(parallel/expert.py).

The load-balance auxiliary loss uses the FULL top-k assignment (every
selected expert counts toward the token fraction), matching the reference's
Aggregate backward which accumulates over all k slots (src/ops/aggregate.cu
agg_backward_kernel loops k).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.initializers import DefaultWeightInitializer
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op
from flexflow_tpu.ops.moe import (expert_capacity, load_balance_loss,
                                  make_dispatch_tensors)


@register_op(OperatorType.EXPERTS)
class Experts(Op):
    """inputs: (x [B, D], gate [B, E] router probabilities) -> [B, D]."""

    def __init__(self, layer, input_shapes):
        p = layer.properties
        self.n_experts = p["n"]
        self.k = p.get("k", 1)
        self.hidden_size = p["hidden_size"]
        self.alpha = p.get("alpha", 2.0)
        self.lambda_bal = p.get("lambda_bal", 0.0)
        # mesh axis experts are sharded over; set by the search when it
        # picks an "_ep" choice (or by the user at build time)
        self.expert_parallel = p.get("expert_parallel", None)
        self.kernel_init = p.get("kernel_initializer") or DefaultWeightInitializer()
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        b, d = self.input_shapes[0]
        return [(b, d)]

    def init_params(self, rng):
        e = self.n_experts
        d = self.input_shapes[0][-1]
        h = self.hidden_size
        ks = jax.random.split(rng, 2)
        return {
            "w_h": self.kernel_init(ks[0], (e, d, h)),
            "b_h": jnp.zeros((e, h)),
            "w_o": self.kernel_init(ks[1], (e, h, d)),
            "b_o": jnp.zeros((e, d)),
        }

    def forward(self, params, inputs, ctx: OpContext):
        x, gate = inputs
        b = x.shape[0]
        values, assign = jax.lax.top_k(gate, self.k)
        cap = expert_capacity(b, self.k, self.n_experts, self.alpha)
        dispatch, combine = make_dispatch_tensors(
            assign, values.astype(jnp.float32), self.n_experts, cap)

        from flexflow_tpu.parallel.expert import (_mesh_axes, dense_moe_ffn,
                                                  expert_parallel_ffn)

        axis = self.expert_parallel
        mesh_axes = _mesh_axes(ctx.mesh) if ctx.mesh is not None else {}
        if axis and mesh_axes.get(axis, 1) > 1:
            y = expert_parallel_ffn(
                x, dispatch, combine, params["w_h"], params["b_h"],
                params["w_o"], params["b_o"], ctx.mesh, expert_axis=axis)
        else:
            y = dense_moe_ffn(x, dispatch, combine, params["w_h"],
                              params["b_h"], params["w_o"], params["b_o"])

        if self.lambda_bal > 0.0:
            self._aux_loss = load_balance_loss(assign, gate, self.n_experts,
                                               self.lambda_bal)
        return [y]

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.CHANNEL)]

    def flops(self):
        b, d = self.input_shapes[0]
        cap = expert_capacity(b, self.k, self.n_experts, self.alpha)
        e, h = self.n_experts, self.hidden_size
        ffn = 2 * e * cap * d * h * 2
        route = 2 * b * self.k * e * cap * d * 2  # dispatch + combine einsums
        return ffn + route

    def params_elems(self):
        e, h = self.n_experts, self.hidden_size
        d = self.input_shapes[0][-1]
        return e * (d * h + h + h * d + d)

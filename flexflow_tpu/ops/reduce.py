"""ReduceSum / Mean / TopK / ArgTopK.

Analogs of src/ops/{reduce,mean,topk}.cc/.cu. TopK uses lax.top_k (TPU
sort-based) instead of the reference's custom GPU heap kernel.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


def _reduced_shape(shape, axes, keepdims):
    axes = tuple(a % len(shape) for a in axes)
    if keepdims:
        return tuple(1 if i in axes else s for i, s in enumerate(shape))
    return tuple(s for i, s in enumerate(shape) if i not in axes)


@register_op(OperatorType.REDUCE_SUM)
class ReduceSum(Op):
    def __init__(self, layer, input_shapes):
        self.axes = tuple(layer.get_property("axes"))
        self.keepdims = layer.get_property("keepdims", False)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [_reduced_shape(self.input_shapes[0], self.axes, self.keepdims)]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.sum(inputs[0], axis=self.axes, keepdims=self.keepdims)]


@register_op(OperatorType.MEAN)
class Mean(Op):
    def __init__(self, layer, input_shapes):
        self.axes = tuple(layer.get_property("axes"))
        self.keepdims = layer.get_property("keepdims", False)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [_reduced_shape(self.input_shapes[0], self.axes, self.keepdims)]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.mean(inputs[0], axis=self.axes, keepdims=self.keepdims)]


@register_op(OperatorType.TOPK)
class TopK(Op):
    """Returns (values, indices) of the k largest along the last dim."""

    def __init__(self, layer, input_shapes):
        self.k = layer.get_property("k")
        self.sorted = layer.get_property("sorted", True)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        s = tuple(self.input_shapes[0][:-1]) + (self.k,)
        return [s, s]

    def forward(self, params, inputs, ctx: OpContext):
        vals, idx = lax.top_k(inputs[0], self.k)
        return [vals, idx]


@register_op(OperatorType.ARG_TOPK)
class ArgTopK(Op):
    def __init__(self, layer, input_shapes):
        self.k = layer.get_property("k")
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [tuple(self.input_shapes[0][:-1]) + (self.k,)]

    def forward(self, params, inputs, ctx: OpContext):
        _, idx = lax.top_k(inputs[0], self.k)
        return [idx]

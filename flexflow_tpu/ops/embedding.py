"""Embedding.

Analog of src/ops/embedding.cc (+ kernels): aggregation modes SUM/AVG/NONE
over a bag of token ids. The vocab (or output) dim of the weight is the
parameter-parallel shardable axis used by DLRM-style strategies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import AggrMode, OperatorType
from flexflow_tpu.initializers import DefaultWeightInitializer
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


@register_op(OperatorType.EMBEDDING)
class Embedding(Op):
    """input ids [B, S](int) -> [B, out_dim] (SUM/AVG over S) or
    [B, S, out_dim] (AGGR_MODE_NONE)."""

    def __init__(self, layer, input_shapes):
        p = layer.properties
        self.num_entries = p["num_entries"]
        self.out_dim = p["out_dim"]
        self.aggr = p.get("aggr", AggrMode.AGGR_MODE_NONE)
        self.kernel_init = p.get("kernel_initializer") or DefaultWeightInitializer()
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        in_shape = self.input_shapes[0]
        if self.aggr == AggrMode.AGGR_MODE_NONE:
            return [tuple(in_shape) + (self.out_dim,)]
        return [tuple(in_shape[:-1]) + (self.out_dim,)]

    def init_params(self, rng):
        return {"kernel": self.kernel_init(rng, (self.num_entries, self.out_dim))}

    def forward(self, params, inputs, ctx: OpContext):
        (ids,) = inputs
        emb = jnp.take(params["kernel"], ids.astype(jnp.int32), axis=0)
        if self.aggr == AggrMode.AGGR_MODE_SUM:
            emb = jnp.sum(emb, axis=-2)
        elif self.aggr == AggrMode.AGGR_MODE_AVG:
            emb = jnp.mean(emb, axis=-2)
        return [emb]

    def output_dim_roles(self):
        # token-position dim of [B,S,E] output is a sequence dim (lookups
        # are independent per position)
        shp = self.output_shapes[0]
        mid = DimRole.SEQ if len(shp) == 3 else DimRole.OTHER
        roles = [DimRole.SAMPLE] + [mid] * (len(shp) - 2) + [DimRole.CHANNEL]
        return [tuple(roles)]

    def params_elems(self):
        return self.num_entries * self.out_dim

"""Operator library: pure-JAX compute ops + metadata for the search.

Analog of the reference's src/ops/*.cc + kernels (SURVEY §2.2), with the
CUDA kernels replaced by XLA HLO lowerings (and Pallas where XLA
underperforms). There are no hand-written backward kernels: autodiff over
the composed forward provides every *_BWD task of the reference.
"""

from flexflow_tpu.ops.base import Op, OpRegistry, register_op
import flexflow_tpu.ops.linear  # noqa: F401
import flexflow_tpu.ops.conv  # noqa: F401
import flexflow_tpu.ops.attention  # noqa: F401
import flexflow_tpu.ops.norm  # noqa: F401
import flexflow_tpu.ops.elementwise  # noqa: F401
import flexflow_tpu.ops.tensor_ops  # noqa: F401
import flexflow_tpu.ops.matmul  # noqa: F401
import flexflow_tpu.ops.embedding  # noqa: F401
import flexflow_tpu.ops.reduce  # noqa: F401
import flexflow_tpu.ops.moe  # noqa: F401
import flexflow_tpu.ops.experts  # noqa: F401
import flexflow_tpu.ops.parallel_ops  # noqa: F401

__all__ = ["Op", "OpRegistry", "register_op"]

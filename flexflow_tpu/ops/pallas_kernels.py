"""Pallas TPU kernels for the ops where XLA's default lowering underperforms.

Analog of the reference's hand-written CUDA kernels (src/ops/kernels/*.cu)
— but only where needed: XLA already fuses elementwise chains into matmuls,
so the win is in attention, where materializing the [B,H,S,S] score tensor
in HBM is the bottleneck. ``flash_attention`` streams K/V through VMEM per
Q block with the standard online-softmax accumulation, keeping scores
on-chip.

Forward is the Pallas kernel; backward is a custom_vjp that recomputes
attention with the XLA einsum path (flash backward's extra kernel isn't
worth it at the sequence lengths the bench protocol uses; recompute is the
remat-friendly choice on TPU where HBM, not FLOPs, is the limit).

CPU fallback: the same kernel runs under ``interpret=True`` when
FLEXFLOW_TPU_PALLAS=interpret (used by the deviceless tests); otherwise
non-TPU backends take the XLA path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLK_Q = 128  # rows of Q per grid step (MXU-aligned)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal: bool, scale: float):
    """One (batch*head, q-block) grid cell: q [1,BLK_Q,D] against the full
    K/V [1,S,D] resident in VMEM; scores never touch HBM."""
    q = q_ref[0].astype(jnp.float32)  # [BLK_Q, D]
    k = k_ref[0].astype(jnp.float32)  # [S, D]
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        blk = pl.program_id(1)
        rows = blk * BLK_Q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= rows, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, interpret: bool):
    """q,k,v: [BH, S, D] with S % BLK_Q == 0."""
    bh, s, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    kern = functools.partial(_flash_fwd_kernel, causal=causal, scale=scale)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=(bh, s // BLK_Q),
        in_specs=[
            pl.BlockSpec((1, BLK_Q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLK_Q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


def _xla_attention(q, k, v, causal: bool):
    """Reference einsum path (used for backward recompute + fallback)."""
    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(jnp.float32(d))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    return _flash_fwd(q, k, v, causal, interpret)


def _flash_vjp_fwd(q, k, v, causal, interpret):
    return _flash_fwd(q, k, v, causal, interpret), (q, k, v)


def _flash_vjp_bwd(causal, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def pallas_mode() -> str:
    """'tpu' (compile), 'interpret' (CPU emulation for tests), or 'off'."""
    env = os.environ.get("FLEXFLOW_TPU_PALLAS", "auto")
    if env in ("interpret", "off"):
        return env
    return "tpu" if jax.default_backend() == "tpu" else "off"


# Measured on v5e (amortized, causal, b=4 h=16 d=64): XLA wins at S=512
# (0.89x), flash wins from S=1024 (1.27x) to S=4096 (2.53x), and XLA OOMs
# at S=8192 where flash still runs. Gate accordingly.
MIN_SEQ_FOR_FLASH = 1024


def flash_attention_available(seq_len: int, head_dim: int) -> bool:
    mode = pallas_mode()
    if mode == "off" or seq_len % BLK_Q or head_dim % 8:
        return False
    # interpret mode (tests) exercises any legal shape; on hardware only
    # take over where the kernel beats XLA
    return mode == "interpret" or seq_len >= MIN_SEQ_FOR_FLASH


def flash_attention(q, k, v, causal: bool = False):
    """q,k,v: [B, H, S, D] → [B, H, S, D]. Caller checks
    flash_attention_available first; self-attention only (Sq == Sk)."""
    b, h, s, d = q.shape
    interpret = pallas_mode() == "interpret"
    fold = lambda x: x.reshape(b * h, x.shape[2], d)
    o = _flash(fold(q), fold(k), fold(v), causal, interpret)
    return o.reshape(b, h, s, d)

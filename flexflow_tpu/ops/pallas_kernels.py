"""Pallas TPU kernels for the ops where XLA's default lowering underperforms.

Analog of the reference's hand-written CUDA kernels (src/ops/kernels/*.cu)
— but only where needed: XLA already fuses elementwise chains into matmuls,
so the win is in attention, where materializing the [B,H,S,S] score tensor
in HBM is the bottleneck. ``flash_attention`` streams K/V through VMEM per
Q block with the standard online-softmax accumulation, keeping scores
on-chip.

Forward is the Pallas kernel (it also emits the per-row logsumexp).
Backward: for sequences whose full S x S score tile fits VMEM
(S <= MAX_BWD_SEQ) a fused Pallas backward kernel recomputes P from the
saved LSE and produces dQ/dK/dV without ever materializing scores in HBM
— slope-measured 1.87x over the XLA einsum fwd+bwd at the bench shape
(b8 h16 s512 d64; 601us vs 1124us). Longer sequences fall back to XLA-einsum recompute
(the remat-friendly choice where the score tensor wouldn't fit anyway).

CPU fallback: the same kernels run under ``interpret=True`` when
FLEXFLOW_TPU_PALLAS=interpret (used by the deviceless tests); otherwise
non-TPU backends take the XLA path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLK_Q = 128  # rows of Q per grid step (MXU-aligned)


def _fwd_blk(s: int) -> int:
    """Q-block rows for the forward kernel. 128 everywhere: a same-chip
    A/B through the FULL bert train step measured 228.1 samples/s at 128
    vs 222.3 at 256 (r5) — an isolated-kernel microbench had suggested
    256, but in the fused step the larger block loses (and a 256-block
    forward feeding the single-block backward triggers a pathological
    relayout in standalone use). Keep the block parameterized so the
    experiment stays one-line."""
    return BLK_Q


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                      scale: float, blk_q: int):
    """One (batch*head, q-block) grid cell: q [1,BLK_Q,D] against the full
    K/V [1,S,D] resident in VMEM; scores never touch HBM. Also emits the
    per-row logsumexp so the fused backward can recompute P exactly."""
    q = q_ref[0].astype(jnp.float32)  # [BLK_Q, D]
    k = k_ref[0].astype(jnp.float32)  # [S, D]
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        blk = pl.program_id(1)
        rows = blk * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= rows, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, causal: bool, interpret: bool, out_dtype=None):
    """q,k,v: [BH, S, D] with S % BLK_Q == 0 -> (o, lse[BH, S])."""
    bh, s, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    blk = _fwd_blk(s)
    kern = functools.partial(_flash_fwd_kernel, causal=causal, scale=scale,
                             blk_q=blk)
    return pl.pallas_call(
        kern,
        # lse is (bh, 1, s): TPU requires the last two block dims be
        # (8,128)-aligned or span the array — a middle singleton satisfies
        # that while keeping one row per (batch*head)
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), out_dtype or q.dtype),
                   jax.ShapeDtypeStruct((bh, 1, s), jnp.float32)),
        grid=(bh, s // blk),
        in_specs=[
            pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 1, blk), lambda b, i: (b, 0, i))),
        interpret=interpret,
    )(q, k, v)


# Longest sequence whose full S x S f32 score tile (plus q/k/v/do/dq/dk/dv
# panels) fits one core's VMEM in the single-block backward kernel.
MAX_BWD_SEQ = 1024
# Longest sequence the K-blocked backward kernel handles: VMEM holds the
# full Q/dO/dQ panels (S x D) plus S x BLK_Q score tiles — ~2.2 KB per row
# at D=64, so 16k rows ~= 35 MB, comfortably inside a v5e core's VMEM.
MAX_BWD_BLOCKED_SEQ = 16384


def _flash_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      glse_ref, dq_ref, dk_ref, dv_ref, *, causal: bool,
                      scale: float):
    """FlashAttention-2 backward, one (batch*head) per grid cell with the
    whole sequence in VMEM (gated by MAX_BWD_SEQ): recompute P from Q,K and
    the saved LSE, then dV = P^T dO; dS = P * (dO V^T - delta + g_lse);
    dQ = dS K * scale; dK = dS^T Q * scale. Scores/probabilities never
    touch HBM — the reason XLA's einsum backward loses at these shapes.
    ``g_lse`` is the upstream gradient on the logsumexp output (zero when
    only o is consumed; nonzero under ring attention's streaming merge,
    where the merge weights are functions of each block's lse)."""
    q = q_ref[0].astype(jnp.float32)   # [S, D]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]                 # [S]
    delta = delta_ref[0, 0]             # [S] rowsum(dO * O)
    glse = glse_ref[0, 0]               # [S] upstream d/d lse
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= rows, s, -jnp.inf)
    p = jnp.exp(s - lse[:, None])       # exact softmax probs
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None] + glse[:, None])
    dq_ref[0] = (jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                 * scale).astype(dq_ref.dtype)
    dk_ref[0] = (jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                 * scale).astype(dk_ref.dtype)
    dv_ref[0] = jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32
                                    ).astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal: bool, interpret: bool,
               glse=None):
    bh, s, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]
    if glse is None:
        glse = jnp.zeros((bh, 1, s), jnp.float32)
    kern = functools.partial(_flash_bwd_kernel, causal=causal, scale=scale)
    seq_spec = pl.BlockSpec((1, s, d), lambda b: (b, 0, 0))
    row_spec = pl.BlockSpec((1, 1, s), lambda b: (b, 0, 0))
    return pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)),
        grid=(bh,),
        in_specs=[seq_spec, seq_spec, seq_spec, seq_spec, row_spec,
                  row_spec, row_spec],
        out_specs=(seq_spec, seq_spec, seq_spec),
        interpret=interpret,
    )(q, k, v, do, lse, delta, glse)


def _flash_bwd_blocked_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, glse_ref, dq_ref, dk_ref, dv_ref,
                              *, causal: bool, scale: float, blk: int):
    """FA2 backward for sequences past MAX_BWD_SEQ: grid cell = one
    (batch*head, K-block). The full Q/dO panels are resident; the
    [S, BLK] score tile for this K-block is recomputed in VMEM; dK/dV
    write their block, and dQ accumulates in-place across the K-block
    grid dimension (same output block revisited -> Pallas keeps it in
    VMEM between consecutive steps)."""
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)    # [S, D]
    k = k_ref[0].astype(jnp.float32)    # [BLK, D]
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)  # [S, D]
    lse = lse_ref[0, 0]                 # [S]
    delta = delta_ref[0, 0]
    glse = glse_ref[0, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= rows, s, -jnp.inf)
    p = jnp.exp(s - lse[:, None])       # [S, BLK]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None] + glse[:, None])
    dk_ref[0] = (jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                 * scale).astype(dk_ref.dtype)
    dv_ref[0] = jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32
                                    ).astype(dv_ref.dtype)
    dq_blk = (jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
              * scale)

    @pl.when(j == 0)
    def _init():
        dq_ref[0] = dq_blk

    @pl.when(j > 0)
    def _acc():
        dq_ref[0] += dq_blk


def _flash_bwd_blocked(q, k, v, o, lse, do, causal: bool, interpret: bool,
                       glse=None):
    bh, s, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]
    if glse is None:
        glse = jnp.zeros((bh, 1, s), jnp.float32)
    blk = BLK_Q
    kern = functools.partial(_flash_bwd_blocked_kernel, causal=causal,
                             scale=scale, blk=blk)
    seq_spec = pl.BlockSpec((1, s, d), lambda b, j: (b, 0, 0))
    kblk_spec = pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, 1, s), lambda b, j: (b, 0, 0))
    dq, dk, dv = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), jnp.float32),  # dq acc
                   jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)),
        grid=(bh, s // blk),
        in_specs=[seq_spec, kblk_spec, kblk_spec, seq_spec, row_spec,
                  row_spec, row_spec],
        out_specs=(seq_spec, kblk_spec, kblk_spec),
        interpret=interpret,
    )(q, k, v, do, lse, delta, glse)
    return dq.astype(q.dtype), dk, dv


def _xla_attention(q, k, v, causal: bool):
    """Reference einsum path (used for backward recompute + fallback)."""
    return _xla_attention_lse(q, k, v, causal)[0]


def _xla_attention_lse(q, k, v, causal: bool):
    """Einsum path that also emits the per-row logsumexp (long-seq
    backward fallback for flash_attention_lse)."""
    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(jnp.float32(d))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    return _flash_fwd(q, k, v, causal, interpret)[0]


def _flash_vjp_fwd(q, k, v, causal, interpret):
    o, lse = _flash_fwd(q, k, v, causal, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, interpret, res, g):
    q, k, v, o, lse = res
    if q.shape[1] <= MAX_BWD_SEQ:
        return _flash_bwd(q, k, v, o, lse, g, causal, interpret)
    if q.shape[1] <= MAX_BWD_BLOCKED_SEQ:
        # K-blocked kernel: scores stay in VMEM tiles at any length the
        # Q/dO/dQ panels fit
        return _flash_bwd_blocked(q, k, v, o, lse, g, causal, interpret)
    # extreme lengths: XLA einsum recompute (materializes S x S in HBM)
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_lse(q, k, v, causal, interpret):
    """Flash attention returning (o, lse[BH, S]) — the streaming-merge
    primitive ring attention accumulates per K/V block. Differentiable:
    the backward kernel carries the upstream lse gradient (the merge
    weights are functions of lse). q,k,v: [BH, S, D]. ``o`` is emitted in
    f32: the ring merge accumulates in f32, and rounding each block's
    normalized output to bf16 first would compound per-block error."""
    o, lse = _flash_fwd(q, k, v, causal, interpret, out_dtype=jnp.float32)
    return o, lse[:, 0, :]


def _flash_lse_vjp_fwd(q, k, v, causal, interpret):
    o, lse = _flash_fwd(q, k, v, causal, interpret, out_dtype=jnp.float32)
    return (o, lse[:, 0, :]), (q, k, v, o, lse)


def _flash_lse_vjp_bwd(causal, interpret, res, gs):
    q, k, v, o, lse = res
    g_o, g_lse = gs
    glse = g_lse[:, None, :].astype(jnp.float32)
    if q.shape[1] <= MAX_BWD_SEQ:
        return _flash_bwd(q, k, v, o, lse, g_o, causal, interpret,
                          glse=glse)
    if q.shape[1] <= MAX_BWD_BLOCKED_SEQ:
        return _flash_bwd_blocked(q, k, v, o, lse, g_o, causal, interpret,
                                  glse=glse)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_attention_lse(q_, k_, v_, causal), q, k, v)
    return vjp((g_o, g_lse))


flash_attention_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def pallas_mode() -> str:
    """'tpu' (compile), 'interpret' (CPU emulation for tests), or 'off'."""
    env = os.environ.get("FLEXFLOW_TPU_PALLAS", "auto")
    if env in ("interpret", "off"):
        return env
    return "tpu" if jax.default_backend() == "tpu" else "off"


# Slope-measured on v5e (b=8 h=16 d=64, dispatch/round-trip cancelled):
# flash fwd 261us vs XLA 375us at S=512, and with the fused Pallas
# backward fwd+bwd 601us vs 1124us — flash wins from S=512 up (and XLA
# OOMs at S=8192 where flash still runs). Earlier rounds gated at 1024
# based on block_until_ready timings, which the tunneled backend renders
# meaningless (it is not a real fence).
MIN_SEQ_FOR_FLASH = 512


def flash_attention_available(seq_len: int, head_dim: int) -> bool:
    mode = pallas_mode()
    if mode == "off" or seq_len % BLK_Q or head_dim % 8:
        return False
    # interpret mode (tests) exercises any legal shape; on hardware only
    # take over where the kernel beats XLA
    return mode == "interpret" or seq_len >= MIN_SEQ_FOR_FLASH


def flash_attention(q, k, v, causal: bool = False):
    """q,k,v: [B, H, S, D] → [B, H, S, D]. Caller checks
    flash_attention_available first; self-attention only (Sq == Sk)."""
    b, h, s, d = q.shape
    interpret = pallas_mode() == "interpret"
    fold = lambda x: x.reshape(b * h, x.shape[2], d)
    o = _flash(fold(q), fold(k), fold(v), causal, interpret)
    return o.reshape(b, h, s, d)


def flash_attention_sharded(q, k, v, mesh, batch_axis=None, head_axis=None,
                            causal: bool = False):
    """Flash attention inside a GSPMD-sharded jit: a bare ``pallas_call``
    is an unpartitionable custom call to the partitioner, so wrap it in
    ``shard_map`` over the mesh axes the batch/head dims are sharded on —
    each device runs the kernel on its local [B/dp, H/mp, S, D] block
    (scores never cross shards; no collectives needed). Axes not named
    stay replicated, which GSPMD enforces on entry."""
    from flexflow_tpu.utils.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, head_axis, None, None)
    fn = functools.partial(flash_attention, causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)

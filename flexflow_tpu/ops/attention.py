"""MultiHeadAttention.

Analog of src/ops/attention.cc/.cu (cuDNN cudnnMultiHeadAttnForward,
attention.cu:35). TPU design: the four projections are MXU matmuls with an
explicit head dimension — weights are stored [num_heads, ...] so the head
dim is a first-class shardable axis (attribute parallelism,
substitution.cc:1764-1770 create_partition_attention_combine). The scaled
dot-product core is jnp.einsum, which XLA fuses; a Pallas flash-attention
kernel (ops/pallas_kernels.py) is used for long sequences when enabled.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.initializers import DefaultWeightInitializer
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


def rotary_embedding(x, *, theta: float = 10000.0, position_offset=0):
    """Apply RoPE to [B, H, S, D] (HF Llama rotate-half convention):
    positions offset..offset+S-1, inv_freq = theta^(-2i/D).
    ``position_offset`` (static or traced scalar) is the absolute
    position of the first row — the incremental-decode path rotates the
    new token at its true position, not at 0."""
    b, h, s, d = x.shape
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    pos = position_offset + jnp.arange(s, dtype=jnp.float32)
    angles = pos[:, None] * inv_freq[None, :]
    cos = jnp.concatenate([jnp.cos(angles)] * 2, axis=-1)  # [S, D]
    sin = jnp.concatenate([jnp.sin(angles)] * 2, axis=-1)
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos + rotated.astype(jnp.float32) * sin
            ).astype(x.dtype)


def scaled_dot_product_attention(q, k, v, *, causal=False, dropout_rate=0.0,
                                 rng=None, compute_dtype=jnp.float32):
    """q,k,v: [B, H, S, D] -> [B, H, S, D]. Softmax in f32 for stability."""
    d = q.shape[-1]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk",
        q.astype(compute_dtype),
        k.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    ) / jnp.sqrt(jnp.float32(d))
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd",
        probs.astype(compute_dtype),
        v.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return out


@register_op(OperatorType.MULTIHEAD_ATTENTION)
class MultiHeadAttention(Op):
    """inputs: query [B,Sq,E], key [B,Sk,E], value [B,Sk,E] -> [B,Sq,E].

    Weight layout keeps an explicit head axis: wq/wk/wv [H, E, D],
    wo [H, D, E] — the head axis is the attribute-parallel dim the search
    may shard on the model mesh axis (reference attention.cc:214).
    """

    def __init__(self, layer, input_shapes):
        p = layer.properties
        self.embed_dim = p["embed_dim"]
        self.num_heads = p["num_heads"]
        self.kdim = p.get("kdim") or self.embed_dim
        self.vdim = p.get("vdim") or self.embed_dim
        self.head_dim = self.embed_dim // self.num_heads
        self.dropout = p.get("dropout", 0.0)
        self.causal = p.get("causal", False)
        self.use_bias = p.get("bias", True)
        # grouped-query attention (Llama-family): kv heads may be fewer
        # than query heads; kv repeat to H before the core
        self.num_kv_heads = p.get("num_kv_heads") or self.num_heads
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"attention '{layer.name}': num_heads ({self.num_heads}) "
                f"must be a multiple of num_kv_heads ({self.num_kv_heads})")
        # rotary position embeddings applied to q/k after projection
        self.rope = p.get("rope", False)
        self.rope_theta = p.get("rope_theta", 10000.0)
        # separate q/k/v projection biases (torch nn.MultiheadAttention
        # parity — in_proj_bias). Off by default: they cost an extra
        # elementwise pass over q/k/v every step and native models
        # initialize them to zero anyway.
        self.qkv_bias = p.get("qkv_bias", False)
        # sequence/context parallelism: run the attention core as ring
        # attention over this mesh axis (SURVEY §5.7 — new vs reference)
        self.seq_parallel = p.get("seq_parallel", None)
        # head/attribute parallelism axis (set by the search when it picks a
        # "head" choice) so ring attention keeps heads sharded in shard_map
        self.head_parallel = p.get("head_parallel", None)
        # searched kernel implementation (ISSUE 15, set by apply_strategy
        # from the "_k:<impl>" choice suffix or pinned by model.compile):
        # "flash" forces the Pallas kernel where available, "einsum" pins
        # the reference einsum path even when flash is available, None =
        # availability-based auto pick (pre-kernel-search behavior).
        # When a forced "flash" cannot run (platform/shape), forward
        # falls back to einsum and records why in _kernel_fallback —
        # fflint FFL209 surfaces the priced-vs-executed gap.
        self.kernel_impl = p.get("kernel_impl", None)
        self._kernel_fallback = None
        # batch-dim sharding (str or tuple of mesh axes under the sample2
        # 'data+model' 2-D partition), recorded by apply_strategy
        self.batch_parallel = p.get("batch_parallel", None)
        self.kernel_init = p.get("kernel_initializer") or DefaultWeightInitializer()
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        b, sq, _ = self.input_shapes[0]
        return [(b, sq, self.embed_dim)]

    def init_params(self, rng):
        h, e, d = self.num_heads, self.embed_dim, self.head_dim
        hk = self.num_kv_heads
        ks = jax.random.split(rng, 4)
        params = {
            "wq": self.kernel_init(ks[0], (h, e, d)),
            "wk": self.kernel_init(ks[1], (hk, self.kdim, d)),
            "wv": self.kernel_init(ks[2], (hk, self.vdim, d)),
            "wo": self.kernel_init(ks[3], (h, d, e)),
        }
        if self.use_bias:
            params["bo"] = jnp.zeros((e,))
            if self.qkv_bias:
                # head axis first so attribute parallelism shards them
                # with the weights (torch in_proj_bias parity); bk/bv
                # carry the kv-head count under GQA
                params["bq"] = jnp.zeros((h, d))
                params["bk"] = jnp.zeros((hk, d))
                params["bv"] = jnp.zeros((hk, d))
        return params

    def forward(self, params, inputs, ctx: OpContext):
        query, key, value = (inputs + inputs[:1] * 2)[:3] if len(inputs) == 1 else inputs
        cd = ctx.compute_dtype
        q = jnp.einsum("bse,hed->bhsd", query.astype(cd), params["wq"].astype(cd),
                       preferred_element_type=jnp.float32)
        k = jnp.einsum("bse,hed->bhsd", key.astype(cd), params["wk"].astype(cd),
                       preferred_element_type=jnp.float32)
        v = jnp.einsum("bse,hed->bhsd", value.astype(cd), params["wv"].astype(cd),
                       preferred_element_type=jnp.float32)
        if self.qkv_bias and "bq" in params:
            q = q + params["bq"][None, :, None, :]
            k = k + params["bk"][None, :, None, :]
            v = v + params["bv"][None, :, None, :]
        if self.rope:
            q = rotary_embedding(q, theta=self.rope_theta)
            k = rotary_embedding(k, theta=self.rope_theta)
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        rng = ctx.next_rng() if (self.dropout > 0 and ctx.training) else None
        dropout_rate = self.dropout if ctx.training else 0.0
        # the attention core consumes q/k/v in the compute dtype (the
        # projections accumulate in f32): softmax/accumulation inside every
        # path below is f32 regardless, and bf16 kernel I/O halves the
        # flash kernel's HBM traffic
        q, k, v = q.astype(cd), k.astype(cd), v.astype(cd)
        seq_axis = self.seq_parallel
        mesh_axes = (dict(zip(ctx.mesh.axis_names, ctx.mesh.devices.shape))
                     if ctx.mesh is not None else {})
        if (seq_axis and mesh_axes.get(seq_axis, 1) > 1
                and q.shape[2] == k.shape[2]):
            if dropout_rate > 0.0 and not getattr(self, "_warned_dropout", False):
                import warnings

                warnings.warn(
                    f"attention '{self.name}': attention-prob dropout "
                    f"(rate={dropout_rate}) is not applied under "
                    f"seq_parallel ring attention; training proceeds "
                    f"without it", stacklevel=2)
                self._warned_dropout = True
            # ring attention over the 'seq' mesh axis: K/V rotate on the ICI
            # ring, scores never leave the shard
            from flexflow_tpu.parallel.ring_attention import ring_attention

            o = ring_attention(q, k, v, ctx.mesh, seq_axis=seq_axis,
                               head_axis=self.head_parallel,
                               causal=self.causal)
        elif (self.kernel_impl != "einsum"
              and dropout_rate == 0.0 and q.shape[2] == k.shape[2]):
            from flexflow_tpu.ops.pallas_kernels import (
                flash_attention, flash_attention_available,
                flash_attention_sharded)

            available = flash_attention_available(q.shape[2], q.shape[3])
            if self.kernel_impl == "flash" and not available:
                # the search chose flash but this platform/shape cannot
                # run it: record the silent fallback for fflint FFL209
                self._kernel_fallback = (
                    f"flash unavailable at runtime (seq={q.shape[2]}, "
                    f"head_dim={q.shape[3]}) — einsum executed instead")
            if available:
                if any(s > 1 for s in mesh_axes.values()):
                    # non-trivial mesh: the raw pallas_call would be an
                    # unpartitionable custom call under GSPMD — run it
                    # per-shard via shard_map over the batch axes (possibly
                    # the joint ('data','model') sample2 partition) and,
                    # when the search picked a head choice, the head axis
                    bp = getattr(self, "batch_parallel", None) or "data"
                    bp = bp if isinstance(bp, tuple) else (bp,)
                    bp = tuple(a for a in bp if mesh_axes.get(a, 1) > 1)
                    bsz = int(np.prod([mesh_axes[a] for a in bp])) if bp else 1
                    batch_axis = (bp if bp and q.shape[0] % bsz == 0
                                  else None)
                    if batch_axis is not None and len(batch_axis) == 1:
                        batch_axis = batch_axis[0]
                    hp = self.head_parallel
                    in_batch = batch_axis if isinstance(batch_axis, tuple) \
                        else (batch_axis,)
                    head_axis = (hp if hp and hp not in in_batch
                                 and mesh_axes.get(hp, 1) > 1
                                 and q.shape[1] % mesh_axes[hp] == 0
                                 else None)
                    o = flash_attention_sharded(
                        q, k, v, ctx.mesh, batch_axis=batch_axis,
                        head_axis=head_axis, causal=self.causal)
                else:
                    o = flash_attention(q, k, v, causal=self.causal)
            else:
                o = scaled_dot_product_attention(
                    q, k, v, causal=self.causal, dropout_rate=0.0,
                    rng=None, compute_dtype=cd)
        else:
            if self.kernel_impl == "flash" and self._kernel_fallback is None:
                # forced flash but this forward cannot take the flash
                # branch at all (attention-prob dropout in training, or
                # cross-attention) — record the silent fallback so
                # fflint FFL209 surfaces the priced-vs-executed gap
                self._kernel_fallback = (
                    f"flash has no lowering for this forward "
                    f"(dropout_rate={dropout_rate}, Sq={q.shape[2]}, "
                    f"Sk={k.shape[2]}) — einsum executed instead")
            o = scaled_dot_product_attention(
                q, k, v, causal=self.causal, dropout_rate=dropout_rate,
                rng=rng, compute_dtype=cd,
            )
        y = jnp.einsum("bhsd,hde->bse", o.astype(cd), params["wo"].astype(cd),
                       preferred_element_type=jnp.float32)
        if self.use_bias:
            y = y + params["bo"]
        return [y.astype(query.dtype)]

    def selected_impl(self, mesh_axes=None, training: bool = False) -> str:
        """Which attention kernel ``forward`` will execute on THIS
        platform ('ring' | 'flash' | 'einsum') — a static derivation of
        forward's dispatch, recorded by serve observability and checked
        by fflint so provenance never re-derives (and disagrees with)
        the executed path. The KV-cache ``decode_forward`` is always the
        cached einsum — flash has no incremental decomposition there."""
        from flexflow_tpu.ops.pallas_kernels import (
            flash_attention_available)

        mesh_axes = mesh_axes or {}
        if self.seq_parallel and mesh_axes.get(self.seq_parallel, 1) > 1:
            return "ring"
        if self.kernel_impl == "einsum" or (training and self.dropout > 0):
            return "einsum"
        b, s, e = self.input_shapes[0]
        sk = self.input_shapes[1][1] if len(self.input_shapes) > 1 else s
        if s == sk and flash_attention_available(s, self.head_dim):
            return "flash"
        return "einsum"

    def decode_forward(self, params, inputs, ctx: OpContext,
                       k_cache, v_cache, pos):
        """KV-cache incremental forward (flexflow_tpu/serve/kv_cache.py).

        ``inputs``: the NEW token block only — query/key/value rows
        ``[B, T, E]`` at absolute positions ``pos..pos+T-1`` (prefill is
        T = prompt length at pos 0; decode is T = 1). ``k_cache`` /
        ``v_cache``: ``[B, Hk, S_max, D]`` with positions < ``pos``
        already filled. Projects the new rows, writes them into the
        cache at ``pos``, and attends the new queries over the filled
        prefix + themselves with the exact causal mask — so prefill +
        N decode steps is numerically the full-sequence forward
        restricted to the last row, without recomputing prior K/V.
        Returns ``(y [B, T, E], k_cache, v_cache)``.

        Only causal attention has a valid incremental decomposition
        (a bidirectional row would need future K/V that doesn't exist
        yet); non-causal ops refuse rather than silently drift.
        """
        if not self.causal:
            raise NotImplementedError(
                f"attention '{self.name}': KV-cache incremental decode "
                f"requires causal attention (bidirectional rows depend "
                f"on future positions)")
        query, key, value = (inputs + inputs[:1] * 2)[:3] \
            if len(inputs) == 1 else inputs
        cd = ctx.compute_dtype
        q = jnp.einsum("bse,hed->bhsd", query.astype(cd),
                       params["wq"].astype(cd),
                       preferred_element_type=jnp.float32)
        k = jnp.einsum("bse,hed->bhsd", key.astype(cd),
                       params["wk"].astype(cd),
                       preferred_element_type=jnp.float32)
        v = jnp.einsum("bse,hed->bhsd", value.astype(cd),
                       params["wv"].astype(cd),
                       preferred_element_type=jnp.float32)
        if self.qkv_bias and "bq" in params:
            q = q + params["bq"][None, :, None, :]
            k = k + params["bk"][None, :, None, :]
            v = v + params["bv"][None, :, None, :]
        if self.rope:
            q = rotary_embedding(q, theta=self.rope_theta,
                                 position_offset=pos)
            k = rotary_embedding(k, theta=self.rope_theta,
                                 position_offset=pos)
        # write the new rows into the cache at their absolute positions
        # (cache dtype is the cache's own policy — serve keeps bf16/f32
        # per the executor compute dtype)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, pos, 0))
        b, _, t, d = q.shape
        s_max = k_cache.shape[2]
        rep = self.num_heads // self.num_kv_heads
        # GQA: contract the grouped query heads against the UN-expanded
        # cache (a jnp.repeat here would materialize rep x the whole
        # cache's bytes every decode step — the cache read dominates a
        # single-token step)
        grouped = rep > 1
        if grouped:
            qq = q.reshape(b, self.num_kv_heads, rep, t, d)
            scores = jnp.einsum("bgrqd,bgkd->bgrqk", qq.astype(cd),
                                k_cache.astype(cd),
                                preferred_element_type=jnp.float32)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(cd),
                                k_cache.astype(cd),
                                preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(self.head_dim))
        # causal over absolute positions: key j visible to the query at
        # absolute position pos+i iff j <= pos+i (this also masks every
        # not-yet-written cache slot, since those have j >= pos+t)
        qpos = pos + jnp.arange(t)[:, None]
        visible = jnp.arange(s_max)[None, :] <= qpos
        scores = jnp.where(visible[(None, None, None) if grouped
                                   else (None, None)],
                           scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1)
        if grouped:
            o = jnp.einsum("bgrqk,bgkd->bgrqd", probs.astype(cd),
                           v_cache.astype(cd),
                           preferred_element_type=jnp.float32
                           ).reshape(b, self.num_heads, t, d)
        else:
            o = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(cd),
                           v_cache.astype(cd),
                           preferred_element_type=jnp.float32)
        y = jnp.einsum("bhsd,hde->bse", o.astype(cd),
                       params["wo"].astype(cd),
                       preferred_element_type=jnp.float32)
        if self.use_bias:
            y = y + params["bo"]
        return y.astype(query.dtype), k_cache, v_cache

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.SEQ, DimRole.CHANNEL)]

    def flops(self):
        b, sq, e = self.input_shapes[0]
        sk = self.input_shapes[1][1] if len(self.input_shapes) > 1 else sq
        h, d = self.num_heads, self.head_dim
        hk = self.num_kv_heads  # GQA: k/v projections use the kv heads
        proj = (2 * b * h * d * (sq * e + sq * e)
                + 2 * b * hk * d * (sk * self.kdim + sk * self.vdim))
        core = 2 * b * h * sq * sk * d * 2
        return proj + core

    def params_elems(self):
        h, e, d = self.num_heads, self.embed_dim, self.head_dim
        hk = self.num_kv_heads
        n = h * d * (e + e) + hk * d * (self.kdim + self.vdim)
        if self.use_bias:
            n += e + ((h + 2 * hk) * d if self.qkv_bias else 0)
        return n

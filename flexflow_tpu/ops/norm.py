"""LayerNorm, Softmax, Dropout.

Analogs of src/ops/layer_norm.cc/.cu, softmax.cc (cuDNN softmax),
dropout.cc (cuDNN dropout). All are single fused XLA computations; the
reference's custom Welford CUDA kernels are unnecessary — XLA fuses the
mean/var reductions with the affine apply.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


@register_op(OperatorType.LAYERNORM)
class LayerNorm(Op):
    def __init__(self, layer, input_shapes):
        self.axes = tuple(layer.get_property("axes", (-1,)))
        self.elementwise_affine = layer.get_property("elementwise_affine", True)
        self.eps = layer.get_property("eps", 1e-5)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def _norm_shape(self):
        shp = self.input_shapes[0]
        axes = tuple(a % len(shp) for a in self.axes)
        return tuple(shp[a] for a in sorted(axes))

    def init_params(self, rng):
        if not self.elementwise_affine:
            return {}
        ns = self._norm_shape()
        return {"scale": jnp.ones(ns), "bias": jnp.zeros(ns)}

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=self.axes, keepdims=True)
        var = jnp.var(xf, axis=self.axes, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        if self.elementwise_affine:
            y = y * params["scale"] + params["bias"]
        return [y.astype(x.dtype)]

    def output_dim_roles(self):
        shp = self.output_shapes[0]
        roles = [DimRole.SAMPLE] + [DimRole.OTHER] * (len(shp) - 1)
        # dim1 of a rank-3 tensor is a position dim (normalization is per
        # position when it is not a normalized axis) — seq-shardable
        norm_axes = {a % len(shp) for a in self.axes}
        if len(shp) == 3 and 1 not in norm_axes:
            roles[1] = DimRole.SEQ
        return [tuple(roles)]

    def params_elems(self):
        return 2 * int(np.prod(self._norm_shape())) if self.elementwise_affine else 0


@register_op(OperatorType.GROUPNORM)
class GroupNorm(Op):
    """nn.GroupNorm for NCHW/NC inputs: normalize each of ``groups``
    channel groups over (C/G, *spatial), per-channel affine (r4 torch.fx
    frontend parity; reference table python/flexflow/torch/model.py)."""

    def __init__(self, layer, input_shapes):
        self.groups = layer.get_property("groups", 1)
        self.eps = layer.get_property("eps", 1e-5)
        self.affine = layer.get_property("affine", True)
        c = input_shapes[0][1]
        if c % self.groups:
            raise ValueError(
                f"group_norm: {c} channels not divisible by "
                f"{self.groups} groups")
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def init_params(self, rng):
        if not self.affine:
            return {}
        c = self.input_shapes[0][1]
        return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        g = self.groups
        nhwc = getattr(self, "exec_layout", "NCHW") == "NHWC"
        if nhwc:
            # channels-last: split the minor dim into (g, c/g); each
            # group normalizes over (*spatial, c/g)
            n, c = x.shape[0], x.shape[-1]
            xf = x.astype(jnp.float32).reshape(x.shape[:-1] + (g, c // g))
            axes = tuple(range(1, xf.ndim - 2)) + (xf.ndim - 1,)
        else:
            n, c = x.shape[0], x.shape[1]
            xf = x.astype(jnp.float32).reshape((n, g, c // g) + x.shape[2:])
            axes = tuple(range(2, xf.ndim))
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, axis=axes, keepdims=True)
        y = ((xf - mean) * jax.lax.rsqrt(var + self.eps)).reshape(x.shape)
        if self.affine:
            shape = ((1,) * (x.ndim - 1) + (c,) if nhwc
                     else (1, c) + (1,) * (x.ndim - 2))
            y = y * params["scale"].reshape(shape) \
                + params["bias"].reshape(shape)
        return [y.astype(x.dtype)]

    def params_elems(self):
        return 2 * int(self.input_shapes[0][1]) if self.affine else 0


@register_op(OperatorType.RMSNORM)
class RMSNorm(Op):
    """Root-mean-square normalization over the last dim (Llama/T5 family;
    new scope vs the reference). y = x / rms(x) * scale, computed in f32."""

    def __init__(self, layer, input_shapes):
        self.eps = layer.get_property("eps", 1e-6)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def init_params(self, rng):
        return {"scale": jnp.ones((self.input_shapes[0][-1],))}

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        xf = x.astype(jnp.float32)
        rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                            + self.eps)
        return [(xf * rms * params["scale"]).astype(x.dtype)]

    def output_dim_roles(self):
        shp = self.output_shapes[0]
        roles = [DimRole.SAMPLE] + [DimRole.OTHER] * (len(shp) - 1)
        if len(shp) == 3:
            roles[1] = DimRole.SEQ  # per-position norm: seq-shardable
        return [tuple(roles)]

    def params_elems(self):
        return int(self.input_shapes[0][-1])


@register_op(OperatorType.SOFTMAX)
class Softmax(Op):
    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", -1)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        return [jax.nn.softmax(x.astype(jnp.float32), axis=self.axis).astype(x.dtype)]

    def output_dim_roles(self):
        shp = self.output_shapes[0]
        roles = [DimRole.SAMPLE] + [DimRole.OTHER] * (len(shp) - 1)
        if len(shp) == 3 and self.axis % len(shp) != 1:
            roles[1] = DimRole.SEQ
        return [tuple(roles)]


@register_op(OperatorType.DROPOUT)
class Dropout(Op):
    def __init__(self, layer, input_shapes):
        self.rate = layer.get_property("rate", 0.5)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        if not ctx.training or self.rate <= 0.0:
            return [x]
        keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - self.rate, x.shape)
        return [jnp.where(keep, x / (1.0 - self.rate), 0).astype(x.dtype)]

    def output_dim_roles(self):
        from flexflow_tpu.ops.elementwise import _elementwise_roles
        return [_elementwise_roles(self.output_shapes[0])]

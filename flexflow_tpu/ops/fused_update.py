"""Fused one-dispatch optimizer update — the ``_k:fused`` kernel choice.

The reference update path is a *triad*: under WUS the gradient
reduce-scatter's epilogue, the per-leaf update kernels (read p/g/m/v,
write p/m/v), and the compute-param all-gather's prologue lower as
separate dispatch regions, each re-reading the parameter shard it needs
(three param round trips + three launches — the dispatch-bound tail the
ROADMAP names as the BERT proxy's remaining gap). The searched
``_k:fused`` twin collapses a chosen op's update into ONE region:

* **Pallas path** (TPU, or CPU under ``FLEXFLOW_TPU_PALLAS=interpret``):
  a single elementwise kernel reads p/g/m/v once from HBM and writes
  p'/m'/v' once — one launch, the minimal (2 + 2·state-copies) HBM
  round trips the native ``update_triad_time`` prices.
* **XLA fallback** (Pallas unavailable or shape not lane-aligned):
  ``lax.optimization_barrier`` fences the leaf's inputs so XLA forms
  one fused loop over the update instead of interleaving it with
  neighboring regions.

Both paths evaluate EXACTLY the reference optimizers' expression,
operand order included, so the fused update is **bit-compatible** with
the triad (asserted by tests/test_kernel_search.py) — the choice moves
dispatches, never values. Unknown optimizer classes fall back to the
whole-tree reference ``update`` (no fused ops), so a custom optimizer
degrades safely rather than silently drifting.
"""

from __future__ import annotations

import functools
from typing import Dict, Set, Tuple

import jax
import jax.numpy as jnp


# Row block of the Pallas update kernel's grid ([rows, 128] view of the
# flattened leaf). 512 rows x 128 lanes x 4 B x 7 resident arrays stays
# well inside one core's VMEM.
_BLK_ROWS = 512


def _adam_math(p, g, m, v, alpha_t, *, beta1, beta2, eps, wd):
    """The reference AdamOptimizer.update step — EXACT expression/order
    (flexflow_tpu/optimizers.py); any edit must change both."""
    sdt = m.dtype
    g = g.astype(p.dtype) + wd * p
    m_new = beta1 * m.astype(p.dtype) + (1 - beta1) * g
    v_new = beta2 * v.astype(p.dtype) + (1 - beta2) * g * g
    p_new = p - alpha_t * m_new / (jnp.sqrt(v_new) + eps)
    return p_new, m_new.astype(sdt), v_new.astype(sdt)


def _adam_kernel(alpha_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref,
                 vo_ref, *, beta1, beta2, eps, wd):
    p = p_ref[...]
    pn, mn, vn = _adam_math(p, g_ref[...], m_ref[...], v_ref[...],
                            alpha_ref[0, 0], beta1=beta1, beta2=beta2,
                            eps=eps, wd=wd)
    po_ref[...] = pn
    mo_ref[...] = mn.astype(mo_ref.dtype)
    vo_ref[...] = vn.astype(vo_ref.dtype)


def _pallas_rows(size: int):
    """(rows, block_rows) of the [rows, 128] kernel view, or None when
    the leaf is not lane-aligned / row-blockable — XLA fallback then."""
    if size <= 0 or size % 128:
        return None
    rows = size // 128
    if rows <= _BLK_ROWS:
        return rows, rows
    if rows % _BLK_ROWS == 0:
        return rows, _BLK_ROWS
    return None


def fused_adam_leaf(p, g, m, v, alpha_t, *, beta1, beta2, eps, wd):
    """One leaf's fused Adam update -> (p', m', v')."""
    from flexflow_tpu.ops.pallas_kernels import pallas_mode

    mode = pallas_mode()
    geom = _pallas_rows(int(p.size)) if mode != "off" else None
    if geom is None:
        # XLA-fused fallback: the barrier fences the four inputs into
        # one region boundary; identity on values
        p, g, m, v = jax.lax.optimization_barrier((p, g, m, v))
        return _adam_math(p, g, m, v, alpha_t, beta1=beta1, beta2=beta2,
                          eps=eps, wd=wd)
    from jax.experimental import pallas as pl

    rows, blk = geom
    shp = p.shape
    view = lambda x: x.reshape(rows, 128)
    kern = functools.partial(_adam_kernel, beta1=beta1, beta2=beta2,
                             eps=eps, wd=wd)
    row_spec = pl.BlockSpec((blk, 128), lambda i: (i, 0))
    alpha2 = jnp.asarray(alpha_t, jnp.float32).reshape(1, 1)
    pn, mn, vn = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((rows, 128), p.dtype),
                   jax.ShapeDtypeStruct((rows, 128), m.dtype),
                   jax.ShapeDtypeStruct((rows, 128), v.dtype)),
        grid=(rows // blk,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                  row_spec, row_spec, row_spec, row_spec],
        out_specs=(row_spec, row_spec, row_spec),
        interpret=mode == "interpret",
    )(alpha2, view(p), view(g), view(m), view(v))
    return pn.reshape(shp), mn.reshape(shp), vn.reshape(shp)


def _sgd_math(opt, p, g, v):
    """The reference SGDOptimizer.update step (momentum form)."""
    g = g + opt.weight_decay * p
    v_new = opt.momentum * v + g
    upd = g + opt.momentum * v_new if opt.nesterov else v_new
    return p - opt.lr * upd, v_new


def fused_optimizer_update(opt, grads, state, params,
                           fused_ops: Set[str]) -> Tuple[Dict, Dict]:
    """``optimizer.update`` with the ``fused_ops`` subtrees routed
    through the fused one-dispatch region; value-identical to the
    reference update (same math, same order) by construction."""
    from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer

    rest_names = [k for k in params if k not in fused_ops]
    rest_p = {k: params[k] for k in rest_names}
    rest_g = {k: grads[k] for k in rest_names}

    if isinstance(opt, AdamOptimizer):
        t = state["t"] + 1
        bc = jnp.sqrt(1.0 - opt.beta2 ** t.astype(jnp.float32)) / (
            1.0 - opt.beta1 ** t.astype(jnp.float32)
        )
        alpha_t = opt.alpha * bc
        new_p: Dict = {}
        new_m: Dict = {}
        new_v: Dict = {}
        if rest_names:
            # complement subtree through the REFERENCE update (no math
            # duplication to drift); its t advance equals ours
            rp, rs = opt.update(rest_g, dict(
                m={k: state["m"][k] for k in rest_names},
                v={k: state["v"][k] for k in rest_names},
                t=state["t"]), rest_p)
            new_p.update(rp)
            new_m.update(rs["m"])
            new_v.update(rs["v"])
        for op_name in fused_ops:
            if op_name not in params:
                continue
            sp: Dict = {}
            sm: Dict = {}
            sv: Dict = {}
            for pn, p in params[op_name].items():
                sp[pn], sm[pn], sv[pn] = fused_adam_leaf(
                    p, grads[op_name][pn], state["m"][op_name][pn],
                    state["v"][op_name][pn], alpha_t, beta1=opt.beta1,
                    beta2=opt.beta2, eps=opt.epsilon,
                    wd=opt.weight_decay)
            new_p[op_name] = sp
            new_m[op_name] = sm
            new_v[op_name] = sv
        return new_p, {"m": new_m, "v": new_v, "t": t}

    if isinstance(opt, SGDOptimizer):
        if opt.momentum == 0.0:
            new_p = {}
            if rest_names:
                rp, _ = opt.update(rest_g, state, rest_p)
                new_p.update(rp)
            for op_name in fused_ops:
                if op_name not in params:
                    continue
                sub = {}
                for pn, p in params[op_name].items():
                    g = grads[op_name][pn]
                    p, g = jax.lax.optimization_barrier((p, g))
                    sub[pn] = p - opt.lr * (g + opt.weight_decay * p)
                new_p[op_name] = sub
            return new_p, state
        new_p = {}
        new_v = {}
        if rest_names:
            rp, rs = opt.update(rest_g, dict(
                v={k: state["v"][k] for k in rest_names}), rest_p)
            new_p.update(rp)
            new_v.update(rs["v"])
        for op_name in fused_ops:
            if op_name not in params:
                continue
            sp = {}
            sv = {}
            for pn, p in params[op_name].items():
                g = grads[op_name][pn]
                v = state["v"][op_name][pn]
                p, g, v = jax.lax.optimization_barrier((p, g, v))
                sp[pn], sv[pn] = _sgd_math(opt, p, g, v)
            new_p[op_name] = sp
            new_v[op_name] = sv
        return new_p, {"v": new_v}

    # unknown optimizer class: the fused region has no reference math to
    # mirror — degrade to the whole-tree reference update
    return opt.update(grads, state, params)

"""Conv2D / Pool2D / BatchNorm / Flat.

Analog of src/ops/conv_2d.cc, pool_2d.cc, batch_norm.cc, flat.cc and their
cuDNN kernels. Layout note: the reference is NCHW (cuDNN) and NCHW stays
the API/PCG boundary layout for parity, but "let XLA pick internal
layouts" measured ~7% MFU vs BERT's 60% on the chip (VERDICT Weak #1) —
so each op also carries an NHWC *execution* mode (``self.exec_layout``,
assigned by the compile-time layout pass, flexflow_tpu/layout.py) that
computes via ``dimension_numbers=("NHWC","HWIO","NHWC")`` with the
boundary transposes hoisted to conv-chain edges. Parameters stay in the
reference OIHW layout either way, so checkpoints and strategy files are
layout-independent.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from flexflow_tpu.ffconst import ActiMode, OperatorType, PoolType
from flexflow_tpu.initializers import DefaultBiasInitializer, DefaultWeightInitializer
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op
from flexflow_tpu.ops.linear import apply_activation


@register_op(OperatorType.CONV2D)
class Conv2D(Op):
    """x:[N,C,H,W] * w:[Cout,Cin/groups,KH,KW] -> [N,Cout,H',W']."""

    def __init__(self, layer, input_shapes):
        p = layer.properties
        self.out_channels = p["out_channels"]
        self.kernel = (p["kernel_h"], p["kernel_w"])
        self.stride = (p["stride_h"], p["stride_w"])
        self.padding = (p["padding_h"], p["padding_w"])
        self.groups = p.get("groups", 1)
        self.activation = p.get("activation", ActiMode.AC_MODE_NONE)
        self.use_bias = p.get("use_bias", True)
        self.kernel_init = p.get("kernel_initializer") or DefaultWeightInitializer()
        self.bias_init = p.get("bias_initializer") or DefaultBiasInitializer()
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        n, c, h, w = self.input_shapes[0]
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        return [(n, self.out_channels, oh, ow)]

    def init_params(self, rng):
        _, c, _, _ = self.input_shapes[0]
        k1, k2 = jax.random.split(rng)
        wshape = (self.out_channels, c // self.groups, *self.kernel)
        params = {"kernel": self.kernel_init(k1, wshape)}
        if self.use_bias:
            params["bias"] = self.bias_init(k2, (self.out_channels,))
        return params

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        return [self._conv_forward(params["kernel"],
                                   params.get("bias") if self.use_bias
                                   else None,
                                   x, ctx, self.activation)]

    def _conv_forward(self, kernel, bias, x, ctx: OpContext, activation):
        """Shared conv core: kernel arrives OIHW (the parameter layout),
        ``bias`` may be None, the bias+activation epilogue is fused into
        the same XLA computation. Also the execution body of the
        Conv+BN(+ReLU) fold (layout.FoldedConvBN)."""
        layout = getattr(self, "exec_layout", "NCHW")
        w = kernel.astype(ctx.compute_dtype)
        if layout == "NHWC":
            # OIHW -> HWIO; a pure device-side relayout of the weights XLA
            # folds into its own kernel prologue — far cheaper than the
            # per-activation transposes the NCHW dimension numbers imply
            w = jnp.transpose(w, (2, 3, 1, 0))
            dn = ("NHWC", "HWIO", "NHWC")
        else:
            dn = ("NCHW", "OIHW", "NCHW")
        # no preferred_element_type: conv_general_dilated's transpose rule
        # rejects mixed (bf16 operand, f32 cotangent) convs under autodiff;
        # the TPU MXU accumulates bf16 convs in f32 internally regardless
        y = lax.conv_general_dilated(
            x.astype(ctx.compute_dtype),
            w,
            window_strides=self.stride,
            padding=[(self.padding[0], self.padding[0]), (self.padding[1], self.padding[1])],
            dimension_numbers=dn,
            feature_group_count=self.groups,
        ).astype(jnp.float32)
        if bias is not None:
            y = y + (bias if layout == "NHWC"
                     else bias[None, :, None, None])
        return apply_activation(y, activation).astype(x.dtype)

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.CHANNEL, DimRole.OTHER, DimRole.OTHER)]

    def flops(self):
        n, co, oh, ow = self.output_shapes[0]
        cin = self.input_shapes[0][1]
        return 2 * n * co * oh * ow * (cin // self.groups) * self.kernel[0] * self.kernel[1]

    def params_elems(self):
        _, c, _, _ = self.input_shapes[0]
        n = self.out_channels * (c // self.groups) * self.kernel[0] * self.kernel[1]
        return n + (self.out_channels if self.use_bias else 0)


@register_op(OperatorType.POOL2D)
class Pool2D(Op):
    def __init__(self, layer, input_shapes):
        p = layer.properties
        self.kernel = (p["kernel_h"], p["kernel_w"])
        self.stride = (p["stride_h"], p["stride_w"])
        self.padding = (p["padding_h"], p["padding_w"])
        self.pool_type = p.get("pool_type", PoolType.POOL_MAX)
        self.activation = p.get("activation", ActiMode.AC_MODE_NONE)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        n, c, h, w = self.input_shapes[0]
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        return [(n, c, oh, ow)]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        hw_pad = ((self.padding[0], self.padding[0]),
                  (self.padding[1], self.padding[1]))
        if getattr(self, "exec_layout", "NCHW") == "NHWC":
            window = (1, *self.kernel, 1)
            strides = (1, *self.stride, 1)
            pads = ((0, 0), *hw_pad, (0, 0))
        else:
            window = (1, 1, *self.kernel)
            strides = (1, 1, *self.stride)
            pads = ((0, 0), (0, 0), *hw_pad)
        if self.pool_type == PoolType.POOL_MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            y = s / (self.kernel[0] * self.kernel[1])
        return [apply_activation(y, self.activation)]

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.CHANNEL, DimRole.OTHER, DimRole.OTHER)]


@register_op(OperatorType.BATCHNORM)
class BatchNorm(Op):
    """Batch normalization over N,H,W for NCHW input (batch_norm.cu).

    Running stats are non-trainable state updated outside autodiff (the
    model keeps them in a separate 'state' collection).
    """

    def __init__(self, layer, input_shapes):
        self.relu = layer.get_property("relu", True)
        self.momentum = layer.get_property("momentum", 0.9)
        self.eps = layer.get_property("eps", 1e-5)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def init_params(self, rng):
        c = self.input_shapes[0][1]
        return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}

    def init_state(self):
        c = self.input_shapes[0][1]
        return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}

    def forward(self, params, inputs, ctx: OpContext, state=None):
        (x,) = inputs
        nhwc = getattr(self, "exec_layout", "NCHW") == "NHWC"
        axes = (0, 1, 2) if nhwc else (0, 2, 3)
        # statistics in f32 even under the bf16 master-weight regime: the
        # variance of a bf16 activation tensor loses most of its mantissa;
        # the normalize/affine apply below stays in the compute dtype
        xf = x.astype(jnp.float32)
        if ctx.training:
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            new_state = None
            if state is not None:
                new_state = {
                    "mean": self.momentum * state["mean"] + (1 - self.momentum) * mean,
                    "var": self.momentum * state["var"] + (1 - self.momentum) * var,
                }
        else:
            mean = state["mean"] if state is not None else jnp.mean(xf, axis=axes)
            var = state["var"] if state is not None else jnp.var(xf, axis=axes)
            new_state = state
        inv = lax.rsqrt(var + self.eps) * params["scale"].astype(jnp.float32)
        bias = params["bias"].astype(jnp.float32)
        if not nhwc:
            mean = mean[None, :, None, None]
            inv = inv[None, :, None, None]
            bias = bias[None, :, None, None]
        y = (xf - mean) * inv + bias
        if self.relu:
            y = jax.nn.relu(y)
        self._new_state = new_state
        return [y.astype(x.dtype)]

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.CHANNEL, DimRole.OTHER, DimRole.OTHER)]

    def params_elems(self):
        return 2 * self.input_shapes[0][1]


@register_op(OperatorType.FLAT)
class Flat(Op):
    """NCHW -> N,(C*H*W) flatten (src/ops/flat.cc)."""

    def compute_output_shapes(self):
        n = self.input_shapes[0][0]
        return [(n, int(np.prod(self.input_shapes[0][1:])))]

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        return [x.reshape(x.shape[0], -1)]

    def output_dim_roles(self):
        return [(DimRole.SAMPLE, DimRole.CHANNEL)]

"""Shape/layout ops: Concat, Split, Reshape, Transpose, Reverse, Cast, Gather.

Analogs of src/ops/{concat,split,reshape,transpose,reverse,cast,gather}.cc.
All are pure XLA data-movement ops (often layout-only after fusion).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


def _default_roles(shp):
    return tuple(DimRole.SAMPLE if i == 0 else DimRole.OTHER for i in range(len(shp)))


@register_op(OperatorType.CONCAT)
class Concat(Op):
    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        ax = self.axis % len(self.input_shapes[0])
        out = list(self.input_shapes[0])
        out[ax] = sum(s[ax] for s in self.input_shapes)
        return [tuple(out)]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.concatenate(inputs, axis=self.axis)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.SPLIT)
class Split(Op):
    def __init__(self, layer, input_shapes):
        self.sizes = tuple(layer.get_property("sizes"))
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        ax = self.axis % len(self.input_shapes[0])
        outs = []
        for sz in self.sizes:
            s = list(self.input_shapes[0])
            s[ax] = sz
            outs.append(tuple(s))
        return outs

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        idx = np.cumsum(self.sizes)[:-1]
        return list(jnp.split(x, idx, axis=self.axis))

    def output_dim_roles(self):
        return [_default_roles(s) for s in self.output_shapes]


@register_op(OperatorType.RESHAPE)
class Reshape(Op):
    def __init__(self, layer, input_shapes):
        self.target = tuple(layer.get_property("shape"))
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.target]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0].reshape(self.target)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.TRANSPOSE)
class Transpose(Op):
    def __init__(self, layer, input_shapes):
        self.perm = tuple(layer.get_property("perm"))
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        s = self.input_shapes[0]
        return [tuple(s[p] for p in self.perm)]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.transpose(inputs[0], self.perm)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.REVERSE)
class Reverse(Op):
    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.flip(inputs[0], axis=self.axis)]


@register_op(OperatorType.CAST)
class Cast(Op):
    def __init__(self, layer, input_shapes):
        self.target_dtype: DataType = layer.get_property("dtype")
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0].astype(self.target_dtype.jnp_dtype)]


@register_op(OperatorType.GATHER)
class Gather(Op):
    """take_along_axis gather (src/ops/gather.cc): out[idx] along dim."""

    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[1]]

    def forward(self, params, inputs, ctx: OpContext):
        x, idx = inputs
        return [jnp.take_along_axis(x, idx.astype(jnp.int32), axis=self.axis)]

"""Shape/layout ops: Concat, Split, Reshape, Transpose, Reverse, Cast, Gather.

Analogs of src/ops/{concat,split,reshape,transpose,reverse,cast,gather}.cc.
All are pure XLA data-movement ops (often layout-only after fusion).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op


def _default_roles(shp):
    return tuple(DimRole.SAMPLE if i == 0 else DimRole.OTHER for i in range(len(shp)))


@register_op(OperatorType.CONCAT)
class Concat(Op):
    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        ax = self.axis % len(self.input_shapes[0])
        out = list(self.input_shapes[0])
        out[ax] = sum(s[ax] for s in self.input_shapes)
        return [tuple(out)]

    def forward(self, params, inputs, ctx: OpContext):
        ax = self.axis
        if getattr(self, "exec_layout", "NCHW") == "NHWC" \
                and len(self.input_shapes[0]) == 4:
            # values arrive channels-last (layout pass): remap the logical
            # NCHW axis onto the physical NHWC dim
            ax = {0: 0, 1: 3, 2: 1, 3: 2}[ax % 4]
        return [jnp.concatenate(inputs, axis=ax)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.SPLIT)
class Split(Op):
    def __init__(self, layer, input_shapes):
        self.sizes = tuple(layer.get_property("sizes"))
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        ax = self.axis % len(self.input_shapes[0])
        outs = []
        for sz in self.sizes:
            s = list(self.input_shapes[0])
            s[ax] = sz
            outs.append(tuple(s))
        return outs

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        idx = np.cumsum(self.sizes)[:-1]
        return list(jnp.split(x, idx, axis=self.axis))

    def output_dim_roles(self):
        return [_default_roles(s) for s in self.output_shapes]


@register_op(OperatorType.RESHAPE)
class Reshape(Op):
    def __init__(self, layer, input_shapes):
        self.target = tuple(layer.get_property("shape"))
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.target]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0].reshape(self.target)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.TRANSPOSE)
class Transpose(Op):
    def __init__(self, layer, input_shapes):
        self.perm = tuple(layer.get_property("perm"))
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        s = self.input_shapes[0]
        return [tuple(s[p] for p in self.perm)]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.transpose(inputs[0], self.perm)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.REVERSE)
class Reverse(Op):
    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.flip(inputs[0], axis=self.axis)]


@register_op(OperatorType.CAST)
class Cast(Op):
    def __init__(self, layer, input_shapes):
        self.target_dtype: DataType = layer.get_property("dtype")
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[0]]

    def forward(self, params, inputs, ctx: OpContext):
        return [inputs[0].astype(self.target_dtype.jnp_dtype)]


@register_op(OperatorType.CONST)
class Const(Op):
    """Embedded constant tensor (torch.fx get_attr buffers — e.g. a GPT-2
    causal mask registered as a module buffer). With ``trainable=True``
    the value becomes a leaf parameter updated by the optimizer (a bare
    ``nn.Parameter`` used directly in forward, e.g. a learned positional
    embedding) instead of being baked into the traced program."""

    def __init__(self, layer, input_shapes):
        self.value = np.asarray(layer.get_property("value"))
        self.trainable = bool(layer.get_property("trainable", False))
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [tuple(self.value.shape)]

    def init_params(self, rng):
        if self.trainable:
            return {"weight": jnp.asarray(self.value)}
        return {}

    def forward(self, params, inputs, ctx: OpContext):
        if self.trainable:
            return [params["weight"]]
        return [jnp.asarray(self.value)]

    def params_elems(self):
        return int(self.value.size) if self.trainable else 0

    def output_dim_roles(self):
        return [tuple(DimRole.OTHER for _ in self.value.shape)]


@register_op(OperatorType.WHERE)
class Where(Op):
    """select(cond, a, b) — torch.where / masked_fill. cond may be bool
    or a 0/1 float mask; broadcasting follows numpy rules."""

    def compute_output_shapes(self):
        return [tuple(np.broadcast_shapes(*self.input_shapes))]

    def forward(self, params, inputs, ctx: OpContext):
        cond, a, b = inputs
        return [jnp.where(cond.astype(bool), a, b)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.EXPAND)
class Expand(Op):
    """broadcast_to (torch expand / repeat with unit source dims)."""

    def __init__(self, layer, input_shapes):
        self.target = tuple(layer.get_property("shape"))
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.target]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.broadcast_to(inputs[0], self.target)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.EINSUM)
class Einsum(Op):
    """General einsum contraction (torch.einsum). The MXU path for any
    equation XLA can lower to dots."""

    def __init__(self, layer, input_shapes):
        self.equation = layer.get_property("equation")
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        lhs, _, out = self.equation.replace(" ", "").partition("->")
        terms = lhs.split(",")
        sizes = {}
        for term, shp in zip(terms, self.input_shapes):
            for ch, d in zip(term, shp):
                sizes[ch] = d
        if not out and "->" not in self.equation:
            # implicit output: sorted letters appearing exactly once
            from collections import Counter
            c = Counter("".join(terms))
            out = "".join(sorted(ch for ch, k in c.items() if k == 1))
        return [tuple(sizes[ch] for ch in out)]

    def forward(self, params, inputs, ctx: OpContext):
        cd = ctx.compute_dtype
        return [jnp.einsum(self.equation, *[x.astype(cd) for x in inputs],
                           preferred_element_type=jnp.float32
                           ).astype(inputs[0].dtype)]

    def flops(self):
        lhs, _, _ = self.equation.replace(" ", "").partition("->")
        sizes = {}
        for term, shp in zip(lhs.split(","), self.input_shapes):
            for ch, d in zip(term, shp):
                sizes[ch] = d
        total = 1
        for d in sizes.values():
            total *= d
        return 2 * total

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.REDUCE_MAX)
class ReduceMax(Op):
    def __init__(self, layer, input_shapes):
        self.axes = tuple(layer.get_property("axes"))
        self.keepdims = layer.get_property("keepdims", False)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        s = list(self.input_shapes[0])
        axes = sorted(a % len(s) for a in self.axes)
        for a in reversed(axes):
            if self.keepdims:
                s[a] = 1
            else:
                s.pop(a)
        return [tuple(s)]

    def forward(self, params, inputs, ctx: OpContext):
        return [jnp.max(inputs[0], axis=self.axes, keepdims=self.keepdims)]

    def output_dim_roles(self):
        return [_default_roles(self.output_shapes[0])]


@register_op(OperatorType.GATHER)
class Gather(Op):
    """take_along_axis gather (src/ops/gather.cc): out[idx] along dim."""

    def __init__(self, layer, input_shapes):
        self.axis = layer.get_property("axis", 0)
        super().__init__(layer, input_shapes)

    def compute_output_shapes(self):
        return [self.input_shapes[1]]

    def forward(self, params, inputs, ctx: OpContext):
        x, idx = inputs
        return [jnp.take_along_axis(x, idx.astype(jnp.int32), axis=self.axis)]

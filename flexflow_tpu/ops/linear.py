"""Linear (dense) operator.

Analog of src/ops/linear.cc + kernels/linear_kernels.cu: y = act(x W + b).
The reference's cuBLAS GemmEx maps to a single jnp.dot lowered onto the
MXU; inputs are cast to the compute dtype (bf16 by default) with f32
accumulation (preferred_element_type), parameters stay f32.

Sharding surface (search): weight [in, out] may shard 'out' on the model
axis (column-parallel → Combine on output) or 'in' (row-parallel →
Replicate input / Reduction output), matching
create_partition_linear_combine / create_replicate_linear_combine
(src/runtime/substitution.cc:1756,1809).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from flexflow_tpu.ffconst import ActiMode, OperatorType
from flexflow_tpu.initializers import DefaultBiasInitializer, DefaultWeightInitializer
from flexflow_tpu.ops.base import DimRole, Op, OpContext, register_op
import jax


def apply_activation(x, act: ActiMode):
    if act == ActiMode.AC_MODE_RELU:
        return jax.nn.relu(x)
    if act == ActiMode.AC_MODE_SIGMOID:
        return jax.nn.sigmoid(x)
    if act == ActiMode.AC_MODE_TANH:
        return jnp.tanh(x)
    if act == ActiMode.AC_MODE_GELU:
        return jax.nn.gelu(x)
    return x


@register_op(OperatorType.LINEAR)
class Linear(Op):
    def __init__(self, layer, input_shapes):
        self.out_dim = layer.get_property("out_dim")
        self.activation = layer.get_property("activation", ActiMode.AC_MODE_NONE)
        self.use_bias = layer.get_property("use_bias", True)
        self.kernel_init = layer.get_property("kernel_initializer") or DefaultWeightInitializer()
        self.bias_init = layer.get_property("bias_initializer") or DefaultBiasInitializer()
        super().__init__(layer, input_shapes)
        self.in_dim = self.input_shapes[0][-1]

    def compute_output_shapes(self):
        (in_shape,) = self.input_shapes
        return [tuple(in_shape[:-1]) + (self.out_dim,)]

    def init_params(self, rng):
        in_dim = self.input_shapes[0][-1]
        k1, k2 = jax.random.split(rng)
        params = {"kernel": self.kernel_init(k1, (in_dim, self.out_dim))}
        if self.use_bias:
            params["bias"] = self.bias_init(k2, (self.out_dim,))
        return params

    def forward(self, params, inputs, ctx: OpContext):
        (x,) = inputs
        w = params["kernel"].astype(ctx.compute_dtype)
        y = jnp.dot(
            x.astype(ctx.compute_dtype), w, preferred_element_type=jnp.float32
        )
        if self.use_bias:
            y = y + params["bias"]
        y = apply_activation(y, self.activation)
        return [y.astype(x.dtype)]

    def output_dim_roles(self):
        # dim1 of a rank-3 input is a position dim the matmul treats
        # independently — a sequence dim the search may context-shard
        shp = self.output_shapes[0]
        mid = DimRole.SEQ if len(shp) == 3 else DimRole.OTHER
        roles = [DimRole.SAMPLE] + [mid] * (len(shp) - 2) + [DimRole.CHANNEL]
        return [tuple(roles)]

    def flops(self):
        batch = int(np.prod(self.input_shapes[0][:-1]))
        return 2 * batch * self.in_dim * self.out_dim

    def params_elems(self):
        return self.in_dim * self.out_dim + (self.out_dim if self.use_bias else 0)

"""flexflow_tpu — a TPU-native distributed DNN training framework.

A ground-up JAX/XLA re-design of the capabilities of FlexFlow
(reference: tengjiang/FlexFlow): PyTorch-like / Keras-like model building,
a Parallel Computation Graph (PCG) whose tensors carry per-dimension
sharding degrees, automatic hybrid-parallelization search (substitutions +
DP/MCMC over an execution simulator with a TPU machine model), and
execution via a single pjit-compiled step function over a
``jax.sharding.Mesh`` (GSPMD) instead of a task runtime.

Layer map (cf. reference SURVEY.md §1):
  L1 kernels        -> XLA HLO + Pallas (flexflow_tpu/ops/pallas_kernels)
  L2 operators      -> flexflow_tpu/ops (pure JAX functions + Op metadata)
  L3 core runtime   -> flexflow_tpu/model.FFModel (compile/fit/forward/...)
  L4 mapper         -> mesh axis assignment (flexflow_tpu/machine)
  L5 auto-parallel  -> flexflow_tpu/search (PCG, substitutions, simulator)
  L6/L7 frontends   -> flexflow_tpu/keras, torch_frontend, onnx_frontend
  L9 models         -> flexflow_tpu/models
  observability     -> flexflow_tpu/obs (step tracing, HLO cost/collective
                       census, search-drift calibration; --trace-dir)
  static analysis   -> flexflow_tpu/analysis (fflint: pass-based strategy
                       & graph verifier; --lint / scripts/fflint.py)

``__version__`` (from flexflow_tpu/version.py) is stamped into every
trace/census/drift artifact header the obs subsystem writes.
"""

from flexflow_tpu.version import __version__
from flexflow_tpu.ffconst import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    ParameterSyncType,
    PoolType,
)
from flexflow_tpu.config import FFConfig
from flexflow_tpu.analysis import (EdgeReshard, LintReport, Severity,
                                   edge_reshard_table, lint_model)
from flexflow_tpu.tensor import ParallelDim, ParallelTensorShape, Tensor
from flexflow_tpu.machine import MachineSpec, MachineView
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer
from flexflow_tpu.dataloader import DataLoaderSet, SingleDataLoader, create_data_loaders
from flexflow_tpu.recompile import RecompileState
from flexflow_tpu.initializers import (
    ConstantInitializer,
    GlorotUniformInitializer,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)

__all__ = [
    "__version__",
    "ActiMode",
    "AggrMode",
    "CompMode",
    "DataType",
    "LossType",
    "MetricsType",
    "OperatorType",
    "ParameterSyncType",
    "PoolType",
    "FFConfig",
    "EdgeReshard",
    "LintReport",
    "Severity",
    "edge_reshard_table",
    "lint_model",
    "ParallelDim",
    "ParallelTensorShape",
    "Tensor",
    "MachineSpec",
    "MachineView",
    "FFModel",
    "AdamOptimizer",
    "SGDOptimizer",
    "DataLoaderSet",
    "SingleDataLoader",
    "create_data_loaders",
    "RecompileState",
    "ConstantInitializer",
    "GlorotUniformInitializer",
    "NormInitializer",
    "UniformInitializer",
    "ZeroInitializer",
]

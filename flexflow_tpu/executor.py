"""Graph executor: lowers the materialized op graph to jitted step functions.

This is the TPU replacement for the reference's execution loop
(FFModel::forward/backward/update, src/runtime/model.cc:2415-2475, plus the
Legion trace around each iteration): instead of launching per-op index
tasks that a mapper routes to devices, the whole iteration — forward, loss,
autodiff backward, metrics, optimizer update (with its gradient psum over
the data axis) — is one XLA computation compiled by jax.jit against a
``jax.sharding.Mesh``. The per-op sharding decisions from the strategy are
applied as (a) NamedShardings on parameters and (b)
``with_sharding_constraint`` on op outputs (the four parallel ops of the
PCG become constraint boundaries — SURVEY §2.3 mapping).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexflow_tpu.ffconst import CompMode, LossType, OperatorType
from flexflow_tpu.losses import get_loss_fn
from flexflow_tpu.metrics import Metrics
from flexflow_tpu.obs.registry import get_registry
from flexflow_tpu.ops.base import Op, OpContext


# pseudo-entry in the op-state dict holding the bf16 parameter working
# copy under the master-weight mixed-precision regime (never collides with
# op names, which come from Layer naming)
COMPUTE_PARAMS_KEY = "__compute_params__"


class OpNode:
    """One materialized operator + where its inputs come from.

    ``input_refs``: list of ('op', producer_guid, out_idx) or
    ('input', input_name) or ('label', 0).
    """

    def __init__(self, op: Op, input_refs: List[Tuple]):
        self.op = op
        self.input_refs = input_refs
        # sharding decision: per-output PartitionSpec (set by the strategy)
        self.output_specs: List[Optional[P]] = [None] * len(op.output_shapes)
        self.param_specs: Dict[str, P] = {}

    @property
    def guid(self):
        return self.op.guid


class GraphExecutor:
    def __init__(
        self,
        nodes: List[OpNode],
        input_names: List[str],
        final_ref,
        mesh: Mesh,
        loss_type: LossType,
        metrics: Metrics,
        optimizer,
        compute_dtype=jnp.bfloat16,
        data_axes: Tuple[str, ...] = ("data",),
        final_is_softmax: bool = False,
        fold_conv_bn: bool = True,
        weight_update_sharding: bool = False,
        wus_ops: Optional[set] = None,
        overlap_grad_sync: bool = False,
        overlap_bucket_bytes: int = 4 << 20,
        kernel_choices: Optional[Dict[str, str]] = None,
        remat_ops: Optional[set] = None,
    ):
        self.nodes = nodes
        self.by_guid = {n.guid: n for n in nodes}
        self.input_names = input_names
        # (guid, out_idx) of the user-designated model output
        self.final_ref = tuple(final_ref)
        self.mesh = mesh
        self.loss_type = loss_type
        self.metrics = metrics
        self.optimizer = optimizer
        self.compute_dtype = compute_dtype
        self.data_axes = data_axes
        self.final_is_softmax = final_is_softmax
        # mixed-precision master-weight regime (bf16 compute): forward and
        # backward run on a bf16 copy of the parameters that is produced
        # INSIDE the previous step's optimizer fusion (state key
        # '__compute_params__'), so the per-step f32->bf16 cast costs one
        # extra bf16 write instead of an f32 read + bf16 write, gradients
        # arrive in bf16 (halving the backward dW writes and any
        # data-parallel gradient psum bytes), and the f32 master copy is
        # touched only by the optimizer. Measured on v5e (r4,
        # scripts/measure_flat_opt.py): the per-leaf update is already
        # bandwidth-bound (~620 GB/s marginal), so byte reduction — not a
        # flat-buffer layout — is the lever.
        self.use_master_copy = compute_dtype != jnp.float32
        self.fold_conv_bn = fold_conv_bn
        # weight-update sharding (WUS): the data-axis gradient sync runs
        # as a reduce-scatter onto a per-param shard spec, the f32 master
        # copy + optimizer moments live sharded over the data axes, and
        # the next step's bf16 compute params are all-gathered inside the
        # same optimizer fusion (preserving the one-extra-bf16-write
        # property). Per-chip optimizer HBM then scales with params/chip
        # instead of total params. Only meaningful with a data degree > 1.
        self.weight_update_sharding = bool(
            weight_update_sharding and self._data_degree() > 1)
        # per-op WUS granularity: when the search picked "_wus" choices
        # per op, only those ops' params/state shard — the rest keep the
        # plain all-reduce sync, closing the priced-vs-emitted gap on
        # mixed strategies. None = every eligible op (forced/heuristic).
        self.wus_ops = set(wus_ops) if wus_ops is not None else None
        # comms-compute overlap: the WUS gradient sync issues as
        # size-targeted bucketed async reduce-scatters in reverse-
        # backward order (each bucket's collective depends only on its
        # own grads plus the previous bucket's issue, so XLA's async
        # collective scheduler hides it under the remaining backward
        # compute), and the next step's bf16 param all-gathers chain in
        # forward order under the optimizer fusion tail. Identity on
        # values — bit-for-bit parity with the synchronous sync.
        self.grad_overlap = bool(overlap_grad_sync
                                 and self.weight_update_sharding)
        self.overlap_bucket_bytes = max(1, int(overlap_bucket_bytes))
        # per-op searched kernel implementations (ISSUE 15): {op name ->
        # impl}. "fused" routes the op's optimizer update through the
        # one-dispatch fused region (ops/fused_update.py, bit-compatible
        # with the triad); "conv_bn_fused" executes the Conv2D and its
        # BatchNorm consumer as one fused train-time region
        # (layout.TrainFusedConvBN); attention impls ("flash"/"einsum")
        # live on the op itself (MultiHeadAttention.kernel_impl, set by
        # apply_strategy). None = no searched kernel dimension — every
        # op keeps its availability-based default, bit-identical to
        # pre-kernel-search execution.
        self.kernel_choices = dict(kernel_choices) if kernel_choices else None
        # per-op searched rematerialization (ISSUE 20): names of ops whose
        # '_r' choice won — their forward runs under jax.checkpoint, so
        # backward keeps only the op's boundary (inputs + params) and
        # recomputes the interior. The native gate (ffs_strategy.hpp
        # remat_gate) only spawns '_r' twins for stateless, collective-free
        # ops, so the plain-forward branch below is the only wrap point.
        # None/empty = no remat, bit-identical to pre-remat execution.
        self.remat_ops = set(remat_ops) if remat_ops else None
        self.fused_update_ops = {
            n for n, impl in (self.kernel_choices or {}).items()
            if impl == "fused"}
        self._by_name = {n.op.name: n for n in nodes}
        self._jit_train = None
        self._jit_eval = None
        self._jit_fwd = {}  # keyed by training flag

    # ---- weight-update sharding (WUS) -------------------------------------
    def _data_degree(self) -> int:
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        deg = 1
        for a in self.data_axes:
            deg *= sizes.get(a, 1)
        return deg

    def _wus_axis_entry(self):
        da = tuple(self.data_axes)
        return da[0] if len(da) == 1 else da

    def wus_spec(self, op_name: str, pname: str,
                 shape: Tuple[int, ...]) -> Optional[P]:
        """Data-sharded spec for a master-param/optimizer-state leaf, or
        None when the leaf stays replicated (WUS off, scalar, or no free
        dim the data degree divides). Composes with the strategy's param
        spec: the data axes land on the first unsharded dividing dim, so
        a model-sharded kernel shards 2-D (model x data)."""
        if not self.weight_update_sharding:
            return None
        if self.wus_ops is not None and op_name not in self.wus_ops:
            return None  # the search chose plain sync for this op
        node = self._by_name.get(op_name)
        if node is None:
            return None
        base = node.param_specs.get(pname, P())
        entries = (list(base) + [None] * len(shape))[:len(shape)]
        deg = self._data_degree()
        for d, e in enumerate(entries):
            if e is None and shape[d] > 0 and shape[d] % deg == 0:
                entries[d] = self._wus_axis_entry()
                return P(*entries)
        return None

    def wus_param_specs(self) -> Dict[str, Dict[str, P]]:
        """{op name: {param name: sharded spec}} of every leaf WUS
        actually shards — the sharded-state truth fflint's sharding pass
        verifies against the mesh."""
        if not self.weight_update_sharding:
            return {}
        from flexflow_tpu.search.unity import _param_shapes
        out: Dict[str, Dict[str, P]] = {}
        for node in self.nodes:
            for pname, shp in _param_shapes(node.op).items():
                spec = self.wus_spec(node.op.name, pname, tuple(shp))
                if spec is not None:
                    out.setdefault(node.op.name, {})[pname] = spec
        return out

    def _wus_shard(self, tree):
        """Constrain every float leaf of a params-shaped (sub)tree onto
        its WUS spec. Applied to the gradients inside the train step,
        this turns the data-axis gradient psum GSPMD would emit as an
        all-reduce into a reduce-scatter (each chip keeps only its shard
        of the summed gradient); applied to the updated params/moments it
        pins the shard layout through the optimizer fusion.

        Under ``grad_overlap`` the constraints apply bucket by bucket in
        reverse-backward order (``_chain_constrained``): each bucket's
        reduce-scatter depends only on its own grads plus the previous
        bucket's issue, so XLA's async collective machinery hides it
        under the remaining backward compute instead of sinking one
        combined sync to the end of the step."""
        if not self.weight_update_sharding:
            return tree
        if self.grad_overlap:
            leaves = self._collect_spec_leaves(tree, self.wus_spec)
            if not leaves:
                return tree
            return self._chain_constrained(
                tree, leaves, self._bucket_order(leaves, reverse=True))

        def leaf(path, x):
            if len(path) < 2 or not hasattr(x, "shape"):
                return x
            spec = self.wus_spec(getattr(path[-2], "key", None),
                                 getattr(path[-1], "key", None), x.shape)
            if spec is None:
                return x
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(leaf, tree)

    # ---- bucketed async constraint chaining (comms-compute overlap) -------
    def _collect_spec_leaves(self, tree, spec_fn):
        """{(op name, param name): (leaf, spec)} for every float leaf of
        a params-shaped tree where ``spec_fn(op, pname, shape)`` returns
        a PartitionSpec (None = leave alone)."""
        out: Dict[Tuple[str, str], Tuple[jax.Array, P]] = {}

        def leaf(path, x):
            if len(path) >= 2 and hasattr(x, "shape"):
                op_name = getattr(path[-2], "key", None)
                pname = getattr(path[-1], "key", None)
                spec = spec_fn(op_name, pname, x.shape)
                if spec is not None:
                    out[(op_name, pname)] = (x, spec)
            return x

        jax.tree_util.tree_map_with_path(leaf, tree)
        return out

    def _bucket_order(self, leaves, reverse: bool):
        """Leaf keys in graph-topological op order (``reverse=True`` for
        the backward-completion order the gradient buckets follow)."""
        by_op: Dict[str, list] = {}
        for k in leaves:
            by_op.setdefault(k[0], []).append(k)
        order = []
        for node in (reversed(self.nodes) if reverse else self.nodes):
            order.extend(by_op.pop(node.op.name, ()))
        for rest in by_op.values():  # unknown ops: stable tail
            order.extend(rest)
        return order

    def _chain_constrained(self, tree, leaves, order):
        """Apply sharding constraints to ``leaves`` in size-targeted
        buckets (``overlap_bucket_bytes`` of payload each), chaining
        consecutive buckets through ``lax.optimization_barrier``: bucket
        k's constraint inputs depend on one of bucket k-1's constrained
        outputs, so the lowered collectives issue in bucket order — the
        structure XLA's async collective scheduler needs to hide each
        bucket under the compute still running when it fires. The
        barrier is the identity on values, so this path is bit-for-bit
        identical to the unchained constraints (tests/test_overlap.py).
        """
        buckets, cur, size = [], [], 0
        for key in order:
            x, _ = leaves[key]
            cur.append(key)
            size += int(x.size) * x.dtype.itemsize
            if size >= self.overlap_bucket_bytes:
                buckets.append(cur)
                cur, size = [], 0
        if cur:
            buckets.append(cur)
        done: Dict[Tuple[str, str], jax.Array] = {}
        prev = None
        for bucket in buckets:
            vals = [leaves[k][0] for k in bucket]
            if prev is not None:
                chained = jax.lax.optimization_barrier(tuple(vals) + (prev,))
                vals = list(chained[:-1])
            vals = [
                jax.lax.with_sharding_constraint(
                    v, NamedSharding(self.mesh, leaves[k][1]))
                for k, v in zip(bucket, vals)
            ]
            prev = vals[0]
            done.update(zip(bucket, vals))

        def replace(path, x):
            if len(path) >= 2:
                k = (getattr(path[-2], "key", None),
                     getattr(path[-1], "key", None))
                if k in done:
                    return done[k]
            return x

        return jax.tree_util.tree_map_with_path(replace, tree)

    def _constrain_compute(self, tree):
        """Constrain a params-shaped tree onto the strategy (compute)
        specs — the all-gather over the data axes that rebuilds the next
        step's replicated bf16 working copy from the WUS shards, fused
        into the optimizer update.

        Under ``grad_overlap`` the gathers chain in FORWARD op order
        (``_chain_constrained``): the first layers' compute params — the
        ones the next step's forward needs first — prefetch under the
        optimizer fusion tail while later leaves' update math still
        runs."""
        if not self.weight_update_sharding:
            return tree
        if self.grad_overlap:
            def spec_fn(op_name, pname, shape):
                node = self._by_name.get(op_name)
                if node is None:
                    return None
                return node.param_specs.get(pname, P())

            leaves = self._collect_spec_leaves(tree, spec_fn)
            if not leaves:
                return tree
            return self._chain_constrained(
                tree, leaves, self._bucket_order(leaves, reverse=False))

        def leaf(path, x):
            if len(path) < 2 or not hasattr(x, "shape"):
                return x
            node = self._by_name.get(getattr(path[-2], "key", None))
            if node is None:
                return x
            spec = node.param_specs.get(getattr(path[-1], "key", None), P())
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(leaf, tree)

    # ---- parameter / state initialization ---------------------------------
    def init_params_and_state(self, rng) -> Tuple[Dict, Dict]:
        params: Dict[str, Dict[str, jax.Array]] = {}
        state: Dict[str, Dict[str, jax.Array]] = {}

        def _init(rng):
            p = {}
            for node in self.nodes:
                rng, sub = jax.random.split(rng)
                ps = node.op.init_params(sub)
                if ps:
                    p[node.op.name] = ps
            return p

        params = jax.jit(_init)(rng)
        params = jax.device_put(params, self.param_shardings(params,
                                                            master=True))
        for node in self.nodes:
            if hasattr(node.op, "init_state"):
                state[node.op.name] = node.op.init_state()
        if self.use_master_copy:
            state[COMPUTE_PARAMS_KEY] = self.cast_compute_copy(params)
        return params, state

    def cast_compute_copy(self, params):
        """bf16 copy of the float parameter leaves (the forward/backward
        working set under the master-weight regime). Under WUS the master
        leaves are data-sharded, so the copy is all-gathered back onto the
        compute (strategy) shardings here."""
        if not hasattr(self, "_cast_jit"):
            # cached: repeated refreshes (per-weight import loops) must not
            # retrace a fresh jit each call
            self._cast_jit = jax.jit(
                lambda p: jax.tree.map(self._cast_leaf, p))
        out = self._cast_jit(params)
        if self.weight_update_sharding:
            out = jax.device_put(out, self.param_shardings(out))
        return out

    def _cast_leaf(self, x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x

    def param_shardings(self, params, master: bool = False):
        """NamedShardings tree for a params-shaped tree: the compute
        (strategy) shardings, or — ``master=True`` under WUS — the
        data-sharded master layout the optimizer state follows (zeros_like
        inherits it, so sharded params get sharded m/v for free)."""
        def spec_for(op_name, pname, arr):
            node = self._by_name[op_name]
            spec = node.param_specs.get(pname, P())
            if master:
                w = self.wus_spec(op_name, pname, tuple(arr.shape))
                if w is not None:
                    spec = w
            return NamedSharding(self.mesh, spec)

        return {
            op_name: {
                pn: spec_for(op_name, pn, a) for pn, a in sub.items()
            }
            for op_name, sub in params.items()
        }

    # ---- forward graph traversal ------------------------------------------
    def _output_layout(self, guid: int, idx: int) -> str:
        """Physical layout of a produced value (layout pass metadata on
        the producing node; absent = NCHW, the boundary contract)."""
        node = self.by_guid.get(guid)
        ols = getattr(node, "output_layouts", None) if node is not None else None
        return ols[idx] if ols and idx < len(ols) else "NCHW"

    def run_graph(self, params, state, inputs: Dict[str, jax.Array],
                  ctx: OpContext, nodes=None):
        """Evaluate ops in topo order; returns (values, new_state, aux_losses).

        aux_losses collects regularizer terms ops emit during forward (e.g.
        the MoE load-balance loss the reference computes inside Aggregate's
        backward, src/ops/aggregate.cu) — they are added to the objective.
        ``nodes`` overrides the node list (the inference executables run
        the Conv+BN-folded graph).
        """
        values: Dict[Tuple[int, int], jax.Array] = {}
        new_state: Dict[str, Any] = {}
        aux_losses: List[jax.Array] = []
        self._run_nodes(nodes if nodes is not None else self.nodes,
                        params, state, inputs, values,
                        new_state, aux_losses, ctx)
        # the designated output leaves in the boundary layout whatever the
        # execution layout of its producer was
        if self._output_layout(*self.final_ref) == "NHWC":
            from flexflow_tpu.layout import TO_NCHW
            values[self.final_ref] = jnp.transpose(
                values[self.final_ref], TO_NCHW)
        return values, new_state, aux_losses

    def _run_nodes(self, nodes, params, state, inputs, values, new_state,
                   aux_losses, ctx: OpContext):
        """Evaluate the given nodes in order, reading/writing the shared
        ``values`` dict (lets the pipeline executor run head/tail subsets
        around the shard_map'd body).

        Values are stored in their producer's execution layout (the layout
        pass metadata, flexflow_tpu/layout.py); where a consumer expects
        the other layout, the transpose materializes HERE, cached per
        (value, layout) — so after propagation each conv chain pays one
        boundary pair, not one pair per op."""
        from flexflow_tpu.layout import TO_NCHW, TO_NHWC

        relayout_cache: Dict[Tuple, jax.Array] = {}

        def fetch(ref, want: str):
            if ref[0] == "op":
                have = self._output_layout(ref[1], ref[2])
                v = values[(ref[1], ref[2])]
            else:  # graph inputs are staged NCHW (API boundary)
                have = "NCHW"
                v = inputs[ref[1]]
            if want == have or getattr(v, "ndim", 0) != 4:
                return v
            key = (tuple(ref), want)
            if key not in relayout_cache:
                relayout_cache[key] = jnp.transpose(
                    v, TO_NHWC if want == "NHWC" else TO_NCHW)
            return relayout_cache[key]

        for node in nodes:
            op = node.op
            in_layouts = getattr(node, "input_layouts", None)
            args = [
                fetch(ref, in_layouts[j] if in_layouts else "NCHW")
                for j, ref in enumerate(node.input_refs)
            ]
            sources = getattr(op, "param_sources", None)
            if sources is not None:
                # fused execution-time op (FoldedConvBN eval fold /
                # TrainFusedConvBN searched kernel): reads the
                # parameter/state subtrees of the ops it folded
                outs = op.forward(
                    {s: params.get(s, {}) for s in sources}, args, ctx,
                    state={s: state.get(s) for s in sources})
                # train-time fused regions update their sources' state
                # (BN running stats) under the SOURCE names, keeping the
                # state tree's shape checkpoint-compatible
                ns = getattr(op, "_new_states", None)
                if ns:
                    new_state.update(ns)
                    op._new_states = None
                else:
                    for s in sources:
                        if s in state and state[s] is not None \
                                and hasattr(self._by_name.get(s, None),
                                            "op") \
                                and hasattr(self._by_name[s].op,
                                            "init_state"):
                            new_state.setdefault(s, state[s])
            elif hasattr(op, "init_state"):
                outs = op.forward(params.get(op.name, {}), args, ctx,
                                  state=state.get(op.name))
                if getattr(op, "_new_state", None) is not None:
                    new_state[op.name] = op._new_state
                    op._new_state = None
                elif op.name in state:
                    new_state[op.name] = state[op.name]
            elif ctx.training and self.remat_ops \
                    and op.name in self.remat_ops:
                # searched '_r' choice: checkpoint the op's boundary and
                # recompute its interior in backward (gate-legal ops are
                # stateless with no aux side channel)
                outs = jax.checkpoint(
                    lambda p_, a_, f_=op.forward: tuple(f_(p_, list(a_),
                                                           ctx))
                )(params.get(op.name, {}), tuple(args))
            else:
                outs = op.forward(params.get(op.name, {}), args, ctx)
            if getattr(op, "_aux_loss", None) is not None:
                aux_losses.append(op._aux_loss)
                op._aux_loss = None
            out_layouts = getattr(node, "output_layouts", None)
            for i, o in enumerate(outs):
                spec = node.output_specs[i]
                if spec is not None:
                    if out_layouts and i < len(out_layouts) \
                            and out_layouts[i] == "NHWC" \
                            and getattr(o, "ndim", 0) == 4:
                        from flexflow_tpu.layout import permute_spec_nhwc
                        spec = permute_spec_nhwc(spec)
                    o = jax.lax.with_sharding_constraint(
                        o, NamedSharding(self.mesh, spec)
                    )
                values[(op.guid, i)] = o

    # ---- jitted steps ------------------------------------------------------
    def _loss_value(self, logits, labels):
        fn = get_loss_fn(self.loss_type)
        if self.final_is_softmax and self.loss_type in (
            LossType.CATEGORICAL_CROSSENTROPY,
            LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        ):
            # final op already produced probabilities (reference pairs a
            # Softmax op with CE loss — loss_functions.cc:41)
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-12, 1.0))
            if self.loss_type == LossType.CATEGORICAL_CROSSENTROPY:
                return -jnp.mean(jnp.sum(labels * logp, axis=-1))
            lab = labels.reshape(labels.shape[0], -1)[:, 0].astype(jnp.int32)
            return -jnp.mean(jnp.take_along_axis(logp, lab[:, None], axis=-1))
        return fn(logits, labels)

    def _training_nodes(self):
        """Node list the TRAIN step runs: (Conv2D, BatchNorm) pairs whose
        searched kernel choice is ``_k:conv_bn_fused`` execute as one
        fused region (layout.TrainFusedConvBN — batch-stats BN, state
        updates preserved); everything else is ``self.nodes`` untouched.
        Built once per executor."""
        names = {n for n, impl in (self.kernel_choices or {}).items()
                 if impl == "conv_bn_fused"}
        if not names:
            return self.nodes
        if not hasattr(self, "_train_fused_nodes"):
            from flexflow_tpu.layout import fuse_conv_bn_train
            self._train_fused_nodes = fuse_conv_bn_train(
                self.nodes, names, keep_guids={self.final_ref[0]})
        return self._train_fused_nodes

    def _optimizer_update(self, grads, opt_state, params):
        """Optimizer update honoring per-op ``_k:fused`` kernel choices:
        the chosen ops' leaves update through the one-dispatch fused
        region (ops/fused_update.py, bit-compatible with the reference
        triad); the rest take ``optimizer.update`` unchanged. No fused
        choices = exactly the pre-kernel-search call."""
        fused = {n for n in self.fused_update_ops if n in params}
        if not fused:
            return self.optimizer.update(grads, opt_state, params)
        from flexflow_tpu.ops.fused_update import fused_optimizer_update
        return fused_optimizer_update(self.optimizer, grads, opt_state,
                                      params, fused)

    def _train_step_fn(self):
        """The raw (unjitted) train-step function, for composition into
        multi-step scans."""
        train_nodes = self._training_nodes()

        def train_step(params, opt_state, state, inputs, labels, rng):
            cparams = (state[COMPUTE_PARAMS_KEY]
                       if self.use_master_copy else params)

            def loss_fn(p):
                ctx = OpContext(training=True, rng=rng,
                                compute_dtype=self.compute_dtype,
                                mesh=self.mesh)
                values, new_state, aux = self.run_graph(p, state, inputs, ctx,
                                                        nodes=train_nodes)
                logits = values[self.final_ref]
                loss = self._loss_value(logits, labels)
                for a in aux:
                    loss = loss + a
                return loss, (logits, new_state)

            (loss, (logits, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(cparams)
            # gradient sync over the data axes is inserted by GSPMD here
            # (in bf16 under the master-weight regime — half the bytes).
            # Under WUS the shard constraint turns that all-reduce into a
            # reduce-scatter: each chip receives only the gradient shard
            # whose master-param/moment shard it owns.
            grads = self._wus_shard(grads)
            new_params, new_opt_state = self._optimizer_update(
                grads, opt_state, params
            )
            new_params = self._wus_shard(new_params)
            if self.use_master_copy:
                # next step's bf16 working copy, fused into the update loop
                # (one extra bf16 write instead of a separate cast pass;
                # under WUS the compute-spec constraint is the all-gather
                # that rebuilds the replicated copy from the shards)
                new_state[COMPUTE_PARAMS_KEY] = self._constrain_compute(
                    jax.tree.map(self._cast_leaf, new_params))
            metric_vals = self.metrics.compute(logits, labels)
            return new_params, new_opt_state, new_state, loss, metric_vals

        return train_step

    def make_train_step(self):
        if getattr(self, "comp_mode", CompMode.TRAINING) == CompMode.INFERENCE:
            raise RuntimeError(
                "model compiled with CompMode.INFERENCE is forward-only; "
                "re-compile with CompMode.TRAINING to train")
        if self._jit_train is None:
            self._jit_train = jax.jit(self._train_step_fn(),
                                      donate_argnums=(0, 1, 2))
            get_registry().inc("executor.train_step_jits")
            get_registry().gauge("executor.num_ops", len(self.nodes))
        return self._jit_train

    def make_multi_step(self, num_iters: int, stacked: bool = False):
        """Compile ``num_iters`` training steps into ONE XLA program via
        lax.scan — the TPU analog of the reference's Legion trace replay
        (begin_trace/end_trace around each iteration, flexflow_cffi.py:2079):
        after the first compile the whole iteration block runs with zero
        per-step dispatch overhead.

        ``stacked=False``: (inputs, labels) is one batch reused every
        iteration (the reference examples' 'load data once' benchmark mode).
        ``stacked=True``: each array carries a leading [num_iters] axis and
        iteration i consumes slice i.
        """
        if getattr(self, "comp_mode", CompMode.TRAINING) == CompMode.INFERENCE:
            raise RuntimeError(
                "model compiled with CompMode.INFERENCE is forward-only; "
                "re-compile with CompMode.TRAINING to train")

        step = self._train_step_fn()

        def multi(params, opt_state, state, inputs, labels, rng):
            def body(carry, xs):
                params, opt_state, state, rng = carry
                rng, sub = jax.random.split(rng)
                inp, lab = xs if stacked else (inputs, labels)
                params, opt_state, state, loss, mvals = step(
                    params, opt_state, state, inp, lab, sub)
                return (params, opt_state, state, rng), loss

            xs = (inputs, labels) if stacked else None
            (params, opt_state, state, rng), losses = jax.lax.scan(
                body, (params, opt_state, state, rng), xs,
                length=None if stacked else num_iters)
            return params, opt_state, state, losses

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def _inference_nodes(self):
        """Node list the forward-only executables run: eligible Conv2D→
        BatchNorm(+ReLU) pairs folded into single convolutions
        (flexflow_tpu/layout.fold_conv_bn — eval BN is an affine transform
        of running stats, which collapses into the conv weights; the
        training step keeps the full graph since batch statistics cannot
        fold). Built once per executor."""
        if not self.fold_conv_bn:
            return self.nodes
        if not hasattr(self, "_folded_nodes"):
            from flexflow_tpu.layout import fold_conv_bn
            self._folded_nodes = fold_conv_bn(
                self.nodes, keep_guids={self.final_ref[0]})
        return self._folded_nodes

    def make_eval_step(self):
        if self._jit_eval is not None:
            return self._jit_eval
        inf_nodes = self._inference_nodes()

        def eval_step(params, state, inputs, labels):
            ctx = OpContext(training=False, compute_dtype=self.compute_dtype,
                            mesh=self.mesh)
            values, _, _ = self.run_graph(params, state, inputs, ctx,
                                          nodes=inf_nodes)
            logits = values[self.final_ref]
            loss = self._loss_value(logits, labels)
            return loss, logits, self.metrics.compute(logits, labels)

        self._jit_eval = jax.jit(eval_step)
        get_registry().inc("executor.eval_step_jits")
        return self._jit_eval

    def make_forward(self, training: bool = False):
        if training in self._jit_fwd:
            return self._jit_fwd[training]
        inf_nodes = None if training else self._inference_nodes()

        def fwd(params, state, inputs, rng):
            ctx = OpContext(training=training, rng=rng,
                            compute_dtype=self.compute_dtype, mesh=self.mesh)
            values, new_state, _ = self.run_graph(params, state, inputs, ctx,
                                                  nodes=inf_nodes)
            return values[self.final_ref], new_state

        self._jit_fwd[training] = jax.jit(fwd)
        return self._jit_fwd[training]

    def batch_sharding(self):
        da = tuple(self.data_axes)
        return NamedSharding(self.mesh, P(da) if da else P())

    def label_sharding(self):
        """Sharding for staged label arrays. Defaults to the batch
        sharding; executors that stage inputs in a different layout
        (the pipeline's pipe-sharded microbatch queue) keep labels
        data-sharded — labels only meet the loss, after the boundary
        output is already back in the data layout."""
        return self.batch_sharding()

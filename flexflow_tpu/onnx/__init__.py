"""ONNX frontend (python/flexflow/onnx/model.py analog)."""

from flexflow_tpu.onnx.model import ONNXModel

__all__ = ["ONNXModel"]

"""ONNX graph → FFModel translation.

Analog of the reference's python/flexflow/onnx/model.py: walks
``model.graph.node`` in order and emits the corresponding FFModel layer
per ONNX op_type, deriving Gemm/MatMul/Conv/BatchNorm configurations from
the graph's **initializer payloads** — exactly as the reference reads
tensor data to size its layers — so standard exported models load with no
custom attributes. The trained weights themselves transfer into the
compiled model via :meth:`ONNXModel.copy_weights_to`.

Accepted inputs: a ``.onnx`` path or raw ModelProto bytes (parsed by the
dependency-free reader in :mod:`flexflow_tpu.onnx.proto`), an ``onnx``
package ModelProto (when that package is importable), or any duck-typed
object with the ModelProto structure.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


# AttributeProto.AttributeType values (onnx.proto): which field is live
_ATTR_TYPE_FIELD = {1: "f", 2: "i", 3: "s", 4: "t", 6: "floats", 7: "ints"}


def _attrs(node) -> Dict[str, Any]:
    out = {}
    for a in getattr(node, "attribute", []):
        atype = getattr(a, "type", None)
        if atype in _ATTR_TYPE_FIELD:
            # real protobuf: every field exists with a default — the type
            # tag alone decides which one carries the value
            fields = (_ATTR_TYPE_FIELD[atype],)
        else:
            # duck-typed stand-in (tests / no onnx package): first field
            # actually set wins
            fields = ("i", "f", "s", "t", "ints", "floats")
        for field in fields:
            v = getattr(a, field, None)
            if v is None:
                continue
            if field == "s" and isinstance(v, bytes):
                v = v.decode()
            if field in ("ints", "floats"):
                v = list(v)
            if field == "t":
                v = _tensor_to_numpy(v)
            out[a.name] = v
            break
    return out


def _tensor_to_numpy(t) -> np.ndarray:
    """TensorProto (own reader, onnx package, or duck-typed) → ndarray."""
    if isinstance(t, np.ndarray):
        return t
    if hasattr(t, "to_numpy"):
        return t.to_numpy()
    try:
        from onnx import numpy_helper  # pragma: no cover

        return numpy_helper.to_array(t)
    except ImportError:
        pass
    from flexflow_tpu.onnx.proto import TENSOR_DTYPES

    dtype = TENSOR_DTYPES.get(getattr(t, "data_type", 1), np.float32)
    shape = tuple(getattr(t, "dims", ()))
    raw = getattr(t, "raw_data", b"")
    if raw:
        return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
    for field in ("float_data", "int64_data", "int32_data", "double_data"):
        data = list(getattr(t, field, []) or [])
        if data:
            return np.asarray(data, dtype=dtype).reshape(shape)
    return np.zeros(shape, dtype=dtype)


class ONNXModel:
    def __init__(self, model):
        if isinstance(model, (str, bytes)):
            if isinstance(model, str):
                with open(model, "rb") as f:
                    model = f.read()
            from flexflow_tpu.onnx.proto import parse_model

            model = parse_model(model)
        self.model = model
        # initializer payloads: name -> ndarray (the reference reads these
        # to size Gemm/Conv and we additionally keep them for weight import)
        self.initializers: Dict[str, np.ndarray] = {}
        for t in getattr(model.graph, "initializer", []):
            self.initializers[t.name] = _tensor_to_numpy(t)
        # layer name -> {ff param name: ndarray} staged for copy_weights_to
        self._imports: Dict[str, Dict[str, np.ndarray]] = {}
        # layer name -> {state name: ndarray} (BatchNorm running stats live
        # in the model's non-trainable state collection, not params)
        self._state_imports: Dict[str, Dict[str, np.ndarray]] = {}

    # ---- graph walk --------------------------------------------------------
    def apply(self, ff: FFModel, input_tensors: Dict[str, Any]):
        """Translate the graph; returns the tensor of the last node output.

        ``input_tensors`` maps ONNX graph-input names to FFModel tensors.
        """
        env: Dict[str, Any] = dict(input_tensors)
        out = None
        for node in self.model.graph.node:
            out = self._emit(ff, node, env)
        return out

    def copy_weights_to(self, ff: FFModel) -> int:
        """After ``ff.compile``: load the ONNX initializer weights into the
        model's parameters. Returns the number of arrays copied."""
        import jax
        import jax.numpy as jnp

        copied = 0
        for layer_name, params in self._imports.items():
            for pname, arr in params.items():
                try:
                    ff.set_parameter(layer_name, arr, pname)
                    copied += 1
                except (KeyError, ValueError):
                    pass  # layer absent after rewrites / shape mismatch
        for layer_name, stats in self._state_imports.items():
            st = ff.state.get(layer_name)
            if st is None:
                continue
            for sname, arr in stats.items():
                old = st.get(sname)
                if old is None or tuple(old.shape) != tuple(arr.shape):
                    continue
                st[sname] = jax.device_put(jnp.asarray(arr, old.dtype),
                                           old.sharding)
                copied += 1
        return copied

    def _weights(self, node) -> List[Optional[np.ndarray]]:
        """Initializer payload per node input (None for activations)."""
        return [self.initializers.get(i) for i in node.input]

    def _emit(self, ff: FFModel, node, env: Dict[str, Any]):
        op = node.op_type
        at = _attrs(node)
        # data inputs only (weights come from initializers)
        ins = [env[i] for i in node.input if i in env]
        wts = self._weights(node)
        name = node.output[0]

        def done(t):
            env[name] = t
            return t

        if op == "Constant":
            value = at.get("value")
            if value is None:
                raise ValueError(f"Constant {name}: no tensor attribute")
            self.initializers[name] = np.asarray(value)
            return None
        if op == "Gemm" or op == "MatMul":
            if op == "Gemm" and at.get("transA", 0):
                # dense computes x @ W; transposing the activation is not
                # expressible as a weight fold — refuse rather than silently
                # computing wrong numerics (advisor r3 finding)
                raise NotImplementedError(
                    f"Gemm node {name}: transA=1 is not supported")
            w = next((w for w in wts[1:] if w is not None and w.ndim == 2),
                     None)
            if w is not None:
                trans_b = bool(at.get("transB", 0)) if op == "Gemm" else False
                kernel = w.T if trans_b else w  # ff dense kernel: [in, out]
                out_dim = kernel.shape[1]
                bias = next((b for b in wts[1:]
                             if b is not None and b.ndim == 1), None)
                # Gemm computes alpha*(A@B) + beta*C — fold both scalars
                # into the imported weights so numerics match exactly
                alpha = float(at.get("alpha", 1.0)) if op == "Gemm" else 1.0
                beta = float(at.get("beta", 1.0)) if op == "Gemm" else 1.0
                t = ff.dense(ins[0], int(out_dim),
                             use_bias=bias is not None, name=name)
                imp = {"kernel": np.ascontiguousarray(kernel,
                                                      dtype=np.float32) * alpha}
                if bias is not None:
                    imp["bias"] = np.asarray(bias, dtype=np.float32) * beta
                self._imports[name] = imp
                return done(t)
            # no initializer (dynamic weight or legacy stand-in): fall back
            # to the explicit attribute the pre-initializer frontend used
            out_dim = at.get("out_dim") or at.get("N")
            if out_dim is None:
                raise ValueError(
                    f"{op} node {name}: weight initializer not found and no "
                    f"'out_dim' attribute given")
            return done(ff.dense(ins[0], int(out_dim),
                                 use_bias=(op == "Gemm"), name=name))
        if op == "Conv":
            w = wts[1] if len(wts) > 1 else None
            if w is not None and w.ndim == 4:
                out_ch = w.shape[0]  # OIHW, matches ff conv2d kernel layout
                k = at.get("kernel_shape", list(w.shape[2:]))
                imp = {"kernel": np.asarray(w, dtype=np.float32)}
                bias = wts[2] if len(wts) > 2 else None
                if bias is not None:
                    imp["bias"] = np.asarray(bias, dtype=np.float32)
                self._imports[name] = imp
            else:
                k = at.get("kernel_shape", [1, 1])
                out_ch = at.get("out_channels")
                bias = None
                if out_ch is None:
                    raise ValueError(
                        f"Conv node {name}: weight initializer not found "
                        f"and no 'out_channels' attribute given")
            s = at.get("strides", [1, 1])
            p = at.get("pads", [0, 0, 0, 0])
            return done(ff.conv2d(ins[0], int(out_ch), k[0], k[1], s[0], s[1],
                                  p[0], p[1], groups=int(at.get("group", 1)),
                                  use_bias=(w is None or len(wts) > 2),
                                  name=name))
        if op in ("MaxPool", "AveragePool"):
            k = at.get("kernel_shape", [2, 2])
            s = at.get("strides", k)
            p = at.get("pads", [0, 0, 0, 0])
            pt = PoolType.POOL_MAX if op == "MaxPool" else PoolType.POOL_AVG
            return done(ff.pool2d(ins[0], k[0], k[1], s[0], s[1], p[0], p[1],
                                  pool_type=pt, name=name))
        if op == "GlobalAveragePool":
            return done(ff.mean(ins[0], [2, 3], keepdims=True, name=name))
        if op == "BatchNormalization":
            # inputs: X, scale, B, input_mean, input_var (onnx.proto)
            t = ff.batch_norm(ins[0], relu=False, name=name)
            if len(wts) >= 3 and wts[1] is not None and wts[2] is not None:
                self._imports[name] = {
                    "scale": np.asarray(wts[1], dtype=np.float32),
                    "bias": np.asarray(wts[2], dtype=np.float32),
                }
            if len(wts) >= 5 and wts[3] is not None and wts[4] is not None:
                # trained running stats: inference must use them, not the
                # init-state defaults (mean=0, var=1)
                self._state_imports[name] = {
                    "mean": np.asarray(wts[3], dtype=np.float32),
                    "var": np.asarray(wts[4], dtype=np.float32),
                }
            return done(t)
        if op == "LayerNormalization":
            t = ff.layer_norm(ins[0], name=name)
            if len(wts) >= 2 and wts[1] is not None:
                imp = {"scale": np.asarray(wts[1], dtype=np.float32)}
                if len(wts) >= 3 and wts[2] is not None:
                    imp["bias"] = np.asarray(wts[2], dtype=np.float32)
                self._imports[name] = imp
            return done(t)
        if op == "Relu":
            return done(ff.relu(ins[0], name=name))
        if op == "Gelu":
            return done(ff.gelu(ins[0], name=name))
        if op == "Sigmoid":
            return done(ff.sigmoid(ins[0], name=name))
        if op == "Tanh":
            return done(ff.tanh(ins[0], name=name))
        if op == "Elu":
            return done(ff.elu(ins[0], name=name))
        if op == "Exp":
            return done(ff.exp(ins[0], name=name))
        if op == "Softmax":
            return done(ff.softmax(ins[0], axis=int(at.get("axis", -1)),
                                   name=name))
        if op == "Dropout":
            return done(ff.dropout(ins[0], float(at.get("ratio", 0.5)),
                                   name=name))
        if op == "Add":
            return done(ff.add(ins[0], ins[1], name=name))
        if op == "Sub":
            return done(ff.subtract(ins[0], ins[1], name=name))
        if op == "Mul":
            return done(ff.multiply(ins[0], ins[1], name=name))
        if op == "Div":
            return done(ff.divide(ins[0], ins[1], name=name))
        if op == "Max":
            return done(ff.max(ins[0], ins[1], name=name))
        if op == "Min":
            return done(ff.min(ins[0], ins[1], name=name))
        if op == "Concat":
            return done(ff.concat(ins, int(at.get("axis", 0)), name=name))
        if op == "Split":
            sizes = at.get("split")
            if sizes is None and len(node.input) > 1:
                arr = self.initializers.get(node.input[1])
                if arr is not None:  # opset >= 13: sizes as constant input
                    sizes = [int(x) for x in arr]
            outs = ff.split(ins[0], sizes if sizes else len(node.output),
                            int(at.get("axis", 0)), name=name)
            for out_name, t in zip(node.output, outs):
                env[out_name] = t
            return outs
        if op == "Flatten":
            return done(ff.flat(ins[0], name=name))
        if op == "Reshape":
            shape = at.get("shape")
            if shape is None and len(node.input) > 1:
                arr = self.initializers.get(node.input[1])
                if arr is not None:  # standard export: shape as constant
                    shape = [int(x) for x in arr]
            if shape is None:
                raise ValueError(f"Reshape {name}: shape neither attribute "
                                 f"nor constant initializer")
            batch = ins[0].shape[0]
            shape = [batch if s in (0, -1) and i == 0 else int(s)
                     for i, s in enumerate(shape)]
            return done(ff.reshape(ins[0], shape, name=name))
        if op == "Transpose":
            return done(ff.transpose(ins[0], at.get("perm"), name=name))
        if op == "Cast":
            return done(ff.identity(ins[0], name=name))
        if op == "ReduceMean":
            return done(ff.mean(ins[0], at.get("axes", [-1]),
                                keepdims=bool(at.get("keepdims", 1)),
                                name=name))
        if op == "ReduceSum":
            return done(ff.reduce_sum(ins[0], at.get("axes", [-1]),
                                      keepdims=bool(at.get("keepdims", 1)),
                                      name=name))
        if op == "Gather":
            return done(ff.gather(ins[0], ins[1], axis=int(at.get("axis", 0)),
                                  name=name))
        if op == "Identity":
            return done(ff.identity(ins[0], name=name))
        raise NotImplementedError(f"ONNX op {op} has no translation")

"""ONNX graph → FFModel translation.

Analog of python/flexflow/onnx/model.py (375 LoC in the reference): walks
``model.graph.node`` in order and emits the corresponding FFModel layer per
ONNX op_type. The ``onnx`` package is optional in this environment (no
pip installs): ``ONNXModel(path)`` requires it, but ``ONNXModel(model)``
accepts any object with the ModelProto structure (``graph.node[*].op_type/
input/output/attribute``), which is also how the unit tests drive the
translation table devicelessly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel


# AttributeProto.AttributeType values (onnx.proto): which field is live
_ATTR_TYPE_FIELD = {1: "f", 2: "i", 3: "s", 6: "floats", 7: "ints"}


def _attrs(node) -> Dict[str, Any]:
    out = {}
    for a in getattr(node, "attribute", []):
        atype = getattr(a, "type", None)
        if atype in _ATTR_TYPE_FIELD:
            # real protobuf: every field exists with a default — the type
            # tag alone decides which one carries the value
            fields = (_ATTR_TYPE_FIELD[atype],)
        else:
            # duck-typed stand-in (tests / no onnx package): first field
            # actually set wins
            fields = ("i", "f", "s", "ints", "floats")
        for field in fields:
            v = getattr(a, field, None)
            if v is None:
                continue
            if field == "s" and isinstance(v, bytes):
                v = v.decode()
            if field in ("ints", "floats"):
                v = list(v)
            out[a.name] = v
            break
    return out


class ONNXModel:
    def __init__(self, model):
        if isinstance(model, str):
            try:
                import onnx
            except ImportError as e:  # pragma: no cover
                raise ImportError(
                    "the 'onnx' package is required to load .onnx files; "
                    "pass a ModelProto-like object instead") from e
            model = onnx.load(model)
        self.model = model

    def apply(self, ff: FFModel, input_tensors: Dict[str, Any]):
        """Translate the graph; returns the tensor of the last node output.

        ``input_tensors`` maps ONNX graph-input names to FFModel tensors.
        """
        env: Dict[str, Any] = dict(input_tensors)
        out = None
        for node in self.model.graph.node:
            out = self._emit(ff, node, env)
        return out

    def _emit(self, ff: FFModel, node, env: Dict[str, Any]):
        op = node.op_type
        at = _attrs(node)
        # data inputs only (weights come from initializers and are created
        # by the FFModel layer itself)
        ins = [env[i] for i in node.input if i in env]
        name = node.output[0]

        def done(t):
            env[name] = t
            return t

        if op == "Gemm" or op == "MatMul":
            # out_dim from the weight initializer is not available without
            # the tensor data; FFModel needs it via attribute or env hint
            out_dim = at.get("out_dim") or at.get("N")
            if out_dim is None:
                raise ValueError(
                    f"{op} node {name}: provide 'out_dim' attribute (the "
                    f"frontend does not read initializer payloads)")
            return done(ff.dense(ins[0], int(out_dim),
                                 use_bias=(op == "Gemm"), name=name))
        if op == "Conv":
            k = at.get("kernel_shape", [1, 1])
            s = at.get("strides", [1, 1])
            p = at.get("pads", [0, 0, 0, 0])
            out_ch = at.get("out_channels")
            if out_ch is None:
                raise ValueError(f"Conv node {name}: provide 'out_channels'")
            return done(ff.conv2d(ins[0], int(out_ch), k[0], k[1], s[0], s[1],
                                  p[0], p[1], groups=int(at.get("group", 1)),
                                  name=name))
        if op in ("MaxPool", "AveragePool"):
            k = at.get("kernel_shape", [2, 2])
            s = at.get("strides", k)
            p = at.get("pads", [0, 0, 0, 0])
            pt = PoolType.POOL_MAX if op == "MaxPool" else PoolType.POOL_AVG
            return done(ff.pool2d(ins[0], k[0], k[1], s[0], s[1], p[0], p[1],
                                  pool_type=pt, name=name))
        if op == "GlobalAveragePool":
            return done(ff.mean(ins[0], [2, 3], keepdims=True, name=name))
        if op == "BatchNormalization":
            return done(ff.batch_norm(ins[0], relu=False, name=name))
        if op == "LayerNormalization":
            return done(ff.layer_norm(ins[0], name=name))
        if op == "Relu":
            return done(ff.relu(ins[0], name=name))
        if op == "Gelu":
            return done(ff.gelu(ins[0], name=name))
        if op == "Sigmoid":
            return done(ff.sigmoid(ins[0], name=name))
        if op == "Tanh":
            return done(ff.tanh(ins[0], name=name))
        if op == "Elu":
            return done(ff.elu(ins[0], name=name))
        if op == "Exp":
            return done(ff.exp(ins[0], name=name))
        if op == "Softmax":
            return done(ff.softmax(ins[0], axis=int(at.get("axis", -1)),
                                   name=name))
        if op == "Dropout":
            return done(ff.dropout(ins[0], float(at.get("ratio", 0.5)),
                                   name=name))
        if op == "Add":
            return done(ff.add(ins[0], ins[1], name=name))
        if op == "Sub":
            return done(ff.subtract(ins[0], ins[1], name=name))
        if op == "Mul":
            return done(ff.multiply(ins[0], ins[1], name=name))
        if op == "Div":
            return done(ff.divide(ins[0], ins[1], name=name))
        if op == "Max":
            return done(ff.max(ins[0], ins[1], name=name))
        if op == "Min":
            return done(ff.min(ins[0], ins[1], name=name))
        if op == "Concat":
            return done(ff.concat(ins, int(at.get("axis", 0)), name=name))
        if op == "Split":
            sizes = at.get("split")
            outs = ff.split(ins[0], sizes if sizes else len(node.output),
                            int(at.get("axis", 0)), name=name)
            for out_name, t in zip(node.output, outs):
                env[out_name] = t
            return outs
        if op == "Flatten":
            return done(ff.flat(ins[0], name=name))
        if op == "Reshape":
            shape = at.get("shape")
            if shape is None:
                raise ValueError(f"Reshape {name}: constant-input reshape "
                                 f"needs 'shape' attribute")
            batch = ins[0].shape[0]
            shape = [batch if s in (0, -1) and i == 0 else int(s)
                     for i, s in enumerate(shape)]
            return done(ff.reshape(ins[0], shape, name=name))
        if op == "Transpose":
            return done(ff.transpose(ins[0], at.get("perm"), name=name))
        if op == "Cast":
            return done(ff.identity(ins[0], name=name))
        if op == "ReduceMean":
            return done(ff.mean(ins[0], at.get("axes", [-1]),
                                keepdims=bool(at.get("keepdims", 1)),
                                name=name))
        if op == "ReduceSum":
            return done(ff.reduce_sum(ins[0], at.get("axes", [-1]),
                                      keepdims=bool(at.get("keepdims", 1)),
                                      name=name))
        if op == "Gather":
            return done(ff.gather(ins[0], ins[1], axis=int(at.get("axis", 0)),
                                  name=name))
        if op == "Identity":
            return done(ff.identity(ins[0], name=name))
        raise NotImplementedError(f"ONNX op {op} has no translation")

"""Self-contained ONNX ModelProto reader (+ writer, used by tests).

The reference frontend (python/flexflow/onnx/model.py) depends on the
``onnx`` package to deserialize models and read initializer payloads. That
package is not part of this environment, so this module speaks the
protobuf wire format directly for the subset of onnx.proto3 the frontend
needs: ModelProto → GraphProto → NodeProto / AttributeProto / TensorProto
/ ValueInfoProto. Real ``.onnx`` files (e.g. ``torch.onnx.export`` output)
parse with no third-party dependency; when the ``onnx`` package *is*
importable the frontend still accepts its protos, which duck-type the
classes here.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# TensorProto.DataType → numpy
TENSOR_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}

# ---- wire-format primitives ----------------------------------------------


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(data: bytes):
    """Yield (field_number, wire_type, value) triples of one message.
    value: int for varint/fixed, bytes for length-delimited."""
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = _read_varint(data, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v, pos = _read_varint(data, pos)
        elif wt == 1:  # fixed64
            v = struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(data, pos)
            v = data[pos:pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            v = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _packed_varints(v, wt) -> List[int]:
    if wt == 0:
        return [v]
    out, pos = [], 0
    while pos < len(v):
        x, pos = _read_varint(v, pos)
        out.append(x)
    return out


def _zigzag64(v: int) -> int:
    """Interpret a 64-bit varint as two's-complement signed."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---- proto classes (duck-type the onnx package's) -------------------------


class TensorProto:
    def __init__(self):
        self.dims: List[int] = []
        self.data_type: int = 1
        self.name: str = ""
        self.raw_data: bytes = b""
        self.float_data: List[float] = []
        self.int32_data: List[int] = []
        self.int64_data: List[int] = []
        self.double_data: List[float] = []

    @classmethod
    def parse(cls, data: bytes) -> "TensorProto":
        t = cls()
        for field, wt, v in _fields(data):
            if field == 1:
                t.dims.extend(_zigzag64(x) for x in _packed_varints(v, wt))
            elif field == 2:
                t.data_type = v
            elif field == 4:
                if wt == 5:
                    t.float_data.append(struct.unpack("<f", struct.pack("<I", v))[0])
                else:
                    t.float_data.extend(
                        struct.unpack(f"<{len(v) // 4}f", v))
            elif field == 5:
                t.int32_data.extend(_packed_varints(v, wt))
            elif field == 7:
                t.int64_data.extend(
                    _zigzag64(x) for x in _packed_varints(v, wt))
            elif field == 8:
                t.name = v.decode()
            elif field == 9:
                t.raw_data = v
            elif field == 10:
                if wt == 1:
                    t.double_data.append(
                        struct.unpack("<d", struct.pack("<Q", v))[0])
                else:
                    t.double_data.extend(
                        struct.unpack(f"<{len(v) // 8}d", v))
        return t

    def to_numpy(self) -> np.ndarray:
        dtype = TENSOR_DTYPES.get(self.data_type, np.float32)
        shape = tuple(self.dims)
        if self.raw_data:
            return np.frombuffer(self.raw_data, dtype=dtype).reshape(shape).copy()
        for data in (self.float_data, self.int64_data, self.int32_data,
                     self.double_data):
            if data:
                return np.asarray(data, dtype=dtype).reshape(shape)
        return np.zeros(shape, dtype=dtype)


class AttributeProto:
    def __init__(self):
        self.name = ""
        self.type: Optional[int] = None
        self.f: Optional[float] = None
        self.i: Optional[int] = None
        self.s: Optional[bytes] = None
        self.t: Optional[TensorProto] = None
        self.floats: List[float] = []
        self.ints: List[int] = []

    @classmethod
    def parse(cls, data: bytes) -> "AttributeProto":
        a = cls()
        for field, wt, v in _fields(data):
            if field == 1:
                a.name = v.decode()
            elif field == 2:
                a.f = struct.unpack("<f", struct.pack("<I", v))[0]
            elif field == 3:
                a.i = _zigzag64(v)
            elif field == 4:
                a.s = v
            elif field == 5:
                a.t = TensorProto.parse(v)
            elif field == 7:
                if wt == 5:
                    a.floats.append(
                        struct.unpack("<f", struct.pack("<I", v))[0])
                else:
                    a.floats.extend(struct.unpack(f"<{len(v) // 4}f", v))
            elif field == 8:
                a.ints.extend(_zigzag64(x) for x in _packed_varints(v, wt))
            elif field == 20:
                a.type = v
        return a


class NodeProto:
    def __init__(self):
        self.input: List[str] = []
        self.output: List[str] = []
        self.name = ""
        self.op_type = ""
        self.attribute: List[AttributeProto] = []

    @classmethod
    def parse(cls, data: bytes) -> "NodeProto":
        n = cls()
        for field, wt, v in _fields(data):
            if field == 1:
                n.input.append(v.decode())
            elif field == 2:
                n.output.append(v.decode())
            elif field == 3:
                n.name = v.decode()
            elif field == 4:
                n.op_type = v.decode()
            elif field == 5:
                n.attribute.append(AttributeProto.parse(v))
        return n


class ValueInfoProto:
    def __init__(self):
        self.name = ""
        self.elem_type: Optional[int] = None
        self.shape: Optional[List[Optional[int]]] = None

    @classmethod
    def parse(cls, data: bytes) -> "ValueInfoProto":
        vi = cls()
        for field, _, v in _fields(data):
            if field == 1:
                vi.name = v.decode()
            elif field == 2:  # TypeProto
                for f2, _, v2 in _fields(v):
                    if f2 != 1:  # tensor_type
                        continue
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            vi.elem_type = v3
                        elif f3 == 2:  # TensorShapeProto
                            dims: List[Optional[int]] = []
                            for f4, _, v4 in _fields(v3):
                                if f4 != 1:
                                    continue
                                dv: Optional[int] = None
                                for f5, _, v5 in _fields(v4):
                                    if f5 == 1:
                                        dv = v5
                                dims.append(dv)
                            vi.shape = dims
        return vi


class GraphProto:
    def __init__(self):
        self.node: List[NodeProto] = []
        self.name = ""
        self.initializer: List[TensorProto] = []
        self.input: List[ValueInfoProto] = []
        self.output: List[ValueInfoProto] = []

    @classmethod
    def parse(cls, data: bytes) -> "GraphProto":
        g = cls()
        for field, _, v in _fields(data):
            if field == 1:
                g.node.append(NodeProto.parse(v))
            elif field == 2:
                g.name = v.decode()
            elif field == 5:
                g.initializer.append(TensorProto.parse(v))
            elif field == 11:
                g.input.append(ValueInfoProto.parse(v))
            elif field == 12:
                g.output.append(ValueInfoProto.parse(v))
        return g


class ModelProto:
    def __init__(self):
        self.ir_version = 0
        self.graph = GraphProto()

    @classmethod
    def parse(cls, data: bytes) -> "ModelProto":
        m = cls()
        for field, _, v in _fields(data):
            if field == 1:
                m.ir_version = v
            elif field == 7:
                m.graph = GraphProto.parse(v)
        return m


def parse_model(data: bytes) -> ModelProto:
    return ModelProto.parse(data)


# ---- writer (tests build real wire-format models with it) -----------------


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field: int, wt: int) -> bytes:
    return _varint((field << 3) | wt)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def encode_tensor(name: str, arr: np.ndarray) -> bytes:
    dtype_code = {v: k for k, v in TENSOR_DTYPES.items()}[arr.dtype.type]
    out = b""
    for d in arr.shape:
        out += _tag(1, 0) + _varint(d)
    out += _tag(2, 0) + _varint(dtype_code)
    out += _ld(8, name.encode())
    out += _ld(9, np.ascontiguousarray(arr).tobytes())
    return out


def encode_attribute(name: str, value: Any) -> bytes:
    out = _ld(1, name.encode())
    if isinstance(value, float):
        out += _tag(2, 5) + struct.pack("<f", value)
        out += _tag(20, 0) + _varint(1)
    elif isinstance(value, bool) or isinstance(value, int):
        out += _tag(3, 0) + _varint(int(value) & ((1 << 64) - 1))
        out += _tag(20, 0) + _varint(2)
    elif isinstance(value, (bytes, str)):
        out += _ld(4, value.encode() if isinstance(value, str) else value)
        out += _tag(20, 0) + _varint(3)
    elif isinstance(value, np.ndarray):
        out += _ld(5, encode_tensor(name, value))
        out += _tag(20, 0) + _varint(4)
    elif isinstance(value, (list, tuple)) and all(
            isinstance(x, int) for x in value):
        for x in value:
            out += _tag(8, 0) + _varint(int(x) & ((1 << 64) - 1))
        out += _tag(20, 0) + _varint(7)
    elif isinstance(value, (list, tuple)):
        for x in value:
            out += _tag(7, 5) + struct.pack("<f", float(x))
        out += _tag(20, 0) + _varint(6)
    else:
        raise TypeError(f"attribute {name}: {type(value)}")
    return out


def encode_node(op_type: str, inputs: List[str], outputs: List[str],
                name: str = "", **attrs) -> bytes:
    out = b""
    for i in inputs:
        out += _ld(1, i.encode())
    for o in outputs:
        out += _ld(2, o.encode())
    out += _ld(3, (name or outputs[0]).encode())
    out += _ld(4, op_type.encode())
    for k, v in attrs.items():
        out += _ld(5, encode_attribute(k, v))
    return out


def _encode_value_info(name: str, shape, elem_type: int = 1) -> bytes:
    dims = b""
    for d in shape:
        dims += _ld(1, _tag(1, 0) + _varint(d))
    tensor_type = _tag(1, 0) + _varint(elem_type) + _ld(2, dims)
    return _ld(1, name.encode()) + _ld(2, _ld(1, tensor_type))


def encode_model(nodes: List[bytes], initializers: Dict[str, np.ndarray],
                 inputs: Dict[str, tuple], outputs: Dict[str, tuple]) -> bytes:
    """Assemble ModelProto bytes from encode_node() payloads + named
    initializer arrays + graph input/output shapes."""
    g = b""
    for n in nodes:
        g += _ld(1, n)
    g += _ld(2, b"graph")
    for name, arr in initializers.items():
        g += _ld(5, encode_tensor(name, arr))
    for name, shape in inputs.items():
        g += _ld(11, _encode_value_info(name, shape))
    for name, shape in outputs.items():
        g += _ld(12, _encode_value_info(name, shape))
    return _tag(1, 0) + _varint(8) + _ld(7, g)  # ir_version 8 + graph

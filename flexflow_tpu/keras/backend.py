"""Minimal keras.backend shim for scripts ported from the reference."""

from __future__ import annotations

import numpy as np

_IMAGE_DATA_FORMAT = "channels_first"  # reference keras frontend is NCHW


def image_data_format() -> str:
    return _IMAGE_DATA_FORMAT


def set_image_data_format(fmt: str) -> None:
    global _IMAGE_DATA_FORMAT
    if fmt not in ("channels_first", "channels_last"):
        raise ValueError(fmt)
    _IMAGE_DATA_FORMAT = fmt


def to_categorical(y, num_classes: int) -> np.ndarray:
    y = np.asarray(y, dtype=np.int64).reshape(-1)
    out = np.zeros((y.shape[0], num_classes), dtype=np.float32)
    out[np.arange(y.shape[0]), y] = 1.0
    return out

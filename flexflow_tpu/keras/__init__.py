"""Keras-compatible frontend (python/flexflow/keras analog).

Usage mirrors tf.keras / the reference's flexflow.keras:

    from flexflow_tpu.keras import Sequential
    from flexflow_tpu.keras.layers import Dense, Input

    model = Sequential([Input((784,)), Dense(128, activation="relu"),
                        Dense(10, activation="softmax")])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=64)
    model.fit(x, y, epochs=5)
"""

from flexflow_tpu.keras.models import Model, Sequential
from flexflow_tpu.keras import layers, optimizers, callbacks, datasets, backend

__all__ = ["Model", "Sequential", "layers", "optimizers", "callbacks",
           "datasets", "backend"]

"""Keras-compatible layer objects.

Analog of python/flexflow/keras/layers/ (core.py, convolutional.py,
pool.py, normalization.py, merge.py, attention.py): each layer is a
deferred config object; calling it on a symbolic tensor records an edge in
the Keras graph, and Model.compile translates the graph into FFModel layer
calls (the reference translates to flexflow_c calls the same way).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType, PoolType

_ACTIVATIONS = {
    None: ActiMode.AC_MODE_NONE,
    "linear": ActiMode.AC_MODE_NONE,
    "relu": ActiMode.AC_MODE_RELU,
    "sigmoid": ActiMode.AC_MODE_SIGMOID,
    "tanh": ActiMode.AC_MODE_TANH,
    "gelu": ActiMode.AC_MODE_GELU,
}


def _acti(name) -> ActiMode:
    if isinstance(name, ActiMode):
        return name
    if name == "softmax":  # handled as a separate trailing op
        return ActiMode.AC_MODE_NONE
    if name not in _ACTIVATIONS:
        raise ValueError(f"unsupported activation {name!r}")
    return _ACTIVATIONS[name]


class KTensor:
    """Symbolic tensor in the Keras-level graph."""

    def __init__(self, shape: Tuple[int, ...], producer: Optional["KLayer"],
                 producer_idx: int = 0):
        self.shape = tuple(shape)  # includes batch dim (None -> set at compile)
        self.producer = producer
        self.producer_idx = producer_idx


class KLayer:
    """Base layer: records inbound tensors on call; emits FFModel ops later."""

    _counter: Dict[str, int] = {}

    def __init__(self, name: Optional[str] = None):
        base = type(self).__name__.lower()
        if name is None:
            KLayer._counter[base] = KLayer._counter.get(base, 0) + 1
            name = f"{base}_{KLayer._counter[base]}"
        self.name = name
        self.inbound: List[KTensor] = []
        self.outputs: List[KTensor] = []
        self._ff_layer_name: Optional[str] = None  # set at compile

    # shape inference given input shapes (with concrete batch)
    def output_shape(self, input_shapes: List[Tuple[int, ...]]) -> Tuple[int, ...]:
        return input_shapes[0]

    def __call__(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.inbound = list(ins)
        out_shape = self.output_shape([t.shape for t in ins])
        out = KTensor(out_shape, self, 0)
        self.outputs = [out]
        return out

    # emit: build the corresponding FFModel op(s); returns output Tensor
    def emit(self, ff, inputs):
        raise NotImplementedError

    def get_weights(self, ffmodel=None):
        model = ffmodel or getattr(self, "_model", None)
        names = self._param_names()
        return [model.ff.get_parameter(self._ff_layer_name, n) for n in names]

    def set_weights(self, weights, ffmodel=None):
        model = ffmodel or getattr(self, "_model", None)
        for n, w in zip(self._param_names(), weights):
            model.ff.set_parameter(self._ff_layer_name, w, n)

    def _param_names(self):
        return []


class InputLayer(KLayer):
    def __init__(self, shape: Sequence[int], dtype="float32", name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.dtype = DataType(dtype) if isinstance(dtype, str) else dtype
        self.outputs = [KTensor((None,) + self.shape, self, 0)]

    @property
    def output(self):
        return self.outputs[0]


def Input(shape: Sequence[int], dtype="float32", name=None) -> KTensor:
    return InputLayer(shape, dtype, name).output


class Dense(KLayer):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_initializer=None, bias_initializer=None, name=None):
        super().__init__(name)
        self.units = units
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer

    def output_shape(self, input_shapes):
        return input_shapes[0][:-1] + (self.units,)

    def emit(self, ff, inputs):
        t = ff.dense(inputs[0], self.units, activation=_acti(self.activation),
                     use_bias=self.use_bias,
                     kernel_initializer=self.kernel_initializer,
                     bias_initializer=self.bias_initializer, name=self.name)
        if self.activation == "softmax":
            t = ff.softmax(t, name=f"{self.name}_softmax")
        return t

    def _param_names(self):
        return ["kernel", "bias"] if self.use_bias else ["kernel"]


class Conv2D(KLayer):
    """NCHW, matching the reference Keras frontend's channel-first layout."""

    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, groups: int = 1,
                 use_bias: bool = True, name=None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.strides = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
        self.padding = padding
        self.activation = activation
        self.groups = groups
        self.use_bias = use_bias

    def _pads(self):
        if self.padding == "same":
            return (self.kernel_size[0] // 2, self.kernel_size[1] // 2)
        if self.padding == "valid":
            return (0, 0)
        return tuple(self.padding)

    def output_shape(self, input_shapes):
        n, c, h, w = input_shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.kernel_size[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel_size[1]) // self.strides[1] + 1
        return (n, self.filters, oh, ow)

    def emit(self, ff, inputs):
        ph, pw = self._pads()
        t = ff.conv2d(inputs[0], self.filters, self.kernel_size[0],
                      self.kernel_size[1], self.strides[0], self.strides[1],
                      ph, pw, activation=_acti(self.activation),
                      groups=self.groups, use_bias=self.use_bias,
                      name=self.name)
        if self.activation == "softmax":
            t = ff.softmax(t, name=f"{self.name}_softmax")
        return t

    def _param_names(self):
        return ["kernel", "bias"] if self.use_bias else ["kernel"]


class _Pool2D(KLayer):
    pool_type = PoolType.POOL_MAX

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid", name=None):
        super().__init__(name)
        self.pool_size = (pool_size,) * 2 if isinstance(pool_size, int) else tuple(pool_size)
        strides = strides or self.pool_size
        self.strides = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
        self.padding = padding

    def _pads(self):
        if self.padding == "same":
            return (self.pool_size[0] // 2, self.pool_size[1] // 2)
        return (0, 0)

    def output_shape(self, input_shapes):
        n, c, h, w = input_shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool_size[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool_size[1]) // self.strides[1] + 1
        return (n, c, oh, ow)

    def emit(self, ff, inputs):
        ph, pw = self._pads()
        return ff.pool2d(inputs[0], self.pool_size[0], self.pool_size[1],
                         self.strides[0], self.strides[1], ph, pw,
                         pool_type=self.pool_type, name=self.name)


class MaxPooling2D(_Pool2D):
    pool_type = PoolType.POOL_MAX


class AveragePooling2D(_Pool2D):
    pool_type = PoolType.POOL_AVG


class Flatten(KLayer):
    def output_shape(self, input_shapes):
        s = input_shapes[0]
        n = 1
        for d in s[1:]:
            n *= d
        return (s[0], n)

    def emit(self, ff, inputs):
        return ff.flat(inputs[0], name=self.name)


class Activation(KLayer):
    def __init__(self, activation, name=None):
        super().__init__(name)
        self.activation = activation

    def emit(self, ff, inputs):
        a = self.activation
        fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
              "gelu": ff.gelu, "elu": ff.elu, "exp": ff.exp,
              "softmax": ff.softmax, "linear": ff.identity}.get(a)
        if fn is None:
            raise ValueError(f"unsupported activation {a!r}")
        return fn(inputs[0], name=self.name)


class Dropout(KLayer):
    def __init__(self, rate: float, seed: int = 0, name=None):
        super().__init__(name)
        self.rate = rate
        self.seed = seed

    def emit(self, ff, inputs):
        return ff.dropout(inputs[0], self.rate, self.seed, name=self.name)


class BatchNormalization(KLayer):
    def __init__(self, relu: bool = False, name=None):
        super().__init__(name)
        self.relu = relu

    def emit(self, ff, inputs):
        return ff.batch_norm(inputs[0], relu=self.relu, name=self.name)


class LayerNormalization(KLayer):
    def __init__(self, axis=-1, epsilon: float = 1e-5, name=None):
        super().__init__(name)
        self.axis = axis if isinstance(axis, (list, tuple)) else (axis,)
        self.epsilon = epsilon

    def emit(self, ff, inputs):
        return ff.layer_norm(inputs[0], axes=self.axis, eps=self.epsilon,
                             name=self.name)


class Embedding(KLayer):
    def __init__(self, input_dim: int, output_dim: int, name=None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def output_shape(self, input_shapes):
        return input_shapes[0] + (self.output_dim,)

    def emit(self, ff, inputs):
        return ff.embedding(inputs[0], self.input_dim, self.output_dim,
                            aggr=AggrMode.AGGR_MODE_NONE, name=self.name)

    def _param_names(self):
        return ["kernel"]


class Concatenate(KLayer):
    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def output_shape(self, input_shapes):
        ax = self.axis % len(input_shapes[0])
        out = list(input_shapes[0])
        out[ax] = sum(s[ax] for s in input_shapes)
        return tuple(out)

    def emit(self, ff, inputs):
        return ff.concat(inputs, self.axis, name=self.name)


class _Merge(KLayer):
    op = "add"

    def emit(self, ff, inputs):
        fn = {"add": ff.add, "subtract": ff.subtract,
              "multiply": ff.multiply, "maximum": ff.max,
              "minimum": ff.min}[self.op]
        return fn(inputs[0], inputs[1], name=self.name)


class Add(_Merge):
    op = "add"


class Subtract(_Merge):
    op = "subtract"


class Multiply(_Merge):
    op = "multiply"


class Maximum(_Merge):
    op = "maximum"


class Minimum(_Merge):
    op = "minimum"


class Reshape(KLayer):
    def __init__(self, target_shape, name=None):
        super().__init__(name)
        self.target_shape = tuple(target_shape)

    def output_shape(self, input_shapes):
        return (input_shapes[0][0],) + self.target_shape

    def emit(self, ff, inputs):
        batch = inputs[0].shape[0]
        return ff.reshape(inputs[0], (batch,) + self.target_shape, name=self.name)


class MultiHeadAttention(KLayer):
    """Self/cross attention; called as layer([q, k, v]) or layer(x) for
    self-attention (python/flexflow/keras attention layer analog)."""

    def __init__(self, num_heads: int, key_dim: int, use_bias: bool = True,
                 dropout: float = 0.0, causal: bool = False, name=None):
        super().__init__(name)
        self.num_heads = num_heads
        self.key_dim = key_dim
        self.use_bias = use_bias
        self.dropout = dropout
        self.causal = causal

    def __call__(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs] * 3
        if len(ins) == 2:
            ins = [ins[0], ins[1], ins[1]]
        return super().__call__(ins)

    def output_shape(self, input_shapes):
        return input_shapes[0]

    def emit(self, ff, inputs):
        embed_dim = self.num_heads * self.key_dim
        return ff.multihead_attention(
            inputs[0], inputs[1], inputs[2], embed_dim, self.num_heads,
            dropout=self.dropout, bias=self.use_bias, causal=self.causal,
            name=self.name)

    def _param_names(self):
        return ["wq", "wk", "wv", "wo"]

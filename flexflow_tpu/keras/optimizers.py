"""Keras-style optimizer wrappers (python/flexflow/keras/optimizers.py)."""

from __future__ import annotations

from flexflow_tpu import optimizers as ff


class SGD:
    def __init__(self, learning_rate: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def to_ff(self) -> ff.Optimizer:
        return ff.SGDOptimizer(lr=self.learning_rate, momentum=self.momentum,
                               nesterov=self.nesterov,
                               weight_decay=self.weight_decay)


class Adam:
    def __init__(self, learning_rate: float = 0.001, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8,
                 weight_decay: float = 0.0):
        self.learning_rate = learning_rate
        self.beta_1 = beta_1
        self.beta_2 = beta_2
        self.epsilon = epsilon
        self.weight_decay = weight_decay

    def to_ff(self) -> ff.Optimizer:
        return ff.AdamOptimizer(alpha=self.learning_rate, beta1=self.beta_1,
                                beta2=self.beta_2, epsilon=self.epsilon,
                                weight_decay=self.weight_decay)

"""Dataset loaders (python/flexflow/keras/datasets analog).

The reference downloads MNIST/CIFAR from the network; this environment has
no egress, so each loader first looks for a local copy under
``$FLEXFLOW_TPU_DATA`` (mnist.npz / cifar10.npz with the standard keras
key layout) and otherwise generates a deterministic synthetic stand-in
with the same shapes/dtypes — sufficient for the test/bench protocol,
which measures throughput and pipeline correctness rather than dataset
accuracy.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

_DATA_DIR = os.environ.get("FLEXFLOW_TPU_DATA", os.path.expanduser("~/.flexflow_tpu"))


def _synthetic_classification(n, shape, num_classes, seed):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, num_classes, n).astype(np.int64)
    protos = rs.randn(num_classes, *shape).astype(np.float32) * 2
    x = protos[y] + rs.randn(n, *shape).astype(np.float32)
    x = ((x - x.min()) / (x.max() - x.min()) * 255).astype(np.uint8)
    return x, y


def _load_npz(name: str, keys=("x_train", "y_train", "x_test", "y_test")):
    path = os.path.join(_DATA_DIR, name)
    if os.path.exists(path):
        d = np.load(path)
        return tuple(d[k] for k in keys)
    return None


class mnist:
    @staticmethod
    def load_data() -> Tuple[Tuple[np.ndarray, np.ndarray],
                             Tuple[np.ndarray, np.ndarray]]:
        cached = _load_npz("mnist.npz")
        if cached is not None:
            x_tr, y_tr, x_te, y_te = cached
        else:
            x_tr, y_tr = _synthetic_classification(8192, (28, 28), 10, 0)
            x_te, y_te = _synthetic_classification(1024, (28, 28), 10, 1)
        return (x_tr, y_tr), (x_te, y_te)


class cifar10:
    @staticmethod
    def load_data():
        cached = _load_npz("cifar10.npz")
        if cached is not None:
            x_tr, y_tr, x_te, y_te = cached
        else:
            x_tr, y_tr = _synthetic_classification(8192, (32, 32, 3), 10, 2)
            x_te, y_te = _synthetic_classification(1024, (32, 32, 3), 10, 3)
            y_tr = y_tr.reshape(-1, 1)
            y_te = y_te.reshape(-1, 1)
        return (x_tr, y_tr), (x_te, y_te)


class reuters:
    @staticmethod
    def load_data(num_words: int = 10000, maxlen: int = 200):
        cached = _load_npz("reuters.npz")
        if cached is not None:
            x_tr, y_tr, x_te, y_te = cached
            return (x_tr, y_tr), (x_te, y_te)
        rs = np.random.RandomState(4)
        n_tr, n_te, classes = 2048, 512, 46
        x_tr = rs.randint(1, num_words, (n_tr, maxlen)).astype(np.int32)
        x_te = rs.randint(1, num_words, (n_te, maxlen)).astype(np.int32)
        y_tr = rs.randint(0, classes, n_tr).astype(np.int64)
        y_te = rs.randint(0, classes, n_te).astype(np.int64)
        return (x_tr, y_tr), (x_te, y_te)

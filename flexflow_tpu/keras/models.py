"""Keras Model / Sequential driving the FFModel runtime.

Analog of python/flexflow/keras/models/{base_model,sequential,functional}.py:
``compile()`` walks the symbolic layer graph and replays it onto an
``FFModel`` (the reference replays onto flexflow_c); ``fit/evaluate/
predict`` drive the same jitted loop, with Keras-style callbacks invoked
per epoch (base_model.py:376-430).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import DataType, LossType, MetricsType
from flexflow_tpu.keras.layers import InputLayer, KLayer, KTensor
from flexflow_tpu.model import FFModel
from flexflow_tpu import optimizers as ff_optimizers

_LOSSES = {
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}

_METRICS = {
    "accuracy": MetricsType.ACCURACY,
    "categorical_crossentropy": MetricsType.CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
    "mse": MetricsType.MEAN_SQUARED_ERROR,
    "root_mean_squared_error": MetricsType.ROOT_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.MEAN_ABSOLUTE_ERROR,
}


def _to_ff_optimizer(opt):
    if isinstance(opt, ff_optimizers.Optimizer):
        return opt
    if isinstance(opt, str):
        name = opt.lower()
        if name == "sgd":
            return ff_optimizers.SGDOptimizer(lr=0.01)
        if name == "adam":
            return ff_optimizers.AdamOptimizer(alpha=0.001)
        raise ValueError(f"unknown optimizer {opt!r}")
    # keras-style wrapper objects from flexflow_tpu.keras.optimizers
    if hasattr(opt, "to_ff"):
        return opt.to_ff()
    raise TypeError(f"cannot interpret optimizer {opt!r}")


class Model:
    """Functional-API model: Model(inputs=..., outputs=...)."""

    def __init__(self, inputs=None, outputs=None, name: Optional[str] = None,
                 ffconfig: Optional[FFConfig] = None):
        self.name = name or "model"
        self.inputs: List[KTensor] = (
            inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ) if inputs is not None else []
        self.outputs: List[KTensor] = (
            outputs if isinstance(outputs, (list, tuple)) else [outputs]
        ) if outputs is not None else []
        self.ffconfig = ffconfig
        self.ff: Optional[FFModel] = None
        self.layers: List[KLayer] = []
        self._batch_size: Optional[int] = None

    # ---- graph walk --------------------------------------------------------
    def _toposort(self) -> List[KLayer]:
        order: List[KLayer] = []
        seen = set()

        def visit(t: KTensor):
            layer = t.producer
            if layer is None or id(layer) in seen:
                return
            seen.add(id(layer))
            if not isinstance(layer, InputLayer):
                for src in layer.inbound:
                    visit(src)
            order.append(layer)

        for out in self.outputs:
            visit(out)
        return order

    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = (), batch_size: Optional[int] = None,
                **kwargs):
        bs = batch_size or (self.ffconfig.batch_size if self.ffconfig else 64)
        self._batch_size = bs
        cfg = self.ffconfig or FFConfig(batch_size=bs)
        ff = FFModel(cfg)
        tensor_map: Dict[int, Any] = {}
        order = self._toposort()
        self.layers = order
        for layer in order:
            layer._model = self
            if isinstance(layer, InputLayer):
                t = ff.create_tensor((bs,) + layer.shape, dtype=layer.dtype,
                                     name=layer.name)
                tensor_map[id(layer.outputs[0])] = t
                layer._ff_layer_name = layer.name
                continue
            ins = [tensor_map[id(src)] for src in layer.inbound]
            out = layer.emit(ff, ins)
            outs = out if isinstance(out, tuple) else (out,)
            for kt, t in zip(layer.outputs, outs):
                tensor_map[id(kt)] = t
            # parameters are keyed by the FFModel layer that owns them —
            # for Dense/Conv with activation='softmax' that is the layer's
            # own name, not the trailing softmax op's
            if layer._param_names():
                layer._ff_layer_name = layer.name
            else:
                first = outs[0]
                layer._ff_layer_name = (
                    first.owner_layer.name if first.owner_layer else layer.name)

        loss_type = _LOSSES[loss] if isinstance(loss, str) else loss
        mts = [_METRICS[m] if isinstance(m, str) else m for m in metrics]
        ff.compile(_to_ff_optimizer(optimizer), loss_type, mts, **kwargs)
        self.ff = ff

    # ---- train / eval ------------------------------------------------------
    def fit(self, x, y, batch_size: Optional[int] = None, epochs: int = 1,
            callbacks: Sequence = (), verbose: bool = True,
            validation_data=None):
        if self.ff is None:
            raise RuntimeError("call compile() before fit()")
        history = {"loss": [], "throughput": []}
        for cb in callbacks:
            cb.set_model(self)
            cb.on_train_begin()
        stop = False
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            thr = self.ff.fit(x, y, batch_size=batch_size, epochs=1,
                              verbose=verbose)
            logs = dict(self.ff._metrics_acc.report())
            logs["loss"] = self.ff._last_loss
            history["loss"].append(logs["loss"])
            history["throughput"].append(thr)
            if validation_data is not None:
                val = self.evaluate(*validation_data, verbose=False)
                logs.update({f"val_{k}": v for k, v in val.items()})
            for cb in callbacks:
                if cb.on_epoch_end(epoch, logs) is False:
                    stop = True
            if stop:
                break
        for cb in callbacks:
            cb.on_train_end()
        return history

    def evaluate(self, x, y, batch_size: Optional[int] = None, verbose=True):
        rep = self.ff.evaluate(x, y, batch_size=batch_size)
        if verbose:
            print(" ".join(f"{k}={v:.4f}" for k, v in rep.items()))
        return rep

    def predict(self, x, batch_size: Optional[int] = None):
        bs = self._batch_size
        xs = x if isinstance(x, (list, tuple)) else [x]
        n = xs[0].shape[0]
        outs = []
        for start in range(0, n, bs):
            sl = [xx[start:start + bs] for xx in xs]
            if sl[0].shape[0] < bs:  # pad the tail to the jitted batch size
                pad = bs - sl[0].shape[0]
                sl = [np.concatenate(
                    [s, np.repeat(s[-1:], pad, axis=0)], axis=0) for s in sl]
                outs.append(self.ff.predict(sl)[:bs - pad])
            else:
                outs.append(self.ff.predict(sl))
        return np.concatenate(outs, axis=0)

    def summary(self):
        lines = [f'Model: "{self.name}"', "_" * 60]
        for layer in self.layers:
            shape = layer.outputs[0].shape if layer.outputs else None
            lines.append(f"{layer.name:30s} {type(layer).__name__:20s} {shape}")
        print("\n".join(lines))

    def get_weights(self):
        return [w for l in self.layers for w in
                (l.get_weights(self) if l._param_names() else [])]


class Sequential(Model):
    """Linear layer stack (python/flexflow/keras sequential analog)."""

    def __init__(self, layers: Sequence[KLayer] = (), name=None,
                 ffconfig: Optional[FFConfig] = None):
        super().__init__(name=name or "sequential", ffconfig=ffconfig)
        self._stack: List[KLayer] = []
        for l in layers:
            self.add(l)

    def add(self, layer: KLayer):
        self._stack.append(layer)

    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = (), input_shape: Optional[Sequence[int]] = None,
                batch_size: Optional[int] = None, **kwargs):
        stack = list(self._stack)
        if isinstance(stack[0], KTensor):  # Input(...) returns a KTensor
            t = stack.pop(0)
        elif isinstance(stack[0], InputLayer):
            inp = stack.pop(0)
            t = inp.output
        else:
            if input_shape is None:
                raise ValueError("Sequential needs an InputLayer first or "
                                 "input_shape= at compile()")
            t = InputLayer(input_shape).output
        self.inputs = [t]
        for layer in stack:
            t = layer(t)
        self.outputs = [t]
        super().compile(optimizer, loss, metrics, batch_size=batch_size, **kwargs)

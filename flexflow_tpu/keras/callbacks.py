"""Keras-style callbacks (python/flexflow/keras/callbacks.py analog).

``on_epoch_end`` returning False stops training (the reference implements
EarlyStopping the same way via its callback list in base_model.fit)."""

from __future__ import annotations

from typing import Dict, Optional


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self):
        pass

    def on_train_end(self):
        pass

    def on_epoch_begin(self, epoch: int):
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
        pass


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", min_delta: float = 0.0,
                 patience: int = 0, mode: str = "min"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self.best: Optional[float] = None
        self.wait = 0

    def on_train_begin(self):
        self.best = None
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        if not logs or self.monitor not in logs:
            return None
        cur = logs[self.monitor]
        improved = (
            self.best is None
            or (self.mode == "min" and cur < self.best - self.min_delta)
            or (self.mode == "max" and cur > self.best + self.min_delta)
        )
        if improved:
            self.best = cur
            self.wait = 0
            return None
        self.wait += 1
        if self.wait > self.patience:
            return False
        return None


class History(Callback):
    def on_train_begin(self):
        self.history: Dict[str, list] = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class LambdaCallback(Callback):
    def __init__(self, on_epoch_end=None, on_train_begin=None,
                 on_train_end=None):
        self._on_epoch_end = on_epoch_end
        self._on_train_begin = on_train_begin
        self._on_train_end = on_train_end

    def on_train_begin(self):
        if self._on_train_begin:
            self._on_train_begin()

    def on_train_end(self):
        if self._on_train_end:
            self._on_train_end()

    def on_epoch_end(self, epoch, logs=None):
        if self._on_epoch_end:
            return self._on_epoch_end(epoch, logs)

"""CLI launcher: ``python -m flexflow_tpu.driver [flags] script.py [args]``.

Analog of the reference's flexflow_python / flexflow/driver.py (SURVEY §1
L8): consume FFConfig flags, expose the parsed config to the script via
``flexflow_tpu.driver.get_config()``, then exec the script with the
remaining argv — so reference-style launch lines carry over:

    python -m flexflow_tpu.driver -b 64 --budget 30 my_model.py --my-flag
"""

from __future__ import annotations

import runpy
import sys
from typing import Optional

from flexflow_tpu.config import FFConfig

_config: Optional[FFConfig] = None


def get_config() -> FFConfig:
    """The FFConfig parsed by the launcher (fresh default outside it)."""
    global _config
    if _config is None:
        _config = FFConfig()
    return _config


def main(argv=None) -> int:
    global _config
    argv = list(sys.argv[1:] if argv is None else argv)
    cfg = FFConfig()
    rest = cfg.parse_args(argv)
    script = next((a for a in rest if a.endswith(".py")), None)
    if script is None:
        print("usage: python -m flexflow_tpu.driver [flags] script.py [args]",
              file=sys.stderr)
        return 2
    rest.remove(script)
    _config = cfg
    # multi-host launch (--nodes N > 1, one driver process per host):
    # rendezvous through the JAX distributed runtime before the script
    # builds any mesh, so jax.devices() spans all hosts
    from flexflow_tpu import distributed
    distributed.initialize_from_config(cfg)
    sys.argv = [script] + rest
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Channels-last compute-layout propagation for the conv family.

The API/PCG boundary layout stays NCHW for reference parity (the reference
is cuDNN-NCHW, src/ops/conv_2d.cc), but the TPU's vector units want the
channel dim minor: convs executed with ``("NCHW","OIHW","NCHW")`` dimension
numbers make XLA pad/transpose internally per op, which is where most of
the conv family's 8x efficiency gap vs matmuls came from (VERDICT Weak #1;
"A Learned Performance Model for TPUs" and SCALE-Sim, PAPERS.md, both put
layout among the first-order conv cost terms).

This pass assigns each materialized op an *execution* layout: conv-family
ops (Conv2D / Pool2D / BatchNorm / GroupNorm) compute via
``dimension_numbers=("NHWC","HWIO","NHWC")``, layout-oblivious ops
(elementwise, dropout) pass NHWC values straight through, and Concat
remaps its channel axis — so the boundary transposes materialize once per
conv *chain* (at graph inputs and at the first NCHW-only consumer), not
once per op. The executor (GraphExecutor._run_nodes) inserts the
transposes exactly where the recorded producer/consumer layouts disagree
and caches them per value, which makes the once-per-chain property a
consequence of propagation rather than a separate optimization.

Also here: ``fold_conv_bn`` — the execution-time Conv+BN(+ReLU) fold used
by the inference/eval executables (the XLA analog of the reference's fused
conv kernels, src/ops/kernels/conv_2d_kernels.cu).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from flexflow_tpu.ffconst import ActiMode, OperatorType

NCHW = "NCHW"
NHWC = "NHWC"

# physical-dim permutations between the two layouts
TO_NHWC = (0, 2, 3, 1)  # NCHW value -> NHWC value
TO_NCHW = (0, 3, 1, 2)  # NHWC value -> NCHW value

# ops that gain an NHWC execution mode (forward consults self.exec_layout)
_NHWC_COMPUTE = {
    OperatorType.CONV2D,
    OperatorType.POOL2D,
    OperatorType.BATCHNORM,
    OperatorType.GROUPNORM,
}

# layout-oblivious single-input ops: forward is elementwise, so an NHWC
# value flows through untouched and the chain stays unbroken
_PASS_THROUGH = {
    OperatorType.RELU, OperatorType.GELU, OperatorType.SIGMOID,
    OperatorType.TANH, OperatorType.ELU, OperatorType.EXP,
    OperatorType.SIN, OperatorType.COS, OperatorType.RSQRT,
    OperatorType.LOG, OperatorType.IDENTITY, OperatorType.POW,
    OperatorType.SCALAR_MULTIPLY, OperatorType.SCALAR_ADD,
    OperatorType.SCALAR_SUB, OperatorType.SCALAR_TRUE_DIV,
    OperatorType.DROPOUT,
}

# elementwise binaries: NHWC-transparent when both operands carry the
# same 4-D shape (broadcast against a differently-ranked operand would
# change meaning under a permuted layout)
_BINARY = {
    OperatorType.EW_ADD, OperatorType.EW_SUB, OperatorType.EW_MUL,
    OperatorType.EW_DIV, OperatorType.EW_MAX, OperatorType.EW_MIN,
}


def _rank4(shape) -> bool:
    return len(shape) == 4


def layout_enabled(mode: str, on_tpu: bool) -> bool:
    """'nhwc' forces the pass on, 'nchw' off; 'auto' enables it exactly
    where it pays — real accelerators. CPU keeps the reference layout so
    numerics tests exercise the parity path by default."""
    if mode == "nhwc":
        return True
    if mode == "nchw":
        return False
    return on_tpu


def propagate_layouts(nodes, mode: str = "auto",
                      on_tpu: bool = False) -> Dict[str, Any]:
    """Assign execution layouts over a materialized OpNode list.

    Sets, on every node, ``input_layouts``/``output_layouts`` (what its
    forward consumes/produces) and, on NHWC-computing ops,
    ``op.exec_layout = "NHWC"``. Returns a summary dict:
    ``enabled``, ``nhwc_ops`` (ops computing channels-last), and
    ``transposes`` — the number of boundary transposes the executor will
    materialize (each chain contributes one entry pair: in + out).
    """
    enabled = layout_enabled(mode, on_tpu)
    layout_of: Dict[Tuple[int, int], str] = {}
    nhwc_ops = 0
    boundary: set = set()  # (ref, want) pairs that force a transpose

    for node in nodes:
        op = node.op
        in_layouts: List[str] = []
        have: List[str] = []
        for ref in node.input_refs:
            if ref[0] == "op":
                have.append(layout_of.get((ref[1], ref[2]), NCHW))
            else:  # graph inputs are staged NCHW (API boundary contract)
                have.append(NCHW)

        t = op.op_type
        out_layout = NCHW
        if enabled and t in _NHWC_COMPUTE and op.input_shapes \
                and _rank4(op.input_shapes[0]):
            in_layouts = [NHWC] * len(node.input_refs)
            out_layout = NHWC
            op.exec_layout = NHWC
            nhwc_ops += 1
        elif enabled and t == OperatorType.CONCAT \
                and all(_rank4(s) for s in op.input_shapes) \
                and have and all(h == NHWC for h in have):
            in_layouts = [NHWC] * len(node.input_refs)
            out_layout = NHWC
            op.exec_layout = NHWC
            nhwc_ops += 1
        elif enabled and t in _PASS_THROUGH and op.input_shapes \
                and _rank4(op.input_shapes[0]) and have and have[0] == NHWC:
            in_layouts = [NHWC] * len(node.input_refs)
            out_layout = NHWC
        elif enabled and t in _BINARY and len(op.input_shapes) == 2 \
                and all(_rank4(s) for s in op.input_shapes) \
                and op.input_shapes[0] == op.input_shapes[1] \
                and all(h == NHWC for h in have):
            in_layouts = [NHWC, NHWC]
            out_layout = NHWC
        else:
            in_layouts = [NCHW] * len(node.input_refs)

        node.input_layouts = in_layouts
        node.output_layouts = [out_layout] * len(op.output_shapes)
        for i in range(len(op.output_shapes)):
            layout_of[(op.guid, i)] = out_layout
        for ref, want, h in zip(node.input_refs, in_layouts, have):
            if want != h:
                boundary.add((tuple(ref), want))
    return dict(enabled=enabled, nhwc_ops=nhwc_ops,
                transposes=len(boundary),
                boundaries=sorted(boundary, key=repr))


def permute_spec_nhwc(spec):
    """PartitionSpec written against the logical NCHW dims, re-expressed
    for a physically-NHWC value (entry i of the result constrains
    physical dim i = logical dim TO_NHWC[i])."""
    from jax.sharding import PartitionSpec as P

    entries = list(tuple(spec)) + [None] * (4 - len(tuple(spec)))
    permuted = [entries[d] for d in TO_NHWC]
    while permuted and permuted[-1] is None:
        permuted.pop()
    return P(*permuted)


# ---------------------------------------------------------------------------
# Conv + BN (+ReLU) execution-time folding — inference/eval executables


class FoldedConvBN:
    """Conv2D + BatchNorm(+ReLU) collapsed into one convolution at
    execution time (eval/inference only — training BN normalizes with
    batch statistics, which cannot fold into weights).

    With running stats (m, v) and BN affine (g, b):
      w' = w * g/sqrt(v+eps)   (per output channel)
      b' = (conv_bias - m) * g/sqrt(v+eps) + b
    so the folded op runs ONE conv kernel with a fused bias+ReLU epilogue
    — the reference's fused conv path (conv_2d_kernels.cu) expressed as a
    weight-space rewrite XLA constant-folds into the step.

    Complementary to ``transforms.fold_conv_batchnorm`` (the OFFLINE
    pass: user-invoked on an INFERENCE-compiled model, bakes folded
    weights in and recompiles): this fold is automatic, traced fresh
    each eval step from the live params/running stats, so a model that
    keeps TRAINING (and updating BN stats) still gets fused eval.

    The op reads both source ops' parameter subtrees; the executor feeds
    them via ``param_sources`` (see GraphExecutor._run_nodes).
    """

    op_type = OperatorType.CONV2D

    def __init__(self, conv_op, bn_op):
        self.conv = conv_op
        self.bn = bn_op
        self.name = f"{conv_op.name}+{bn_op.name}"
        self.guid = bn_op.guid  # consumers reference the BN output
        self.input_shapes = list(conv_op.input_shapes)
        self.output_shapes = list(bn_op.output_shapes)
        self.dtype = conv_op.dtype
        self.param_sources = (conv_op.name, bn_op.name)

    @property
    def exec_layout(self):
        return getattr(self.conv, "exec_layout", NCHW)

    def output_dim_roles(self):
        return self.bn.output_dim_roles()

    def flops(self):
        return self.conv.flops()

    def params_elems(self):
        return 0  # reads its sources' params; owns none

    def forward(self, params, inputs, ctx, state=None):
        import jax.numpy as jnp
        from jax import lax

        (x,) = inputs
        cp = params.get(self.conv.name, {})
        bp = params.get(self.bn.name, {})
        st = (state or {}).get(self.bn.name) or {}
        mean = st["mean"].astype(jnp.float32)
        var = st["var"].astype(jnp.float32)
        inv = lax.rsqrt(var + self.bn.eps) * bp["scale"].astype(jnp.float32)
        w = cp["kernel"].astype(jnp.float32) * inv[:, None, None, None]
        cb = cp.get("bias")
        base = cb.astype(jnp.float32) if cb is not None else 0.0
        b = (base - mean) * inv + bp["bias"].astype(jnp.float32)
        act = ActiMode.AC_MODE_RELU if self.bn.relu else ActiMode.AC_MODE_NONE
        return [self.conv._conv_forward(w, b, x, ctx, act)]

    def __repr__(self):
        return f"FoldedConvBN({self.name})"


def train_fusable_conv_guids(nodes, keep_guids=()) -> set:
    """Conv2D guids whose sole consumer is a foldable BatchNorm — the
    shared eligibility of the eval-time fold (``fold_conv_bn``) and the
    searched train-time ``_k:conv_bn_fused`` kernel twin (the conv node
    ships it to the native search as the ``bn_fusable`` attr, since the
    per-node choice enumeration cannot re-derive a graph property)."""
    return {conv_guid for conv_guid, _ in _fusable_pairs(nodes, keep_guids)}


def _fusable_pairs(nodes, keep_guids=()):
    """(conv guid, bn guid) pairs eligible for Conv+BN fusion: the BN's
    sole input is a Conv2D output nothing else consumes, the conv
    carries no activation of its own, and the conv output is not the
    designated model output."""
    from flexflow_tpu.ops.conv import BatchNorm, Conv2D

    consumers: Dict[Tuple[int, int], int] = {}
    for node in nodes:
        for ref in node.input_refs:
            if ref[0] == "op":
                k = (ref[1], ref[2])
                consumers[k] = consumers.get(k, 0) + 1
    by_guid = {n.op.guid: n for n in nodes}
    pairs = []
    for node in nodes:
        op = node.op
        if not isinstance(op, BatchNorm):
            continue
        ref = node.input_refs[0]
        if ref[0] != "op" or ref[2] != 0:
            continue
        prod = by_guid.get(ref[1])
        if prod is None or not isinstance(prod.op, Conv2D):
            continue
        if prod.op.activation != ActiMode.AC_MODE_NONE:
            continue
        if consumers.get((ref[1], 0), 0) != 1 or ref[1] in keep_guids:
            continue
        pairs.append((prod.op.guid, op.guid))
    return pairs


class TrainFusedConvBN:
    """Conv2D + BatchNorm executed as ONE fused region at TRAIN time —
    the ``_k:conv_bn_fused`` searched kernel choice (ISSUE 15).

    Training BN normalizes with batch statistics, so nothing folds into
    the conv weights (that is the eval-only ``FoldedConvBN``); instead
    the two ops execute inside one composite node: the intermediate
    conv output never becomes a first-class graph value (no separate
    node boundary, no per-node bookkeeping between them), so XLA fuses
    the BN's normalization into the conv's epilogue where the unfused
    lowering emits separate regions. The conv output's sharding
    constraint is PRESERVED inside the fused forward — the lowering is
    collective-for-collective identical to the unfused pair, which is
    what makes the parity bit-for-bit (tests/test_kernel_search.py).

    BN's running-stats state update flows out through ``_new_states``
    (the executor merges it under the BN's own name, so the state tree
    keeps its shape and checkpoints stay compatible).
    """

    def __init__(self, conv_node, bn_node):
        conv_op, bn_op = conv_node.op, bn_node.op
        self.conv = conv_op
        self.bn = bn_op
        self.name = f"{conv_op.name}+{bn_op.name}"
        self.guid = bn_op.guid  # consumers reference the BN output
        self.op_type = OperatorType.CONV2D
        self.input_shapes = list(conv_op.input_shapes)
        self.output_shapes = list(bn_op.output_shapes)
        self.dtype = conv_op.dtype
        self.param_sources = (conv_op.name, bn_op.name)
        # the conv output's sharding constraint, re-applied between the
        # two halves (permuted when the conv executes channels-last)
        spec = conv_node.output_specs[0] if conv_node.output_specs else None
        ols = getattr(conv_node, "output_layouts", None)
        if spec is not None and ols and ols[0] == NHWC:
            spec = permute_spec_nhwc(spec)
        self._mid_spec = spec
        self._new_states = None

    @property
    def exec_layout(self):
        return getattr(self.conv, "exec_layout", NCHW)

    def output_dim_roles(self):
        return self.bn.output_dim_roles()

    def flops(self):
        return self.conv.flops()

    def params_elems(self):
        return 0  # reads its sources' params; owns none

    def forward(self, params, inputs, ctx, state=None):
        y = self.conv.forward(params.get(self.conv.name, {}), inputs,
                              ctx)[0]
        if self._mid_spec is not None and ctx.mesh is not None:
            from jax.sharding import NamedSharding
            import jax
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(ctx.mesh, self._mid_spec))
        outs = self.bn.forward(params.get(self.bn.name, {}), [y], ctx,
                               state=(state or {}).get(self.bn.name))
        if getattr(self.bn, "_new_state", None) is not None:
            self._new_states = {self.bn.name: self.bn._new_state}
            self.bn._new_state = None
        return outs

    def __repr__(self):
        return f"TrainFusedConvBN({self.name})"


def fuse_conv_bn_train(nodes, conv_names, keep_guids=()):
    """Fuse the (Conv2D, BatchNorm) pairs whose conv op NAME is in
    ``conv_names`` (the executor's ``_k:conv_bn_fused`` kernel choices)
    into TrainFusedConvBN nodes. Returns a NEW node list; ineligible or
    unchosen pairs stay untouched, so a stale kernel choice degrades to
    the unfused lowering (fflint FFL209 flags the gap)."""
    from flexflow_tpu.executor import OpNode

    chosen = {(cg, bg) for cg, bg in _fusable_pairs(nodes, keep_guids)}
    by_guid = {n.op.guid: n for n in nodes}
    folded_conv_guids = set()
    replacements: Dict[int, OpNode] = {}
    for conv_guid, bn_guid in chosen:
        conv_node, bn_node = by_guid[conv_guid], by_guid[bn_guid]
        if conv_node.op.name not in conv_names:
            continue
        fused = OpNode(TrainFusedConvBN(conv_node, bn_node),
                       list(conv_node.input_refs))
        fused.output_specs = list(bn_node.output_specs)
        fused.input_layouts = list(getattr(conv_node, "input_layouts", []))
        fused.output_layouts = list(getattr(bn_node, "output_layouts", []))
        replacements[bn_guid] = fused
        folded_conv_guids.add(conv_guid)
    if not replacements:
        return nodes
    out = []
    for node in nodes:
        if node.op.guid in folded_conv_guids:
            continue
        out.append(replacements.get(node.op.guid, node))
    return out


def fold_conv_bn(nodes, keep_guids=()):
    """Fold eligible Conv2D→BatchNorm pairs in an OpNode list.

    Eligible: the BN's sole input is a Conv2D output that nothing else
    consumes (and whose guid is not in ``keep_guids`` — e.g. the
    designated model output), and the conv carries no activation of its
    own (the BN owns the ReLU). Returns a NEW node list; the input list
    is never mutated, so the training executables keep the full graph.
    """
    from flexflow_tpu.executor import OpNode

    by_guid = {n.op.guid: n for n in nodes}
    folded_conv_guids = set()
    replacements: Dict[int, OpNode] = {}  # bn guid -> fused node
    for conv_guid, bn_guid in _fusable_pairs(nodes, keep_guids):
        prod, node = by_guid[conv_guid], by_guid[bn_guid]
        fused = OpNode(FoldedConvBN(prod.op, node.op),
                       list(prod.input_refs))
        fused.output_specs = list(node.output_specs)
        fused.input_layouts = list(getattr(prod, "input_layouts", []))
        fused.output_layouts = list(getattr(node, "output_layouts", []))
        replacements[bn_guid] = fused
        folded_conv_guids.add(conv_guid)
    if not replacements:
        return nodes
    out = []
    for node in nodes:
        if node.op.guid in folded_conv_guids:
            continue  # conv body now lives inside the fused node
        out.append(replacements.get(node.op.guid, node))
    return out

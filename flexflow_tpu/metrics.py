"""Metrics.

Analog of src/metrics_functions/ (metrics_functions.cc:68,85): accuracy,
categorical/sparse CE, MSE, RMSE, MAE. The reference accumulates
PerfMetrics on-device and reduces through a Legion future chain
(UPDATE_METRICS_TASK_ID); here metrics are computed inside the jitted step
and accumulated as a PerfMetrics pytree — the cross-device reduction is
implicit in computing on the global (sharded) batch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import LossType, MetricsType


@dataclasses.dataclass
class PerfMetrics:
    """Mirrors the reference's PerfMetrics accumulator fields."""

    train_all: int = 0
    train_correct: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0

    def update(self, other: Dict[str, jax.Array], batch: int):
        self.train_all += batch
        for k, v in other.items():
            if k == "accuracy":
                self.train_correct += int(v)
            else:
                setattr(self, k, getattr(self, k) + float(v))

    def report(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        n = max(self.train_all, 1)
        if self.train_correct:
            out["accuracy"] = self.train_correct / n
        for f in ("cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss", "mae_loss"):
            v = getattr(self, f)
            if v:
                out[f] = v / n
        return out


class Metrics:
    def __init__(self, loss_type: LossType, metrics: List[MetricsType],
                 preds_are_probs: bool = True):
        self.loss_type = loss_type
        self.metrics = list(metrics)
        # False when the model's final op emits logits (no softmax): the
        # CE metrics then normalize via log_softmax instead of log(p)
        self.preds_are_probs = preds_are_probs

    def _log_probs(self, preds: jax.Array) -> jax.Array:
        if self.preds_are_probs:
            return jnp.log(jnp.clip(preds.astype(jnp.float32), 1e-12, 1.0))
        return jax.nn.log_softmax(preds.astype(jnp.float32), axis=-1)

    def compute(self, preds: jax.Array, labels: jax.Array) -> Dict[str, jax.Array]:
        """Per-batch metric sums (not averaged), jit-traceable."""
        out: Dict[str, jax.Array] = {}
        b = preds.shape[0]
        for m in self.metrics:
            if m == MetricsType.ACCURACY:
                if self.loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
                    lab = labels.reshape(b, -1)[:, 0].astype(jnp.int32)
                    correct = jnp.argmax(preds, axis=-1) == lab
                elif preds.ndim >= 2 and preds.shape[-1] > 1:
                    correct = jnp.argmax(preds, axis=-1) == jnp.argmax(labels, axis=-1)
                else:
                    correct = (preds > 0.5).astype(jnp.int32).reshape(b, -1)[:, 0] == labels.reshape(b, -1)[:, 0]
                out["accuracy"] = jnp.sum(correct.astype(jnp.int32))
            elif m == MetricsType.CATEGORICAL_CROSSENTROPY:
                logp = self._log_probs(preds)
                out["cce_loss"] = -jnp.sum(labels * logp)
            elif m == MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY:
                lab = labels.reshape(b, -1)[:, 0].astype(jnp.int32)
                logp = self._log_probs(preds)
                out["sparse_cce_loss"] = -jnp.sum(
                    jnp.take_along_axis(logp, lab[:, None], axis=-1)
                )
            elif m == MetricsType.MEAN_SQUARED_ERROR:
                out["mse_loss"] = jnp.sum(jnp.mean((preds - labels) ** 2, axis=-1))
            elif m == MetricsType.ROOT_MEAN_SQUARED_ERROR:
                out["rmse_loss"] = jnp.sum(jnp.sqrt(jnp.mean((preds - labels) ** 2, axis=-1)))
            elif m == MetricsType.MEAN_ABSOLUTE_ERROR:
                out["mae_loss"] = jnp.sum(jnp.mean(jnp.abs(preds - labels), axis=-1))
        return out

"""fflint orchestrator: run the pass pipeline over a compiled model.

The verifier runs over three progressively-more-expensive views of the
same training program:

(a) the materialized PCG (``OpNode`` list + mesh + strategy) — every
    pass reads this; pure static analysis, no device work;
(b) the searched strategy's priced collective set (native simulator
    replay) — the collective-inference pass prices the strategy when
    the native core is available;
(c) the optimized HLO of the compiled step — optional (``hlo=``),
    because lower+compile is minutes of XLA on a real chip; when given,
    the emitted collective census joins the diff and the multihost
    pass can compare per-host programs.

A pass that cannot run records a skip reason in ``report.passes``
instead of pretending it found nothing, and a pass that crashes
becomes an FFL000 diagnostic rather than killing the lint run.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from flexflow_tpu.analysis.diagnostics import LintReport, error


class SkipPass(Exception):
    """Raised by a pass that cannot run in this context (e.g. the
    multihost pass with a single program); the reason lands in
    ``report.passes`` so skipped != clean."""


class LintContext:
    """Everything a pass may read. ``ff`` is optional — hand-built
    contexts (tests, strategy files without a model) carry nodes/mesh/
    strategy directly; passes needing the model degrade or skip."""

    def __init__(self, nodes, mesh, strategy=None, machine_spec=None,
                 config=None, final_ref: Optional[Tuple[int, int]] = None,
                 ff=None, hlo_text: Optional[str] = None,
                 hlo_per_host: Optional[List[str]] = None,
                 slice_of_host: Optional[List[int]] = None,
                 priced: Optional[Dict[str, float]] = None,
                 emitted: Optional[Dict[str, float]] = None,
                 searched: Optional[bool] = None):
        self.nodes = nodes
        self.mesh = mesh
        self.strategy = strategy or {}
        self.machine_spec = machine_spec
        self.config = config
        self.final_ref = tuple(final_ref) if final_ref is not None else None
        self.ff = ff
        self.hlo_text = hlo_text
        self.hlo_per_host = hlo_per_host
        # multi-slice process topology: slice_of_host[i] is the slice id
        # of hlo_per_host[i]'s process — the multihost-order pass then
        # checks within-slice order per slice AND the cross-slice leader
        # agreement (FFL503) instead of one flat comparison
        self.slice_of_host = slice_of_host
        self.priced = priced      # simulator-priced {kind: bytes}, lazy
        self.emitted = emitted    # HLO-census {kind: bytes}, lazy
        # whether the strategy came from the auto-parallelization search
        # (the calibration pass only meaningfully audits searched runs)
        if searched is None:
            searched = bool(ff is not None
                            and isinstance(getattr(ff, "search_info", None),
                                           dict))
        self.searched = searched
        self.by_guid = {n.op.guid: n for n in nodes}
        self._consumers = None

    @property
    def axis_sizes(self) -> Dict[str, int]:
        if self.mesh is None:
            return {}
        return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))

    def consumers(self) -> Dict[Tuple[int, int], List]:
        """(producer guid, out idx) -> list of (consumer node, input pos).
        Memoized — the graph is not mutated during a lint run, and
        several passes (hygiene, layout, dtype) walk this map."""
        if self._consumers is None:
            out: Dict[Tuple[int, int], List] = {}
            for node in self.nodes:
                for j, ref in enumerate(node.input_refs):
                    if ref[0] == "op":
                        out.setdefault((ref[1], ref[2]), []).append((node, j))
            self._consumers = out
        return self._consumers

    def ensure_priced(self) -> Optional[Dict[str, float]]:
        """Simulator-priced collectives for the model's strategy (native
        replay); None when no model / native core is attached."""
        if self.priced is not None:
            return self.priced
        if self.ff is None:
            return None
        from flexflow_tpu.search.native import available
        if not available():
            return None
        from flexflow_tpu.search.validate import priced_collectives
        self.priced = priced_collectives(self.ff)
        return self.priced

    def ensure_emitted(self) -> Optional[Dict[str, float]]:
        """Collectives emitted in the optimized HLO (requires hlo_text)."""
        if self.emitted is not None:
            return self.emitted
        if not self.hlo_text:
            return None
        from flexflow_tpu.search.validate import emitted_collectives
        self.emitted = emitted_collectives(self.hlo_text)
        return self.emitted


def all_passes():
    """The shipped pass pipeline, in execution order (cheap graph-shape
    checks first so their findings frame the expensive ones)."""
    from flexflow_tpu.analysis.passes.calibration import CalibrationPass
    from flexflow_tpu.analysis.passes.checkpoint import CheckpointIntegrityPass
    from flexflow_tpu.analysis.passes.collectives import CollectiveInferencePass
    from flexflow_tpu.analysis.passes.dtype import DtypePolicyPass
    from flexflow_tpu.analysis.passes.hygiene import GraphHygienePass
    from flexflow_tpu.analysis.passes.layout import LayoutConsistencyPass
    from flexflow_tpu.analysis.passes.multihost import MultihostOrderPass
    from flexflow_tpu.analysis.passes.sharding import ShardingLegalityPass
    return [
        GraphHygienePass(),
        ShardingLegalityPass(),
        LayoutConsistencyPass(),
        DtypePolicyPass(),
        CollectiveInferencePass(),
        MultihostOrderPass(),
        CalibrationPass(),
        CheckpointIntegrityPass(),
    ]


def run_passes(ctx: LintContext, passes=None) -> LintReport:
    report = LintReport()
    report.context = dict(
        num_ops=len(ctx.nodes),
        mesh_axes=ctx.axis_sizes,
        searched=ctx.searched,
        hlo="yes" if ctx.hlo_text else "no",
    )
    for p in passes if passes is not None else all_passes():
        try:
            report.extend(p.run(ctx), p.name)
            report.passes[p.name] = "ok"
        except SkipPass as e:
            report.passes[p.name] = f"skipped: {e}"
        except Exception as e:  # a broken pass must not kill the lint run
            report.passes[p.name] = f"crashed: {e!r}"
            report.extend([error(
                "FFL000", f"pass crashed: {e!r}",
                hint="fflint internal error — report with the model config"
            )], p.name)
    return report


def lint_model(ff, hlo=None, passes=None,
               hlo_per_host: Optional[List[str]] = None,
               slice_of_host: Optional[List[int]] = None) -> LintReport:
    """Lint a compiled FFModel.

    ``hlo``: None runs the static passes only; ``True`` lowers+compiles
    the train step to include the emitted-HLO checks (expensive — one
    full XLA compile); a string is used as the optimized-HLO text
    directly (e.g. from a saved dump or a prior ``train_step_hlo``).
    ``slice_of_host``: per-entry slice ids for ``hlo_per_host`` on a
    multi-slice deployment — the multihost-order pass then reports
    within-slice divergence with slice attribution plus FFL503 when
    the slice leaders disagree across the DCN.
    """
    if ff.executor is None:
        raise ValueError("lint_model needs a compiled model — call "
                         "model.compile(...) first")
    hlo_text = None
    if hlo is True:
        from flexflow_tpu.search.validate import train_step_hlo
        hlo_text = train_step_hlo(ff)
    elif isinstance(hlo, str):
        hlo_text = hlo
    ctx = LintContext(
        nodes=ff.executor.nodes, mesh=ff.mesh, strategy=ff.strategy,
        machine_spec=ff.machine_spec, config=ff.config,
        final_ref=ff.executor.final_ref, ff=ff, hlo_text=hlo_text,
        hlo_per_host=hlo_per_host, slice_of_host=slice_of_host)
    return run_passes(ctx, passes=passes)

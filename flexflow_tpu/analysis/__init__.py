"""fflint — static strategy & graph verifier for the PCG, searched
strategies, and emitted HLO.

A pass-based static-analysis framework that verifies a compiled model's
parallelization BEFORE anything runs: sharding legality against the
mesh, the collective census the strategy implies vs what the simulator
priced (and, optionally, what XLA emitted), layout and dtype policy,
cross-host collective ordering, and graph hygiene. Entry points:

* ``lint_model(ff)`` — lint a compiled FFModel (static passes only);
  ``lint_model(ff, hlo=True)`` additionally compiles the step and runs
  the emitted-HLO checks;
* ``model.compile(..., lint="warn"|"error")`` / ``FFConfig --lint`` —
  inline linting at compile time;
* ``scripts/fflint.py --model <zoo> [--json] [--hlo]`` — the CLI.

Rule catalog: README.md §fflint.
"""

from flexflow_tpu.analysis.dataflow import (EdgeReshard,
                                            classify_transition,
                                            edge_reshard_table,
                                            required_input_specs,
                                            verify_rewrite_dataflow,
                                            weight_movement_edges)
from flexflow_tpu.analysis.diagnostics import (Diagnostic, LintReport,
                                               Severity)
from flexflow_tpu.analysis.orchestrator import (LintContext, SkipPass,
                                                all_passes, lint_model,
                                                run_passes)

__all__ = [
    "Diagnostic",
    "LintReport",
    "Severity",
    "LintContext",
    "SkipPass",
    "all_passes",
    "lint_model",
    "run_passes",
    "EdgeReshard",
    "classify_transition",
    "edge_reshard_table",
    "required_input_specs",
    "verify_rewrite_dataflow",
    "weight_movement_edges",
]

"""sharding-legality: per-dim degrees and parallel-op compatibility.

The PCG's core invariant (tensor.ParallelDim: size % degree == 0) is
enforced dynamically at materialization for degree-form shapes, but a
strategy arrives as PartitionSpecs whose degrees are implied by mesh-axis
extents — nothing checked those until GSPMD failed (or worse, silently
padded). This pass verifies, without compiling anything:

* FFL101  a spec shards a dim whose extent the implied degree does not
          divide (GSPMD pads — the simulator priced the unpadded tensor);
* FFL102  a spec names a mesh axis the mesh does not carry;
* FFL103  a parameter spec is illegal against the op's parameter shapes;
* FFL104  a parallel op (repartition/combine/replicate/reduction) is
          incompatible with its mesh axis or its producer's sharding;
* FFL105  one spec uses the same mesh axis on two dims;
* FFL106  a pipe mesh whose stage count does not divide the repeated
          blocks (or that has no repeated-block body at all);
* FFL107  dropout/stateful ops inside the repeated blocks a pipe mesh
          would pipeline (op state/rng cannot ride the shard_map body);
* FFL108  the batch does not divide microbatches x data degree.

The FFL106-108 family is the static form of the ValueErrors
``PipelineGraphExecutor.__init__`` raises at compile time — lint
surfaces them pre-compile with fix hints instead.

Under weight-update sharding the pass additionally verifies the
executor's sharded master/optimizer-state specs (``wus:<param>``
tensors) with the same FFL101/102/105 rules — an illegal WUS shard
would otherwise only surface as GSPMD padding deep inside jit.
"""

from __future__ import annotations

import math
from typing import Dict, List

from flexflow_tpu.analysis.diagnostics import Diagnostic, error, warning
from flexflow_tpu.ffconst import OperatorType
# parameter name -> shape via eval_shape: the strategy decoder's own
# notion of which params an op owns, so lint and decode never disagree
from flexflow_tpu.search.unity import _param_shapes


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def _spec_entries(spec, ndim: int) -> List:
    entries = list(spec) if spec is not None else []
    return (entries + [None] * ndim)[:ndim]


def _check_spec(spec, shape, axis_sizes: Dict[str, int], op_name: str,
                guid: int, what: str) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    if spec is None:
        return diags
    entries = _spec_entries(spec, len(shape))
    if len(tuple(spec)) > len(shape):
        diags.append(error(
            "FFL103",
            f"{what}: spec {tuple(spec)} has more entries than the "
            f"rank-{len(shape)} tensor",
            op=op_name, guid=guid, tensor=what,
            hint="drop the extra entries; specs index tensor dims"))
    used: Dict[str, int] = {}
    for d, entry in enumerate(entries):
        axes = _entry_axes(entry)
        degree = 1
        for ax in axes:
            if ax not in axis_sizes:
                diags.append(error(
                    "FFL102",
                    f"{what}: dim {d} sharded over mesh axis {ax!r} "
                    f"but the mesh carries {sorted(axis_sizes)}",
                    op=op_name, guid=guid, tensor=what,
                    hint="axis dropped or renamed — re-export the "
                         "strategy against this mesh"))
                continue
            degree *= axis_sizes[ax]
            used[ax] = used.get(ax, 0) + 1
        if degree > 1 and d < len(shape) and shape[d] % degree != 0:
            diags.append(error(
                "FFL101",
                f"{what}: dim {d} extent {shape[d]} not divisible by "
                f"sharding degree {degree} ({'+'.join(axes)})",
                op=op_name, guid=guid, tensor=what,
                hint="GSPMD will pad the shards; the simulator priced "
                     "the unpadded tensor — pick a dividing degree"))
    for ax, n in used.items():
        if n > 1:
            diags.append(error(
                "FFL105",
                f"{what}: mesh axis {ax!r} shards {n} dims of the same "
                f"tensor",
                op=op_name, guid=guid, tensor=what,
                hint="an axis can shard at most one dim per tensor"))
    return diags


class ShardingLegalityPass:
    name = "sharding-legality"

    def run(self, ctx) -> List[Diagnostic]:
        diags: List[Diagnostic] = []
        axis_sizes = ctx.axis_sizes
        if not axis_sizes:
            from flexflow_tpu.analysis.orchestrator import SkipPass
            raise SkipPass("no mesh in context")
        for node in ctx.nodes:
            op = node.op
            # the applied (post-apply_strategy) specs on the node are the
            # executor's truth; fall back to the raw strategy entry for
            # contexts built from a strategy alone
            specs = getattr(node, "output_specs", None)
            st = ctx.strategy.get(op.guid)
            if specs is None and st is not None:
                specs = st.output_specs
            for i, spec in enumerate(specs or []):
                if i >= len(op.output_shapes):
                    break
                diags.extend(_check_spec(
                    spec, op.output_shapes[i], axis_sizes, op.name,
                    op.guid, f"out[{i}]"))
            param_specs = getattr(node, "param_specs", None)
            if not param_specs and st is not None:
                param_specs = st.param_specs
            if param_specs:
                shapes = _param_shapes(op)
                for pname, spec in param_specs.items():
                    shp = shapes.get(pname)
                    if shp is None:
                        diags.append(warning(
                            "FFL103",
                            f"param spec for {pname!r} but the op owns no "
                            f"such parameter",
                            op=op.name, guid=op.guid, tensor=pname,
                            hint="stale strategy file? parameter names "
                                 "are the executor's param-tree keys"))
                        continue
                    diags.extend(_check_spec(
                        spec, tuple(shp), axis_sizes, op.name, op.guid,
                        f"param:{pname}"))
            diags.extend(self._check_parallel_op(node, ctx, axis_sizes))
        diags.extend(self._check_wus_specs(ctx, axis_sizes))
        diags.extend(self._check_pipeline(ctx, axis_sizes))
        return diags

    # ---- pipeline legality on pipe meshes (FFL106-108) ---------------------
    @staticmethod
    def _check_pipeline(ctx, axis_sizes) -> List[Diagnostic]:
        pp = axis_sizes.get("pipe", 1)
        if pp <= 1:
            return []
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks)
        diags: List[Diagnostic] = []
        pb = detect_repeated_blocks(ctx.nodes)
        if pb is None:
            # distinguish "repeated but stateful body" (FFL107) from
            # "no repeated structure at all" (FFL106)
            relaxed = detect_repeated_blocks(ctx.nodes, allow_stateful=True)
            if relaxed is None:
                diags.append(error(
                    "FFL106",
                    f"mesh carries a pipe axis ({pp}) but the graph has "
                    f"no repeated-block body to pipeline",
                    hint="pipeline parallelism needs a run of >= 2 "
                         "structurally-identical shape-preserving blocks; "
                         "drop the pipe axis or restructure the body"))
                return diags
            aux_types = {OperatorType.DROPOUT, OperatorType.EXPERTS,
                         OperatorType.AGGREGATE,
                         OperatorType.AGGREGATE_SPEC, OperatorType.GROUP_BY}
            bad = sorted({
                ctx.nodes[i].op.name
                for blk in relaxed.blocks for i in blk
                if hasattr(ctx.nodes[i].op, "init_state")
                or getattr(ctx.nodes[i].op, "dropout", 0.0)
                or ctx.nodes[i].op.op_type in aux_types})
            diags.append(error(
                "FFL107",
                f"repeated blocks carry dropout/stateful ops "
                f"({', '.join(bad[:4])}{', ...' if len(bad) > 4 else ''}) "
                f"— op state/rng cannot ride the pipeline's shard_map "
                f"body",
                hint="remove dropout from the repeated body (or fold the "
                     "stateful op) before pipelining, or drop the pipe "
                     "axis"))
            pb = relaxed  # divisibility checks still apply
        if pb.num_blocks % pp:
            diags.append(error(
                "FFL106",
                f"{pb.num_blocks} repeated blocks do not divide into "
                f"{pp} pipeline stages",
                hint=f"pick a pipe degree dividing {pb.num_blocks}, or "
                     f"change the repeated-layer count"))
        dp = 1
        for ax in ("data", "replica"):
            dp *= axis_sizes.get(ax, 1)
        ex = getattr(ctx.ff, "executor", None) if ctx.ff is not None \
            else None
        M = int(getattr(ex, "microbatches", 0) or
                getattr(ctx.config, "pipeline_microbatches", 0) or 2 * pp)
        batch = ctx.nodes[pb.blocks[0][0]].op.output_shapes[0][0]
        if batch % (M * dp):
            diags.append(error(
                "FFL108",
                f"batch {batch} does not divide microbatches x data "
                f"degree ({M} x {dp})",
                hint="pick --pipeline-microbatches dividing batch/data "
                     "(or 'auto', which sweeps the divisor lattice)"))
        return diags

    # ---- weight-update-sharding state specs -------------------------------
    @staticmethod
    def _check_wus_specs(ctx, axis_sizes) -> List[Diagnostic]:
        """Verify the data-sharded master-param/optimizer-state layout
        the executor derived for weight-update sharding (the specs the
        f32 master, Adam moments, and the reduce-scattered gradients
        actually live on)."""
        ex = getattr(ctx.ff, "executor", None) if ctx.ff is not None else None
        if ex is None or not getattr(ex, "weight_update_sharding", False):
            return []
        diags: List[Diagnostic] = []
        by_name = {n.op.name: n for n in ctx.nodes}
        for op_name, specs in ex.wus_param_specs().items():
            node = by_name.get(op_name)
            if node is None:
                continue
            shapes = _param_shapes(node.op)
            for pname, spec in specs.items():
                shp = shapes.get(pname)
                if shp is None:
                    continue
                diags.extend(_check_spec(
                    spec, tuple(shp), axis_sizes, op_name, node.op.guid,
                    f"wus:{pname}"))
        return diags

    # ---- parallel-op in/out compatibility (FFL104) ------------------------
    def _check_parallel_op(self, node, ctx, axis_sizes) -> List[Diagnostic]:
        op = node.op
        if not getattr(op, "is_parallel_op", False):
            return []
        diags: List[Diagnostic] = []
        t = op.op_type
        if t == OperatorType.REPARTITION:
            ax = op.axis
            if ax not in axis_sizes:
                diags.append(error(
                    "FFL104",
                    f"repartition over mesh axis {ax!r} but the mesh "
                    f"carries {sorted(axis_sizes)}",
                    op=op.name, guid=op.guid, tensor="out[0]",
                    hint="pass repartition(axis=...) naming a real axis"))
            elif op.repartition_degree != axis_sizes[ax]:
                diags.append(error(
                    "FFL104",
                    f"repartition degree {op.repartition_degree} != mesh "
                    f"axis {ax!r} extent {axis_sizes[ax]}",
                    op=op.name, guid=op.guid, tensor="out[0]",
                    hint="under GSPMD the degree must equal the axis "
                         "extent it maps to"))
        elif t == OperatorType.COMBINE:
            src = self._producer_spec(node, ctx)
            if src is not None:
                d = op.combine_dim % len(op.output_shapes[0])
                entries = _spec_entries(src, len(op.output_shapes[0]))
                if not _entry_axes(entries[d]):
                    diags.append(warning(
                        "FFL104",
                        f"combine(dim={d}) of an input not sharded on "
                        f"that dim — the op is a no-op",
                        op=op.name, guid=op.guid, tensor="in[0]",
                        hint="dead resharding; drop the combine or fix "
                             "the upstream repartition dim"))
        elif t == OperatorType.REDUCTION:
            shp = op.input_shapes[0]
            d = op.reduction_dim % len(shp)
            # degree-divides-extent is enforced at materialization; what
            # is NOT is the degree matching an actual replica factor:
            # reducing a dim the strategy never produced partial copies
            # on silently averages real data
            src = self._producer_spec(node, ctx)
            if src is not None:
                entries = _spec_entries(src, len(shp))
                axes = _entry_axes(entries[d])
                degree = math.prod(axis_sizes.get(a, 1) for a in axes)
                if axes and degree != op.reduction_degree:
                    diags.append(error(
                        "FFL104",
                        f"reduction(dim={d}, degree="
                        f"{op.reduction_degree}) over a dim sharded "
                        f"{degree}-way",
                        op=op.name, guid=op.guid, tensor="in[0]",
                        hint="the reduction degree must equal the "
                             "replica count laid out on that dim"))
        return diags

    @staticmethod
    def _producer_spec(node, ctx):
        ref = node.input_refs[0] if node.input_refs else None
        if not ref or ref[0] != "op":
            return None
        prod = ctx.by_guid.get(ref[1])
        if prod is None:
            return None
        specs = getattr(prod, "output_specs", None)
        if specs is None:
            st = ctx.strategy.get(ref[1])
            specs = st.output_specs if st is not None else None
        if not specs or ref[2] >= len(specs):
            return None
        return specs[ref[2]]

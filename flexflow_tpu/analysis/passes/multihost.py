"""multihost-order: the static deadlock detector.

Multi-controller SPMD's cardinal rule: every process must issue the
same collectives in the same order, or the fleet deadlocks with each
host parked in a different all-reduce (the failure takes a wall-clock
timeout to even notice on real pods). Per-host programs are identical
by construction when every host runs the same compiled step — but the
moment anything host-dependent leaks into compilation (host-conditional
graph edits, per-host shape differences from a skewed dataloader, a
rank-gated layer) the orders diverge.

This pass takes the per-host optimized-HLO texts
(``LintContext.hlo_per_host``, e.g. collected by the multihost dryrun)
and compares the ordered collective sequences:

* FFL501  two hosts disagree on the k-th collective (kind or shape) —
          a guaranteed deadlock/corruption at step time;
* FFL502  a host's program has a different collective COUNT (one host
          will wait forever on a collective its peers never enter).

On a multi-slice deployment (``LintContext.slice_of_host`` maps each
program to its slice) the comparison is hierarchical, matching the
fabric the collectives rendezvous over: FFL501/502 are checked WITHIN
each slice (against the slice's first host, diagnostics name the
slice), and the slice leaders are then compared across the DCN:

* FFL503  two slices' leader programs diverge (order, kind, shape, or
          count) — the cross-slice collective (the DCN gradient sync)
          deadlocks even though every slice is internally consistent.
"""

from __future__ import annotations

import re
from typing import List, Tuple

from flexflow_tpu.analysis.diagnostics import Diagnostic, error
from flexflow_tpu.obs.inspect import COLLECTIVE_KINDS

_SEQ_RE = re.compile(
    # "%name = SHAPE opcode(" — SHAPE is a typed array (with optional
    # layout braces) or a tuple; requiring the "= SHAPE" prefix keeps
    # LHS names like %all-reduce.58 from matching
    r"\S+\s*=\s*((?:\w+\[[^\]]*\](?:\{[^}]*\})?|\([^)]*\)))\s*"
    r"(" + "|".join(COLLECTIVE_KINDS) + r")(-start|-done)?[.\d]*\(")


def collective_sequence(hlo_text: str) -> List[Tuple[str, str]]:
    """Ordered (kind, shape) list of collectives in an HLO module, in
    program order. Async -start/-done pairs count once (the -start is
    where the host enters the rendezvous)."""
    seq: List[Tuple[str, str]] = []
    for line in hlo_text.splitlines():
        m = _SEQ_RE.search(line)
        if not m or m.group(3) == "-done":
            continue
        seq.append((m.group(2), m.group(1).strip()))
    return seq


class MultihostOrderPass:
    name = "multihost-order"

    def run(self, ctx) -> List[Diagnostic]:
        texts = ctx.hlo_per_host
        if not texts or len(texts) < 2:
            from flexflow_tpu.analysis.orchestrator import SkipPass
            raise SkipPass("needs >= 2 per-host HLO programs "
                           "(hlo_per_host); single-program runs are "
                           "order-consistent by construction")
        diags: List[Diagnostic] = []
        seqs = [collective_sequence(t) for t in texts]
        slices = getattr(ctx, "slice_of_host", None)
        if slices and len(slices) == len(seqs):
            # hierarchical (multi-slice) comparison: within-slice order
            # per slice, then the slice leaders across the DCN
            groups = {}
            for host, sl in enumerate(slices):
                groups.setdefault(sl, []).append(host)
            for sl, hosts in sorted(groups.items()):
                lead = hosts[0]
                for host in hosts[1:]:
                    diags.extend(self._compare(
                        seqs[lead], seqs[host],
                        f"host {lead} (slice {sl})",
                        f"host {host} (slice {sl})",
                        "FFL502", "FFL501"))
            leaders = [hosts[0] for _, hosts in sorted(groups.items())]
            for sl, host in zip(sorted(groups)[1:], leaders[1:]):
                diags.extend(self._compare(
                    seqs[leaders[0]], seqs[host],
                    f"slice {sorted(groups)[0]} leader (host "
                    f"{leaders[0]})",
                    f"slice {sl} leader (host {host})",
                    "FFL503", "FFL503"))
            return diags
        ref = seqs[0]
        for host, seq in enumerate(seqs[1:], start=1):
            diags.extend(self._compare(ref, seq, "host 0", f"host {host}",
                                       "FFL502", "FFL501"))
        return diags

    @staticmethod
    def _compare(ref, seq, ref_name: str, name: str, count_rule: str,
                 order_rule: str) -> List[Diagnostic]:
        """FFL50x diff of two collective sequences: one count
        diagnostic and/or the first order divergence."""
        diags: List[Diagnostic] = []
        cross = count_rule == "FFL503"
        if len(seq) != len(ref):
            diags.append(error(
                count_rule,
                f"{name} issues {len(seq)} collectives, {ref_name} "
                f"issues {len(ref)} — a host will block forever on "
                f"a rendezvous its peers never enter",
                hint=("cross-slice programs must agree for the DCN "
                      "collectives to rendezvous — diff the slice "
                      "leaders' programs" if cross else
                      "diff the per-host programs; something "
                      "host-dependent leaked into compilation")))
        for k, (a, b) in enumerate(zip(ref, seq)):
            if a != b:
                diags.append(error(
                    order_rule,
                    f"collective order diverges at position {k}: "
                    f"{ref_name} runs {a[0]} {a[1]}, {name} runs "
                    f"{b[0]} {b[1]}",
                    hint=("the cross-slice gradient sync deadlocks "
                          "even with every slice internally "
                          "consistent" if cross else
                          "mismatched collective sequences deadlock "
                          "(or silently corrupt when kinds pair up "
                          "wrong) — per-host programs must be "
                          "identical")))
                break  # first divergence per pair is enough
        return diags

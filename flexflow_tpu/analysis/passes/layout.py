"""layout-consistency: NHWC/NCHW boundary audit.

The layout pass (flexflow_tpu/layout.py) records on every node which
physical layout its forward consumes/produces; the executor inserts a
transpose wherever they disagree. That metadata makes layout bugs and
layout waste statically visible:

* FFL301  redundant transpose pair: two user-level TRANSPOSE ops whose
          composed permutation is the identity;
* FFL302  broken NHWC chain: a value round-trips NHWC -> NCHW -> NHWC
          because an NCHW-only op sits between two channels-last ops
          (two boundary transpose pairs where teaching the middle op
          NHWC would cost zero);
* FFL303  layout metadata contradiction: a consumer is recorded as
          reading a layout its producer does not emit AND the value is
          not rank-4 (the executor's transpose fallback only handles
          rank-4), or the per-input/per-output layout lists do not
          match the node's arity.
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.analysis.diagnostics import Diagnostic, error, warning
from flexflow_tpu.ffconst import OperatorType

_IDENT_OK = ("NCHW", "NHWC")


class LayoutConsistencyPass:
    name = "layout-consistency"

    def run(self, ctx) -> List[Diagnostic]:
        diags: List[Diagnostic] = []
        diags.extend(self._redundant_transposes(ctx))
        diags.extend(self._metadata_audit(ctx))
        diags.extend(self._chain_breaks(ctx))
        return diags

    # ---- FFL301 ------------------------------------------------------------
    def _redundant_transposes(self, ctx) -> List[Diagnostic]:
        diags = []
        consumers = ctx.consumers()
        for node in ctx.nodes:
            op = node.op
            if op.op_type != OperatorType.TRANSPOSE:
                continue
            ref = node.input_refs[0]
            if ref[0] != "op":
                continue
            prod = ctx.by_guid.get(ref[1])
            if prod is None or prod.op.op_type != OperatorType.TRANSPOSE:
                continue
            inner = prod.op.layer.get_property("perm")
            outer = op.layer.get_property("perm")
            if inner is None or outer is None:
                continue
            composed = tuple(inner[p] for p in outer)
            if composed == tuple(range(len(composed))):
                # only truly redundant if nothing else reads the
                # intermediate permuted value
                others = [c for c, _ in consumers.get((ref[1], ref[2]), [])
                          if c is not node]
                if not others:
                    diags.append(warning(
                        "FFL301",
                        f"transpose pair {prod.op.name} -> {op.name} "
                        f"composes to the identity",
                        op=op.name, guid=op.guid,
                        hint="drop both ops; they move every byte of the "
                             "tensor twice for nothing"))
        return diags

    # ---- FFL303 ------------------------------------------------------------
    def _metadata_audit(self, ctx) -> List[Diagnostic]:
        diags = []
        for node in ctx.nodes:
            op = node.op
            in_l = getattr(node, "input_layouts", None)
            out_l = getattr(node, "output_layouts", None)
            if in_l is not None and len(in_l) != len(node.input_refs):
                diags.append(error(
                    "FFL303",
                    f"input_layouts has {len(in_l)} entries for "
                    f"{len(node.input_refs)} inputs",
                    op=op.name, guid=op.guid,
                    hint="layout pass metadata out of sync with the "
                         "graph — re-run propagate_layouts"))
                continue
            if out_l is not None and len(out_l) != len(op.output_shapes):
                diags.append(error(
                    "FFL303",
                    f"output_layouts has {len(out_l)} entries for "
                    f"{len(op.output_shapes)} outputs",
                    op=op.name, guid=op.guid,
                    hint="layout pass metadata out of sync with the "
                         "graph — re-run propagate_layouts"))
                continue
            for i, lay in enumerate(out_l or []):
                if lay not in _IDENT_OK:
                    diags.append(error(
                        "FFL303", f"unknown layout {lay!r} on output {i}",
                        op=op.name, guid=op.guid))
                elif lay == "NHWC" and len(op.output_shapes[i]) != 4:
                    diags.append(error(
                        "FFL303",
                        f"output {i} recorded NHWC but is rank "
                        f"{len(op.output_shapes[i])} — the executor's "
                        f"boundary transpose only handles rank-4 values",
                        op=op.name, guid=op.guid,
                        hint="an NHWC layout on a non-image tensor will "
                             "silently never be transposed back"))
            for j, (want, ref) in enumerate(zip(in_l or [],
                                                node.input_refs)):
                if want not in _IDENT_OK:
                    diags.append(error(
                        "FFL303", f"unknown layout {want!r} on input {j}",
                        op=op.name, guid=op.guid))
                    continue
                have = self._produced_layout(ctx, ref)
                shp = (op.input_shapes[j]
                       if j < len(op.input_shapes) else ())
                if want != have and len(shp) != 4:
                    diags.append(error(
                        "FFL303",
                        f"input {j} wants {want} but its producer emits "
                        f"{have} and the value is rank {len(shp)} — no "
                        f"transpose exists for it",
                        op=op.name, guid=op.guid,
                        hint="the layout pass must only relayout rank-4 "
                             "values"))
        return diags

    # ---- FFL302 ------------------------------------------------------------
    def _chain_breaks(self, ctx) -> List[Diagnostic]:
        """A value produced NHWC, consumed by an NCHW-only op whose own
        output is transposed back to NHWC downstream: two transpose
        pairs an NHWC port of the middle op would eliminate."""
        diags = []
        consumers = ctx.consumers()
        for node in ctx.nodes:
            op = node.op
            in_l = getattr(node, "input_layouts", None) or []
            out_l = getattr(node, "output_layouts", None) or []
            if not in_l or not out_l:
                continue
            # this op consumes NCHW from an NHWC producer...
            breaks_chain = any(
                want == "NCHW"
                and self._produced_layout(ctx, ref) == "NHWC"
                for want, ref in zip(in_l, node.input_refs))
            if not breaks_chain or out_l[0] != "NCHW":
                continue
            # ...and a consumer immediately re-transposes its output
            rejoins = any(
                (getattr(c, "input_layouts", None) or ["NCHW"] * (j + 1))[j]
                == "NHWC"
                for i in range(len(op.output_shapes))
                for c, j in consumers.get((op.guid, i), []))
            if rejoins:
                diags.append(warning(
                    "FFL302",
                    f"{op.op_type.name} breaks an NHWC chain (value "
                    f"round-trips NHWC->NCHW->NHWC around it)",
                    op=op.name, guid=op.guid,
                    hint="teach this op an NHWC execution mode "
                         "(flexflow_tpu/layout.py _NHWC_COMPUTE / "
                         "_PASS_THROUGH) to drop two transposes"))
        return diags

    @staticmethod
    def _produced_layout(ctx, ref) -> str:
        if ref[0] != "op":
            return "NCHW"  # graph inputs are staged NCHW (API boundary)
        prod = ctx.by_guid.get(ref[1])
        if prod is None:
            return "NCHW"
        out_l = getattr(prod, "output_layouts", None)
        return out_l[ref[2]] if out_l and ref[2] < len(out_l) else "NCHW"

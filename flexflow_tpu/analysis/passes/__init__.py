"""fflint passes. Each module exports one pass class with a stable
``name`` and a ``run(ctx) -> List[Diagnostic]``; the rule-id ranges are

    FFL0xx  framework (internal errors)
    FFL1xx  sharding-legality
    FFL2xx  collective-inference
    FFL3xx  layout-consistency
    FFL4xx  dtype-policy
    FFL5xx  multihost-order
    FFL6xx  graph-hygiene
    FFL7xx  calibration

The catalog with per-rule descriptions lives in README.md §fflint.
"""

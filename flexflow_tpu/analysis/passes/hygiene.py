"""graph-hygiene: dead ops, unused inputs, shape contradictions.

The cheapest pass and the one that catches editor-class mistakes before
they cost a compile: a dead subgraph still gets materialized, jitted,
differentiated, and (if it owns parameters) allocated and optimizer-
stepped — XLA's DCE removes the forward compute but not the parameter
memory or the gradient-sync collectives fflint's other passes price.

* FFL601  dead op: no path from any of its outputs to the designated
          model output (whole dead chains are reported at their root);
* FFL602  unused graph input: an INPUT layer no live op consumes
          (callers must still feed it every step);
* FFL603  shape contradiction: a consumer's recorded input shape
          disagrees with its producer's output shape (impossible from
          the builder; reachable through hand-edited graphs and
          substitution rewrites — the executor would crash deep inside
          jit with an inscrutable broadcast error);
* FFL604  duplicate op names: parameters are keyed by name, so two ops
          sharing one silently share (and doubly-update) parameters.
"""

from __future__ import annotations

from typing import Dict, List, Set

from flexflow_tpu.analysis.diagnostics import Diagnostic, error, warning


class GraphHygienePass:
    name = "graph-hygiene"

    def run(self, ctx) -> List[Diagnostic]:
        diags: List[Diagnostic] = []
        live = self._live_set(ctx)
        diags.extend(self._dead_ops(ctx, live))
        diags.extend(self._unused_inputs(ctx, live))
        diags.extend(self._shape_contradictions(ctx))
        diags.extend(self._duplicate_names(ctx))
        return diags

    def _live_set(self, ctx) -> Set[int]:
        """Guids reachable backward from the designated output. Without
        a final_ref everything is considered live (a bare node list has
        no notion of 'the' output)."""
        if ctx.final_ref is None:
            return {n.op.guid for n in ctx.nodes}
        live: Set[int] = set()
        stack = [ctx.final_ref[0]]
        while stack:
            g = stack.pop()
            if g in live:
                continue
            live.add(g)
            node = ctx.by_guid.get(g)
            if node is None:
                continue
            for ref in node.input_refs:
                if ref[0] == "op" and ref[1] not in live:
                    stack.append(ref[1])
        return live

    def _dead_ops(self, ctx, live: Set[int]) -> List[Diagnostic]:
        diags = []
        consumers = ctx.consumers()
        for node in ctx.nodes:
            op = node.op
            if op.guid in live:
                continue
            # report dead chains at their root: a dead op all of whose
            # consumers are also dead is interior — flag only ops whose
            # outputs nothing consumes at all, plus dead ops feeding a
            # live op is impossible by construction of the live set
            has_consumer = any(
                consumers.get((op.guid, i))
                for i in range(len(op.output_shapes)))
            if has_consumer:
                continue
            nparams = op.params_elems()
            extra = (f"; its {nparams} parameters still allocate, "
                     f"gradient-sync, and optimizer-step"
                     if nparams else "")
            diags.append(warning(
                "FFL601",
                f"dead op: no path from {op.name} to the model output"
                + extra,
                op=op.name, guid=op.guid,
                hint="remove the layer (or designate its output via "
                     "compile(outputs=...) if it was meant to be "
                     "the head)"))
        return diags

    def _unused_inputs(self, ctx, live: Set[int]) -> List[Diagnostic]:
        diags = []
        used: Set[str] = set()
        for node in ctx.nodes:
            if node.op.guid not in live:
                continue
            for ref in node.input_refs:
                if ref[0] == "input":
                    used.add(ref[1])
        declared = None
        if ctx.ff is not None and ctx.ff.executor is not None:
            declared = list(ctx.ff.executor.input_names)
        for name in declared or []:
            if name not in used:
                diags.append(warning(
                    "FFL602",
                    f"graph input {name!r} feeds no live op — callers "
                    f"must still stage it every step",
                    tensor=name,
                    hint="drop the create_tensor call or wire the "
                         "tensor into the graph"))
        return diags

    def _shape_contradictions(self, ctx) -> List[Diagnostic]:
        diags = []
        for node in ctx.nodes:
            op = node.op
            for j, ref in enumerate(node.input_refs):
                if ref[0] != "op" or j >= len(op.input_shapes):
                    continue
                prod = ctx.by_guid.get(ref[1])
                if prod is None:
                    diags.append(error(
                        "FFL603",
                        f"input {j} references op guid {ref[1]} which "
                        f"is not in the graph",
                        op=op.name, guid=op.guid,
                        hint="a rewrite removed the producer without "
                             "repointing its consumers"))
                    continue
                if ref[2] >= len(prod.op.output_shapes):
                    diags.append(error(
                        "FFL603",
                        f"input {j} references output {ref[2]} of "
                        f"{prod.op.name}, which has only "
                        f"{len(prod.op.output_shapes)} outputs",
                        op=op.name, guid=op.guid))
                    continue
                want = tuple(op.input_shapes[j])
                have = tuple(prod.op.output_shapes[ref[2]])
                if want != have:
                    diags.append(error(
                        "FFL603",
                        f"input {j} was materialized at shape {want} "
                        f"but its producer {prod.op.name} emits {have}",
                        op=op.name, guid=op.guid,
                        hint="shape-inference contradiction — the graph "
                             "was edited after materialization; "
                             "re-materialize from layers"))
        return diags

    def _duplicate_names(self, ctx) -> List[Diagnostic]:
        diags = []
        seen: Dict[str, int] = {}
        for node in ctx.nodes:
            name = node.op.name
            if name in seen:
                diags.append(error(
                    "FFL604",
                    f"op name {name!r} is also used by guid "
                    f"{seen[name]} — parameters are keyed by name, so "
                    f"these ops silently share parameters",
                    op=name, guid=node.op.guid,
                    hint="rename one op; FFModel deduplicates names at "
                         "build time, so this came from a manual edit "
                         "or a rewrite"))
            else:
                seen[name] = node.op.guid
        return diags

"""calibration: is the cost model the search just used trustworthy?

The recalibration loop (scripts/calibrate.py --ingest-drift) folds
observed predicted-vs-measured drift from real training runs into
CALIBRATION.json as per-op-type correction factors, which
search/profile.py applies to the measured tables it feeds the native
simulator. This pass audits a searched strategy against that state:

* FFL701  the search priced ops with the analytic roofline only — no
          microbenchmarks (--search-measure-ops) and no ingested drift
          corrections exist for this platform;
* FFL702  op types in this graph carry no correction factor while other
          types do (their relative pricing is the raw analytic model —
          exactly the asymmetry that mis-ranks candidate strategies);
* FFL703  calibration data exists but was taken on a different
          platform/device — stale for this machine.
* FFL704  (INFO) the search priced op classes with a LEARNED cost model
          (flexflow_tpu/costmodel) whose held-out error for that class
          exceeds the calibration tolerance — a stale or low-coverage
          model: its rankings for those classes deserve a fresh corpus
          (re-trace + scripts/costmodel.py train) before being trusted.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from flexflow_tpu.analysis.diagnostics import Diagnostic, info, warning


def calibration_path() -> str:
    env = os.environ.get("FFS_CALIBRATION_FILE")
    if env:
        return env
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))), "CALIBRATION.json")


def load_calibration() -> Optional[Dict[str, Any]]:
    try:
        with open(calibration_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class CalibrationPass:
    name = "calibration"

    def run(self, ctx) -> List[Diagnostic]:
        if not ctx.searched:
            from flexflow_tpu.analysis.orchestrator import SkipPass
            raise SkipPass("strategy is heuristic (not searched) — "
                           "cost-model calibration does not gate it")
        diags: List[Diagnostic] = []
        cal = load_calibration()
        # op_corrections is platform-first: {platform: {op type: entry}}
        # (scripts/calibrate.py derive_op_corrections) — only the
        # current platform's bucket ever scales measured tables
        all_corrections = (cal or {}).get("op_corrections", {})
        platform = _current_platform()
        corrections = (all_corrections.get(platform, {})
                       if platform is not None else {})
        measured_ran = bool(ctx.config is not None
                            and getattr(ctx.config, "search_measure_ops",
                                        False))
        learned_ran = bool(
            isinstance(getattr(getattr(ctx, "ff", None), "search_info",
                               None), dict)
            and ctx.ff.search_info.get("cost_model") == "learned")
        if not all_corrections and not measured_ran:
            if not learned_ran:
                # learned pricing IS measurement-derived: when it
                # engaged, the "priced purely analytically" warning is
                # wrong — the staleness audit below applies instead
                diags.append(warning(
                    "FFL701",
                    "search priced every op from the analytic roofline: "
                    "no --search-measure-ops microbenchmarks and no "
                    "ingested drift corrections",
                    hint="run a traced fit (--trace-dir) then "
                         "scripts/calibrate.py --ingest-drift TRACE_DIR "
                         "to close the loop"))
            diags.extend(self._learned_model_diags(ctx, cal))
            return diags
        if cal is not None and platform is not None:
            cal_platform = cal.get("platform")
            if cal_platform and cal_platform != platform:
                diags.append(warning(
                    "FFL703",
                    f"calibration data is from platform "
                    f"{cal_platform!r}; this run is on {platform!r}",
                    hint="re-run scripts/calibrate.py on this machine — "
                         "cross-platform correction factors mislead the "
                         "search"))
        if all_corrections and not corrections:
            diags.append(warning(
                "FFL703",
                f"drift corrections exist only for platform(s) "
                f"{', '.join(sorted(all_corrections))} — none apply on "
                f"{platform!r}",
                hint="re-ingest drift observed on this platform"))
        if corrections:
            graph_types = {n.op.op_type.name for n in ctx.nodes
                           if n.op.flops() > 0}
            missing = sorted(t for t in graph_types
                             if t not in corrections)
            if missing and len(missing) < len(graph_types):
                diags.append(warning(
                    "FFL702",
                    f"no drift correction for op types "
                    f"{', '.join(missing)} while "
                    f"{len(graph_types) - len(missing)} other type(s) "
                    f"are corrected — relative pricing is skewed",
                    hint="ingest drift from a run containing these ops "
                         "(scripts/calibrate.py --ingest-drift)"))
        diags.extend(self._learned_model_diags(ctx, cal))
        return diags

    def _learned_model_diags(self, ctx, cal) -> List[Diagnostic]:
        """FFL704: this strategy was priced by a learned cost model
        whose held-out error for one of the graph's op classes exceeds
        the calibration tolerance (stale / low-coverage model). Keyed
        off the search's own provenance (search_info.cost_model ==
        "learned") so the lint only fires when learned pricing actually
        engaged, and off the COSTMODEL.json artifact's per-class
        held-out error — the number the trainer measured, not a
        re-derivation."""
        search_info = getattr(getattr(ctx, "ff", None), "search_info",
                              None)
        if not isinstance(search_info, dict) \
                or search_info.get("cost_model") != "learned":
            return []
        try:
            from flexflow_tpu.costmodel import load_model
            model = load_model()
        except Exception:
            return []
        if model is None:
            return []
        tolerance = float((cal or {}).get("tolerance", 0.25))
        graph_types = {n.op.op_type.name for n in ctx.nodes
                       if n.op.flops() > 0}
        diags: List[Diagnostic] = []
        for cname in sorted(graph_types & set(model.classes)):
            cm = model.classes[cname]
            if cm.err_factor - 1.0 <= tolerance:
                continue
            diags.append(info(
                "FFL704",
                f"search priced {cname} with a learned cost model whose "
                f"held-out error is x{cm.err_factor:.2f} "
                f"(> {1 + tolerance:.2f}x calibration tolerance; "
                f"{cm.n_train} training rows, {cm.n_test} held out) — "
                f"stale or low-coverage model for this class",
                hint="collect more traces for this op class (traced "
                     "fits with --search-measure-ops, or "
                     "scripts/roofline.py) and re-run "
                     "scripts/costmodel.py train"))
        return diags


def _current_platform() -> Optional[str]:
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return None

"""calibration: is the cost model the search just used trustworthy?

The recalibration loop (scripts/calibrate.py --ingest-drift) folds
observed predicted-vs-measured drift from real training runs into
CALIBRATION.json as per-op-type correction factors, which
search/profile.py applies to the measured tables it feeds the native
simulator. This pass audits a searched strategy against that state:

* FFL701  the search priced ops with the analytic roofline only — no
          microbenchmarks (--search-measure-ops) and no ingested drift
          corrections exist for this platform;
* FFL702  op types in this graph carry no correction factor while other
          types do (their relative pricing is the raw analytic model —
          exactly the asymmetry that mis-ranks candidate strategies);
* FFL703  calibration data exists but was taken on a different
          platform/device — stale for this machine.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from flexflow_tpu.analysis.diagnostics import Diagnostic, warning


def calibration_path() -> str:
    env = os.environ.get("FFS_CALIBRATION_FILE")
    if env:
        return env
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))), "CALIBRATION.json")


def load_calibration() -> Optional[Dict[str, Any]]:
    try:
        with open(calibration_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class CalibrationPass:
    name = "calibration"

    def run(self, ctx) -> List[Diagnostic]:
        if not ctx.searched:
            from flexflow_tpu.analysis.orchestrator import SkipPass
            raise SkipPass("strategy is heuristic (not searched) — "
                           "cost-model calibration does not gate it")
        diags: List[Diagnostic] = []
        cal = load_calibration()
        # op_corrections is platform-first: {platform: {op type: entry}}
        # (scripts/calibrate.py derive_op_corrections) — only the
        # current platform's bucket ever scales measured tables
        all_corrections = (cal or {}).get("op_corrections", {})
        platform = _current_platform()
        corrections = (all_corrections.get(platform, {})
                       if platform is not None else {})
        measured_ran = bool(ctx.config is not None
                            and getattr(ctx.config, "search_measure_ops",
                                        False))
        if not all_corrections and not measured_ran:
            diags.append(warning(
                "FFL701",
                "search priced every op from the analytic roofline: no "
                "--search-measure-ops microbenchmarks and no ingested "
                "drift corrections",
                hint="run a traced fit (--trace-dir) then "
                     "scripts/calibrate.py --ingest-drift TRACE_DIR to "
                     "close the loop"))
            return diags
        if cal is not None and platform is not None:
            cal_platform = cal.get("platform")
            if cal_platform and cal_platform != platform:
                diags.append(warning(
                    "FFL703",
                    f"calibration data is from platform "
                    f"{cal_platform!r}; this run is on {platform!r}",
                    hint="re-run scripts/calibrate.py on this machine — "
                         "cross-platform correction factors mislead the "
                         "search"))
        if all_corrections and not corrections:
            diags.append(warning(
                "FFL703",
                f"drift corrections exist only for platform(s) "
                f"{', '.join(sorted(all_corrections))} — none apply on "
                f"{platform!r}",
                hint="re-ingest drift observed on this platform"))
        if corrections:
            graph_types = {n.op.op_type.name for n in ctx.nodes
                           if n.op.flops() > 0}
            missing = sorted(t for t in graph_types
                             if t not in corrections)
            if missing and len(missing) < len(graph_types):
                diags.append(warning(
                    "FFL702",
                    f"no drift correction for op types "
                    f"{', '.join(missing)} while "
                    f"{len(graph_types) - len(missing)} other type(s) "
                    f"are corrected — relative pricing is skewed",
                    hint="ingest drift from a run containing these ops "
                         "(scripts/calibrate.py --ingest-drift)"))
        return diags


def _current_platform() -> Optional[str]:
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return None

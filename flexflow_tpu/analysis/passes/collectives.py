"""collective-inference: the static census invariant.

The classic silent failure (SURVEY §7, search/validate.py): a searched
strategy underperforms its prediction because GSPMD inserted collectives
the simulator never priced. This pass closes the loop in three layers:

1. *Infer* — derive, from the strategy alone (no compile, no native
   core), the collective kinds the program must contain: the gradient
   all-reduce of every data-replicated parameter, the partial-sum psum
   of every row-parallel contraction, the all-gather behind every
   Combine/Replicate boundary, the reshard behind every
   axis-moving Repartition, the ring ppermute of seq-parallel
   attention, the expert-dispatch all-to-all. This is a LOWER bound:
   GSPMD may insert more, never less.
2. *Price* — replay the strategy through the native simulator
   (validate.priced_collectives) when it is available. An inferred
   kind the simulator never charged is an FFL204 error: the search
   compared candidate strategies while blind to a cost this one
   provably carries.
3. *Emit* — when the caller supplies the optimized HLO, diff the
   priced set against the emitted census (validate.diff_collectives):
   an emitted kind with no priced coverage is the FFL201 error the
   ROADMAP's "census as a search invariant" item asks for.

Since the edge-level dataflow pass (analysis/dataflow.py) the *Infer*
layer is edge-attributed, not kind-aggregated: every producer→consumer
spec disagreement contributes its exact implied collective (kind,
per-device bytes, mesh axes, fabric) to the inferred set, and the
rules that used to be heuristic became exact:

* FFL205 is an ERROR — an implicit edge reshard nothing prices,
  named ``producer.out[i] -> consumer.in[j]`` with the spec pair and
  bytes (no simulator replay needed);
* FFL210 (ERROR) — an implicit edge reshard whose kind the simulator
  replay priced zero bytes for: the search ranked this strategy blind
  to an edge cost it provably carries;
* FFL211 (WARNING) — two implicit reshards on one chain that compose
  to a round trip (resharded into a layout and straight back out);
* FFL212 (WARNING) — a large output materialized replicated although
  every consumer immediately shards it;
* FFL213 (ERROR) — an accepted substitution rewrite whose post-rewrite
  edge-spec map implies MORE collective bytes than the pre-rewrite map
  (dataflow.verify_rewrite_dataflow, recorded by graph_optimize).

The tiny-batch weight-movement special case is gone: the general rule
(dataflow.weight_movement_edges) derives the weight all-gather from
spec + shape for any row-parallel contraction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flexflow_tpu.analysis.dataflow import (edge_reshard_table,
                                            weight_movement_edges)
from flexflow_tpu.analysis.diagnostics import (Diagnostic, error, info,
                                               warning)
from flexflow_tpu.ffconst import CompMode, OperatorType

# which priced kinds cover an inferred/emitted kind — the shared
# definition (XLA AR decomposition, reshard covering permute/a2a) lives
# next to diff_collectives so both layers always classify alike
from flexflow_tpu.search.validate import COLLECTIVE_COVER as _COVER

# payloads below this are scalar loss/metric reductions the simulator
# deliberately does not price — the inference skips them symmetrically
_MIN_BYTES = float(1 << 12)


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def _spec_degree(spec, axis_sizes) -> int:
    if spec is None:
        return 1
    deg = 1
    for entry in spec:
        for ax in _entry_axes(entry):
            deg *= axis_sizes.get(ax, 1)
    return deg


def _node_param_specs(node, ctx) -> Dict[str, Any]:
    ps = getattr(node, "param_specs", None)
    if ps:
        return ps
    st = ctx.strategy.get(node.op.guid)
    return st.param_specs if st is not None else {}


def infer_strategy_collectives(ctx, edge_table=None,
                               weight_moves=None) -> Dict[str, Dict[str, Any]]:
    """{kind: {bytes, sources: [op names], edges: [...]}} the strategy
    implies. Edge-attributed: node-local terms (grad sync, psum,
    explicit parallel-op boundaries, rings, pipeline hops) carry their
    op name as the source; implicit producer→consumer reshards carry
    the full edge (``a.out[i] -> b.in[j]`` plus spec pair) under the
    ``edges`` key so a diagnostic can name the exact seam.

    Bytes are per-device payloads (the census convention): an
    all-reduce of a replicated gradient moves the full tensor per
    device; a reshard moves the shard. Grad/activation payloads use
    the executor's compute dtype width (bf16 halves them under the
    master-weight regime, matching the simulator's
    ``comm_bytes_factor``)."""
    axis_sizes = ctx.axis_sizes
    out: Dict[str, Dict[str, Any]] = {}

    def add(kind: str, nbytes: float, src: str, edge=None):
        if nbytes < _MIN_BYTES:
            return
        e = out.setdefault(kind, dict(bytes=0.0, sources=[], edges=[]))
        e["bytes"] += nbytes
        e["sources"].append(src)
        if edge is not None:
            e["edges"].append(edge.to_json())

    elem = 4.0
    training = True
    if ctx.ff is not None and ctx.ff.executor is not None:
        elem = float(np.dtype(ctx.ff.executor.compute_dtype).itemsize)
        training = getattr(ctx.ff.executor, "comp_mode",
                           CompMode.TRAINING) == CompMode.TRAINING
    data_deg = 1
    for ax in ("data", "replica"):
        data_deg *= axis_sizes.get(ax, 1)
    # weight-update sharding: the executor's runtime flag is the truth
    # (searched strategies additionally mark per-op "_wus" choices)
    executor = ctx.ff.executor if ctx.ff is not None else None
    wus_on = bool(executor is not None
                  and getattr(executor, "weight_update_sharding", False))
    # leaves the executor ACTUALLY shards (per-param divisibility): the
    # gather payload is their element count, not the op's full nelem —
    # non-divisible leaves keep a plain all-reduce with no gather
    wus_specs = (executor.wus_param_specs()
                 if wus_on and hasattr(executor, "wus_param_specs") else {})
    # pipeline: stacked body params live 1/pp per device, so their
    # grad-sync payloads divide by pp (per-device census convention —
    # matches simulate_pipeline's body_gs_*/pp records)
    pp = axis_sizes.get("pipe", 1)
    pb = getattr(executor, "pb", None)
    body_guids = ({ctx.nodes[i].op.guid for blk in pb.blocks for i in blk}
                  if pp > 1 and pb is not None else set())

    for node in ctx.nodes:
        op = node.op
        nelem = float(op.params_elems())
        pspecs = _node_param_specs(node, ctx)
        specs = getattr(node, "output_specs", None) or []
        spec0 = specs[0] if specs else None
        if spec0 is None:
            st = ctx.strategy.get(op.guid)
            if st is not None and st.output_specs:
                spec0 = st.output_specs[0]
        data_sharded = any(
            ax in ("data", "replica")
            for entry in (tuple(spec0) if spec0 is not None else ())
            for ax in _entry_axes(entry))
        if training and data_deg > 1 and nelem > 0 and data_sharded:
            # gradient sync: a batch-sharded op's replicated params see
            # different rows per device, so their grads all-reduce over
            # the data axes. A fully replicated op ("rep" choice)
            # computes identical grads on every device and needs no sync.
            st_choice = getattr(ctx.strategy.get(op.guid), "choice",
                                None) or ""
            stage_div = pp if op.guid in body_guids else 1
            if wus_on or "_wus" in st_choice:
                # weight-update sharding: the sync is a reduce-scatter
                # (XLA's AR-decomposition half — stays in the allreduce
                # bucket) plus the all-gather rebuilding the next step's
                # compute params from the updated shards. Only the
                # leaves the executor shards gather; hand-built contexts
                # without an executor conservatively gather everything.
                sharded = nelem
                if executor is not None:
                    from flexflow_tpu.search.unity import _param_shapes
                    leaf_specs = wus_specs.get(op.name, {})
                    sharded = float(sum(
                        int(np.prod(shp))
                        for pname, shp in _param_shapes(op).items()
                        if pname in leaf_specs))
                add("allreduce", nelem * elem / stage_div,
                    f"{op.name}:grad-rs")
                if sharded > 0:
                    add("allgather", sharded * elem / stage_div,
                        f"{op.name}:wus-gather")
            else:
                add("allreduce", nelem * elem / stage_div,
                    f"{op.name}:grad")
        # row-parallel contractions produce partial sums -> psum: a
        # contraction-dim-sharded kernel (Linear in-dim, attention
        # head-dim on wo, embedding vocab-dim)
        psum_axes = ()
        if op.op_type == OperatorType.LINEAR:
            psum_axes = _entry_axes(_dim0(pspecs.get("kernel")))
        elif op.op_type == OperatorType.MULTIHEAD_ATTENTION:
            psum_axes = _entry_axes(_dim0(pspecs.get("wo")))
        elif op.op_type == OperatorType.EMBEDDING:
            psum_axes = _entry_axes(_dim0(pspecs.get("kernel")))
        if psum_axes:
            out_bytes = float(np.prod(op.output_shapes[0])) * elem
            specs = getattr(node, "output_specs", None) or []
            shard = out_bytes / _spec_degree(specs[0] if specs else None,
                                             axis_sizes)
            add("allreduce", shard, f"{op.name}:psum")
        # explicit PCG resharding boundaries
        if getattr(op, "is_parallel_op", False):
            self_bytes = float(np.prod(op.output_shapes[0])) * elem
            src_spec = _producer_spec(node, ctx)
            src_deg = _spec_degree(src_spec, axis_sizes)
            t = op.op_type
            if t == OperatorType.COMBINE and src_deg > 1:
                add("allgather", self_bytes, op.name)
            elif t == OperatorType.REPLICATE and src_deg > 1:
                add("allgather", self_bytes, op.name)
            elif t == OperatorType.REPARTITION and src_spec is not None:
                # moving an axis between dims is an all-to-all reshard
                d = op.repartition_dim % len(op.output_shapes[0])
                entries = list(src_spec) + [None] * len(op.output_shapes[0])
                if op.axis in axis_sizes \
                        and any(op.axis in _entry_axes(e)
                                for i, e in enumerate(entries) if i != d):
                    add("reshard",
                        self_bytes / axis_sizes[op.axis], op.name)
            elif t == OperatorType.REDUCTION and src_deg > 1:
                add("allreduce", self_bytes, op.name)
        # ring attention: per-step K/V rotation over the seq axis
        if getattr(op, "seq_parallel", None) and axis_sizes.get("seq", 1) > 1:
            sp = axis_sizes["seq"]
            kv_bytes = sum(float(np.prod(s)) for s in op.input_shapes[1:3])
            add("ppermute", kv_bytes * elem / sp * (3 if training else 1),
                f"{op.name}:ring")
        # expert parallelism: token dispatch/combine all-to-all
        if getattr(op, "expert_parallel", None) \
                and axis_sizes.get("expert", 1) > 1:
            add("reshard", float(np.prod(op.output_shapes[0])) * elem,
                f"{op.name}:dispatch")
    # pipeline parallelism: every tick ppermutes the in-flight microbatch
    # activation one hop (backward: the returning gradient too); the
    # sharded microbatch queue adds the input/output streams
    if pp > 1 and pb is not None:
        last = ctx.nodes[pb.blocks[-1][-1]]
        shp = last.op.output_shapes[pb.body_out[2]]
        M = int(getattr(executor, "microbatches", 0) or 2 * pp)
        k = max(1, pb.num_blocks // pp)
        rounds = k if getattr(executor, "schedule", "gpipe") == "circular" \
            else 1
        ticks = rounds * M + pp - 1
        qshard = bool(getattr(executor, "shard_queue", False)) \
            and M % pp == 0
        # byte width: the op's declared dtype, matching the priced side
        # (pipeline_meta_json ships block_out_bytes at op dtype into
        # simulate_pipeline's census record) — NOT the compute dtype,
        # which would diverge 2x under the bf16 regime
        hop = float(np.prod(shp)) * last.op.dtype.size / (M * data_deg)
        # sharded queue: 3 streams per tick + the pp-1 output-drain hops
        # (must match simulate_pipeline's census record, or the
        # priced-vs-inferred drift gate reports a permanent discrepancy)
        hops = ticks * (3.0 if qshard else 1.0) + (pp - 1 if qshard else 0)
        add("ppermute", hops * hop * (2.0 if training else 1.0),
            "pipeline:hop")
    # implicit GSPMD reshards at producer→consumer spec disagreements:
    # the edge table is the general rule (explicit parallel-op
    # boundaries and pipe hops were already priced above; pure
    # additional slicing moves nothing)
    if edge_table is None:
        edge_table = edge_reshard_table(ctx)
    for e in edge_table:
        if e.explicit or e.kind == "slice":
            continue
        add(e.kind, e.bytes, f"{e.edge}:edge", edge=e)
    # tiny-batch weight movement, generalized: row-parallel
    # contractions whose per-chip row count fits one MXU tile resolve
    # by all-gathering the model-sharded weight
    if weight_moves is None:
        weight_moves = weight_movement_edges(ctx)
    for e in weight_moves:
        add(e.kind, e.bytes, f"{e.producer}:weight-move", edge=e)
    return out


def _dim0(spec):
    if spec is None:
        return None
    entries = tuple(spec)
    return entries[0] if entries else None


def _producer_spec(node, ctx):
    ref = node.input_refs[0] if node.input_refs else None
    if not ref or ref[0] != "op":
        return None
    prod = ctx.by_guid.get(ref[1])
    if prod is None:
        return None
    specs = getattr(prod, "output_specs", None)
    if specs is None:
        st = ctx.strategy.get(ref[1])
        specs = st.output_specs if st is not None else None
    return specs[ref[2]] if specs and ref[2] < len(specs) else None


class CollectiveInferencePass:
    name = "collective-inference"

    # Bucketed reduce-scatter note (ISSUE 9): under the comms-compute
    # overlap structuring the ONE per-leaf grad reduce-scatter becomes N
    # size-targeted bucket collectives issued in reverse-backward order.
    # The inference above and the emitted census both aggregate BYTES per
    # kind, so N bucket collectives summing to the unbucketed payload
    # diff clean by construction (counts may differ; bytes must not) —
    # asserted by tests/test_overlap.py::TestFflint.

    # chosen-but-sync strategies whose priced collectives exceed this
    # share of the op's total time get the FFL207 INFO when a
    # latency-hiding '_ovl' twin was enumerated and rejected
    OVL_EXPOSED_SHARE = 0.2

    def _overlap_rejections(self, ctx) -> List[Diagnostic]:
        """FFL207 (INFO): the search enumerated a latency-hiding '_ovl'
        twin for an op, rejected it, and the chosen candidate still
        prices a large exposed-collective share — either the rejection
        is justified (tiny sync, launch overhead dominates) or the
        hiding window is underpriced; the search trace's overlap sweep
        says which."""
        ff = ctx.ff
        if ff is None or not isinstance(getattr(ff, "search_info", None),
                                        dict):
            return []
        ops = (ff.search_info.get("search_trace") or {}).get("ops") or []
        out: List[Diagnostic] = []
        for oj in ops:
            chosen_name = oj.get("chosen") or ""
            if "_ovl" in chosen_name:
                continue
            cands = oj.get("candidates") or []
            if not any("_ovl" in (c.get("choice") or "") for c in cands):
                continue  # no twin enumerated — nothing was rejected
            chosen = next((c for c in cands if c.get("chosen")), None)
            terms = (chosen or {}).get("terms") or {}
            total = terms.get("total_s") or 0.0
            coll = terms.get("collective_s") or 0.0
            if total > 0 and coll / total > self.OVL_EXPOSED_SHARE:
                out.append(info(
                    "FFL207",
                    f"'{chosen_name}' prices {coll / total:.0%} of op time "
                    f"as exposed collectives while a latency-hiding "
                    f"'_ovl' twin was enumerated but rejected",
                    op=oj.get("name"),
                    hint="read the search trace's overlap sweep for this "
                         "op — if the hiding window is underpriced the "
                         "search leaves comms-compute overlap unused"))
        return out

    def _kernel_choice_checks(self, ctx) -> List[Diagnostic]:
        """FFL208 (ERROR): a strategy's recorded ``_k:`` kernel choice
        is structurally illegal on the executing shape — the search
        priced a lowering decode cannot deliver (a stale strategy file,
        or a seq-bucket/graph edit after the search). FFL209 (INFO): the
        choice is shape-legal but THIS platform cannot run it (Pallas
        off / below the hardware take-over threshold) — the executor
        silently falls back, so the priced and the executed kernel
        differ. The same priced-vs-executed closure FFL207 gave the
        '_ovl' dimension."""
        from flexflow_tpu.ffconst import OperatorType
        from flexflow_tpu.ops.pallas_kernels import (BLK_Q, pallas_mode)
        from flexflow_tpu.search.unity import kernel_choice_of

        out: List[Diagnostic] = []
        fusable = None
        for node in ctx.nodes:
            ch = getattr(ctx.strategy.get(node.op.guid), "choice",
                         None) or ""
            impl = kernel_choice_of(ch)
            if impl is None:
                continue
            op = node.op
            if impl == "flash":
                if op.op_type != OperatorType.MULTIHEAD_ATTENTION:
                    out.append(error(
                        "FFL208",
                        f"'_k:flash' recorded on a non-attention op",
                        op=op.name, hint="re-search the strategy"))
                    continue
                seq = op.input_shapes[0][1]
                sk = (op.input_shapes[1][1]
                      if len(op.input_shapes) > 1 else seq)
                if sk != seq:
                    out.append(error(
                        "FFL208",
                        f"'_k:flash' recorded on cross-attention "
                        f"(Sq={seq} != Sk={sk}) — flash only lowers "
                        f"self-attention",
                        op=op.name,
                        hint="the graph changed since the search — "
                             "re-search the strategy"))
                    continue
                training = True
                if ctx.ff is not None and ctx.ff.executor is not None:
                    training = getattr(ctx.ff.executor, "comp_mode",
                                       CompMode.TRAINING) \
                        == CompMode.TRAINING
                if seq % BLK_Q or op.head_dim % 8:
                    out.append(error(
                        "FFL208",
                        f"'_k:flash' is illegal at this shape (seq={seq}"
                        f" % {BLK_Q} != 0 or head_dim={op.head_dim} % 8"
                        f" != 0) — the priced kernel cannot execute",
                        op=op.name,
                        hint="re-search (the flash gate rejects this "
                             "shape) or drop the stale strategy file"))
                elif training and getattr(op, "dropout", 0) > 0:
                    # mirrors the native gate's
                    # attention_prob_dropout_unsupported: the training
                    # forward can never take the flash branch
                    out.append(error(
                        "FFL208",
                        f"'_k:flash' recorded on an attention op with "
                        f"prob dropout ({op.dropout}) — the training "
                        f"forward has no flash lowering for it",
                        op=op.name,
                        hint="the dropout changed since the search — "
                             "re-search the strategy"))
                else:
                    from flexflow_tpu.ops.pallas_kernels import (
                        flash_attention_available)
                    if not flash_attention_available(seq, op.head_dim):
                        out.append(info(
                            "FFL209",
                            f"'_k:flash' was priced but this platform "
                            f"falls back to einsum (pallas mode "
                            f"'{pallas_mode()}', seq={seq}) — the "
                            f"executed kernel differs from the priced "
                            f"one",
                            op=op.name,
                            hint="set FLEXFLOW_TPU_PALLAS=interpret "
                                 "(tests) or run on TPU; predictions "
                                 "for this op are optimistic meanwhile"))
            elif impl == "conv_bn_fused":
                if fusable is None:
                    from flexflow_tpu.layout import train_fusable_conv_guids
                    # same keep_guids as the executor's fuse_conv_bn_train:
                    # the check must agree with what EXECUTES
                    keep = ()
                    if ctx.ff is not None and ctx.ff.executor is not None:
                        keep = {ctx.ff.executor.final_ref[0]}
                    fusable = train_fusable_conv_guids(ctx.nodes,
                                                      keep_guids=keep)
                if op.guid not in fusable:
                    out.append(error(
                        "FFL208",
                        "'_k:conv_bn_fused' recorded but the conv no "
                        "longer has a foldable BatchNorm sole consumer",
                        op=op.name,
                        hint="the graph changed since the search — "
                             "re-search the strategy"))
            elif impl == "fused":
                ex = ctx.ff.executor if ctx.ff is not None else None
                if ex is not None and op.name not in (
                        getattr(ex, "fused_update_ops", None) or ()):
                    out.append(info(
                        "FFL209",
                        "'_k:fused' was priced but the executor is not "
                        "routing this op's update through the fused "
                        "region (kernel search disabled at compile?)",
                        op=op.name,
                        hint="compile with --kernel-search auto so the "
                             "executed update matches the priced one"))
        # runtime-recorded silent fallbacks (the executor sets
        # _kernel_fallback the first time a forced impl cannot run)
        for node in ctx.nodes:
            fb = getattr(node.op, "_kernel_fallback", None)
            if fb:
                out.append(info(
                    "FFL209", f"executor fell back: {fb}",
                    op=node.op.name,
                    hint="the priced kernel never ran — simulated "
                         "predictions for this op are optimistic"))
        return out

    # replicated outputs below this are cheap enough to materialize
    # everywhere without comment (FFL212)
    REPLICATED_MAT_BYTES = float(1 << 16)

    def _redundant_pairs(self, ctx, implicit) -> List[Diagnostic]:
        """FFL211 (WARNING): two implicit reshards on one chain whose
        specs compose to a round trip — the tensor is resharded into an
        intermediate layout and straight back out, so either the
        interior op's spec is wrong or the pair should cancel."""
        out: List[Diagnostic] = []
        by_consumer: Dict[int, list] = {}
        for e in implicit:
            if e.in_idx >= 0:
                by_consumer.setdefault(e.consumer_guid, []).append(e)
        for e2 in implicit:
            if e2.in_idx < 0:
                continue
            for e1 in by_consumer.get(e2.producer_guid, ()):
                if e1.src_spec == e2.dst_spec \
                        and e1.dst_spec == e2.src_spec:
                    out.append(warning(
                        "FFL211",
                        f"redundant reshard pair: '{e1.edge}' then "
                        f"'{e2.edge}' compose to a round trip "
                        f"({e1.bytes / 1e6:.2f} + {e2.bytes / 1e6:.2f} "
                        f"MB moved to end where it started)",
                        op=e1.consumer, tensor=f"out[{e2.out_idx}]",
                        hint=f"give '{e1.consumer}' the producer's "
                             f"layout (or let it follow) so neither "
                             f"reshard is needed"))
        return out

    def _replicated_materializations(self, ctx, table) -> List[Diagnostic]:
        """FFL212 (WARNING): a large compute-op output materialized
        fully replicated although every consumer immediately shards it
        — the op burns replicated FLOPs and memory to produce data
        each device then throws most of away; shard at the producer."""
        out: List[Diagnostic] = []
        try:
            cons = ctx.consumers()
        except Exception:
            cons = None
        elem = 4.0
        if ctx.ff is not None and ctx.ff.executor is not None:
            elem = float(np.dtype(ctx.ff.executor.compute_dtype).itemsize)
        by_out: Dict[tuple, list] = {}
        for e in table:
            if e.in_idx >= 0:
                by_out.setdefault((e.producer_guid, e.out_idx),
                                  []).append(e)
        for (guid, idx), edges in sorted(by_out.items()):
            if not all(e.kind == "slice" and not e.explicit
                       for e in edges):
                continue
            if any(x is not None for x in edges[0].src_spec):
                continue  # producer output is sharded already
            node = ctx.by_guid.get(guid)
            if node is None or getattr(node.op, "is_parallel_op", False):
                continue
            if node.op.op_type in (OperatorType.NOOP, OperatorType.CONST):
                continue
            gbytes = float(np.prod(node.op.output_shapes[idx])) * elem
            if gbytes < self.REPLICATED_MAT_BYTES:
                continue
            if cons is not None \
                    and len(edges) < len(cons.get((guid, idx), ())):
                continue  # some consumer really wants it replicated
            names = ", ".join(sorted({e.consumer for e in edges})[:4])
            out.append(warning(
                "FFL212",
                f"'{node.op.name}' materializes out[{idx}] "
                f"({gbytes / 1e6:.2f} MB) replicated but every consumer "
                f"({names}) shards it",
                op=node.op.name, tensor=f"out[{idx}]",
                hint="shard the producer's output spec to the "
                     "consumers' layout — replicated compute and "
                     "memory are being thrown away"))
        return out

    def _rewrite_verification(self, ctx) -> List[Diagnostic]:
        """FFL213 (ERROR): graph_optimize accepted a substitution
        rewrite whose post-rewrite edge-spec map implies MORE implicit
        collective bytes than the pre-rewrite map — the rewrite won on
        the simulator's op-local terms while opening a reshard seam the
        static dataflow can see (dataflow.verify_rewrite_dataflow,
        recorded in search_info['rewrite_verification'])."""
        ff = ctx.ff
        if ff is None or not isinstance(getattr(ff, "search_info", None),
                                        dict):
            return []
        rv = ff.search_info.get("rewrite_verification")
        if not rv or rv.get("ok", True):
            return []
        out: List[Diagnostic] = []
        for f in rv.get("findings", ()):
            where = f" (worst edge '{f['edge']}', {f['src_spec']} -> " \
                    f"{f['dst_spec']})" if f.get("edge") else ""
            out.append(error(
                "FFL213",
                f"accepted rewrite regressed the edge-reshard map: "
                f"implicit {f['kind']} bytes "
                f"{f['pre_bytes'] / 1e6:.2f} -> "
                f"{f['post_bytes'] / 1e6:.2f} MB{where}",
                hint="the substitution won on op-local simulated terms "
                     "but introduced a reshard seam — reject the "
                     "rewrite or re-search with it pinned off"))
        return out

    def run(self, ctx) -> List[Diagnostic]:
        diags: List[Diagnostic] = []
        diags.extend(self._overlap_rejections(ctx))
        diags.extend(self._kernel_choice_checks(ctx))
        diags.extend(self._rewrite_verification(ctx))
        table = edge_reshard_table(ctx)
        wmoves = weight_movement_edges(ctx)
        inferred = infer_strategy_collectives(ctx, edge_table=table,
                                              weight_moves=wmoves)
        priced: Optional[Dict[str, float]] = None
        try:
            priced = ctx.ensure_priced()
        except NotImplementedError as e:
            diags.append(info(
                "FFL206", f"priced-side diff skipped: {e}",
                hint="pipeline strategies cannot be replayed through "
                     "the simulator yet"))
        except Exception as e:
            diags.append(warning(
                "FFL206", f"simulator replay failed: {e!r}",
                hint="the priced-vs-inferred diff did not run — fix the "
                     "replay before trusting this strategy's prediction"))
        emitted = ctx.ensure_emitted()

        # edge-level rules: every implicit producer→consumer reshard
        # must be PRICED (searched or replayed) — an edge cost nothing
        # accounted for means the strategy was ranked blind to it
        implicit = [e for e in table
                    if not e.explicit and e.kind in ("allgather",
                                                     "reshard")
                    and e.bytes >= _MIN_BYTES]
        implicit += [e for e in wmoves if e.bytes >= _MIN_BYTES]
        if priced is not None:
            for e in implicit:
                pb = sum(priced.get(k, 0.0)
                         for k in _COVER.get(e.kind, {e.kind}))
                if pb <= 0:
                    diags.append(error(
                        "FFL210",
                        f"unpriced edge reshard: '{e.edge}' "
                        f"({_fmt_spec(e.src_spec)} -> "
                        f"{_fmt_spec(e.dst_spec)}) implies a "
                        f"{e.kind} of {e.bytes / 1e6:.2f} MB over "
                        f"{list(e.axes)} ({e.fabric}) the simulator "
                        f"priced zero bytes for",
                        op=e.consumer, tensor=f"in[{e.in_idx}]"
                        if e.in_idx >= 0 else "param[kernel]",
                        hint="the native cost model replayed this "
                             "strategy without charging the edge — its "
                             "ranking is unreliable here"))
        elif not getattr(ctx, "searched", False):
            # no replay and no search: nothing has EVER priced these
            # edges — the exact failure mode FFL205 exists for, now
            # named per edge instead of guessed from the HLO census
            for e in implicit:
                diags.append(error(
                    "FFL205",
                    f"implicit edge reshard nothing prices: '{e.edge}' "
                    f"({_fmt_spec(e.src_spec)} -> "
                    f"{_fmt_spec(e.dst_spec)}) implies a {e.kind} of "
                    f"{e.bytes / 1e6:.2f} MB over {list(e.axes)} "
                    f"({e.fabric})",
                    op=e.consumer, tensor=f"in[{e.in_idx}]"
                    if e.in_idx >= 0 else "param[kernel]",
                    hint="GSPMD will insert this collective at the "
                         "spec seam — search the strategy (or price "
                         "it via the simulator) before trusting any "
                         "prediction for this model"))
        diags.extend(self._redundant_pairs(ctx, implicit))
        diags.extend(self._replicated_materializations(ctx, table))

        if priced is not None:
            # inferred kind the simulator never charged: the search
            # compared strategies blind to a cost this one provably has
            for kind, entry in inferred.items():
                pb = sum(priced.get(k, 0.0)
                         for k in _COVER.get(kind, {kind}))
                if pb <= 0:
                    srcs = ", ".join(entry["sources"][:4])
                    diags.append(error(
                        "FFL204",
                        f"strategy implies {kind} "
                        f"({entry['bytes'] / 1e6:.2f} MB from {srcs}) but "
                        f"the simulator priced none",
                        hint="the native cost model is blind to this "
                             "collective — its strategy ranking is "
                             "unreliable here"))
        if emitted is not None and priced is not None:
            from flexflow_tpu.search.validate import diff_collectives
            for problem in diff_collectives(priced, emitted):
                if "priced none" in problem:
                    diags.append(error(
                        "FFL201", f"unpriced collective: {problem}",
                        hint="GSPMD inserted data movement the search "
                             "never costed — the predicted iteration "
                             "time is an undercount"))
                elif "emitted none" in problem:
                    diags.append(warning(
                        "FFL203", f"phantom priced collective: {problem}",
                        hint="the simulator charges for movement XLA "
                             "optimized away — predictions overcount"))
                else:
                    diags.append(warning(
                        "FFL202", f"collective byte drift: {problem}",
                        hint="priced and emitted payloads disagree "
                             "beyond tolerance — recalibrate "
                             "(scripts/calibrate.py)"))
        elif emitted is not None:
            # no simulator: the static inference (node terms + the
            # edge table) is the only priced-side proxy; an emitted
            # kind it cannot explain means GSPMD inserted movement the
            # dataflow never derived — since edge-level inference that
            # is an ERROR, not a shrug
            for kind, eb in emitted.items():
                ib = sum(inferred.get(k, {}).get("bytes", 0.0)
                         for k in _COVER.get(kind, {kind}))
                if ib <= 0:
                    diags.append(error(
                        "FFL205",
                        f"emitted {kind} ({eb / 1e6:.2f} MB) matches no "
                        f"statically-inferred collective (node terms or "
                        f"edge reshards)",
                        hint="the edge-level dataflow cannot explain "
                             "this movement — a transfer rule is "
                             "missing or the strategy file is stale"))
        return diags


def _fmt_spec(entries) -> str:
    from flexflow_tpu.analysis.dataflow import _spec_str
    return _spec_str(entries)

"""dtype-policy: the bf16 master-weight regime's f32 islands.

Under mixed precision the executor feeds every op bf16 working copies
of the parameters and bf16 activations; the regime is only numerically
safe because specific computations deliberately upcast: normalization
statistics (a bf16 variance loses most of its mantissa), loss math, and
metric accumulation. This pass verifies those islands statically by
abstractly tracing each norm-family op with bf16 inputs/params and
inspecting the jaxpr — no device work, no concrete arrays:

* FFL401  a norm op (BatchNorm/GroupNorm/LayerNorm/RMSNorm) accumulates
          a statistics reduction in a 16-bit dtype (a reduce-sum with a
          16-bit output in its traced forward — ``jnp.mean``/``var``
          upcast their accumulator automatically, so this only fires on
          genuinely bf16-accumulated reductions: manual lax reductions
          and explicit ``dtype=bfloat16`` sums);
* FFL402  a norm's statistics VALUES are 16-bit where they are applied
          or stored (new-state leaves non-f32) — the EMA accumulates
          rounding step after step and the normalize subtracts a mean
          that lost 2^-8 of relative precision;
* FFL403  loss/metric accumulation poisoned at the graph level: an
          explicit CAST to a 16-bit dtype feeds the designated model
          output (the loss would compute on truncated logits) or a
          large reduction (low-precision accumulation).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from flexflow_tpu.analysis.diagnostics import Diagnostic, error, warning
from flexflow_tpu.ffconst import DataType, OperatorType

_NORM_OPS = {OperatorType.BATCHNORM, OperatorType.GROUPNORM,
             OperatorType.LAYERNORM, OperatorType.RMSNORM}
_LOW_PRECISION = {DataType.HALF, DataType.BFLOAT16}
_REDUCE_OPS = {OperatorType.REDUCE_SUM, OperatorType.MEAN}
# reductions this small are epilogue math, not accumulation
_MIN_REDUCED_ELEMS = 1024


def _bf16_struct(shape):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(tuple(shape), jnp.bfloat16)


def _trace_norm(op):
    """Abstractly trace the op's forward under the bf16 regime. Returns
    (bad_reduce, new_state_dtypes) — bad_reduce is True when a
    reduction in the traced computation accumulates in a 16-bit float
    (a reduce-sum whose output aval is bf16/f16), new_state_dtypes maps
    state keys to result dtypes for stateful ops (None otherwise)."""
    import jax
    import jax.numpy as jnp

    params = jax.eval_shape(op.init_params, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda s: _bf16_struct(s.shape)
        if jnp.issubdtype(s.dtype, jnp.floating) else s, params)
    state = op.init_state() if hasattr(op, "init_state") else None
    shp = op.input_shapes[0]
    if getattr(op, "exec_layout", "NCHW") == "NHWC" and len(shp) == 4:
        shp = tuple(shp[d] for d in (0, 2, 3, 1))
    x = _bf16_struct(shp)

    from flexflow_tpu.ops.base import OpContext

    def run(p, s, xx):
        ctx = OpContext(training=True, compute_dtype=jnp.bfloat16)
        if s is not None:
            outs = op.forward(p, [xx], ctx, state=s)
        else:
            outs = op.forward(p, [xx], ctx)
        ns = getattr(op, "_new_state", None)
        op._new_state = None  # never leak tracers into the executor
        return outs, ns

    try:
        jaxpr = jax.make_jaxpr(run)(params, state, x)
    finally:
        op._new_state = None
    bad_reduce = False
    low = (jnp.bfloat16, jnp.float16)
    # additive reductions only: max/min/and/or reductions are exact in
    # any dtype, and jnp.mean/var/sum force an f32 accumulator for
    # 16-bit inputs — so a 16-bit additive reduce here means raw
    # lax.reduce/lax.reduce_sum accumulation, the genuinely lossy case
    _exact = ("reduce_max", "reduce_min", "reduce_or", "reduce_and",
              "reduce_precision", "reduce_window")
    for eqn in jaxpr.jaxpr.eqns:
        name = eqn.primitive.name
        if not name.startswith("reduce") or name.startswith(_exact):
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and dt in low:
                bad_reduce = True
    _, ns_shape = jax.eval_shape(run, params, state, x)
    ns_dtypes = None
    if ns_shape is not None:
        ns_dtypes = {k: v.dtype for k, v in ns_shape.items()}
    return bad_reduce, ns_dtypes


class DtypePolicyPass:
    name = "dtype-policy"

    def run(self, ctx) -> List[Diagnostic]:
        diags: List[Diagnostic] = []
        seen: Dict = {}
        for node in ctx.nodes:
            op = node.op
            if op.op_type in _NORM_OPS:
                key = op.param_key()
                if key in seen:
                    verdict = seen[key]
                else:
                    try:
                        verdict = _trace_norm(op)
                    except Exception:
                        verdict = None  # untraceable: covered by runtime
                    seen[key] = verdict
                if verdict is None:
                    continue
                bad_reduce, ns_dtypes = verdict
                if bad_reduce:
                    diags.append(error(
                        "FFL401",
                        f"{op.op_type.name} accumulates a statistics "
                        f"reduction in a 16-bit dtype",
                        op=op.name, guid=op.guid,
                        hint="upcast before the mean/var reduction "
                             "(x.astype(f32)); a bf16 accumulator loses "
                             "most of its mantissa"))
                import jax.numpy as jnp
                for k, dt in (ns_dtypes or {}).items():
                    if jnp.issubdtype(dt, jnp.floating) \
                            and dt != jnp.float32:
                        diags.append(error(
                            "FFL402",
                            f"running statistic {k!r} accumulates in "
                            f"{jnp.dtype(dt).name}",
                            op=op.name, guid=op.guid, tensor=k,
                            hint="EMA state must stay f32 — per-step "
                                 "rounding compounds over training"))
            diags.extend(self._cast_audit(node, ctx))
        return diags

    # ---- FFL403 ------------------------------------------------------------
    def _cast_audit(self, node, ctx) -> List[Diagnostic]:
        op = node.op
        if op.op_type != OperatorType.CAST \
                or op.dtype not in _LOW_PRECISION:
            return []
        diags: List[Diagnostic] = []
        if ctx.final_ref is not None and op.guid == ctx.final_ref[0]:
            diags.append(error(
                "FFL403",
                f"designated model output is a cast to {op.dtype.value} "
                f"— loss/metrics would compute on truncated logits",
                op=op.name, guid=op.guid,
                hint="the loss path upcasts internally but a 16-bit "
                     "output has already lost the mantissa; drop the "
                     "cast or move it off the loss path"))
        for cnode, _ in ctx.consumers().get((op.guid, 0), []):
            if cnode.op.op_type in _REDUCE_OPS:
                axes = cnode.op.layer.get_property("axes", ())
                shp = cnode.op.input_shapes[0]
                reduced = int(np.prod(
                    [shp[a % len(shp)] for a in axes])) if axes else 1
                if reduced >= _MIN_REDUCED_ELEMS:
                    diags.append(warning(
                        "FFL403",
                        f"{cnode.op.op_type.name} accumulates "
                        f"{reduced} elements in {op.dtype.value}",
                        op=cnode.op.name, guid=cnode.op.guid,
                        hint="sum in f32 and cast after — bf16 "
                             "accumulation plateaus once the running "
                             "sum dwarfs the addend"))
        return diags

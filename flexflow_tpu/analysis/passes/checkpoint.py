"""checkpoint-integrity: will this run's checkpoint actually restore?

Audits the configured checkpoint directory (``--checkpoint-dir``)
against the COMPILED model before training commits to it — the failure
modes that otherwise only surface hours later, at restore time on a
degraded fleet:

* FFL801  the directory holds step directories but NO complete
          (manifest-committed) checkpoint — every save so far died
          before its commit record, so a preemption now loses the run;
* FFL802  the newest complete checkpoint fails deep verification
          (missing shard files, checksum mismatches, shard boxes that
          do not tile a leaf) — on-disk corruption a resume would
          refuse;
* FFL803  the checkpoint's saved state tree is incompatible with the
          live model (leaf missing / extra / global-shape mismatch) —
          the graph changed since the save and resume will raise;
* FFL804  (INFO) the checkpoint was taken on a different mesh — legal,
          the elastic re-shard path engages on load; stated so a
          reviewer knows resume will re-place every shard.

Skips (not "clean") when no checkpoint directory is configured or the
directory is still empty (a fresh launch). The byte-level FFL802
re-read is gated to checkpoints up to ``DEEP_VERIFY_MAX_BYTES``
(256 MB): the lint pipeline runs at compile/startup time, and
re-checksumming a multi-GB checkpoint there would cost minutes of
blocking I/O — above the gate the pass checks structure only
(manifest/index presence, shard-key existence, coverage arithmetic)
and ``scripts/ckpt_inspect.py`` remains the offline home of the full
rot scan.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from flexflow_tpu.analysis.diagnostics import Diagnostic, error, info


DEEP_VERIFY_MAX_BYTES = 256 << 20


class CheckpointIntegrityPass:
    name = "checkpoint-integrity"

    def run(self, ctx) -> List[Diagnostic]:
        from flexflow_tpu.analysis.orchestrator import SkipPass

        cdir = getattr(ctx.config, "checkpoint_dir", None) \
            if ctx.config is not None else None
        if not cdir:
            raise SkipPass("no checkpoint directory configured "
                           "(--checkpoint-dir)")
        from flexflow_tpu.ckpt import manifest as mf
        steps = mf.list_steps(cdir)
        if not steps:
            raise SkipPass(f"checkpoint directory '{cdir}' holds no "
                           f"checkpoints yet (fresh launch)")
        diags: List[Diagnostic] = []
        complete = [(s, p) for s, p, ok in steps if ok]
        if not complete:
            diags.append(error(
                "FFL801",
                f"checkpoint directory '{cdir}' holds "
                f"{len(steps)} step director{'ies' if len(steps) != 1 else 'y'} "
                f"but not one complete checkpoint — every save died before "
                f"its manifest commit",
                hint="check the writer logs (fs barrier timeouts point at "
                     "a non-shared filesystem); a preemption now would "
                     "lose the run"))
            return diags
        step, step_dir = complete[-1]
        rep = mf.verify_step_dir(step_dir, deep=False)
        if not rep["errors"] and rep["payload_bytes"] <= DEEP_VERIFY_MAX_BYTES:
            rep = mf.verify_step_dir(step_dir, deep=True)
        for msg in rep["errors"]:
            diags.append(error(
                "FFL802",
                f"checkpoint step {step}: {msg}",
                hint="scripts/ckpt_inspect.py shows the full report; "
                     "restore refuses corrupt checkpoints, so fix or GC "
                     "this one"))
        manifest = rep["manifest"] or {}
        diags.extend(self._tree_compat(ctx, manifest, step))
        mesh_saved = {k: int(v)
                      for k, v in (manifest.get("mesh") or {}).items()}
        mesh_live = dict(ctx.axis_sizes)
        if mesh_saved and mesh_saved != mesh_live:
            diags.append(info(
                "FFL804",
                f"checkpoint step {step} was saved on mesh {mesh_saved}; "
                f"the live mesh is {mesh_live} — elastic resume will "
                f"reassemble every leaf from the shard index and re-place "
                f"it onto the live strategy's shardings",
                hint="expected after a topology change; the recorded "
                     "strategy is only reusable verbatim on the saved "
                     "mesh (ckpt/elastic.plan_resume)"))
        return diags

    def _tree_compat(self, ctx, manifest: Dict[str, Any],
                     step: int) -> List[Diagnostic]:
        """Diff the manifest's params subtree against the LIVE params
        tree (global shapes) — the structure restore will demand."""
        ff = ctx.ff
        if ff is None or not manifest.get("leaves"):
            return []
        from flexflow_tpu.ckpt.tree import flatten_tree
        live = {f"params/{k}": tuple(int(d) for d in v.shape)
                for k, v in flatten_tree(ff.params)
                if hasattr(v, "shape")}
        saved = {k: tuple(int(d) for d in meta["shape"])
                 for k, meta in manifest["leaves"].items()
                 if k.startswith("params/")}
        out: List[Diagnostic] = []
        for k in sorted(set(live) | set(saved)):
            op = k.split("/")[1] if "/" in k else None
            if k not in saved:
                out.append(error(
                    "FFL803",
                    f"checkpoint step {step} has no leaf '{k}' the live "
                    f"model requires — the graph changed since the save "
                    f"and resume will fail",
                    op=op, tensor=k,
                    hint="restore into the model architecture that "
                         "saved, or start fresh"))
            elif k not in live:
                out.append(error(
                    "FFL803",
                    f"checkpoint step {step} carries leaf '{k}' the live "
                    f"model does not own — structure mismatch at resume",
                    op=op, tensor=k,
                    hint="restore into the model architecture that "
                         "saved, or start fresh"))
            elif saved[k] != live[k]:
                out.append(error(
                    "FFL803",
                    f"checkpoint step {step} leaf '{k}' has global shape "
                    f"{list(saved[k])} but the live model expects "
                    f"{list(live[k])}",
                    op=op, tensor=k,
                    hint="parameter shapes must match across resume "
                         "(shardings may differ; shapes may not)"))
        return out

"""Edge-level sharding dataflow: per-edge reshard inference.

The collective-inference pass (passes/collectives.py) historically
inferred collectives per *kind* from node-local strategy entries, so an
implicit GSPMD reshard at a producer→consumer spec disagreement was only
a heuristic FFL205 WARNING and the native simulator's replay stayed the
arbiter. This module is the static arbiter: an abstract interpretation
over the materialized PCG that

1. derives, per op, the PartitionSpec each INPUT must arrive in given
   the op's chosen output/param specs (``required_input_specs`` — the
   Python mirror of the native ``Choice.in`` vectors,
   native/ffs_strategy.hpp enumerate_choices);
2. diffs that requirement against the producer's output spec on every
   producer→consumer edge and classifies the disagreement into the
   exact collective GSPMD must insert (``classify_transition`` — the
   set-logic mirror of native ``reshard_cost``: src ⊆ dst is a free
   local slice, dst ⊆ src is an all-gather, mixed is an all-to-all
   reshard), with per-device payload bytes (census convention), the
   mesh axes communicated over, and the fabric (``ici`` within a
   slice, ``dcn`` when the ``slice`` axis moves);
3. exposes the result as a per-edge ``EdgeReshard`` table
   (``edge_reshard_table``) the collective-inference pass, the fflint
   CLI (``--edges``), and explain.py all read.

The weight-movement rule (``weight_movement_edges``) generalizes the
tiny-batch special case the native row-parallel Linear/Conv choices
price (ffs_strategy.hpp tiny_batch_weight_movement): a row-parallel
contraction with fewer MXU rows per chip than one tile edge resolves by
moving the WEIGHT — an all-gather of the model-sharded kernel — which
the static inference now derives from the spec + shape alone instead of
leaving to a per-op special case.

``verify_rewrite_dataflow`` is the substitution-engine hook: after
``graph_optimize`` accepts a rewrite, the post-rewrite edge-spec map
must be collective-equivalent-or-cheaper than the pre-rewrite map —
a rewrite that introduces a reshard seam the DP's local pricing missed
is an FFL213 ERROR, caught statically, before anything compiles.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole

# sentinel: "this input accepts any layout" (unmodeled op class) — NOT
# the same as an all-None spec, which is a hard replication requirement
ANY = object()

# mesh axes that carry batch replicas (grad-sync rings) — matches
# passes/collectives.py
_DATA_AXES = ("data", "replica")

# activation payloads below this are scalar-ish and never priced —
# matches passes/collectives._MIN_BYTES and the simulator
MIN_EDGE_BYTES = float(1 << 12)

# the MXU tile edge the tiny-batch weight-movement rule keys on
# (native/ffs_strategy.hpp uses the same 128-row threshold)
_MXU_ROWS = 128.0

# shape-preserving same-rank ops whose inputs must arrive in the op's
# own output layout (the native rep/dp choices carry identical in/out
# specs for these)
_SAME_RANK_FOLLOW = frozenset({
    OperatorType.RELU, OperatorType.GELU, OperatorType.SIGMOID,
    OperatorType.TANH, OperatorType.ELU, OperatorType.EXP,
    OperatorType.SIN, OperatorType.COS, OperatorType.POW,
    OperatorType.RSQRT, OperatorType.IDENTITY, OperatorType.LOG,
    OperatorType.SCALAR_MULTIPLY, OperatorType.SCALAR_ADD,
    OperatorType.SCALAR_SUB, OperatorType.SCALAR_TRUE_DIV,
    OperatorType.DROPOUT, OperatorType.CAST, OperatorType.SOFTMAX,
    OperatorType.LAYERNORM, OperatorType.RMSNORM, OperatorType.BATCHNORM,
    OperatorType.GROUPNORM, OperatorType.POOL2D, OperatorType.REVERSE,
    OperatorType.EW_ADD, OperatorType.EW_SUB, OperatorType.EW_MUL,
    OperatorType.EW_DIV, OperatorType.EW_MAX, OperatorType.EW_MIN,
    OperatorType.WHERE,
})


@dataclasses.dataclass
class EdgeReshard:
    """One producer→consumer edge whose specs disagree.

    ``kind``: ``allgather`` | ``reshard`` | ``ppermute`` (pipe hop) |
    ``slice`` (pure additional slicing — free locally, recorded for the
    FFL212 replicated-materialization rule). ``bytes`` follow the census
    convention (per-device payload at compute dtype). ``explicit`` edges
    terminate at a parallel op whose boundary IS the reshard — the
    node-level inference prices those; implicit edges are the GSPMD
    insertions this module exists to catch."""

    producer: str
    producer_guid: int
    out_idx: int
    consumer: str
    consumer_guid: int
    in_idx: int
    src_spec: Tuple
    dst_spec: Tuple
    kind: str
    bytes: float
    axes: Tuple[str, ...]
    fabric: str
    explicit: bool = False
    reason: str = ""

    @property
    def edge(self) -> str:
        return (f"{self.producer}.out[{self.out_idx}] -> "
                f"{self.consumer}.in[{self.in_idx}]")

    def to_json(self) -> Dict[str, Any]:
        return dict(
            edge=self.edge, producer=self.producer, out_idx=self.out_idx,
            consumer=self.consumer, in_idx=self.in_idx,
            src_spec=_spec_str(self.src_spec),
            dst_spec=_spec_str(self.dst_spec),
            kind=self.kind, bytes=self.bytes, axes=list(self.axes),
            fabric=self.fabric, explicit=self.explicit, reason=self.reason)


# ---- spec algebra ----------------------------------------------------------

def _norm(spec, rank: int) -> Tuple:
    """PartitionSpec | tuple | None -> entry tuple of length ``rank``."""
    if spec is None:
        return (None,) * rank
    entries = list(spec)
    return tuple((entries + [None] * rank)[:rank])


def _spec_str(entries: Tuple) -> str:
    if not any(e is not None for e in entries):
        return "replicated"
    return "(" + ", ".join(
        "+".join(e) if isinstance(e, tuple) else (e or "·")
        for e in entries) + ")"


def _entry_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def _pairs(entries: Tuple) -> set:
    """(dim, axis) pair set; tuple entries (the ('slice','data') prefix
    or 2-D sample partitions) expand into their base axes so
    data ⊂ slice+data reads as pure additional slicing — the Python
    mirror of the native kDataModel expansion in reshard_cost."""
    out = set()
    for d, entry in enumerate(entries):
        for ax in _entry_axes(entry):
            out.add((d, ax))
    return out


def spec_degree(entries: Tuple, axis_sizes: Dict[str, int]) -> int:
    deg = 1
    for _, ax in _pairs(entries):
        deg *= axis_sizes.get(ax, 1)
    return deg


def classify_transition(src: Tuple, dst: Tuple, shape: Tuple[int, ...],
                        axis_sizes: Dict[str, int], elem: float = 4.0
                        ) -> Optional[Dict[str, Any]]:
    """The collective a src→dst layout change implies, or None when the
    tensor moves nowhere (specs agree, or both are effectively
    unsharded on this mesh). Mirrors native reshard_cost:

    * src ⊆ dst — pure additional slicing, local (kind ``slice``,
      0 bytes; recorded so FFL212 can see replicated materializations);
    * dst ⊆ src — all-gather: every device ends with its dst shard,
      payload = global / deg(dst) per device;
    * mixed — all-to-all reshard within the communicating group,
      payload = the larger shard that moves.
    """
    sa, sb = _pairs(src), _pairs(dst)
    # drop axes of size 1 (or absent): sharding over them moves nothing
    sa = {p for p in sa if axis_sizes.get(p[1], 1) > 1}
    sb = {p for p in sb if axis_sizes.get(p[1], 1) > 1}
    if sa == sb:
        return None
    ka = spec_degree(src, axis_sizes)
    kb = spec_degree(dst, axis_sizes)
    if ka <= 1 and kb <= 1:
        return None
    global_bytes = float(np.prod(shape)) * elem if shape else 0.0
    moved = sorted({ax for _, ax in sa.symmetric_difference(sb)})
    fabric = "dcn" if "slice" in moved else "ici"
    if sa <= sb:
        return dict(kind="slice", bytes=0.0, axes=tuple(moved),
                    fabric=fabric)
    if sb <= sa:
        return dict(kind="allgather", bytes=global_bytes / max(1, kb),
                    axes=tuple(moved), fabric=fabric)
    return dict(kind="reshard", bytes=global_bytes / max(1, ka, kb),
                axes=tuple(moved), fabric=fabric)


# ---- per-op transfer rules -------------------------------------------------

def _copy_matching(out_entries: Tuple, out_shape: Tuple[int, ...],
                   in_shape: Tuple[int, ...]) -> Tuple:
    """Same-rank spec transfer: copy the output entry onto every input
    dim with the same extent (a dim whose extent changed — pooled H/W,
    the concat axis — cannot inherit the sharding)."""
    if len(in_shape) != len(out_shape):
        # broadcast input: only a leading batch dim can follow
        if in_shape and out_shape and in_shape[0] == out_shape[0]:
            return (out_entries[0],) + (None,) * (len(in_shape) - 1)
        return (None,) * len(in_shape)
    return tuple(e if in_shape[d] == out_shape[d] else None
                 for d, e in enumerate(out_entries))


def _reshape_transfer(out_entries: Tuple, out_shape: Tuple[int, ...],
                      in_shape: Tuple[int, ...]) -> Tuple:
    """Axis-mapping through a reshape/flat: factor both shapes into
    aligned groups by prefix products; a sharded output dim transfers to
    the input dim that OPENS its group (the outermost factor — the only
    placement a sharded reshape keeps local). Anything murkier drops to
    replicated, which errs toward inferring a gather (a lower bound must
    not invent freedom GSPMD does not have)."""
    req = [None] * len(in_shape)
    i = j = 0
    while i < len(in_shape) and j < len(out_shape):
        gi, gj = [i], [j]
        pi, pj = in_shape[i], out_shape[j]
        while pi != pj:
            if pi < pj and len(gi) + gi[0] < len(in_shape):
                gi.append(gi[0] + len(gi))
                pi *= in_shape[gi[-1]]
            elif pj < pi and len(gj) + gj[0] < len(out_shape):
                gj.append(gj[0] + len(gj))
                pj *= out_shape[gj[-1]]
            else:
                return tuple(req)  # shapes don't factor — give up
        # the group's leading output entry maps to the leading input dim
        # when the sharded extent survives (same leading extent, or the
        # input leading dim is divisible by the sharding — conservative:
        # require equal leading extents for a transfer)
        lead = out_entries[gj[0]]
        if lead is not None and in_shape[gi[0]] == out_shape[gj[0]]:
            req[gi[0]] = lead
        i, j = gi[-1] + 1, gj[-1] + 1
    return tuple(req)


def required_input_specs(node, getspec, getparam) -> List[Any]:
    """Per-input required layout of ``node`` given its chosen specs —
    the Python mirror of the native ``Choice.in`` vectors. ``getspec``
    maps (node) -> normalized output entry tuple for output 0;
    ``getparam`` maps (node, name) -> param spec or None. Returns one
    entry per input: a normalized entry tuple, or ``ANY`` when the op
    class is unmodeled (accepts whatever arrives — no edge inferred)."""
    op = node.op
    t = op.op_type
    in_shapes = op.input_shapes
    out_shape = op.output_shapes[0] if op.output_shapes else ()
    out0 = getspec(node)

    if getattr(op, "is_parallel_op", False):
        # the boundary IS the reshard: inputs arrive however the
        # producer left them; the node-level inference prices it
        return [ANY] * len(in_shapes)

    if t in _SAME_RANK_FOLLOW:
        return [_copy_matching(out0, out_shape, s) for s in in_shapes]

    if t == OperatorType.LINEAR:
        kspec = _norm(getparam(node, "kernel"), 2)
        req = list(_copy_matching(out0, out_shape, in_shapes[0]))
        if req:
            # contraction dim: row-parallel (kernel dim0 model-sharded)
            # consumes a contraction-sharded input; col keeps it whole
            req[-1] = kspec[0]
        return [tuple(req)] + [ANY] * (len(in_shapes) - 1)

    if t == OperatorType.CONV2D:
        kspec = _norm(getparam(node, "kernel"), 4)  # OIHW
        req = [None] * len(in_shapes[0])
        if len(in_shapes[0]) == 4:
            req[0] = out0[0] if in_shapes[0][0] == out_shape[0] else None
            req[1] = kspec[1]  # row-parallel conv: in-channel sharded
        return [tuple(req)] + [ANY] * (len(in_shapes) - 1)

    if t == OperatorType.EMBEDDING:
        # ids follow the output's batch sharding; the table lookup
        # itself is the op's own (psum-priced) business
        reqs = []
        for s in in_shapes:
            r = [None] * len(s)
            if r and s[0] == out_shape[0]:
                r[0] = out0[0]
            reqs.append(tuple(r))
        return reqs

    if t == OperatorType.MULTIHEAD_ATTENTION:
        # q/k/v arrive [B,S,E]: batch and seq follow the output (ring
        # attention keeps K/V seq-sharded — the rotation is priced as
        # the ring ppermute, not as an edge); E stays whole
        reqs = []
        for s in in_shapes:
            r = [None] * len(s)
            if r and s and s[0] == out_shape[0]:
                r[0] = out0[0]
            if len(r) > 1 and len(out_shape) > 1 and s[1] == out_shape[1]:
                r[1] = out0[1]
            reqs.append(tuple(r))
        return reqs

    if t == OperatorType.BATCHMATMUL:
        reqs = []
        for s in in_shapes:
            r = [None] * len(s)
            if r and s and out_shape and s[0] == out_shape[0]:
                r[0] = out0[0]
            reqs.append(tuple(r))
        return reqs

    if t in (OperatorType.RESHAPE, OperatorType.FLAT):
        return [_reshape_transfer(out0, out_shape, in_shapes[0])]

    if t == OperatorType.TRANSPOSE:
        perm = getattr(op, "perm", None)
        if perm is None:
            return [ANY]
        req = [None] * len(in_shapes[0])
        for j, p in enumerate(perm):  # out dim j carries in dim perm[j]
            req[p] = out0[j]
        return [tuple(req)]

    if t == OperatorType.CONCAT:
        ax = getattr(op, "axis", 0) % max(1, len(out_shape))
        reqs = []
        for s in in_shapes:
            r = list(_copy_matching(out0, out_shape, s))
            if r:
                r[ax] = None  # per-input extents differ on the seam
            reqs.append(tuple(r))
        return reqs

    if t == OperatorType.SPLIT:
        ax = getattr(op, "axis", 0) % max(1, len(in_shapes[0]))
        r = list(_copy_matching(out0, out_shape, in_shapes[0]))
        if r:
            r[ax] = None
        return [tuple(r)]

    # reductions, gathers, MoE dispatch ops, loss heads: index- or
    # reduction-dependent layouts this pass does not model — accept
    # whatever arrives (the inference stays a lower bound)
    return [ANY] * len(in_shapes)


# ---- the edge table --------------------------------------------------------

class _TableCtx:
    """The slice of LintContext edge_reshard_table needs — constructed
    directly by verify_rewrite_dataflow for pre/post node lists that
    never saw apply_strategy."""

    def __init__(self, nodes, strategy, axis_sizes, elem=4.0, ff=None):
        self.nodes = nodes
        self.strategy = strategy or {}
        self.axis_sizes = axis_sizes
        self.elem = elem
        self.ff = ff
        self.by_guid = {n.op.guid: n for n in nodes}


def _ctx_elem(ctx) -> float:
    elem = getattr(ctx, "elem", None)
    if elem:
        return float(elem)
    ff = getattr(ctx, "ff", None)
    if ff is not None and ff.executor is not None:
        return float(np.dtype(ff.executor.compute_dtype).itemsize)
    return 4.0


def _out_entries(ctx, node, idx: int) -> Tuple:
    rank = len(node.op.output_shapes[idx]) if idx < len(
        node.op.output_shapes) else 0
    specs = getattr(node, "output_specs", None)
    if specs and idx < len(specs) and specs[idx] is not None:
        return _norm(specs[idx], rank)
    st = ctx.strategy.get(node.op.guid)
    if st is not None and st.output_specs and idx < len(st.output_specs):
        return _norm(st.output_specs[idx], rank)
    return (None,) * rank


def _param_spec(ctx, node, name: str):
    ps = getattr(node, "param_specs", None)
    if ps and name in ps:
        return ps[name]
    st = ctx.strategy.get(node.op.guid)
    if st is not None:
        return st.param_specs.get(name)
    return None


def _block_of(ctx) -> Dict[int, int]:
    """guid -> repeated-block index on pipe meshes (pipe-hop edges are
    ppermutes over the stage boundary, not GSPMD reshards)."""
    ff = getattr(ctx, "ff", None)
    if ctx.axis_sizes.get("pipe", 1) <= 1 or ff is None:
        return {}
    pb = getattr(ff.executor, "pb", None) if ff.executor is not None else None
    if pb is None:
        return {}
    return {ctx.nodes[i].op.guid: bi
            for bi, blk in enumerate(pb.blocks) for i in blk}


def edge_reshard_table(ctx) -> List[EdgeReshard]:
    """Every producer→consumer edge whose specs disagree, classified.

    ``ctx`` is a LintContext (or _TableCtx). Memoized on the context —
    the graph is never mutated during a lint run."""
    cached = getattr(ctx, "_edge_table", None)
    if cached is not None:
        return cached
    axis_sizes = ctx.axis_sizes
    elem = _ctx_elem(ctx)
    blocks = _block_of(ctx)
    out: List[EdgeReshard] = []
    for node in ctx.nodes:
        op = node.op
        reqs = None
        for j, ref in enumerate(node.input_refs):
            if not ref or ref[0] != "op":
                continue
            prod = ctx.by_guid.get(ref[1])
            if prod is None:
                continue
            src = _out_entries(ctx, prod, ref[2])
            shape = (prod.op.output_shapes[ref[2]]
                     if ref[2] < len(prod.op.output_shapes) else ())
            explicit = bool(getattr(op, "is_parallel_op", False))
            if explicit:
                # the boundary's own constraint is the destination
                dst = _out_entries(ctx, node, 0)
            else:
                if reqs is None:
                    reqs = required_input_specs(
                        node,
                        lambda n: _out_entries(ctx, n, 0),
                        lambda n, name: _param_spec(ctx, n, name))
                dst = reqs[j] if j < len(reqs) else ANY
                if dst is ANY:
                    continue
            cls = classify_transition(src, dst, shape, axis_sizes, elem)
            if cls is None:
                continue
            kind, reason = cls["kind"], ""
            if blocks and blocks.get(prod.op.guid) != blocks.get(op.guid) \
                    and prod.op.guid in blocks and op.guid in blocks:
                # stage boundary: the hop is the pipeline ppermute the
                # node-level inference prices (pipeline:hop), not a
                # GSPMD reshard
                kind, reason, explicit = "ppermute", "pipe-hop", True
            out.append(EdgeReshard(
                producer=prod.op.name, producer_guid=prod.op.guid,
                out_idx=ref[2], consumer=op.name, consumer_guid=op.guid,
                in_idx=j, src_spec=src, dst_spec=dst, kind=kind,
                bytes=cls["bytes"], axes=cls["axes"], fabric=cls["fabric"],
                explicit=explicit, reason=reason))
    try:
        ctx._edge_table = out
    except AttributeError:
        pass
    return out


def weight_movement_edges(ctx) -> List[EdgeReshard]:
    """The tiny-batch weight-movement rule, generalized: a row-parallel
    contraction (model-sharded contraction dim on the kernel, output
    NOT model-sharded — the psum pairing) whose per-chip MXU row count
    is at most one tile edge and whose output is smaller than its
    weight resolves, under GSPMD, by ALL-GATHERING the weight instead
    of psumming activations. One rule over shapes+specs, covering what
    native/ffs_strategy.hpp's per-op special case priced for the
    row-parallel Linear and Conv2D (searched XDL emitted 7x the priced
    bytes before that term existed — ROADMAP / fflint FFL202)."""
    axis_sizes = ctx.axis_sizes
    elem = _ctx_elem(ctx)
    out: List[EdgeReshard] = []
    for node in ctx.nodes:
        op = node.op
        if op.op_type not in (OperatorType.LINEAR, OperatorType.CONV2D):
            continue
        kspec = _param_spec(ctx, node, "kernel")
        if kspec is None:
            continue
        kentries = tuple(kspec)
        model_deg = 1
        for entry in kentries:
            for ax in _entry_axes(entry):
                if ax not in _DATA_AXES:
                    model_deg *= axis_sizes.get(ax, 1)
        if model_deg <= 1:
            continue
        out0 = _out_entries(ctx, node, 0)
        if any(ax not in _DATA_AXES and ax != "seq"
               for _, ax in _pairs(out0)):
            continue  # col-parallel: the output moves, not the weight
        shape = op.output_shapes[0]
        roles = op.output_dim_roles()[0]
        ch = roles.index(DimRole.CHANNEL) if DimRole.CHANNEL in roles \
            else len(shape) - 1
        rows = float(np.prod(shape)) / max(1, shape[ch])
        eff_dp = 1
        for ax in _entry_axes(out0[0] if out0 else None):
            if ax in _DATA_AXES:
                eff_dp *= axis_sizes.get(ax, 1)
        pbytes = float(op.params_elems()) * elem
        out_bytes = float(np.prod(shape)) * elem
        if rows <= 0 or rows / eff_dp > _MXU_ROWS or out_bytes >= pbytes:
            continue
        moved = sorted({ax for entry in kentries
                        for ax in _entry_axes(entry)
                        if ax not in _DATA_AXES})
        out.append(EdgeReshard(
            producer=op.name, producer_guid=op.guid, out_idx=0,
            consumer=op.name, consumer_guid=op.guid, in_idx=-1,
            src_spec=tuple(kentries), dst_spec=(None,) * len(kentries),
            kind="allgather", bytes=pbytes, axes=tuple(moved),
            fabric="dcn" if "slice" in moved else "ici",
            explicit=False, reason="tiny-batch weight movement"))
    return out


# ---- rewrite verification (FFL213) ----------------------------------------

def _implicit_kind_bytes(table: List[EdgeReshard]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for e in table:
        if e.explicit or e.kind == "slice" or e.bytes < MIN_EDGE_BYTES:
            continue
        out[e.kind] = out.get(e.kind, 0.0) + e.bytes
    return out


def _adapt_donor(node, donor, donor_st, di: int):
    """Project a post-rewrite donor op's strategy back onto a removed
    pre-rewrite node: output entries transfer on dims whose extent
    matches or divides the donor's (a fused [B,S,3H] linear's
    ``model``-sharded dim 2 shards each constituent's [B,S,H] dim 2
    identically); param specs transfer by name (``kernel`` → the
    constituent kernel sees the same row/col split)."""
    import types
    dshape = (donor.op.output_shapes[di]
              if di < len(donor.op.output_shapes) else ())
    dspec = _norm(donor_st.output_specs[di]
                  if getattr(donor_st, "output_specs", None)
                  and di < len(donor_st.output_specs) else None,
                  len(dshape))
    specs = []
    for oshape in node.op.output_shapes:
        ent = [None] * len(oshape)
        for d in range(min(len(oshape), len(dshape))):
            if oshape[d] > 0 and (oshape[d] == dshape[d]
                                  or dshape[d] % oshape[d] == 0):
                ent[d] = dspec[d]
        specs.append(tuple(ent))
    return types.SimpleNamespace(
        output_specs=specs,
        param_specs=dict(getattr(donor_st, "param_specs", None) or {}),
        choice=getattr(donor_st, "choice", None))


def _project_strategy(pre_nodes, post_strategy, post_nodes=None,
                      rewrites=None) -> Dict[int, Any]:
    """Strategy for the PRE-rewrite graph under the post-rewrite
    decision: surviving guids keep their entries; removed ops take the
    (shape-adapted) entry of the post node their output was remapped to
    by the rewrite trace; anything still unresolved follows its first
    op-input producer (the layout a folded interior op would run in)."""
    by_guid = {n.op.guid: n for n in pre_nodes}
    post_by_guid = {n.op.guid: n for n in (post_nodes or ())}
    post_by_name = {n.op.name: n for n in (post_nodes or ())}
    remap: Dict[Tuple[int, int], Tuple[int, int]] = {}
    # removed guid -> the rewrite entry's added post nodes (a removed
    # op's layout donor should be the added op of ITS OWN type — a
    # fused LINEAR's output remap points at the adapter SPLIT, whose
    # spec has lost the col-parallel sharding the constituents ran in)
    twins: Dict[int, list] = {}
    for entry in (rewrites or ()):
        for a, b, c, d in entry.get("output_remap", ()):
            remap[(int(a), int(b))] = (int(c), int(d))
        added = [post_by_name[a["name"]] for a in entry.get("added", ())
                 if a.get("name") in post_by_name]
        for g in entry.get("removed", ()):
            twins[int(g)] = added

    def follow_remap(key):
        for _ in range(len(remap) + 1):
            if key not in remap:
                break
            key = remap[key]
        return key

    def donor_of(n):
        dg, di = follow_remap((n.op.guid, 0))
        donor = post_by_guid.get(dg)
        if donor is not None and donor.op.op_type == n.op.op_type:
            return donor, di
        for cand in twins.get(n.op.guid, ()):
            if cand.op.op_type == n.op.op_type \
                    and cand.op.guid in post_strategy:
                return cand, 0
        return donor, di

    def resolve(guid, depth=0):
        if guid in post_strategy or depth > len(by_guid):
            return post_strategy.get(guid)
        node = by_guid.get(guid)
        if node is None:
            return None
        for ref in node.input_refs:
            if ref and ref[0] == "op":
                return resolve(ref[1], depth + 1)
        return None

    out = {}
    for n in pre_nodes:
        guid = n.op.guid
        st = post_strategy.get(guid)
        if st is None:
            donor, di = donor_of(n)
            donor_st = (post_strategy.get(donor.op.guid)
                        if donor is not None else None)
            if donor is not None and donor_st is not None:
                st = _adapt_donor(n, donor, donor_st, di)
        if st is None:
            st = resolve(guid)
        if st is not None:
            out[guid] = st
    return out


def verify_rewrite_dataflow(pre_nodes, post_nodes, strategy, axis_sizes,
                            elem: float = 4.0, tol: float = 1.5,
                            rewrites=None) -> Dict[str, Any]:
    """Static collective-equivalence check for an accepted substitution
    rewrite: the post-rewrite graph's implicit edge-reshard map must be
    collective-equivalent-or-cheaper than the pre-rewrite graph under
    the projected strategy. Compared as TOTAL implicit bytes across
    kinds — a rewrite legitimately trades N small reshards for one
    larger all-gather (the kinds cover each other, COLLECTIVE_COVER),
    and the pre-side strategy is a projection, so only a substantial
    regression (> ``tol`` x, default 1.5) is flagged. Returns
    ``{ok, findings, pre_bytes, post_bytes}``; a finding carries the
    dominant post-rewrite kind and its worst edge — the FFL213
    payload."""
    pre_ctx = _TableCtx(pre_nodes,
                        _project_strategy(pre_nodes, strategy,
                                          post_nodes, rewrites),
                        axis_sizes, elem)
    post_ctx = _TableCtx(post_nodes, strategy, axis_sizes, elem)
    pre = _implicit_kind_bytes(edge_reshard_table(pre_ctx))
    post = _implicit_kind_bytes(edge_reshard_table(post_ctx))
    pre_total = sum(pre.values())
    post_total = sum(post.values())
    findings = []
    if post_total > pre_total * tol + MIN_EDGE_BYTES:
        kind = max(post, key=lambda k: post[k] - pre.get(k, 0.0))
        worst = max((e for e in edge_reshard_table(post_ctx)
                     if not e.explicit and e.kind == kind),
                    key=lambda e: e.bytes, default=None)
        findings.append(dict(
            kind=kind, pre_bytes=pre_total, post_bytes=post_total,
            edge=worst.edge if worst else None,
            src_spec=_spec_str(worst.src_spec) if worst else None,
            dst_spec=_spec_str(worst.dst_spec) if worst else None))
    return dict(ok=not findings, findings=findings,
                pre_bytes=pre, post_bytes=post)

"""fflint's shared diagnostic model.

Every pass (flexflow_tpu/analysis/passes) emits ``Diagnostic`` records:
a stable rule id (``FFL###`` — the catalog lives in README §fflint), a
severity, the op/tensor the finding anchors to, and a fix hint. The
``LintReport`` aggregates them across passes and renders both the human
table (``format_human``) and the machine form (``to_json``) consumed by
``scripts/fflint.py --json`` and the run_t1.sh lint artifact.

Severity contract (enforced by tests/test_analysis.py):

* ``ERROR``   — the strategy/graph is wrong: it will deadlock, compute
  the wrong thing, or run collectives the simulator never priced (the
  searched strategy's prediction is meaningless). ``scripts/fflint.py``
  exits nonzero and ``compile(lint="error")`` raises.
* ``WARNING`` — legal but wasteful or fragile (redundant transpose
  pairs, dead ops, stale calibration).
* ``INFO``    — context a reviewer wants (pass skipped for a stated
  reason, coverage notes).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, List, Optional


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    def __lt__(self, other):  # ERROR sorts first in reports
        order = {"error": 0, "warning": 1, "info": 2}
        return order[self.value] < order[other.value]


@dataclasses.dataclass
class Diagnostic:
    """One finding. ``rule`` is the stable FFL### id; ``op`` names the
    operator (or None for graph-level findings); ``tensor`` names the
    specific tensor/parameter when the finding is narrower than the op."""

    rule: str
    severity: Severity
    message: str
    op: Optional[str] = None
    guid: Optional[int] = None
    tensor: Optional[str] = None
    hint: Optional[str] = None
    lint_pass: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(
            rule=self.rule,
            severity=self.severity.value,
            message=self.message,
            op=self.op,
            guid=self.guid,
            tensor=self.tensor,
            hint=self.hint,
            # "pass" is a keyword in Python but the natural JSON key
            **{"pass": self.lint_pass},
        )

    def format(self) -> str:
        loc = self.op or "<graph>"
        if self.tensor:
            loc = f"{loc}:{self.tensor}"
        line = f"{self.severity.value.upper():7s} {self.rule} [{loc}] {self.message}"
        if self.hint:
            line += f"\n        hint: {self.hint}"
        return line


class LintReport:
    """Diagnostics from one orchestrator run, plus per-pass status
    (ran / skipped / crashed) so "no findings" is distinguishable from
    "pass never ran"."""

    def __init__(self):
        self.diagnostics: List[Diagnostic] = []
        self.passes: Dict[str, str] = {}  # pass name -> "ok"/"skipped: .."/"crashed: .."
        self.context: Dict[str, Any] = {}

    def extend(self, diags: List[Diagnostic], lint_pass: str) -> None:
        for d in diags:
            if d.lint_pass is None:
                d.lint_pass = lint_pass
        self.diagnostics.extend(diags)

    # ---- queries -----------------------------------------------------------
    def by_severity(self, severity: Severity) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self) -> List[Diagnostic]:
        return self.by_severity(Severity.WARNING)

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def has_errors(self) -> bool:
        return bool(self.errors)

    # ---- rendering ---------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        counts = dict(error=len(self.errors), warning=len(self.warnings),
                      info=len(self.by_severity(Severity.INFO)))
        return dict(
            context=self.context,
            passes=self.passes,
            counts=counts,
            diagnostics=[d.to_json() for d in
                         sorted(self.diagnostics,
                                key=lambda d: (d.severity, d.rule))],
        )

    def dumps(self, indent: int = 1) -> str:
        return json.dumps(self.to_json(), indent=indent)

    def format_human(self) -> str:
        lines = []
        if self.context:
            ctx = ", ".join(f"{k}={v}" for k, v in self.context.items())
            lines.append(f"fflint: {ctx}")
        for name, status in self.passes.items():
            if status != "ok":
                lines.append(f"pass {name}: {status}")
        for d in sorted(self.diagnostics, key=lambda d: (d.severity, d.rule)):
            lines.append(d.format())
        lines.append(
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s), "
            f"{len(self.by_severity(Severity.INFO))} info "
            f"({sum(1 for s in self.passes.values() if s == 'ok')}/"
            f"{len(self.passes)} passes ran)")
        return "\n".join(lines)


def error(rule: str, message: str, **kw) -> Diagnostic:
    return Diagnostic(rule, Severity.ERROR, message, **kw)


def warning(rule: str, message: str, **kw) -> Diagnostic:
    return Diagnostic(rule, Severity.WARNING, message, **kw)


def info(rule: str, message: str, **kw) -> Diagnostic:
    return Diagnostic(rule, Severity.INFO, message, **kw)

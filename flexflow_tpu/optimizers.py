"""Optimizers: SGD (+momentum/nesterov) and Adam.

Analog of include/flexflow/optimizer.h:27-110 and
src/runtime/optimizer_kernel.cu:88,196. The reference has two sync paths —
parameter-server and NCCL allreduce-then-local-step; on TPU the gradient
allreduce is the psum GSPMD inserts for the data axis inside the jitted
step, so only the local update remains. Implemented as pure pytree
transforms (optax-compatible shape: init(params) -> state;
update(grads, state, params) -> new_params, new_state).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import ParameterSyncType


class Optimizer:
    parameter_sync = ParameterSyncType.NCCL

    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, state, params) -> Tuple[Any, Any]:
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    """lr, momentum, nesterov, weight_decay — optimizer.h:37-60."""

    def __init__(self, ffmodel=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def init(self, params):
        if self.momentum == 0.0:
            return {}
        return {"v": jax.tree.map(jnp.zeros_like, params)}

    def update(self, grads, state, params):
        wd = self.weight_decay

        if self.momentum == 0.0:
            new_params = jax.tree.map(
                lambda p, g: p - self.lr * (g + wd * p), params, grads
            )
            return new_params, state

        def step(p, g, v):
            g = g + wd * p
            v_new = self.momentum * v + g
            upd = g + self.momentum * v_new if self.nesterov else v_new
            return p - self.lr * upd, v_new

        flat = jax.tree.map(step, params, grads, state["v"])
        new_params = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"v": new_v}


class AdamOptimizer(Optimizer):
    """alpha/beta1/beta2/epsilon/weight_decay with bias-corrected alpha_t
    updated per step exactly like the reference (optimizer.h:77-110,
    AdamOptimizer::next)."""

    def __init__(self, ffmodel=None, alpha: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0,
                 epsilon: float = 1e-8, state_dtype=None):
        self.alpha = alpha
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon
        # optional reduced-precision m/v storage (e.g. jnp.bfloat16): the
        # update math stays f32 (cast in, cast out); halves the optimizer
        # state's HBM traffic and footprint. Default None = parameter dtype
        # (exact reference parity, optimizer.h:77-110).
        self.state_dtype = state_dtype

    def _state_like(self, p):
        # zeros_like (not zeros): keeps the parameter's NamedSharding so
        # sharded params get sharded m/v rather than replicated buffers
        return jnp.zeros_like(p, dtype=self.state_dtype or p.dtype)

    def init(self, params):
        return {
            "m": jax.tree.map(self._state_like, params),
            "v": jax.tree.map(self._state_like, params),
            "t": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, state, params):
        t = state["t"] + 1
        bc = jnp.sqrt(1.0 - self.beta2 ** t.astype(jnp.float32)) / (
            1.0 - self.beta1 ** t.astype(jnp.float32)
        )
        alpha_t = self.alpha * bc

        def step(p, g, m, v):
            sdt = m.dtype
            g = g.astype(p.dtype) + self.weight_decay * p
            m_new = self.beta1 * m.astype(p.dtype) + (1 - self.beta1) * g
            v_new = self.beta2 * v.astype(p.dtype) + (1 - self.beta2) * g * g
            p_new = p - alpha_t * m_new / (jnp.sqrt(v_new) + self.epsilon)
            return p_new, m_new.astype(sdt), v_new.astype(sdt)

        trip = jax.tree.map(step, params, grads, state["m"], state["v"])
        is_t = lambda x: isinstance(x, tuple)
        new_params = jax.tree.map(lambda x: x[0], trip, is_leaf=is_t)
        new_m = jax.tree.map(lambda x: x[1], trip, is_leaf=is_t)
        new_v = jax.tree.map(lambda x: x[2], trip, is_leaf=is_t)
        return new_params, {"m": new_m, "v": new_v, "t": t}

"""Data loading: staged dataset + per-iteration sharded batches.

TPU re-design of the reference's SingleDataLoader
(python/flexflow_dataloader.{h,cc,cu}, flexflow_cffi.py:2433): the
reference stages the entire dataset into zero-copy host memory once, then
per iteration an index-task copies each shard's batch slice to GPU
framebuffer. Here the dataset is staged once as a device array sharded
over the data axis (HBM-resident when it fits, host-resident otherwise),
and ``next_batch`` slices the staged array on device — no host→device
traffic in steady state, which is exactly the role the reference's
PY_DL_*_LOAD_BATCH_GPU tasks play.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


class SingleDataLoader:
    """One input (or label) tensor's loader.

    ``num_samples`` must be a multiple of the batch size for the staged
    path (the reference truncates the same way).
    """

    def __init__(self, ffmodel, input_name: Optional[str], full_array,
                 batch_size: Optional[int] = None, stage_on_device: bool = True):
        self.ff = ffmodel
        self.input_name = input_name  # None => label loader
        arr = np.asarray(full_array)
        bs = batch_size or ffmodel.input_tensors[0].shape[0]
        self.batch_size = bs  # global batch
        # labels stage on the loss-boundary layout (data-sharded), inputs
        # on the executor's batch layout (pipe-sharded under the
        # pipeline's sharded microbatch queue) — same contract as
        # model._shard_batch, or the two staging paths would diverge
        sharding = (ffmodel.executor.batch_sharding()
                    if input_name is not None
                    else ffmodel.executor.label_sharding())
        # multi-host: `full_array` is this process's dataset shard; each
        # batch consumes the local block of the global batch and the rows
        # assemble via make_array_from_process_local_data (host-resident —
        # the on-device staged path needs single-controller addressing)
        self._multihost = jax.process_count() > 1
        if self._multihost:
            from flexflow_tpu import distributed as _dist
            self._local_bs, _ = _dist.local_batch_rows(sharding, bs)
            stage_on_device = False
        else:
            self._local_bs = bs
        usable = (arr.shape[0] // self._local_bs) * self._local_bs
        if usable == 0:
            raise ValueError(
                f"dataset of {arr.shape[0]} samples < (local) batch size "
                f"{self._local_bs}")
        arr = arr[:usable]
        self.num_batches = usable // self._local_bs
        self.num_samples = self.num_batches * bs  # global count
        if self._multihost:
            # agree on num_batches ONCE, up front: unequal per-host dataset
            # shards would otherwise make ranks issue different numbers of
            # per-batch collectives and deadlock with no diagnostic
            # (ADVICE r5). One allgather at construction, zero steady-state
            # cost.
            from flexflow_tpu import distributed as _dist
            counts = _dist.allgather_value(self.num_batches)
            if len(set(counts)) != 1:
                raise ValueError(
                    f"multihost dataloader: per-host num_batches disagree "
                    f"{counts} (process {_dist.process_index()} computed "
                    f"{self.num_batches}) — every process must feed "
                    f"equal-length dataset shards; pad or truncate before "
                    f"constructing the loader")
        if stage_on_device:
            self.data = jax.device_put(jnp.asarray(arr), sharding)
        else:
            self.data = arr
        self._sharding = sharding
        self.next_index = 0

    def reset(self) -> None:
        self.next_index = 0

    def seek(self, batch_index: int) -> None:
        """Position the loader AT ``batch_index`` (0-based within the
        epoch) so the next ``next_batch`` returns that batch — the
        resume path's O(1) reposition, replacing fetch-and-discard of
        every checkpoint-covered batch."""
        b = int(batch_index)
        if not (0 <= b < self.num_batches):
            raise ValueError(
                f"seek({batch_index}) out of range for a loader with "
                f"{self.num_batches} batches per epoch")
        self.next_index = b * self._local_bs

    def next_batch(self, _ff=None):
        """Return the next batch, wrapping around (reference semantics:
        the C++ loader reloads from the start each epoch)."""
        n_local = self.num_batches * self._local_bs
        if self.next_index + self._local_bs > n_local:
            self.next_index = 0
        start = self.next_index
        self.next_index += self._local_bs
        if self._multihost:
            from flexflow_tpu import distributed as _dist
            return _dist.stage_local_batch(
                self.data[start:start + self._local_bs], self._sharding,
                global_rows=self.batch_size)
        if isinstance(self.data, np.ndarray):
            # single transfer straight onto the batch sharding
            return jax.device_put(self.data[start:start + self.batch_size],
                                  self._sharding)
        return jax.lax.dynamic_slice_in_dim(self.data, start, self.batch_size,
                                            axis=0)


class DataLoaderSet:
    """All input + label loaders for a model; drives fit-style loops
    (the reference's ``dataloaders.next_batch`` list in fit,
    flexflow_cffi.py:2080)."""

    def __init__(self, ffmodel, xs: Sequence, y, batch_size: Optional[int] = None,
                 stage_on_device: bool = True):
        names = ffmodel.executor.input_names
        xs = xs if isinstance(xs, (list, tuple)) else [xs]
        if len(xs) != len(names):
            raise ValueError(f"model has {len(names)} inputs, got {len(xs)}")
        self.input_loaders = [
            SingleDataLoader(ffmodel, n, x, batch_size, stage_on_device)
            for n, x in zip(names, xs)
        ]
        self.label_loader = SingleDataLoader(ffmodel, None, y, batch_size,
                                             stage_on_device)
        counts = {l.num_samples for l in self.input_loaders + [self.label_loader]}
        if len(counts) != 1:
            raise ValueError(
                f"input/label loaders disagree on usable sample count "
                f"{sorted(counts)} — all arrays must have the same length")
        self.ff = ffmodel

    @property
    def num_batches(self) -> int:
        return self.input_loaders[0].num_batches

    def reset(self) -> None:
        for l in self.input_loaders:
            l.reset()
        self.label_loader.reset()

    def seek(self, batch_index: int) -> None:
        """Reposition every loader at ``batch_index`` within the epoch
        (fit_loader's resume seam)."""
        for l in self.input_loaders:
            l.seek(batch_index)
        self.label_loader.seek(batch_index)

    def next_batch(self):
        inputs = {l.input_name: l.next_batch() for l in self.input_loaders}
        labels = self.label_loader.next_batch()
        return inputs, labels


def create_data_loaders(ffmodel, x, y, batch_size: Optional[int] = None,
                        stage_on_device: bool = True) -> DataLoaderSet:
    """Sugar matching ffmodel.create_data_loader (flexflow_cffi.py:2178)."""
    return DataLoaderSet(ffmodel, x, y, batch_size, stage_on_device)

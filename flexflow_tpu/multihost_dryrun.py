"""Multi-host (2-process) SPMD dryrun: gradient-sync parity.

Validates the multi-controller execution path without real multi-host
hardware: spawn N processes, each with `devices_per_proc` virtual CPU
devices, rendezvous through `jax.distributed` (gloo collectives), train a
tiny transformer data-parallel over the global mesh with each process
feeding only its local batch rows — then assert the synced parameters
match a single-process run on the same global batch.

Analog of the reference's multinode CI harness
(tests/multinode_helpers/mpi_wrapper1.sh: mpirun -np 2 with per-rank
GPU masks), re-expressed for JAX multi-controller SPMD.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
from typing import Dict, Optional

import numpy as np

_STEPS = 2


def _model_config(total_devices: int):
    from flexflow_tpu.models.transformer import TransformerConfig

    return TransformerConfig(num_layers=1, hidden_size=32, num_heads=2,
                             seq_length=8, batch_size=2 * total_devices)


def _global_batch(cfg):
    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.seq_length,
                 cfg.hidden_size).astype(np.float32)
    y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)
    return x, y


def _multi_axis_legs_possible(total_devices: int) -> bool:
    """Gates the tp AND ring legs plus the checkpoint roundtrip: their
    {model|seq: 2, data: N/2} meshes need an even device count >= 4."""
    return total_devices >= 4 and total_devices % 2 == 0


def _build(total_devices: int, leg: str = "dp"):
    """Compile the dryrun model (no training).

    Legs: "dp" — pure data parallel; "tp" — a {model: 2, data: N/2} mesh
    whose model axis SPANS hosts; "ring" — a {seq: 2, data: N/2} mesh
    whose seq axis spans hosts, so ring attention's K/V ppermute hops
    cross processes (long-context parallelism over the cross-host
    fabric, the brief's first-class requirement)."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.machine import make_mesh
    from flexflow_tpu.models.transformer import create_transformer
    from flexflow_tpu.optimizers import SGDOptimizer

    cfg = _model_config(total_devices)
    if leg == "ring":
        import dataclasses
        cfg = dataclasses.replace(cfg, seq_parallel="seq")
    ff = create_transformer(
        cfg, FFConfig(batch_size=cfg.batch_size,
                      enable_parameter_parallel=(leg == "tp")))
    if leg == "tp":
        # model axis FIRST (outermost): its stride equals half the device
        # list, so each model-ring pairs devices from DIFFERENT processes
        # — the leg exercises cross-host psum/all-gather, not an
        # intra-host copy of them. The data axis then lives within hosts
        # and each host feeds the FULL batch (its devices hold every
        # batch shard), which local_batch_rows resolves below.
        mesh = make_mesh(total_devices,
                         {"model": 2, "data": total_devices // 2})
    elif leg == "ring":
        # seq axis outermost for the same reason: every K/V rotation hop
        # crosses processes
        mesh = make_mesh(total_devices,
                         {"seq": 2, "data": total_devices // 2})
    else:
        mesh = make_mesh(total_devices, {"data": total_devices})
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], mesh=mesh)
    return ff


def _build_and_train(total_devices: int, leg: str = "dp",
                     trace_dir: Optional[str] = None,
                     profile_steps: Optional[str] = None):
    """Compile + train the dryrun model for _STEPS steps on this
    process's rows of the fixed global batch. Returns
    (FFModel, local_x, local_y) — the local slice is derived ONCE here
    and reused by callers (evaluate/predict legs). ``trace_dir``
    activates the obs step tracer; each process writes artifacts keyed
    by its host id (jax.process_index). ``profile_steps`` adds the
    windowed jax.profiler device-trace capture, so each host's merged
    Perfetto lanes include its own device compute/comms rows."""
    import jax

    ff = _build(total_devices, leg)
    cfg = _model_config(total_devices)
    x, y = _global_batch(cfg)
    if jax.process_count() > 1:
        from flexflow_tpu import distributed
        rows, lo = distributed.local_batch_rows(
            ff.executor.batch_sharding(), x.shape[0])
    else:
        rows, lo = x.shape[0], 0
    lx, ly = x[lo:lo + rows], y[lo:lo + rows]
    if leg == "dp":
        # DP leg drives the DataLoader path (SingleDataLoader's
        # multi-host staging), the other legs drive fit() — both per-host
        # feeding mechanisms get parity coverage
        from flexflow_tpu.dataloader import create_data_loaders
        loaders = create_data_loaders(ff, lx, ly)
        ff.fit_loader(loaders, epochs=_STEPS, verbose=False,
                      trace_dir=trace_dir, profile_steps=profile_steps)
    else:
        ff.fit(lx, ly, epochs=_STEPS, verbose=False, trace_dir=trace_dir,
               profile_steps=profile_steps)
    return ff, lx, ly


def _params_to_numpy(ff) -> Dict[str, np.ndarray]:
    from flexflow_tpu import distributed

    flat: Dict[str, np.ndarray] = {}

    def rec(prefix, tree):
        for k in sorted(tree):
            v = tree[k]
            if isinstance(v, dict):
                rec(f"{prefix}{k}/", v)
            else:
                # model-sharded params may not be fully addressable on one
                # host — gather (no-op single-process / replicated)
                flat[f"{prefix}{k}"] = distributed.all_gather_host(v)

    rec("", ff.params)
    return flat


def worker_main(process_id: int, num_processes: int, port: int,
                devices_per_proc: int, out_path: str) -> None:
    """One rendezvous participant (subprocess entry point)."""
    os.environ.pop("JAX_PLATFORMS", None)
    # per-process virtual device count via XLA_FLAGS: must land in the
    # environment BEFORE jax initializes its backend (the jax_num_cpu_devices
    # config knob is unsupported by the pinned JAX — ROADMAP item)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from flexflow_tpu import distributed

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=num_processes,
                           process_id=process_id)
    total = jax.device_count()
    assert total == num_processes * devices_per_proc, (
        f"expected {num_processes * devices_per_proc} global devices, "
        f"got {total}")
    # per-host step tracing (FFS_TRACE_DIR, set by run_dryrun): each
    # worker's fit writes *_hostNN artifacts the parent merges by host
    # id; FFS_PROFILE_STEPS adds the per-host device-trace capture
    trace_dir = os.environ.get("FFS_TRACE_DIR") or None
    profile_steps = os.environ.get("FFS_PROFILE_STEPS") or None
    ff, lx, ly = _build_and_train(total, trace_dir=trace_dir,
                                  profile_steps=profile_steps)
    if trace_dir:
        # per-host optimized-HLO dump for the fflint multihost-order pass
        # (FFL501/502 static deadlock detector): every process writes the
        # text of ITS compiled train step; the parent feeds the set
        # through lint_model(ff, hlo_per_host=[...]) after the run
        from flexflow_tpu.search.validate import train_step_hlo
        hlo_path = os.path.join(trace_dir,
                                f"train_step_host{process_id}.hlo.txt")
        with open(hlo_path, "w") as f:
            f.write(train_step_hlo(ff))
    out = {"loss": np.float64(ff._last_loss)}
    out.update({f"dp/{k}": v for k, v in _params_to_numpy(ff).items()})
    # evaluate + predict on the multi-host path: evaluate consumes local
    # rows; predict gathers the GLOBAL output back to every host
    out["eval_loss"] = np.float64(ff.evaluate(lx, ly)["loss"])
    out["predict"] = ff.predict(lx)
    if _multi_axis_legs_possible(total):
        # leg 2: tensor parallelism whose model axis spans the two hosts
        ff_tp, _, _ = _build_and_train(total, leg="tp")
        out["tp_loss"] = np.float64(ff_tp._last_loss)
        tp_params = _params_to_numpy(ff_tp)
        out.update({f"tp/{k}": v for k, v in tp_params.items()})
        # leg 3: ring attention whose seq axis spans the two hosts —
        # every K/V rotation hop is a cross-process ppermute
        ff_ring, _, _ = _build_and_train(total, leg="ring")
        out["ring_loss"] = np.float64(ff_ring._last_loss)
        out.update({f"ring/{k}": v
                    for k, v in _params_to_numpy(ff_ring).items()})
        # leg 4: cross-host checkpoint roundtrip of the model-sharded
        # state — rank 0 writes (after an all-host gather), every host
        # loads back onto the cross-host shardings
        ckpt = os.path.join(os.path.dirname(out_path), "ckpt_tp")
        ff_tp.save_checkpoint(ckpt)  # barriers internally: durable on return
        ff_rt = _build(total, leg="tp")
        ff_rt.load_checkpoint(ckpt)
        rt_params = _params_to_numpy(ff_rt)
        for key, want in tp_params.items():
            got = rt_params[key]
            # bf16 leaves round-trip through an f32 container
            if not np.allclose(got, want, rtol=1e-5, atol=1e-6):
                raise AssertionError(
                    f"checkpoint roundtrip diverged at {key}: max diff "
                    f"{float(np.max(np.abs(got - want)))}")
        out["ckpt_roundtrip_ok"] = np.float64(1.0)
    np.savez(out_path, **out)


def _lint_per_host_hlo(trace_dir: str, num_processes: int, ff) -> None:
    """Feed the workers' per-host optimized-HLO dumps through fflint's
    multihost-order pass (FFL501/502 static deadlock detector). Raises
    when the per-host collective sequences diverge — the failure class
    that on a real pod only shows as a rendezvous timeout."""
    texts = []
    for p in range(num_processes):
        path = os.path.join(trace_dir, f"train_step_host{p}.hlo.txt")
        if not os.path.exists(path):
            raise AssertionError(
                f"multihost dryrun: worker {p} did not dump its train-step "
                f"HLO ({path}) — per-host collection is broken")
        with open(path) as f:
            texts.append(f.read())
    from flexflow_tpu.analysis import lint_model
    rep = lint_model(ff, hlo_per_host=texts)
    order = [d for d in rep.diagnostics if d.rule in ("FFL501", "FFL502")]
    if order:
        raise AssertionError(
            "multihost dryrun: per-host collective sequences diverge:\n"
            + "\n".join(d.format() for d in order))
    status = rep.passes.get("multihost-order")
    if status != "ok":
        raise AssertionError(
            f"multihost dryrun: multihost-order pass did not run: {status}")
    print(f"multihost dryrun: fflint multihost-order pass ok over "
          f"{len(texts)} per-host HLO programs")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# elastic fault-tolerance legs (ISSUE 10): kill a host mid-epoch via the
# FFS_FAULT harness, then resume from the last complete checkpoint on
# (a) the same mesh — bit-identical loss continuity — and (b) a smaller
# mesh through a re-searched strategy (resume is a strategy decision).


def _worker_env(trace_dir: Optional[str] = None) -> Dict[str, str]:
    env = dict(os.environ)
    env["FFS_MP_CHILD"] = "1"
    env.pop("JAX_PLATFORMS", None)
    # the per-process backend is configured inside the worker via jax
    # config (not env), so a sitecustomize cannot override it
    env.pop("XLA_FLAGS", None)
    env.pop("FFS_FAULT", None)
    if trace_dir:
        env["FFS_TRACE_DIR"] = trace_dir
    else:
        env.pop("FFS_TRACE_DIR", None)
    return env


def _spawn(entry: str, num_processes: int, devices_per_proc: int,
           outs, extra_args, env, timeout: int, tolerate_failures: bool,
           kill_grace: float = 30.0):
    """Spawn the rendezvous participants for one leg and wait.

    ``tolerate_failures`` is the fault-injection mode: the first worker
    to die does NOT fail the leg; its peers get ``kill_grace`` seconds
    to exit (they are mid-collective with a dead peer — gloo may error
    out or hang) and are then killed. Returns the exit-code list."""
    import time as _time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    try:
        for p in range(num_processes):
            code = (
                "import sys; sys.path.insert(0, %r); "
                "from flexflow_tpu.multihost_dryrun import %s; "
                "%s(%d, %d, %d, %d, %s)"
                % (repo, entry, entry, p, num_processes, port,
                   devices_per_proc,
                   ", ".join(repr(a) for a in [outs[p]] + list(extra_args)))
            )
            procs.append(subprocess.Popen([sys.executable, "-c", code],
                                          cwd=repo, env=env))
        if not tolerate_failures:
            return [proc.wait(timeout=timeout) for proc in procs]
        deadline = _time.monotonic() + timeout
        first_death = None
        while _time.monotonic() < deadline:
            codes = [proc.poll() for proc in procs]
            if all(c is not None for c in codes):
                return codes
            if any(c is not None for c in codes):
                if first_death is None:
                    first_death = _time.monotonic()
                elif _time.monotonic() - first_death > kill_grace:
                    break  # survivors are wedged on the dead peer
            _time.sleep(0.1)
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        return [proc.poll() for proc in procs]
    finally:
        # a worker that died pre-rendezvous leaves its peer blocked in
        # jax.distributed.initialize — never orphan it
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def _elastic_train_loop(ff, lx, ly, start: int, steps: int, mgr=None,
                        health=None):
    """The manual iteration protocol with the checkpoint-manager, fault
    and supervision seams fit() uses, returning the per-step losses —
    the loss series the continuity assertions compare bitwise.
    ``health`` (a runtime_health.RuntimeHealth) is fed after every
    step, exactly like fit's epoch loop: a pending preemption raises
    ``Preempted`` out of here AFTER the in-flight step."""
    from flexflow_tpu.ckpt import faults

    losses = []
    ff.set_batch(lx, ly)
    for step in range(start, steps):
        ff.forward()
        ff.backward()
        ff.update()
        losses.append(float(ff._last_loss))
        faults.step_hook(step)
        if health is not None:
            health.step_done(step)
        if mgr is not None:
            if mgr.should_save(ff._iter):
                mgr.save(ff._iter)
            else:
                mgr.note_step(ff._iter)
    return losses


def elastic_worker_main(process_id: int, num_processes: int, port: int,
                        devices_per_proc: int, out_path: str,
                        ckpt_dir: str, steps: int, every: int,
                        resume: int) -> None:
    """One participant of an elastic-training leg: train the dryrun
    model step by step with per-shard async checkpointing, honoring the
    FFS_FAULT plan the parent set (kill_host mid-epoch), optionally
    resuming from the newest complete checkpoint first."""
    os.environ.pop("JAX_PLATFORMS", None)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import distributed

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=num_processes,
                           process_id=process_id)
    total = jax.device_count()
    ff = _build(total)
    cfg = _model_config(total)
    x, y = _global_batch(cfg)
    rows, lo = distributed.local_batch_rows(
        ff.executor.batch_sharding(), x.shape[0])
    lx, ly = x[lo:lo + rows], y[lo:lo + rows]

    mgr = None
    start = 0
    if ckpt_dir:
        from flexflow_tpu.ckpt import CheckpointManager
        mgr = CheckpointManager(ff, ckpt_dir, every=every, retain=3,
                                async_write=True, run_name="dryrun",
                                fs_timeout=60.0)
        if resume:
            start = mgr.resume(require=True)
    losses = _elastic_train_loop(ff, lx, ly, start, steps, mgr)
    if mgr is not None:
        mgr.finalize(elapsed_s=None, steps=None)
    np.savez(out_path, losses=np.asarray(losses, np.float64),
             start=np.int64(start))


def failfast_worker_main(process_id: int, num_processes: int, port: int,
                         devices_per_proc: int, out_path: str,
                         base_dir: str) -> None:
    """Regression worker for the ADVICE r5 hang: every rank points at a
    RANK-PRIVATE checkpoint path (simulating a non-shared filesystem
    where only rank 0 can see the files rank 0 wrote). Both the v1 and
    the v2 load must raise the same actionable error on EVERY rank —
    promptly — instead of FileNotFoundError on some ranks and a
    collective deadlock on the rest."""
    os.environ.pop("JAX_PLATFORMS", None)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import distributed

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=num_processes,
                           process_id=process_id)
    total = jax.device_count()
    ff = _build(total)
    my_dir = os.path.join(base_dir, f"rank{process_id}")
    os.makedirs(my_dir, exist_ok=True)
    # v1: a collective save whose files land only under rank 0's view
    v1_stem = os.path.join(my_dir, "ckpt_v1")
    ff.save_checkpoint(os.path.join(base_dir, "rank0", "ckpt_v1")
                       if process_id == 0 else v1_stem + "_unwritten")
    results = {}
    try:
        ff.load_checkpoint(v1_stem)
        results["v1"] = "no error"
    except FileNotFoundError as e:
        results["v1"] = f"FileNotFoundError: {e}"
    # v2: rank 0 sees a real checkpoint, rank 1 an empty directory
    from flexflow_tpu.ckpt import load_sharded, save_sharded
    shared = os.path.join(base_dir, "shared_v2")
    save_sharded(shared, ff)  # all ranks participate; genuinely shared
    probe = shared if process_id == 0 else my_dir
    try:
        load_sharded(probe, ff)
        results["v2"] = "no error"
    except FileNotFoundError as e:
        results["v2"] = f"FileNotFoundError: {e}"
    np.savez(out_path, **{k: np.str_(v) for k, v in results.items()})


def run_ckpt_failfast_dryrun(num_processes: int = 2,
                             devices_per_proc: int = 1,
                             timeout: int = 240) -> None:
    """Assert the non-shared-filesystem load fails fast on every rank
    (ADVICE r5 regression): both format loaders must raise
    FileNotFoundError naming the invisible ranks, and the whole leg
    must finish well inside the timeout (the old behavior was an
    unbounded hang)."""
    with tempfile.TemporaryDirectory() as td:
        outs = [os.path.join(td, f"ff{p}.npz") for p in range(num_processes)]
        rcs = _spawn("failfast_worker_main", num_processes,
                     devices_per_proc, outs, [os.path.join(td, "ckpts")],
                     _worker_env(), timeout, tolerate_failures=False)
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(
                f"ckpt fail-fast dryrun: worker exit codes {rcs}")
        for p, out in enumerate(outs):
            got = {k: str(v) for k, v in np.load(out).items()}
            for fmt in ("v1", "v2"):
                if not got[fmt].startswith("FileNotFoundError"):
                    raise AssertionError(
                        f"worker {p} {fmt} load did not fail fast: "
                        f"{got[fmt]!r}")
                if "shared" not in got[fmt]:
                    raise AssertionError(
                        f"worker {p} {fmt} error is not actionable "
                        f"(no shared-filesystem hint): {got[fmt]!r}")
    print(f"ckpt fail-fast dryrun ok: {num_processes} ranks, both "
          f"formats raise actionable FileNotFoundError, no hang")


def run_elastic_dryrun(num_processes: int = 2, devices_per_proc: int = 1,
                       steps: int = 6, every: int = 2, kill_step: int = 4,
                       timeout: int = 240) -> dict:
    """Kill-and-resume end to end.

    Phase A: an uninterrupted N-process run records the reference loss
    series. Phase B: the same run with ``FFS_FAULT=kill_host:<last
    rank>@step:<kill_step>`` and per-shard async checkpointing — the
    killed host exits hard mid-epoch, the survivors are reaped, and the
    directory must hold a complete (manifest-committed) checkpoint and
    nothing readable beyond it. ``kill_step`` must leave at least one
    save() call strictly between the first checkpointed iteration and
    the kill: save() joins the PREVIOUS async writer on the training
    thread, so that earlier checkpoint is deterministically committed
    before the kill can fire — the leg never depends on a writer
    thread racing the (millisecond) training steps. Phase C: resume on
    the SAME mesh — the
    continued loss series must be bit-identical to the reference from
    the restored step on. Phase D (in-process): resume on a SMALLER
    mesh (half the devices) — ``plan_resume`` says "research", the
    native search (when available) picks a strategy for the surviving
    topology, and the reassembled state trains on with losses matching
    the reference to reduction-order tolerance. Returns a summary dict.
    """
    import jax

    total = num_processes * devices_per_proc
    kill_rank = num_processes - 1
    summary = {}
    with tempfile.TemporaryDirectory() as td:
        ckpt_dir = os.path.join(td, "ckpts")

        # ---- phase A: uninterrupted reference ---------------------------
        outs = [os.path.join(td, f"ref{p}.npz") for p in range(num_processes)]
        rcs = _spawn("elastic_worker_main", num_processes, devices_per_proc,
                     outs, ["", steps, every, 0], _worker_env(), timeout,
                     tolerate_failures=False)
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(f"elastic dryrun reference: exit codes {rcs}")
        ref = np.load(outs[0])["losses"]
        if len(ref) != steps or not np.all(np.isfinite(ref)):
            raise AssertionError(f"reference losses malformed: {ref}")

        # ---- phase B: kill a host mid-epoch -----------------------------
        from flexflow_tpu.ckpt.faults import KILL_EXIT
        env = _worker_env()
        env["FFS_FAULT"] = f"kill_host:{kill_rank}@step:{kill_step}"
        outs_b = [os.path.join(td, f"fault{p}.npz")
                  for p in range(num_processes)]
        rcs = _spawn("elastic_worker_main", num_processes, devices_per_proc,
                     outs_b, [ckpt_dir, steps, every, 0], env, timeout,
                     tolerate_failures=True)
        if rcs[kill_rank] != KILL_EXIT:
            raise AssertionError(
                f"fault leg: rank {kill_rank} was meant to die with exit "
                f"{KILL_EXIT} at step {kill_step}, got exit codes {rcs}")
        from flexflow_tpu.ckpt import latest_complete, verify_step_dir
        latest = latest_complete(ckpt_dir)
        if latest is None:
            raise AssertionError(
                "fault leg left no complete checkpoint — the pre-kill "
                "saves never committed")
        resume_step, step_dir = latest
        if resume_step > kill_step + 1:
            raise AssertionError(
                f"complete checkpoint at iteration {resume_step} claims "
                f"steps after the kill at step {kill_step}")
        rep = verify_step_dir(step_dir)
        if not rep["complete"]:
            raise AssertionError(
                f"latest checkpoint fails deep verification: "
                f"{rep['errors']}")
        summary["resume_step"] = resume_step

        # ---- phase C: resume on the SAME mesh — bit-identical -----------
        outs_c = [os.path.join(td, f"res{p}.npz")
                  for p in range(num_processes)]
        rcs = _spawn("elastic_worker_main", num_processes, devices_per_proc,
                     outs_c, [ckpt_dir, steps, every, 1], _worker_env(),
                     timeout, tolerate_failures=False)
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(f"elastic dryrun resume: exit codes {rcs}")
        for p, out in enumerate(outs_c):
            got = np.load(out)
            start = int(got["start"])
            if start != resume_step:
                raise AssertionError(
                    f"worker {p} resumed at {start}, expected "
                    f"{resume_step}")
            cont = got["losses"]
            want = ref[start:]
            if not np.array_equal(cont, want):
                raise AssertionError(
                    f"worker {p}: resumed losses diverge from the "
                    f"uninterrupted run on the same mesh — not "
                    f"bit-identical\n  resumed {cont}\n  expected {want}")
        summary["same_mesh_bitwise"] = True

        # ---- phase D: resume on a SMALLER mesh (re-searched) ------------
        n_small = max(1, total // 2)
        if len(jax.devices()) < n_small:
            raise RuntimeError(
                f"elastic dryrun needs {n_small} local devices for the "
                f"smaller-mesh leg, have {len(jax.devices())}")
        # phase C's resumed run has since committed newer checkpoints
        # into the same directory — phase D must restart from the same
        # post-kill state, so it targets the surviving step dir directly
        from flexflow_tpu.ckpt import load_manifest, plan_resume
        plan = plan_resume(load_manifest(step_dir), n_small)
        if plan["action"] != "research":
            raise AssertionError(
                f"plan_resume on {n_small}/{plan['saved_devices']} devices "
                f"should demand a re-search, got {plan}")
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.machine import make_mesh
        from flexflow_tpu.models.transformer import create_transformer
        from flexflow_tpu.optimizers import SGDOptimizer
        from flexflow_tpu.search.native import available as _native_ok
        cfg = _model_config(total)
        budget = 6 if _native_ok() else 0
        ff_small = create_transformer(
            cfg, FFConfig(batch_size=cfg.batch_size,
                          workers_per_node=n_small,
                          search_budget=budget,
                          enable_parameter_parallel=n_small > 1))
        ff_small.compile(SGDOptimizer(lr=0.05),
                         LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                         mesh=None if budget else make_mesh(
                             n_small, {"data": n_small}))
        mesh_small = dict(zip(ff_small.mesh.axis_names,
                              ff_small.mesh.devices.shape))
        it = ff_small.load_checkpoint(step_dir)
        if it != resume_step:
            raise AssertionError(
                f"smaller-mesh load restored iteration {it}, expected "
                f"{resume_step}")
        x, y = _global_batch(cfg)
        cont = _elastic_train_loop(ff_small, x, y, resume_step, steps)
        if not np.all(np.isfinite(cont)):
            raise AssertionError(
                f"smaller-mesh resume produced non-finite losses: {cont}")
        if not np.allclose(cont, ref[resume_step:], rtol=1e-3, atol=1e-5):
            raise AssertionError(
                f"smaller-mesh resumed losses diverged beyond reduction-"
                f"order tolerance\n  resumed {cont}\n  "
                f"expected {ref[resume_step:]}")
        summary["smaller_mesh"] = mesh_small
        summary["researched"] = bool(budget)
    print(f"elastic dryrun ok: {num_processes}x{devices_per_proc} killed "
          f"rank {kill_rank} at step {kill_step}, resumed from iteration "
          f"{summary['resume_step']}: same-mesh continuation bit-identical"
          f"; smaller mesh {summary['smaller_mesh']} "
          f"({'re-searched strategy' if summary['researched'] else 'heuristic strategy'}) "
          f"converges within tolerance")
    return summary


# ---------------------------------------------------------------------------
# preemption-aware supervision legs (ISSUE 12): SIGTERM mid-epoch must
# yield a complete grace-window checkpoint and a bit-identical resume;
# a hung step loop must be reaped by the watchdog and auto-restarted by
# the supervisor; transient checkpoint-write failures must be absorbed
# by retry-with-backoff.


def preempted_worker_main(process_id: int, num_processes: int, port: int,
                          devices_per_proc: int, out_path: str,
                          ckpt_dir: str, steps: int, every: int,
                          resume: int, grace: float) -> None:
    """Elastic worker + RuntimeHealth: honors ``FFS_FAULT`` sigterm
    specs, converts the signal into a grace-window final checkpoint,
    and exits ``PREEMPTED_EXIT`` — the multi-host half of the graceful
    preemption contract (every rank must still reach the commit
    barrier inside the grace window)."""
    os.environ.pop("JAX_PLATFORMS", None)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import distributed
    from flexflow_tpu.runtime_health import (Preempted, PREEMPTED_EXIT,
                                             RuntimeHealth)

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=num_processes,
                           process_id=process_id)
    total = jax.device_count()
    ff = _build(total)
    cfg = _model_config(total)
    x, y = _global_batch(cfg)
    rows, lo = distributed.local_batch_rows(
        ff.executor.batch_sharding(), x.shape[0])
    lx, ly = x[lo:lo + rows], y[lo:lo + rows]

    from flexflow_tpu.ckpt import CheckpointManager
    health = RuntimeHealth(grace_window_s=grace, run_name="dryrun")
    mgr = CheckpointManager(ff, ckpt_dir, every=every, retain=3,
                            async_write=True, run_name="dryrun",
                            fs_timeout=60.0, heartbeat=health.heartbeat)
    start = mgr.resume(require=True) if resume else 0
    health.install()
    try:
        losses = _elastic_train_loop(ff, lx, ly, start, steps, mgr,
                                     health=health)
    except Preempted:
        # the grace path: final checkpoint through the manager (every
        # rank participates in the commit barrier), then the distinct
        # exit code the supervisor classifies as "preempted"
        mgr.finalize(elapsed_s=None, steps=None)
        np.savez(out_path, losses=np.asarray([], np.float64),
                 start=np.int64(start), preempted=np.int64(1))
        health.close()
        sys.exit(PREEMPTED_EXIT)
    mgr.finalize(elapsed_s=None, steps=None)
    health.close()
    np.savez(out_path, losses=np.asarray(losses, np.float64),
             start=np.int64(start), preempted=np.int64(0))


def run_preemption_dryrun(num_processes: int = 2,
                          devices_per_proc: int = 1, steps: int = 6,
                          sigterm_step: int = 3,
                          timeout: int = 240) -> dict:
    """SIGTERM mid-epoch → grace-window checkpoint → bit-identical
    auto-resume, across processes.

    Phase A records the uninterrupted reference loss series. Phase B
    delivers ``FFS_FAULT sigterm`` to EVERY rank at ``sigterm_step``
    (the whole-slice preemption shape a platform maintenance event
    takes): each rank finishes the in-flight step, the grace path cuts
    a final checkpoint through the normal commit barrier, and every
    rank exits ``PREEMPTED_EXIT``. Phase C resumes on the same mesh and
    must continue bit-identically to the reference from the restored
    iteration on."""
    from flexflow_tpu.runtime_health import PREEMPTED_EXIT

    summary = {}
    with tempfile.TemporaryDirectory() as td:
        ckpt_dir = os.path.join(td, "ckpts")

        # ---- phase A: uninterrupted reference ---------------------------
        outs = [os.path.join(td, f"ref{p}.npz") for p in range(num_processes)]
        rcs = _spawn("elastic_worker_main", num_processes, devices_per_proc,
                     outs, ["", steps, 0, 0], _worker_env(), timeout,
                     tolerate_failures=False)
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(
                f"preemption dryrun reference: exit codes {rcs}")
        ref = np.load(outs[0])["losses"]
        if len(ref) != steps or not np.all(np.isfinite(ref)):
            raise AssertionError(f"reference losses malformed: {ref}")

        # ---- phase B: SIGTERM every rank mid-epoch ----------------------
        env = _worker_env()
        env["FFS_FAULT"] = ",".join(
            f"sigterm:{r}@step:{sigterm_step}" for r in range(num_processes))
        outs_b = [os.path.join(td, f"pre{p}.npz")
                  for p in range(num_processes)]
        rcs = _spawn("preempted_worker_main", num_processes,
                     devices_per_proc, outs_b,
                     [ckpt_dir, steps, 0, 0, 60.0], env, timeout,
                     tolerate_failures=False)
        if rcs != [PREEMPTED_EXIT] * num_processes:
            raise AssertionError(
                f"preemption leg: every rank must exit PREEMPTED_EXIT "
                f"({PREEMPTED_EXIT}), got {rcs}")
        from flexflow_tpu.ckpt import latest_complete, verify_step_dir
        latest = latest_complete(ckpt_dir)
        if latest is None:
            raise AssertionError(
                "preemption leg left no complete checkpoint — the grace "
                "window did not produce a committed save")
        resume_step, step_dir = latest
        if resume_step != sigterm_step + 1:
            raise AssertionError(
                f"grace checkpoint at iteration {resume_step}, expected "
                f"{sigterm_step + 1} (the post-in-flight-step state)")
        rep = verify_step_dir(step_dir)
        if not rep["complete"]:
            raise AssertionError(
                f"grace checkpoint fails deep verification: "
                f"{rep['errors']}")
        summary["resume_step"] = resume_step

        # ---- phase C: auto-resume, bit-identical ------------------------
        outs_c = [os.path.join(td, f"res{p}.npz")
                  for p in range(num_processes)]
        rcs = _spawn("preempted_worker_main", num_processes,
                     devices_per_proc, outs_c,
                     [ckpt_dir, steps, 0, 1, 60.0], _worker_env(),
                     timeout, tolerate_failures=False)
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(f"preemption dryrun resume: exit codes {rcs}")
        for p, out in enumerate(outs_c):
            got = np.load(out)
            start = int(got["start"])
            if start != resume_step:
                raise AssertionError(
                    f"worker {p} resumed at {start}, expected "
                    f"{resume_step}")
            cont = got["losses"]
            want = ref[start:]
            if not np.array_equal(cont, want):
                raise AssertionError(
                    f"worker {p}: post-preemption losses diverge from "
                    f"the uninterrupted run — not bit-identical\n  "
                    f"resumed {cont}\n  expected {want}")
        summary["bitwise"] = True
    print(f"preemption dryrun ok: {num_processes}x{devices_per_proc} "
          f"SIGTERM at step {sigterm_step} -> complete grace checkpoint "
          f"at iteration {summary['resume_step']}, resumed continuation "
          f"bit-identical")
    return summary


_SUPERVISED_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
from flexflow_tpu.ffconst import ActiMode
cfg = FFConfig(batch_size=64)
rest = cfg.parse_args(sys.argv[1:])
assert not rest, f"unparsed flags: {{rest}}"
ff = FFModel(cfg)
t = ff.create_tensor((64, 16))
h = ff.dense(t, 32, activation=ActiMode.AC_MODE_RELU, name="h1")
out = ff.dense(h, 4, name="out")
ff.softmax(out)
ff.compile(AdamOptimizer(alpha=0.01))
rs = np.random.RandomState(0)
x = rs.randn(256, 16).astype(np.float32)
y = rs.randint(0, 4, 256).astype(np.int32).reshape(-1, 1)
ff.fit(x, y, epochs=2, verbose=False)
print("supervised child done: loss", float(ff._last_loss), flush=True)
"""


def run_supervised_dryrun(watchdog_timeout: float = 10.0) -> dict:
    """Self-healing auto-resume, end to end, single process per
    attempt: the Supervisor runs a real training subprocess through
    the real ``fit`` wiring (``--watchdog-timeout``/``--grace-window``
    flags), classifies the exit, and restarts with ``--resume``.

    Leg 1 (hang): ``FFS_FAULT hang`` wedges the step loop — the
    watchdog dumps stacks and exits ``HUNG_EXIT``; the supervised
    restart (fault cleared: an injected fault models a one-time event)
    resumes from the last complete checkpoint and finishes clean.
    Leg 2 (kill): ``FFS_FAULT kill_host`` hard-kills mid-epoch; same
    supervised recovery. Leg 3 (io_error, in-process): transient
    checkpoint-write failures are absorbed by retry-with-backoff with
    the retry count visible in obs counters."""
    from flexflow_tpu.ckpt import latest_complete, verify_step_dir
    from flexflow_tpu.ckpt import manifest as mf
    from flexflow_tpu.runtime_health import Supervisor

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_src = _SUPERVISED_CHILD.format(repo=repo)
    summary = {}

    def _run_leg(name, fault, ckpt_dir):
        cmd = [sys.executable, "-c", child_src,
               "--checkpoint-dir", ckpt_dir, "--checkpoint-every", "2",
               "--watchdog-timeout", str(watchdog_timeout),
               "--grace-window", "60"]
        env = _worker_env()
        env["FFS_FAULT"] = fault
        sup = Supervisor(cmd, max_restarts=2, backoff_base_s=0.2,
                         backoff_max_s=2.0, env=env,
                         state_path=os.path.join(ckpt_dir,
                                                 mf.SUPERVISOR_NAME))
        res = sup.run()
        outcomes = [h["outcome"] for h in res["history"]]
        if res["final_outcome"] != "clean":
            raise AssertionError(
                f"{name} leg: supervised run did not converge to clean "
                f"(history {outcomes}, final code {res['final_code']})")
        latest = latest_complete(ckpt_dir)
        if latest is None or not verify_step_dir(latest[1])["complete"]:
            raise AssertionError(
                f"{name} leg: no complete checkpoint after supervised "
                f"recovery")
        sup_state = mf.read_supervisor(ckpt_dir)
        if not sup_state or sup_state.get("restarts", 0) < 1:
            raise AssertionError(
                f"{name} leg: SUPERVISOR.json missing or records no "
                f"restart: {sup_state}")
        return outcomes

    with tempfile.TemporaryDirectory() as td:
        # ---- leg 1: hang -> watchdog HUNG_EXIT -> supervised restart ----
        outcomes = _run_leg("hang", "hang:0@step:3",
                            os.path.join(td, "hang"))
        if outcomes[0] != "hung":
            raise AssertionError(
                f"hang leg: first attempt should be classified 'hung' "
                f"(watchdog exit), got {outcomes}")
        summary["hang"] = outcomes

        # ---- leg 2: kill -> supervised auto-resume ----------------------
        outcomes = _run_leg("kill", "kill_host:0@step:4",
                            os.path.join(td, "kill"))
        if outcomes[0] != "kill":
            raise AssertionError(
                f"kill leg: first attempt should be classified 'kill', "
                f"got {outcomes}")
        summary["kill"] = outcomes

        # ---- leg 3: transient io_error -> retried save completes --------
        from flexflow_tpu.ckpt import save_sharded
        from flexflow_tpu.obs.registry import get_registry
        ff = _build(1)
        cfg = _model_config(1)
        x, y = _global_batch(cfg)
        ff.fit(x, y, epochs=1, verbose=False)
        io_dir = os.path.join(td, "io")
        reg = get_registry()
        before = reg.get("ckpt/io_retries")
        old = os.environ.get("FFS_FAULT")
        from flexflow_tpu.ckpt import faults as _faults
        # the parse cache memoizes FaultPlan per spec string and the
        # io_error budget is mutable on the cached object — a stale
        # (depleted) plan would inject nothing
        _faults._CACHE.pop("io_error:shards_host:2", None)
        os.environ["FFS_FAULT"] = "io_error:shards_host:2"
        try:
            save_sharded(io_dir, ff)
        finally:
            if old is None:
                os.environ.pop("FFS_FAULT", None)
            else:
                os.environ["FFS_FAULT"] = old
        retries = reg.get("ckpt/io_retries") - before
        latest = latest_complete(io_dir)
        if latest is None or not verify_step_dir(latest[1])["complete"]:
            raise AssertionError(
                "io_error leg: retried save did not produce a complete "
                "checkpoint")
        if retries != 2:
            raise AssertionError(
                f"io_error leg: expected 2 visible retries in obs "
                f"counters, got {retries}")
        summary["io_retries"] = int(retries)
    print(f"supervised dryrun ok: hang {summary['hang']}, kill "
          f"{summary['kill']} (auto-resumed to clean under the "
          f"supervisor), io_error absorbed with {summary['io_retries']} "
          f"retries")
    return summary


# ---------------------------------------------------------------------------
# multi-slice legs (ISSUE 16): N process sets stand in for N
# DCN-connected slices — the ('slice', 'data') runtime mesh crosses the
# set boundary exactly where a real deployment crosses the DCN. The
# lint pass checks per-slice collective order (FFL501/502 with slice
# attribution) plus the cross-slice leader agreement (FFL503), and the
# kill-one-slice leg exercises plan_resume's slice_loss topology class.


def _build_multislice(total_devices: int, num_slices: int):
    """Compile the dryrun model over a ('slice', 'data') mesh:
    ``--slices`` splits the flat data mesh in model.compile, so the
    gradient sync's cross-slice leg rides the outer axis."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.machine import make_mesh
    from flexflow_tpu.models.transformer import create_transformer
    from flexflow_tpu.optimizers import SGDOptimizer

    cfg = _model_config(total_devices)
    c = FFConfig(batch_size=cfg.batch_size)
    c.slices = num_slices
    ff = create_transformer(cfg, c)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=make_mesh(total_devices, {"data": total_devices}))
    assert "slice" in ff.mesh.axis_names, ff.mesh.axis_names
    return ff


def multislice_worker_main(process_id: int, num_processes: int, port: int,
                           devices_per_proc: int, out_path: str,
                           ckpt_dir: str, num_slices: int, steps: int,
                           every: int) -> None:
    """One participant of a multi-slice leg: processes form
    ``num_slices`` contiguous sets (slice-major, matching the
    ('slice', ...) mesh's device order), train over the cross-slice
    data axis with per-shard checkpointing, honor FFS_FAULT, and dump
    the per-host optimized HLO for the hierarchical lint pass."""
    os.environ.pop("JAX_PLATFORMS", None)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import distributed
    from flexflow_tpu.multislice import slice_of_process

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=num_processes,
                           process_id=process_id)
    total = jax.device_count()
    my_slice = slice_of_process(process_id, num_processes, num_slices)
    ff = _build_multislice(total, num_slices)
    cfg = _model_config(total)
    x, y = _global_batch(cfg)
    rows, lo = distributed.local_batch_rows(
        ff.executor.batch_sharding(), x.shape[0])
    lx, ly = x[lo:lo + rows], y[lo:lo + rows]
    trace_dir = os.environ.get("FFS_TRACE_DIR") or None
    if trace_dir:
        from flexflow_tpu.search.validate import train_step_hlo
        hlo_path = os.path.join(trace_dir,
                                f"train_step_host{process_id}.hlo.txt")
        with open(hlo_path, "w") as f:
            f.write(train_step_hlo(ff))
    mgr = None
    if ckpt_dir:
        from flexflow_tpu.ckpt import CheckpointManager
        mgr = CheckpointManager(ff, ckpt_dir, every=every, retain=3,
                                async_write=True, run_name="msdryrun",
                                fs_timeout=60.0)
    losses = _elastic_train_loop(ff, lx, ly, 0, steps, mgr)
    if mgr is not None:
        mgr.finalize(elapsed_s=None, steps=None)
    np.savez(out_path, losses=np.asarray(losses, np.float64),
             slice_id=np.int64(my_slice),
             mesh_axes=np.asarray(
                 [f"{a}={s}" for a, s in zip(ff.mesh.axis_names,
                                             ff.mesh.devices.shape)]))


def _lint_per_slice_hlo(trace_dir: str, num_processes: int,
                        num_slices: int, ff) -> None:
    """Feed the workers' per-host HLO dumps through fflint's
    hierarchical multihost-order pass: within-slice FFL501/502 with
    slice attribution plus the FFL503 cross-slice leader comparison.
    Raises on any order diagnostic."""
    from flexflow_tpu.multislice import slice_of_process
    texts = []
    for p in range(num_processes):
        path = os.path.join(trace_dir, f"train_step_host{p}.hlo.txt")
        if not os.path.exists(path):
            raise AssertionError(
                f"multislice dryrun: worker {p} did not dump its "
                f"train-step HLO ({path})")
        with open(path) as f:
            texts.append(f.read())
    slice_of = [slice_of_process(p, num_processes, num_slices)
                for p in range(num_processes)]
    from flexflow_tpu.analysis import lint_model
    rep = lint_model(ff, hlo_per_host=texts, slice_of_host=slice_of)
    order = [d for d in rep.diagnostics
             if d.rule in ("FFL501", "FFL502", "FFL503")]
    if order:
        raise AssertionError(
            "multislice dryrun: per-slice collective sequences diverge:\n"
            + "\n".join(d.format() for d in order))
    if rep.passes.get("multihost-order") != "ok":
        raise AssertionError(
            f"multislice dryrun: multihost-order pass did not run: "
            f"{rep.passes.get('multihost-order')}")
    print(f"multislice dryrun: fflint multihost-order ok over "
          f"{num_slices} slices x {num_processes // num_slices} "
          f"processes (FFL501/502/503 clean)")


def run_multislice_dryrun(num_slices: int = 2, procs_per_slice: int = 2,
                          devices_per_proc: int = 1, steps: int = 6,
                          every: int = 2, kill_step: int = 4,
                          timeout: int = 300) -> dict:
    """Multi-slice training end to end, devicelessly.

    Phase A: ``num_slices x procs_per_slice`` processes train over a
    ('slice', 'data') mesh whose slice axis crosses the process-set
    boundary; every process dumps its optimized HLO and the
    hierarchical fflint pass must come back FFL501/502/503-clean.
    Phase B: the same run with per-shard checkpointing and
    ``FFS_FAULT`` killing a rank in the LAST slice mid-epoch — losing
    a host loses its slice; the directory must hold a complete
    manifest-committed checkpoint whose mesh records the slice axis.
    Phase C (in-process): ``plan_resume`` on the surviving slice's
    device count must classify the change as ``slice_loss`` (1 of
    ``num_slices`` slices lost, resume ``--slices`` = survivors), the
    survivors compile WITHOUT a slice axis (single surviving slice) —
    re-searched when the native search is available — and the
    continued losses match the reference within reduction-order
    tolerance. Returns a summary dict."""
    import jax

    num_processes = num_slices * procs_per_slice
    total = num_processes * devices_per_proc
    kill_rank = num_processes - 1  # a host of the last slice
    summary = {}
    with tempfile.TemporaryDirectory() as td:
        ckpt_dir = os.path.join(td, "ckpts")
        trace_dir = os.path.join(td, "trace")
        os.makedirs(trace_dir)

        # ---- phase A: reference run + hierarchical lint -----------------
        outs = [os.path.join(td, f"ref{p}.npz") for p in range(num_processes)]
        rcs = _spawn("multislice_worker_main", num_processes,
                     devices_per_proc, outs,
                     ["", num_slices, steps, every],
                     _worker_env(trace_dir=trace_dir), timeout,
                     tolerate_failures=False)
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(
                f"multislice dryrun reference: exit codes {rcs}")
        ref = np.load(outs[0])["losses"]
        if len(ref) != steps or not np.all(np.isfinite(ref)):
            raise AssertionError(f"reference losses malformed: {ref}")
        for p, out in enumerate(outs):
            got = np.load(out)
            want_slice = p // procs_per_slice
            if int(got["slice_id"]) != want_slice:
                raise AssertionError(
                    f"worker {p} mapped to slice {int(got['slice_id'])}, "
                    f"expected {want_slice}")
            if not np.array_equal(got["losses"], ref):
                raise AssertionError(
                    f"worker {p} loss series diverges from rank 0 — the "
                    f"cross-slice sync is broken")
        if len(jax.devices()) < total:
            raise RuntimeError(
                f"multislice dryrun needs {total} local devices for the "
                f"lint-context leg, have {len(jax.devices())}")
        ff_lint = _build_multislice(total, num_slices)
        _lint_per_slice_hlo(trace_dir, num_processes, num_slices, ff_lint)
        summary["lint"] = "ok"

        # ---- phase B: kill one slice mid-epoch --------------------------
        from flexflow_tpu.ckpt.faults import KILL_EXIT
        env = _worker_env(trace_dir=None)
        env["FFS_FAULT"] = f"kill_host:{kill_rank}@step:{kill_step}"
        outs_b = [os.path.join(td, f"fault{p}.npz")
                  for p in range(num_processes)]
        rcs = _spawn("multislice_worker_main", num_processes,
                     devices_per_proc, outs_b,
                     [ckpt_dir, num_slices, steps, every], env, timeout,
                     tolerate_failures=True)
        if rcs[kill_rank] != KILL_EXIT:
            raise AssertionError(
                f"fault leg: rank {kill_rank} was meant to die with exit "
                f"{KILL_EXIT} at step {kill_step}, got exit codes {rcs}")
        from flexflow_tpu.ckpt import latest_complete, verify_step_dir
        latest = latest_complete(ckpt_dir)
        if latest is None:
            raise AssertionError(
                "fault leg left no complete checkpoint")
        resume_step, step_dir = latest
        rep = verify_step_dir(step_dir)
        if not rep["complete"]:
            raise AssertionError(
                f"latest checkpoint fails deep verification: "
                f"{rep['errors']}")
        summary["resume_step"] = resume_step

        # ---- phase C: slice-loss resume on the survivors ----------------
        from flexflow_tpu.ckpt import load_manifest, plan_resume
        manifest = load_manifest(step_dir)
        if int(manifest.get("mesh", {}).get("slice", 0)) != num_slices:
            raise AssertionError(
                f"checkpoint manifest does not record the slice axis: "
                f"{manifest.get('mesh')}")
        n_survive = total - total // num_slices
        plan = plan_resume(manifest, n_survive)
        if plan.get("topology") != "slice_loss":
            raise AssertionError(
                f"plan_resume did not classify losing a slice "
                f"({n_survive}/{total} devices): {plan}")
        if (plan["lost_slices"] != 1
                or plan["surviving_slices"] != num_slices - 1
                or plan["slices"] != num_slices - 1):
            raise AssertionError(f"slice_loss plan malformed: {plan}")
        if len(jax.devices()) < n_survive:
            raise RuntimeError(
                f"multislice dryrun needs {n_survive} local devices for "
                f"the resume leg, have {len(jax.devices())}")
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.machine import make_mesh
        from flexflow_tpu.models.transformer import create_transformer
        from flexflow_tpu.optimizers import SGDOptimizer
        from flexflow_tpu.search.native import available as _native_ok
        cfg = _model_config(total)
        budget = 6 if _native_ok() else 0
        c_small = FFConfig(batch_size=cfg.batch_size,
                           workers_per_node=n_survive,
                           search_budget=budget)
        c_small.slices = plan["slices"] if plan["slices"] > 1 else 1
        ff_small = create_transformer(cfg, c_small)
        ff_small.compile(SGDOptimizer(lr=0.05),
                         LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                         mesh=None if budget else make_mesh(
                             n_survive, {"data": n_survive}))
        it = ff_small.load_checkpoint(step_dir)
        if it != resume_step:
            raise AssertionError(
                f"slice-loss load restored iteration {it}, expected "
                f"{resume_step}")
        x, y = _global_batch(cfg)
        cont = _elastic_train_loop(ff_small, x, y, resume_step, steps)
        if not np.all(np.isfinite(cont)):
            raise AssertionError(
                f"slice-loss resume produced non-finite losses: {cont}")
        if not np.allclose(cont, ref[resume_step:], rtol=1e-3, atol=1e-5):
            raise AssertionError(
                f"slice-loss resumed losses diverged beyond reduction-"
                f"order tolerance\n  resumed {cont}\n  "
                f"expected {ref[resume_step:]}")
        summary["surviving_mesh"] = dict(zip(ff_small.mesh.axis_names,
                                             ff_small.mesh.devices.shape))
        summary["researched"] = bool(budget)
    print(f"multislice dryrun ok: {num_slices} slices x "
          f"{procs_per_slice} processes, lint FFL501/502/503 clean; "
          f"killed slice {num_slices - 1} at step {kill_step}, "
          f"plan_resume classified slice_loss, survivors "
          f"{summary['surviving_mesh']} "
          f"({'re-searched' if summary['researched'] else 'heuristic'} "
          f"strategy) resumed from iteration {summary['resume_step']} "
          f"within tolerance")
    return summary


def run_dryrun(num_processes: int = 2, devices_per_proc: int = 2,
               timeout: int = 600,
               trace_dir: Optional[str] = None,
               profile_steps: Optional[str] = None) -> None:
    """Spawn the workers, train, and assert parity with a single-process
    run on the same global batch. Raises on any mismatch.

    The calling process must have >= num_processes * devices_per_proc
    JAX devices for the single-process reference leg. ``trace_dir``
    turns on per-host step tracing in every worker; after the workers
    exit their per-host Chrome traces are merged into one
    ``merged.trace.json`` keyed by host id (pid = host in Perfetto).
    ``profile_steps`` (with ``trace_dir``) additionally captures each
    worker's device trace over that step window, so the merged timeline
    shows every host's device compute/comms lanes on the shared
    wall-clock epoch."""
    import jax

    total = num_processes * devices_per_proc
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    with tempfile.TemporaryDirectory() as td:
        outs = [os.path.join(td, f"worker{p}.npz")
                for p in range(num_processes)]
        procs = []
        env = dict(os.environ)
        env["FFS_MP_CHILD"] = "1"
        env.pop("JAX_PLATFORMS", None)
        if trace_dir:
            env["FFS_TRACE_DIR"] = trace_dir
        else:
            env.pop("FFS_TRACE_DIR", None)
        if trace_dir and profile_steps:
            env["FFS_PROFILE_STEPS"] = profile_steps
        else:
            env.pop("FFS_PROFILE_STEPS", None)
        # the per-process backend is configured inside worker_main via
        # jax config (not env), so a sitecustomize cannot override it
        env.pop("XLA_FLAGS", None)
        try:
            for p in range(num_processes):
                code = (
                    "import sys; sys.path.insert(0, %r); "
                    "from flexflow_tpu.multihost_dryrun import worker_main; "
                    "worker_main(%d, %d, %d, %d, %r)"
                    % (repo, p, num_processes, port, devices_per_proc,
                       outs[p])
                )
                procs.append(subprocess.Popen([sys.executable, "-c", code],
                                              cwd=repo, env=env))
            rcs = [proc.wait(timeout=timeout) for proc in procs]
        finally:
            # a worker that died pre-rendezvous leaves its peer blocked in
            # jax.distributed.initialize — never orphan it
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
        if any(rc != 0 for rc in rcs):
            raise RuntimeError(
                f"multihost dryrun: worker exit codes {rcs}")
        worker_results = [dict(np.load(o)) for o in outs]

    if trace_dir:
        from flexflow_tpu.obs import merge_host_traces
        merged = merge_host_traces(trace_dir)
        if merged:
            print(f"multihost dryrun: merged per-host traces -> {merged}")

    # single-process references on the same global batch
    if len(jax.devices()) < total:
        raise RuntimeError(
            f"multihost dryrun needs {total} local devices for the "
            f"reference leg, have {len(jax.devices())}")
    legs = ["dp"] + (["tp", "ring"] if _multi_axis_legs_possible(total) else [])
    refs = {}
    dp_extra = {}
    dp_model = None
    for leg in legs:
        ref, rx, ry = _build_and_train(total, leg=leg)
        if leg == "dp":
            dp_model = ref
            dp_extra["eval_loss"] = float(ref.evaluate(rx, ry)["loss"])
            dp_extra["predict"] = ref.predict(rx)
        refs[leg] = (_params_to_numpy(ref), float(ref._last_loss))

    if trace_dir:
        # the fflint FFL501/502 static deadlock pass, end-to-end: compare
        # the per-host optimized-HLO collective sequences every worker
        # dumped. A host-dependent divergence here is the bug class that
        # otherwise only shows as a wall-clock timeout on a real pod.
        _lint_per_host_hlo(trace_dir, num_processes, dp_model)

    loss_keys = {"dp": "loss", "tp": "tp_loss", "ring": "ring_loss"}
    for p, got in enumerate(worker_results):
        for leg in legs:
            loss_key = loss_keys[leg]
            ref_params, ref_loss = refs[leg]
            got_loss = float(got.pop(loss_key))
            if not np.isfinite(got_loss) or abs(got_loss - ref_loss) > \
                    1e-4 * (1.0 + abs(ref_loss)):
                raise AssertionError(
                    f"worker {p} {leg} loss {got_loss} != reference "
                    f"{ref_loss}")
            leg_params = {k[len(leg) + 1:]: v for k, v in got.items()
                          if k.startswith(f"{leg}/")}
            missing = set(ref_params) - set(leg_params)
            if missing:
                raise AssertionError(
                    f"worker {p} {leg} missing params: {missing}")
            for k, rv in ref_params.items():
                if not np.allclose(leg_params[k], rv, rtol=1e-4,
                                   atol=1e-5):
                    diff = float(np.max(np.abs(leg_params[k] - rv)))
                    raise AssertionError(
                        f"worker {p} {leg} param {k} diverged from "
                        f"single-process reference (max abs diff {diff})")
        if "tp" in refs and "ckpt_roundtrip_ok" not in got:
            raise AssertionError(
                f"worker {p} skipped the cross-host checkpoint roundtrip")
        # evaluate/predict parity vs the single-process reference
        if abs(float(got["eval_loss"]) - dp_extra["eval_loss"]) > 1e-4 * (
                1.0 + abs(dp_extra["eval_loss"])):
            raise AssertionError(
                f"worker {p} evaluate loss {float(got['eval_loss'])} != "
                f"reference {dp_extra['eval_loss']}")
        if not np.allclose(got["predict"], dp_extra["predict"], rtol=1e-4,
                           atol=1e-5):
            raise AssertionError(f"worker {p} predict diverged")
    names = {"dp": "data-parallel", "tp": "cross-host tensor-parallel",
             "ring": "cross-host ring attention"}
    legs_txt = " + ".join(names[leg] for leg in refs)
    if "tp" in refs:
        legs_txt += " + checkpoint roundtrip"
    losses = ", ".join(f"{leg} loss {refs[leg][1]:.6f}" for leg in refs)
    print(f"multihost dryrun ok: {num_processes} processes x "
          f"{devices_per_proc} devices; {legs_txt} "
          f"match single-process ({losses})")

"""Real-chip op microbenchmarks feeding the search's measured-cost channel.

Analog of the reference's microbenchmark calibration: its simulator times
each operator's forward/backward on the actual device and caches the
result by parameter hash (``measure_operator_cost``,
/root/reference/src/runtime/model.cu:38-74;
``hash_to_operator_cost``, /root/reference/include/flexflow/simulator.h:750-752),
so the search optimizes real costs instead of an analytic model. Here each
materialized Op's ``forward`` (and its JAX-derived backward) is jitted and
timed standalone on the current default device; results are keyed by the
op's structural ``param_key`` hash + platform so repeated compiles and
repeated runs hit the cache.

The native search consumes the table through ``measured`` entries
``"<guid>:fwd"`` / ``"<guid>:bwd"`` (native/ffs_strategy.hpp node_cost):
measured seconds for the *unsharded* op, which the cost model divides by
the sharding's work_div — mirroring how the reference scales its measured
per-op cost by the machine view's degree.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import OpContext

# process-wide cache: param-key hash -> (fwd_s, bwd_s)
_CACHE: Dict[str, Tuple[float, float]] = {}


def op_cost_key(op) -> str:
    """Structural identity of an op config on this platform — two ops with
    identical type/shapes/properties share one measurement (the analog of
    the reference's *Params hash). The execution layout is part of the
    identity: an NHWC conv and an NCHW conv are different programs with
    very different costs (flexflow_tpu/layout.py), so their measurements
    must never alias."""
    platform = jax.devices()[0].platform
    device = getattr(jax.devices()[0], "device_kind", platform)
    raw = repr((op.param_key(), getattr(op, "exec_layout", "NCHW"),
                platform, device))
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def op_io_bytes(op, dtype_size: float = 4.0) -> float:
    """HBM bytes one forward pass of the op must move: inputs + outputs +
    parameters, at ``dtype_size`` bytes/element. The denominator of the
    op's arithmetic intensity in the roofline report
    (flexflow_tpu/obs/roofline.py) — a lower bound (reads each operand
    once), matching the roofline model's convention."""
    elems = sum(float(np.prod(s)) for s in op.input_shapes)
    elems += sum(float(np.prod(s)) for s in op.output_shapes)
    elems += float(op.params_elems())
    return dtype_size * elems


def _example_inputs(op, rs: np.random.RandomState) -> List[jax.Array]:
    """Random inputs honoring the few ops with integral-domain inputs.

    Ops assigned the NHWC execution layout (flexflow_tpu/layout.py)
    consume physically channels-last values — their example inputs must
    be NHWC-shaped or the standalone forward rejects the channel count."""
    nhwc = getattr(op, "exec_layout", "NCHW") == "NHWC"
    arrs = []
    for i, shp in enumerate(op.input_shapes):
        if nhwc and len(shp) == 4:
            shp = tuple(shp[d] for d in (0, 2, 3, 1))  # NCHW -> NHWC
        if op.op_type == OperatorType.EMBEDDING:
            vocab = getattr(op, "num_entries", None) or 2
            a = rs.randint(0, max(1, int(vocab)), size=shp).astype(np.float32)
        else:
            a = rs.uniform(0.05, 1.0, size=shp).astype(np.float32)
        arrs.append(jnp.asarray(a))
    return arrs


def _fence_time(fn, args, repeats: int, warmup: int) -> float:
    """Median wall time of a jitted scalar-returning fn, fenced by fetching
    the result to host. On tunneled devices (axon) ``block_until_ready`` is
    not a real fence — only a host read is — so every timing in this module
    fetches; callers cancel the fixed round-trip latency via slope timing."""
    for _ in range(warmup):
        float(fn(*args))
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


# slope timing: per-iteration time = (T(long) - T(short)) / (long - short),
# cancelling both dispatch overhead and the tunnel round-trip. The long
# run grows adaptively until its extra wall time dominates the round-trip
# (device compute pipelines under the tunnel latency, so a too-short long
# run measures nothing). Loops take their length dynamically (fori_loop),
# so growing costs no recompile.
_SHORT_ITERS = 4
_LONG_ITERS = 36
_MAX_ITERS = 1 << 15
_MIN_DELTA_S = 0.15


def _perturb(xs, acc):
    """Inject a loop-carried O(1) data dependence into the first float
    input so XLA cannot hoist the op out of the timing loop."""
    out, touched = [], False
    for x in xs:
        if not touched and jnp.issubdtype(x.dtype, jnp.floating):
            idx = (0,) * x.ndim
            x = x.at[idx].add(acc.astype(x.dtype) * 1e-12)
            touched = True
        out.append(x)
    return out


_VMEM_BYTES = 128 * 1024 * 1024  # v5e on-chip vector memory


def _param_rotation(params):
    """K stacked copies of every float param, K sized so the set exceeds
    VMEM: the timing loop indexes copy i%K each iteration, forcing the op
    to stream its weights from HBM like the real training step does.
    Without this XLA parks loop-invariant weights in VMEM and a
    bandwidth-bound op (fat Linear, small batch) measures flop-bound."""
    pbytes = float(sum(4.0 * np.prod(w.shape)
                       for w in jax.tree.leaves(params)))
    if pbytes <= 0:
        return None, 1
    k = int(min(8, max(2, np.ceil(2.0 * _VMEM_BYTES / pbytes))))
    stacked = jax.tree.map(
        lambda w: jnp.stack([w] * k)
        if jnp.issubdtype(w.dtype, jnp.floating) else w, params)
    return stacked, k


def _param_copy(stacked, params, i, k):
    if stacked is None:
        return params
    return jax.tree.map(
        lambda s, w: jax.lax.dynamic_index_in_dim(s, i % k, 0,
                                                  keepdims=False)
        if jnp.issubdtype(w.dtype, jnp.floating) else w, stacked, params)


def _artifact_bytes(op, params) -> Tuple[float, float]:
    """HBM bytes the timing loop touches that the real fused step would
    not: (fwd loop, bwd-minus-fwd loop). Forward: one extra write for the
    perturbed first input plus one read of the outputs by the keep-alive
    sum (the param-rotation read IS the op's realistic weight read, not an
    artifact). Backward delta: the keep-alive read of all gradients."""
    in0 = 4.0 * np.prod(op.input_shapes[0]) if op.input_shapes else 0.0
    pbytes = float(sum(4.0 * np.prod(w.shape)
                       for w in jax.tree.leaves(params)))
    obytes = float(sum(4.0 * np.prod(s) for s in op.output_shapes))
    fwd = in0 + obytes
    bwd_delta = pbytes + in0
    return fwd, bwd_delta


def _alive(outs):
    """Scalar depending on every output, so none is dead-code-eliminated.
    Costs one read of the outputs per iteration — small next to the ops
    being calibrated (matmul/conv/attention)."""
    dep = jnp.float32(0)
    for o in outs:
        dep = dep + jnp.sum(o).astype(jnp.float32)
    return dep


def _slope_time(loop_fn, args, repeats: int, warmup: int) -> float:
    """Per-iteration time via two loop lengths: cancels the constant
    (dispatch + tunnel round-trip) term exactly. ``loop_fn(*args, n)``
    must run its body ``n`` times (dynamic length, one compile)."""
    t_short = _fence_time(loop_fn, args + (_SHORT_ITERS,), repeats, warmup)
    n_long = _LONG_ITERS
    while True:
        t_long = _fence_time(loop_fn, args + (n_long,), repeats, 0)
        if t_long - t_short >= _MIN_DELTA_S or n_long >= _MAX_ITERS:
            break
        n_long *= 4
    return max((t_long - t_short) / (n_long - _SHORT_ITERS), 1e-9)


def measure_op(op, repeats: int = 3, warmup: int = 1,
               hbm_bw: float = 0.82e12,
               include_bwd: bool = True) -> Tuple[float, float]:
    """Time one op's forward and backward compute on the default device.

    Returns (fwd_seconds, bwd_seconds). The op runs inside a jitted
    ``lax.scan`` with a loop-carried dependence; timing two loop lengths
    and taking the slope cancels dispatch overhead and the device tunnel's
    round-trip latency, neither of which exists inside the fused training
    step the prediction is compared against — the analog of the reference
    timing kernel execution with CUDA events rather than wall-clocking
    launches (model.cu:54-66). Backward is (fwd+bwd slope) - (fwd slope)
    of a value_and_grad over float params/inputs, not assumed 2x forward.
    Raises on ops whose forward cannot run standalone (caller skips them).
    ``include_bwd=False`` skips the (expensive) backward slope timing
    entirely and returns the 2x-forward estimate for bwd; fwd-only
    measurements cache under a distinct key so they never masquerade as
    measured backward costs.
    """
    key = op_cost_key(op) + ("" if include_bwd else ":fwdonly")
    if key in _CACHE:
        return _CACHE[key]
    # a full measurement already covers the fwd-only request
    if not include_bwd and op_cost_key(op) in _CACHE:
        return _CACHE[op_cost_key(op)]
    rs = np.random.RandomState(0)
    params = op.init_params(jax.random.PRNGKey(0))
    inputs = _example_inputs(op, rs)
    rng = jax.random.PRNGKey(1)

    def fwd_once(p, xs, k):
        ctx = OpContext(training=True, rng=k, compute_dtype=jnp.float32)
        return op.forward(p, list(xs), ctx)

    stacked, kcopies = _param_rotation(params)

    @jax.jit
    def fwd_loop(st, xs, k, n):
        def body(i, carry):
            acc, kk = carry
            kk, sub = jax.random.split(kk)
            p_i = _param_copy(st, params, i, kcopies)
            out = fwd_once(p_i, _perturb(xs, acc), sub)
            return (_alive(out), kk)

        acc, _ = jax.lax.fori_loop(0, n, body, (jnp.float32(0), k))
        return acc

    art_fwd, art_bwd = _artifact_bytes(op, params)
    raw_fwd = _slope_time(fwd_loop, (stacked, inputs, rng), repeats, warmup)
    t_fwd = max(raw_fwd - art_fwd / hbm_bw, 0.25 * raw_fwd)

    def loss(p, xs, k):
        return _alive([o for o in fwd_once(p, xs, k)
                       if jnp.issubdtype(o.dtype, jnp.floating)])

    t_bwd = 2.0 * t_fwd
    has_grad_inputs = any(
        jnp.issubdtype(x.dtype, jnp.floating) for x in inputs)
    if include_bwd and (params or has_grad_inputs):
        argnums = (0, 1) if params and has_grad_inputs else (
            (0,) if params else (1,))
        vag = jax.value_and_grad(loss, argnums=argnums)

        @jax.jit
        def both_loop(st, xs, k, n):
            def body(i, carry):
                acc, kk = carry
                kk, sub = jax.random.split(kk)
                p_i = _param_copy(st, params, i, kcopies)
                v, grads = vag(p_i, _perturb(xs, acc), sub)
                return (v + _alive(jax.tree.leaves(grads)), kk)

            acc, _ = jax.lax.fori_loop(0, n, body, (jnp.float32(0), k))
            return acc

        try:
            raw_both = _slope_time(both_loop, (stacked, inputs, rng),
                                   repeats, warmup)
            t_bwd = max(raw_both - raw_fwd - art_bwd / hbm_bw, 0.1 * t_fwd)
        except Exception:
            pass  # non-differentiable op: keep the 2x-forward estimate
    _CACHE[key] = (t_fwd, t_bwd)
    return _CACHE[key]


def measure_runtime_constants() -> Dict[str, float]:
    """Per-step runtime constants the per-op sum cannot see:

    - ``__step_overhead__``: wall cost of dispatching one jitted step
      (program launch + host runtime), measured as the slope of a trivial
      jitted call chain. On a tunneled device this is hundreds of us.
    - ``__update_bw__``: effective HBM bytes/s of an optimizer-update
      triad (p - lr*g, donated), typically well below the datasheet rate.

    The native simulator reads both keys from the measured table (the
    analog of the reference measuring per-device memory/runtime constants
    alongside per-op costs).
    """
    key = "__runtime__" + jax.devices()[0].platform
    if key in _CACHE:
        oh, bw = _CACHE[key]
        return {"__step_overhead__": oh, "__update_bw__": bw}

    x0 = jnp.ones((8, 8))
    tiny = jax.jit(lambda x: x + 1.0)
    holder = [x0]

    def chain():
        holder[0] = tiny(holder[0])
        return holder[0]

    def chain_time(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = chain()
        float(out.ravel()[0])
        return time.perf_counter() - t0

    chain_time(4)
    n_small, n_big = 4, 64
    t_small = chain_time(n_small)
    while True:
        t_big = chain_time(n_big)
        if t_big - t_small >= _MIN_DELTA_S or n_big >= _MAX_ITERS:
            break
        n_big *= 4
    overhead = max((t_big - t_small) / (n_big - n_small), 1e-7)

    n_elems = 16 << 20  # 64 MB leaves
    p = jnp.zeros((n_elems,))
    g = jnp.ones((n_elems,))
    triad = jax.jit(lambda p, g: p - 0.01 * g, donate_argnums=(0,))
    pref = [p]

    def triad_step():
        pref[0] = triad(pref[0], g)
        return pref[0]

    def triad_time(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = triad_step()
        float(out[0])
        return time.perf_counter() - t0

    triad_time(2)
    t1 = triad_time(4)
    n2 = 32
    while True:
        t2 = triad_time(n2)
        if t2 - t1 >= _MIN_DELTA_S or n2 >= 4096:
            break
        n2 *= 4
    per_call = max((t2 - t1) / (n2 - 4), 1e-9)
    per_call = max(per_call - overhead, 1e-9)
    bw = 3.0 * 4.0 * n_elems / per_call  # read p + read g + write p

    _CACHE[key] = (overhead, bw)
    return {"__step_overhead__": overhead, "__update_bw__": bw}


def load_op_corrections(path: Optional[str] = None,
                        platform: Optional[str] = None
                        ) -> Dict[str, Dict[str, float]]:
    """Drift-derived per-op-type correction factors from CALIBRATION.json
    (written by ``scripts/calibrate.py --ingest-drift``). The file keys
    them platform-first ({platform: {op type: {"factor": ..}}}); this
    returns the bucket for ``platform`` (default: the current JAX
    platform) — a CPU-derived correction must never scale TPU
    measurements. Returns {} when no calibration exists.
    ``FFS_CALIBRATION_FILE`` overrides the path (tests)."""
    path = path or os.environ.get("FFS_CALIBRATION_FILE") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "CALIBRATION.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    corr = data.get("op_corrections", {})
    if not isinstance(corr, dict):
        return {}
    if platform is None:
        platform = jax.devices()[0].platform
    bucket = corr.get(platform, {})
    return bucket if isinstance(bucket, dict) else {}


def apply_drift_corrections(measured: Dict[str, float], nodes,
                            corrections: Optional[Dict] = None
                            ) -> Dict[str, float]:
    """Scale each op's measured fwd/bwd seconds by its op type's
    drift-correction factor — the write-back half of the recalibration
    loop (observed runtime drift, ingested by ``calibrate.py
    --ingest-drift``, flows into every future measured table the search
    consumes). ``corrections`` defaults to the current platform's
    bucket from CALIBRATION.json."""
    if corrections is None:
        corrections = load_op_corrections()
    if not corrections:
        return measured
    out = dict(measured)
    for node in nodes:
        entry = corrections.get(node.op.op_type.name)
        if not entry:
            continue
        factor = float(entry.get("factor", 1.0))
        if factor <= 0:
            continue
        for leg in ("fwd", "bwd"):
            key = f"{node.op.guid}:{leg}"
            if key in out:
                out[key] *= factor
    return out


def microbenchmark(nodes, repeats: int = 3, warmup: int = 1,
                   cache_file: Optional[str] = None,
                   hbm_bw: float = 0.82e12,
                   verbose: bool = False,
                   drift_corrections: bool = True) -> Dict[str, float]:
    """Measure every op in an OpNode list; returns the native search's
    measured table {"<guid>:fwd": s, "<guid>:bwd": s}.

    Ops whose standalone forward fails (e.g. ones needing cross-op state)
    are skipped — the search keeps its analytic estimate for those.
    ``cache_file`` persists measurements across processes, keyed by the
    op-config hash, so a re-run on an unchanged model costs nothing.
    ``drift_corrections`` (default on; ``FFS_NO_DRIFT_CORRECTIONS=1``
    disables) scales the table by the per-op-type factors ingested from
    runtime drift reports — raw measurements stay in the cache, the
    correction applies on the way out.
    """
    disk: Dict[str, List[float]] = {}
    if cache_file and os.path.exists(cache_file):
        try:
            with open(cache_file) as f:
                disk = json.load(f)
        except (OSError, ValueError):
            disk = {}
    for k, v in disk.items():
        if k not in _CACHE and isinstance(v, list) and len(v) == 2:
            _CACHE[k] = (float(v[0]), float(v[1]))

    measured: Dict[str, float] = {}
    dirty = False
    for node in nodes:
        op = node.op
        key = op_cost_key(op)
        if key not in _CACHE:
            try:
                measure_op(op, repeats=repeats, warmup=warmup, hbm_bw=hbm_bw)
                dirty = True
            except Exception as e:
                if verbose:
                    print(f"[profile] skip {op.name}: {e!r}")
                continue
        fwd_s, bwd_s = _CACHE[key]
        measured[f"{op.guid}:fwd"] = fwd_s
        measured[f"{op.guid}:bwd"] = bwd_s
        if verbose:
            print(f"[profile] {op.name}: fwd {fwd_s * 1e6:.1f}us "
                  f"bwd {bwd_s * 1e6:.1f}us")
    measured.update(measure_runtime_constants())
    if cache_file and dirty:
        try:
            with open(cache_file, "w") as f:
                json.dump({k: list(v) for k, v in _CACHE.items()}, f)
        except OSError:
            pass
    if drift_corrections and not os.environ.get("FFS_NO_DRIFT_CORRECTIONS"):
        measured = apply_drift_corrections(measured, nodes)
    return measured

"""Priced-vs-emitted collective validation.

SURVEY §7 hard-part 3 / VERDICT r3 Next #3: the native simulator prices a
set of collectives for a strategy (reshard / psum / all-gather / ring /
gradient all-reduce); GSPMD independently decides which collectives the
compiled step actually contains. This module extracts both sides so tests
can assert they agree — and alert on collectives XLA inserted that the
simulator never charged (the classic way a searched strategy silently
underperforms its prediction).

Emitted side: lower + compile the jitted train step on the live mesh and
scan the optimized HLO for collective ops, summing payload bytes by kind.
Priced side: replay the searched assignment through the native simulator
(ffs_simulate), whose SimTasks now carry (collective, bytes).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from flexflow_tpu.obs.inspect import PRICED_MIN_BYTES, collective_census

# kind normalization: HLO op -> the simulator's collective vocabulary
_HLO_KINDS = {
    "all-reduce": "allreduce",
    "reduce-scatter": "allreduce",      # ar decomposition half
    "all-gather": "allgather",
    "collective-permute": "ppermute",
    "all-to-all": "reshard",
}

# which priced kinds cover an emitted (or statically-inferred) kind —
# the ONE definition shared by diff_collectives and the fflint
# collective-inference pass, so the two layers can never classify the
# same collective differently. An emitted all-gather is covered by a
# priced allreduce because XLA decomposes large ARs into reduce-scatter
# + all-gather (observed on the dp_head psum at the residual add — the
# RS half keeps the 'allreduce' bucket, the AG half lands here);
# 'reshard' prices cover permute/all-to-all layout changes.
COLLECTIVE_COVER = {
    "allreduce": {"allreduce"},
    "allgather": {"allgather", "reshard", "allreduce"},
    "ppermute": {"ppermute", "reshard"},
    "reshard": {"reshard", "allgather", "ppermute"},
}


def emitted_collectives(hlo_text: str, min_bytes: float = PRICED_MIN_BYTES
                        ) -> Dict[str, float]:
    """Collective kind -> summed payload bytes in the optimized HLO.

    A normalization of the obs collective census onto the simulator's
    vocabulary. Byte counting uses each op's OUTPUT shape
    (per-partition in the SPMD module). Ops below ``min_bytes`` are
    ignored (loss/metric scalar reductions the simulator deliberately
    does not price); async -start/-done pairs count once.
    """
    out: Dict[str, float] = defaultdict(float)
    for kind, entry in collective_census(hlo_text,
                                         min_bytes=min_bytes).items():
        out[_HLO_KINDS.get(kind, kind)] += entry["bytes"]
    return dict(out)


def compiled_train_step(ff):
    """Lower + compile the model's jitted train step on the live mesh."""
    ex = ff.executor
    rs = np.random.RandomState(0)
    xs = []
    for t in ff.input_tensors:
        xs.append(rs.randn(*t.shape).astype(np.float32))
    inputs = ff._stage_inputs(xs)
    # label shape: match the designated output
    out_shape = None
    for node in ex.nodes:
        if node.op.guid == ex.final_ref[0]:
            out_shape = node.op.output_shapes[ex.final_ref[1]]
    labels = ff._shard_batch(rs.randn(*out_shape).astype(np.float32))
    step = ex.make_train_step()
    lowered = step.lower(ff.params, ff.opt_state, ff.state, inputs, labels,
                         jax.random.PRNGKey(0))
    return lowered.compile()


def train_step_hlo(ff) -> str:
    """Lower + compile the model's train step; return optimized HLO text."""
    return compiled_train_step(ff).as_text()


def compiled_footprint_bytes(compiled) -> float:
    """Per-device peak the HBM budget must cover: live arguments
    (params + optimizer state + staged batch, resident for the whole
    step) plus XLA's temp allocation. Single definition shared by the
    validator and scripts/calibrate.py."""
    ma = compiled.memory_analysis()
    return float(getattr(ma, "argument_size_in_bytes", 0)
                 + getattr(ma, "temp_size_in_bytes", 0))


def predicted_vs_actual_memory(ff) -> Dict[str, float]:
    """Search-predicted per-device memory vs XLA's compiled memory
    analysis of the train step (SURVEY §7 hard-part 4 / VERDICT r4 #6).

    `actual` counts live arguments (params + optimizer state + staged
    batch, all resident for the step) plus XLA's temp allocation — the
    per-device peak the HBM budget actually has to cover. Requires a
    search-compiled model (compile with search_budget > 0) so
    `search_info["predicted_memory"]` exists.
    """
    info = ff.search_info if isinstance(ff.search_info, dict) else {}
    predicted = info.get("predicted_memory")
    if not predicted:
        raise ValueError(
            "predicted_vs_actual_memory needs a search-compiled model "
            "(set search_budget so predicted_memory is recorded)")
    actual = compiled_footprint_bytes(compiled_train_step(ff))
    return dict(predicted=float(predicted), actual=actual,
                ratio=actual / float(predicted))


def simulate_strategy(ff, learned: Any = "auto") -> Dict[str, Any]:
    """Replay the strategy FFModel.compile selected through the native
    simulator; returns the FULL response — iteration_time / memory /
    fwd/bwd/comm/gradsync breakdown plus the scheduled task list
    (per-task start/finish seconds and collective census records). The
    task schedule is what ``obs/simtrace.py`` renders as the predicted
    Perfetto timeline next to the measured device lanes.

    ``learned``: "auto" (default) prices with the same discovered
    learned cost table the search used (so replayed predictions match
    searched ones); False forces pure analytic pricing (the
    analytic-vs-learned accuracy comparison's control arm); an explicit
    native-table dict uses that table."""
    from flexflow_tpu.search.native import native_simulate
    from flexflow_tpu.search.unity import machine_to_json, serialize_graph

    if learned == "auto":
        try:
            from flexflow_tpu.costmodel import load_native_table
            learned = load_native_table()
        except Exception:
            learned = None
    elif not learned:
        learned = None

    nodes = ff.executor.nodes
    wus_on = bool(getattr(ff.executor, "weight_update_sharding", False))
    wus_ops = getattr(ff.executor, "wus_ops", None)
    ovl_on = bool(getattr(ff.executor, "grad_overlap", False))
    assignment = {}
    for node in nodes:
        st = (ff.strategy or {}).get(node.op.guid)
        choice = getattr(st, "choice", None)
        if choice is None:
            choice = _infer_choice(node, st)
        # replay what the executor EXECUTES, not what the DP picked: the
        # executor honors per-op "_wus" choices when the search supplied
        # them (wus_ops) and applies WUS globally otherwise, the
        # bucketed-async overlap structuring ("_ovl") is an executor
        # property, and the "_k:<impl>" kernel suffix survives exactly
        # when the executor's kernel_choices will run that impl — so the
        # suffixes are normalized to the runtime state (canonical order
        # base[_wus][_ovl][_k:impl]). The native side falls back along
        # the suffix lattice when an op spawns no matching twin.
        base = choice
        ksfx = ""
        if "_k:" in base:
            base, _, kimpl = base.partition("_k:")
            ksfx = "_k:" + kimpl
        for sfx in ("_ovl", "_wus"):
            base = base.replace(sfx, "")
        choice = base
        op_wus = (wus_on and node.op.params_elems()
                  and (wus_ops is None or node.op.name in wus_ops))
        if op_wus:
            choice += "_wus"
            if ovl_on:
                choice += "_ovl"
        kc = getattr(ff.executor, "kernel_choices", None) or {}
        if ksfx and kc.get(node.op.name) == ksfx[3:]:
            choice += ksfx
        assignment[str(node.op.guid)] = choice
    axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
    req = dict(
        nodes=serialize_graph(nodes,
                              final_guid=ff.executor.final_ref[0]),
        machine=machine_to_json(ff.machine_spec, ff.mesh.devices.size,
                                learned=learned),
        config=dict(training=True, overlap=True,
                    opt_state_factor=getattr(ff.config, "opt_state_factor",
                                             2.0)),
        mesh={"data": axes.get("data", 1), "model": axes.get("model", 1),
              "seq": axes.get("seq", 1), "expert": axes.get("expert", 1),
              "pipe": axes.get("pipe", 1)},
        assignment=assignment,
        measured={},
    )
    if axes.get("pipe", 1) > 1:
        # pipe meshes replay through simulate_pipeline: ship the detected
        # repeated-block metadata plus the executor's actual microbatch
        # count / schedule / queue layout so the priced census matches
        # the program the lowering emits
        from flexflow_tpu.parallel.pipeline_detect import pipeline_meta_json
        ex = ff.executor
        req["pipeline"] = dict(
            pipeline_meta_json(nodes, ex.pb),
            microbatches=int(ex.microbatches),
            schedule=ex.schedule,
            shard_queue=bool(ex.shard_queue))
    return native_simulate(req)


def priced_collectives(ff, min_bytes: float = 1 << 12) -> Dict[str, float]:
    """Collective kind -> summed bytes the native simulator charged for
    the strategy FFModel.compile selected."""
    resp = simulate_strategy(ff)
    out: Dict[str, float] = defaultdict(float)
    for t in resp.get("tasks", []):
        if t.get("collective") and t.get("bytes", 0) >= min_bytes:
            out[t["collective"]] += t["bytes"]
    return dict(out)


def _infer_choice(node, st) -> str:
    """Native choice name for a heuristic (non-searched) strategy entry,
    derived from its PartitionSpecs — so explicit-mesh strategies (e.g.
    ring attention over a user mesh) can be replayed through the
    simulator. Mirrors the naming in native/ffs_strategy.hpp
    enumerate_choices."""
    from flexflow_tpu.ffconst import OperatorType

    specs = (st.output_specs if st is not None else None) or []
    entries = list(specs[0]) if specs and specs[0] is not None else []
    base = "dp" if entries and entries[0] == "data" else "rep"
    params = (st.param_specs if st is not None else None) or {}
    kspec = params.get("kernel")
    if kspec is not None and "model" in tuple(kspec):
        if node.op.op_type == OperatorType.LINEAR:
            base = "dp_col" if base == "dp" else "col"
    wq = params.get("wq")
    if wq is not None and tuple(wq) and tuple(wq)[0] == "model":
        base = "dp_head" if base == "dp" else "head"
    if "seq" in entries:
        suffix = ("_ring" if node.op.op_type ==
                  OperatorType.MULTIHEAD_ATTENTION else "_sp")
        base += suffix
    return base


def diff_collectives(priced: Dict[str, float], emitted: Dict[str, float],
                     tol_factor: float = 3.0) -> List[str]:
    """Discrepancy report. Empty list = the priced set covers what XLA
    emitted (within tol_factor on bytes) and vice versa.

    reduce-scatter counts toward allreduce (XLA decomposes big ARs);
    'reshard' prices cover permute/all-to-all layout changes, so emitted
    ppermute/all-to-all match priced 'reshard' too.
    """
    problems = []
    cover = COLLECTIVE_COVER
    for kind, eb in emitted.items():
        pb = sum(priced.get(k, 0.0) for k in cover.get(kind, {kind}))
        if pb <= 0:
            problems.append(
                f"XLA emitted {kind} ({eb / 1e6:.2f} MB) but the simulator "
                f"priced none")
        elif eb > pb * tol_factor:
            problems.append(
                f"{kind}: emitted {eb / 1e6:.2f} MB vs priced "
                f"{pb / 1e6:.2f} MB (> {tol_factor}x)")
    for kind, pb in priced.items():
        eb = sum(emitted.get(k, 0.0) for k in cover.get(kind, {kind}))
        if eb <= 0 and pb > (1 << 16):
            problems.append(
                f"simulator priced {kind} ({pb / 1e6:.2f} MB) but XLA "
                f"emitted none")
    return problems

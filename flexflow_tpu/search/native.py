"""ctypes loader for the native ffsearch library.

Analog of the reference's in-process C++ search invoked through a Legion
task boundary (GRAPH_OPTIMIZE_TASK_ID, src/runtime/model.cc:2825): here the
boundary is a JSON string through a C ABI. The library is built from
native/ by `make`; if the .so is missing we attempt a one-shot build with
the system compiler (g++ is part of the supported toolchain).
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import Any, Dict, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libffsearch.so")

_lib = None
_load_error: Optional[str] = None


def _build(clean: bool = False) -> bool:
    backup = None
    try:
        if clean and os.path.exists(_LIB_PATH):
            # move (not delete) the current library aside: the rebuild
            # gets a fresh inode (glibc dlopen caches by path+inode), and
            # a failed rebuild restores the working .so instead of
            # destroying it
            backup = _LIB_PATH + ".stale"
            os.replace(_LIB_PATH, backup)
        r = subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True,
                           timeout=120)
        ok = r.returncode == 0 and os.path.exists(_LIB_PATH)
        if ok and backup is not None:
            os.remove(backup)
            backup = None
        return ok
    except Exception:
        return False
    finally:
        # restore the known-good library on ANY failed build — including
        # a killed compiler leaving a truncated .so behind
        if backup is not None:
            os.replace(backup, _LIB_PATH)


# exports the load-bearing paths need (search + simulator); a library
# missing one of these is unusable
_CORE_SYMBOLS = ("ffs_optimize", "ffs_simulate", "ffs_free", "ffs_version")
# newer audit/tooling exports: their absence marks a stale build worth
# one rebuild attempt, but never disables the core search
_OPTIONAL_SYMBOLS = ("ffs_list_rules", "ffs_match_rules")


def get_lib():
    """Load (building if necessary) the native library; None if unavailable."""
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        return None
    if not os.path.exists(_LIB_PATH) and not _build():
        _load_error = "libffsearch.so missing and build failed"
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        if not all(hasattr(lib, s)
                   for s in _CORE_SYMBOLS + _OPTIONAL_SYMBOLS):
            # stale prebuilt library from an older checkout: one rebuild
            # attempt; on failure keep whatever the current library CAN
            # do (a failed rebuild restores the old .so — _build)
            if _build(clean=True):
                lib = ctypes.CDLL(_LIB_PATH)
        missing_core = [s for s in _CORE_SYMBOLS if not hasattr(lib, s)]
        if missing_core:
            _load_error = (f"libffsearch.so missing core exports "
                           f"{missing_core} — run `make -C native`")
            return None
        for fn in ("ffs_optimize", "ffs_simulate") + tuple(
                s for s in _OPTIONAL_SYMBOLS if hasattr(lib, s)):
            getattr(lib, fn).argtypes = [ctypes.c_char_p]
            getattr(lib, fn).restype = ctypes.c_void_p
        lib.ffs_free.argtypes = [ctypes.c_void_p]
        lib.ffs_version.restype = ctypes.c_char_p
        _lib = lib
        return _lib
    except OSError as e:  # pragma: no cover
        _load_error = str(e)
        return None


def _call(fn_name: str, request: Dict[str, Any]) -> Dict[str, Any]:
    lib = get_lib()
    if lib is None:
        raise RuntimeError(f"ffsearch native library unavailable: {_load_error}")
    if not hasattr(lib, fn_name):
        raise RuntimeError(
            f"libffsearch.so has no '{fn_name}' export (stale build and "
            f"rebuild unavailable) — run `make -C native`")
    fn = getattr(lib, fn_name)
    ptr = fn(json.dumps(request).encode())
    try:
        out = json.loads(ctypes.string_at(ptr).decode())
    finally:
        lib.ffs_free(ptr)
    if "error" in out:
        raise RuntimeError(f"ffsearch: {out['error']}")
    return out


def native_optimize(request: Dict[str, Any]) -> Dict[str, Any]:
    return _call("ffs_optimize", request)


def native_simulate(request: Dict[str, Any]) -> Dict[str, Any]:
    return _call("ffs_simulate", request)


def native_list_rules(rules: Any) -> Dict[str, Any]:
    """Parse a substitution rule corpus (reference RuleCollection JSON or
    the native list form); returns {"count": N, "names": [...]}."""
    return _call("ffs_list_rules", rules)


def native_match_rules(request: Dict[str, Any]) -> Dict[str, Any]:
    """Offline rule audit (corpus-sweep harness): for each rule in
    request["subst_rules"], count matches on request["nodes"], how many
    structurally apply, and whether every rewritten graph still prices
    under the DP. Returns {rule_name: {matches, applied, priced}}."""
    return _call("ffs_match_rules", request)


def available() -> bool:
    return get_lib() is not None

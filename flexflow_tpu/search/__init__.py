"""Auto-parallelization search stack (SURVEY §2.5 — the crown jewels).

Native C++ core (native/ffs_search.cpp, loaded via ctypes) implementing the
reference's search algorithms re-targeted at TPU/GSPMD:

* frontier DP with memoized sharding states (find_optimal_*_graph_time)
* alpha pruning + budget-scaled beam (base_optimize best-first queue)
* memory-aware lambda binary search (graph_optimize_with_memory)
* MCMC simulated-annealing refinement (FFModel::mcmc_optimize)
* taskgraph simulator with compute/ICI stream overlap (Simulator)
* analytic TPU machine model (Simple/Enhanced/NetworkedMachineModel)

`flexflow_tpu.search.unity.graph_optimize` is the entry point used by
FFModel.compile when `search_budget > 0`.
"""

"""Unity-style graph optimization: op graph → (mesh shape, per-op shardings).

The Python half of the search stack: serialize the materialized op graph
(analog of the PCG handed to Graph::graph_optimize_task,
src/runtime/graph.cc:2047) to the native core, decode the returned strategy
into PartitionSpecs, and provide strategy file export/import
(--export-strategy / --import-strategy, reference config.h:141-142).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import CompMode, OperatorType
from flexflow_tpu.ops.base import DimRole
from flexflow_tpu.parallel.strategy import OpStrategy, Strategy


def _param_shapes(op) -> Dict[str, List[int]]:
    """Parameter name → shape, without materializing arrays."""
    try:
        tree = jax.eval_shape(op.init_params, jax.random.PRNGKey(0))
    except Exception:
        return {}
    return {k: list(v.shape) for k, v in tree.items()}


def _node_attrs(op) -> Dict[str, Any]:
    attrs = {}
    for k in ("num_heads", "num_kv_heads", "groups", "axis", "out_dim",
              "k", "n", "n_experts", "hidden_size", "alpha",
              "out_channels", "dropout"):
        v = getattr(op, k, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            attrs[k] = v
    # conv/pool geometry (stored as (h, w) tuples on the op): needed so a
    # rewrite that re-emits the op (Conv+BN fold) replays into a real
    # Conv2D
    for name, keys in (("kernel", ("kernel_h", "kernel_w")),
                       ("stride", ("stride_h", "stride_w")),
                       ("padding", ("padding_h", "padding_w"))):
        v = getattr(op, name, None)
        if isinstance(v, tuple) and len(v) == 2:
            attrs[keys[0]], attrs[keys[1]] = int(v[0]), int(v[1])
    # explicit mesh-axis name of a Repartition (repartition(axis=...)) —
    # mesh enumeration pins the NAMED axis, not the dim-derived default
    mesh_axis = getattr(op, "axis", None)
    if isinstance(mesh_axis, str):
        attrs["mesh_axis"] = mesh_axis
    # BatchNorm's fused relu flag (PM_RELU in the substitution engine)
    relu = getattr(op, "relu", None)
    if isinstance(relu, bool):
        attrs["relu"] = int(relu)
    # FusedParallelOp step chain (4th element: the step's mesh-axis name,
    # so the native cost model prices the axis the executor uses)
    fused = getattr(op, "fused_ops", None)
    if fused:
        attrs["ops"] = [[k.name if hasattr(k, "name") else str(k),
                         int(d), int(g)] + ([a] if isinstance(a, str)
                                            else [])
                        for (k, d, g, a) in fused]
    # the substitution engine matches on these (PM_* keys, ffs_subst.hpp)
    act = getattr(op, "activation", None)
    if act is not None and hasattr(act, "value"):
        attrs["activation"] = int(act.value)
    use_bias = getattr(op, "use_bias", None)
    if isinstance(use_bias, bool):
        attrs["use_bias"] = int(use_bias)
    for prefix in ("repartition", "combine", "reduction"):
        d = getattr(op, f"{prefix}_dim", None)
        if d is not None:
            attrs["dim"] = int(d)
        g = getattr(op, f"{prefix}_degree", None)
        if g is not None:
            attrs["degree"] = int(g)
    rdeg = getattr(op, "replicate_degree", None)
    if rdeg is not None:
        attrs["degree"] = int(rdeg)
    sizes = getattr(op, "sizes", None)
    if sizes is not None:
        attrs["sizes"] = [int(s) for s in sizes]
    return attrs


def kernel_choice_of(choice: Optional[str]) -> Optional[str]:
    """Kernel impl a choice name selects (the ``_k:<impl>`` suffix of
    the suffix lattice, ISSUE 15), or None for the default lowering.
    The trailing ``_r`` remat suffix (canonical order
    ``base[_wus][_ovl][_k:impl][_r]``) is not part of the impl name."""
    if not choice or "_k:" not in choice:
        return None
    impl = choice.split("_k:", 1)[1]
    if impl.endswith("_r"):
        impl = impl[:-2]
    return impl or None


def remat_choice_of(choice: Optional[str]) -> bool:
    """Whether a choice name selects the rematerialized ("_r") twin —
    the executor then routes the op through jax.checkpoint (ISSUE 20)."""
    return bool(choice) and choice.endswith("_r")


def executed_remat_ops(nodes, strategy) -> set:
    """{op name} whose searched choice carries the ``_r`` remat suffix —
    the per-op checkpoint policy the executor applies (the
    ``wus_ops``/``kernel_choices`` per-op pattern)."""
    out = set()
    for node in nodes:
        st = (strategy or {}).get(node.op.guid)
        if remat_choice_of(getattr(st, "choice", None)):
            out.add(node.op.name)
    return out


def executed_kernel_choices(nodes, strategy, mesh_axes,
                            training: bool = False) -> Dict[str, str]:
    """{op name -> kernel impl} a node list will EXECUTE: explicit
    ``_k:`` suffixes from the strategy win; attention ops without one
    report their static dispatch (``selected_impl`` — ring/flash/einsum
    on this platform at these shapes). The ONE extraction the serve
    bucket reports and the bench provenance column share, so the
    recorded impls cannot drift between surfaces."""
    out: Dict[str, str] = {}
    for node in nodes:
        st = (strategy or {}).get(node.op.guid)
        impl = kernel_choice_of(getattr(st, "choice", None))
        if impl is not None:
            out[node.op.name] = impl
        elif hasattr(node.op, "selected_impl"):
            try:
                out[node.op.name] = node.op.selected_impl(
                    mesh_axes, training=training)
            except Exception:
                pass
    return out


def serialize_graph(nodes, final_guid: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
    from flexflow_tpu.layout import train_fusable_conv_guids
    from flexflow_tpu.search.rewrite import external_input_ids
    neg_of = external_input_ids(nodes)
    # conv guids whose sole consumer is a foldable BatchNorm — the
    # legality the native "_k:conv_bn_fused" kernel twin gates on
    # (shipped as a node attr: the gate is a GRAPH property the native
    # per-node enumeration cannot re-derive). ``final_guid`` excludes
    # the designated model output exactly as the executor's
    # fuse_conv_bn_train does — the search must never price a fusion
    # the executor refuses.
    bn_fusable = train_fusable_conv_guids(
        nodes, keep_guids=() if final_guid is None else {final_guid})
    out = []
    for node in nodes:
        op = node.op
        inputs = []
        for ref in node.input_refs:
            if ref[0] == "op":
                inputs.append([ref[1], ref[2]])
            else:  # graph input staged from host — unique negative guid so
                   # substitution patterns can bind distinct externals
                inputs.append([neg_of[tuple(ref)], 0])
        roles = [[r.value for r in rr] for rr in op.output_dim_roles()]
        attrs = _node_attrs(op)
        if op.guid in bn_fusable:
            attrs["bn_fusable"] = 1
        out.append(dict(
            guid=op.guid,
            type=op.op_type.name,
            name=op.name,
            inputs=inputs,
            input_shapes=[list(s) for s in op.input_shapes],
            output_shapes=[list(s) for s in op.output_shapes],
            roles=roles,
            params=_param_shapes(op),
            flops=float(op.flops()),
            dtype_size=op.dtype.size,
            attrs=attrs,
        ))
    return out


def machine_to_json(spec, num_devices: int,
                    comm_bytes_factor: float = 1.0,
                    learned: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """``learned``: the trained cost-model coefficient table
    (flexflow_tpu/costmodel ``native_table()``) the native evaluator
    prices covered op classes with; None (the default and the
    FFS_NO_LEARNED_COSTS state) keeps pure analytic pricing —
    bit-identical to pre-costmodel behavior."""
    # arbitrary inter-slice fabrics: ship the RAW per-pair link matrix —
    # the native pricer applies the bottleneck-link rule per collective
    # SPAN (MachineModel::dcn_ring), so a 2-slice collective on a fabric
    # whose far link is slow prices at the near pair's bandwidth instead
    # of the global collapse. The scalar (dcn_bw, dcn_latency) stays the
    # uniform fallback; without links, effective_dcn() returns it as-is.
    dcn_links = list(getattr(spec, "dcn_links", None) or [])
    if dcn_links:
        dcn_bw, dcn_latency = spec.dcn_bw, spec.dcn_latency
    else:
        dcn_bw, dcn_latency = (spec.effective_dcn()
                               if hasattr(spec, "effective_dcn")
                               else (spec.dcn_bw, spec.dcn_latency))
    out = dict(
        num_devices=num_devices,
        flops=spec.flops,
        hbm_bw=spec.hbm_bw,
        hbm_cap=spec.hbm_cap,
        ici_bw=spec.ici_bw,
        ici_latency=spec.ici_latency,
        dcn_bw=dcn_bw,
        dcn_latency=dcn_latency,
        num_slices=spec.num_slices,
        mxu_efficiency=getattr(spec, "mxu_efficiency", 0.55),
        # conv-class asymptote (ffs_strategy.hpp node_cost): predicted
        # conv times track the measured conv-vs-matmul efficiency gap
        # instead of assuming matmul-grade MXU utilization
        conv_efficiency=getattr(spec, "conv_efficiency", 0.35),
        min_op_time=getattr(spec, "min_op_time", 5e-7),
        # per-bucket launch cost of the bucketed async gradient sync —
        # the term that stops the '_ovl' bucket sweep from degenerating
        # to infinitely many tiny buckets (ffs_machine.hpp)
        collective_launch_overhead=getattr(spec, "collective_launch_overhead",
                                           2e-6),
        # bf16 activations/grads under mixed precision: collectives move
        # half the nominal f32 bytes (ffs_machine.hpp comm_bytes_factor)
        comm_bytes_factor=comm_bytes_factor,
        # per-slice ICI torus extents — drives the native model's
        # per-axis ring pricing (ffs_machine.hpp assign_torus)
        torus=[int(t) for t in getattr(spec, "torus", None) or []],
    )
    if dcn_links:
        out["dcn_links"] = [[int(a), int(b), float(bw)]
                            for a, b, bw in dcn_links]
    if learned:
        out["learned"] = learned
    return out


def _entries_to_spec(entries: List[Optional[Any]]) -> P:
    while entries and entries[-1] is None:
        entries = entries[:-1]
    return P(*entries)


def decode_strategy(resp: Dict[str, Any], nodes) -> Tuple[Dict[str, int], Strategy]:
    mesh_axes = {k: int(v) for k, v in resp["mesh"].items() if int(v) > 1}
    if not mesh_axes:
        mesh_axes = {"data": 1}
    valid = set(mesh_axes)
    strategy: Strategy = {}
    for node in nodes:
        oj = resp["ops"].get(str(node.op.guid))
        if oj is None:
            continue
        def _entry(e):
            # "data+model": 2-D sample partition -> a PartitionSpec tuple
            # entry over both axes (sample parallelism, config.h:134)
            if e == "data+model":
                axes = tuple(a for a in ("data", "model") if a in valid)
                return axes if len(axes) > 1 else (axes[0] if axes else None)
            return e if e in valid else None

        outs = []
        for entries in oj["outputs"]:
            outs.append(_entries_to_spec([_entry(e) for e in entries]))
        params = {}
        # the native side enumerates param specs from the op TYPE (e.g. a
        # Linear always gets kernel+bias entries); filter against the
        # parameters the materialized op actually owns, or a bias-less
        # rewrite-fused Linear carries a phantom 'bias' spec forever
        # (fflint FFL103)
        owned = _param_shapes(node.op)
        for pname, entries in oj.get("params", {}).items():
            if owned and pname not in owned:
                continue
            params[pname] = _entries_to_spec([_entry(e) for e in entries])
        st = OpStrategy(output_specs=outs, param_specs=params)
        st.choice = oj.get("choice")
        strategy[node.op.guid] = st
    return mesh_axes, strategy


def graph_optimize(nodes, machine_spec, config, num_devices: int,
                   measured: Optional[Dict[str, float]] = None,
                   batch: int = 0,
                   final_ref: Optional[Tuple[int, int]] = None,
                   ) -> Tuple[Dict[str, int], Strategy, Dict[str, Any]]:
    """Run the native Unity search. Returns (mesh_axes, strategy, info).

    When the substitution engine rewrites the graph, ``info`` carries
    ``rewritten_nodes`` (the new OpNode list the strategy is keyed to) and
    ``final_ref`` (where the designated output moved).

    Raises RuntimeError/ImportError when the native core is unavailable —
    callers fall back to the data-parallel default, matching the
    reference's --only-data-parallel escape hatch.
    """
    from flexflow_tpu.search.native import native_optimize

    rules: List[Any] = []
    subst_rules = None
    if (not config.substitution_json
            and getattr(config, "enable_substitution", True)):
        # default shipped corpus (analog of the reference loading
        # substitutions/graph_subst_3_v2.json at search start)
        default = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "substitutions", "ffs_subst_v1.json")
        if os.path.exists(default):
            try:
                with open(default) as f:
                    subst_rules = json.load(f)
            except (OSError, ValueError):
                subst_rules = None
    if config.substitution_json:
        # an explicitly-requested rules file must fail loudly (ValueError is
        # not in compile()'s fallback set, so a bad path/contents aborts
        # instead of silently degrading to data-parallel)
        try:
            with open(config.substitution_json) as f:
                data = json.load(f)
        except OSError as e:
            raise ValueError(
                f"--substitution-json {config.substitution_json}: {e}") from e
        if isinstance(data, dict) and "rules" in data:
            # native per-op choice filters ({"rules": [{op_type, allow}]})
            rules = data["rules"]
        else:
            # graph-rewrite rule corpus: the reference RuleCollection
            # format ({"rule": [...]}, substitution_loader.cc) or this
            # repo's native list-of-rules form
            subst_rules = data
    threshold = 0
    mem_correction = 1.0
    if config.memory_search and config.memory_threshold_mb:
        threshold = config.memory_threshold_mb * (1 << 20)
    elif config.memory_search:
        threshold = config.memory_per_chip_mb * (1 << 20)
    if threshold:
        # calibrated predicted->actual memory correction (SURVEY §7 hard
        # part 4): when the chip's XLA footprint runs `corr`x the
        # simulator's prediction, the DP must aim for budget/corr so the
        # ACTUAL bytes fit
        mem_correction = _memory_correction()
        if mem_correction > 1.0:
            threshold /= mem_correction
    # mixed precision (TPU): activations + grads move in bf16 — halve the
    # collective payloads the cost model prices (matches the executor's
    # master-weight regime; CPU/f32 machines keep 1.0)
    comm_factor = 0.5 if (getattr(config, "allow_mixed_precision", True)
                          and machine_spec.chip != "cpu-sim") else 1.0
    # learned per-op-class cost table (flexflow_tpu/costmodel): trained
    # COSTMODEL.json coefficients the DP queries where coverage exists,
    # analytic fallback elsewhere. None (no trained model, platform
    # mismatch, or FFS_NO_LEARNED_COSTS) keeps pre-costmodel pricing.
    try:
        from flexflow_tpu.costmodel import load_native_table
        learned = load_native_table()
    except Exception:
        learned = None
    # provenance is about THIS graph, not the table: a model whose
    # classes never intersect the graph's op types prices nothing here
    # (everything stays analytic), and claiming "learned" would both
    # misreport and suppress fflint's all-analytic FFL701 warning
    graph_types = {n.op.op_type.name for n in nodes}
    learned_classes = sorted(
        c for c in set((learned or {}).get("classes") or ())
        # per-impl classes ("TYPE:impl", the searched kernel dimension)
        # cover a graph exactly when their base type appears in it
        if c.split(":", 1)[0] in graph_types)
    request = dict(
        nodes=serialize_graph(
            nodes,
            final_guid=final_ref[0] if final_ref is not None else None),
        machine=machine_to_json(machine_spec, num_devices,
                                comm_bytes_factor=comm_factor,
                                learned=learned),
        config=dict(
            budget=config.search_budget,
            alpha=config.search_alpha,
            only_data_parallel=config.only_data_parallel,
            enable_parameter_parallel=config.enable_parameter_parallel
                or config.enable_attribute_parallel,
            overlap=config.search_overlap_backward_update,
            # CompMode.INFERENCE (ffconst.h:46): forward-only cost model —
            # no backward tasks, no gradient sync, no opt-state memory
            training=getattr(config, "computation_mode",
                             CompMode.TRAINING) == CompMode.TRAINING,
            memory_threshold=threshold,
            seed=config.seed,
            batch=batch,
            rules=rules,
            enable_substitution=getattr(config, "enable_substitution", True),
            enable_sample_parallel=getattr(config, "enable_sample_parallel",
                                           True),
            # optimizer-state copies (0 SGD / 1 momentum / 2 Adam), set by
            # FFModel.compile from the actual optimizer
            opt_state_factor=getattr(config, "opt_state_factor", 2.0),
            enable_pipeline_parallel=getattr(
                config, "enable_pipeline_parallel", True),
            pipeline_microbatches=getattr(
                config, "pipeline_microbatches", 0),
            # 'auto' lets the simulator price gpipe vs circular per mesh
            # (the schedule is a searched dimension, ffs_sim.hpp)
            pipeline_schedule=getattr(config, "pipeline_schedule", "auto"),
            # --pipeline-replicated-queue: price the queue layout the
            # lowering will actually emit (memory model differs by ~pp)
            pipeline_shard_queue=getattr(config, "pipeline_shard_queue",
                                         True),
            # --disable-fusion: gate the fuse_parallel_ops rewrite family
            # (kernel fusion itself belongs to XLA)
            perform_fusion=getattr(config, "perform_fusion", True),
            # weight-update sharding as a searched dimension: "auto"/"on"
            # enumerate the reduce-scatter+all-gather "_wus" choice twins
            # (ffs_strategy.hpp); "off" removes them
            weight_update_sharding=getattr(config, "weight_update_sharding",
                                           "auto"),
            # comms-compute overlap as a searched dimension: anything but
            # off/0 enumerates the '_ovl' latency-hiding choice twins
            # whose gradient sync is priced as bucketed async collectives
            # hidden under remaining backward compute (ffs_strategy.hpp)
            comm_overlap=("off" if str(getattr(
                config, "overlap_bucket_mb", "auto")).lower() in ("0", "off")
                else "auto"),
            # kernel-implementation choice as a searched dimension
            # (ISSUE 15): "auto" enumerates the "_k:<impl>" twins
            # (flash attention / fused optimizer update / train-time
            # Conv+BN); "off" or FFS_NO_KERNEL_SEARCH removes the
            # dimension — searches then reproduce pre-kernel-search
            # results bit-identically
            kernel_search=("off" if (
                str(getattr(config, "kernel_search", "auto")).lower()
                == "off" or os.environ.get("FFS_NO_KERNEL_SEARCH"))
                else "auto"),
            # rematerialization as a searched dimension (ISSUE 20):
            # "auto" spawns the "_r" remat choice twins (checkpoint the
            # op, recompute its interior in backward) and the pipeline
            # block-body remat dimension; "off" or FFS_NO_REMAT removes
            # the dimension — searches then reproduce pre-remat-search
            # results bit-identically
            remat_search=("off" if (
                str(getattr(config, "remat_search", "auto")).lower()
                == "off" or os.environ.get("FFS_NO_REMAT"))
                else "auto"),
            # search provenance: per-mesh candidates + rejection reasons,
            # frontier-DP evolution, per-op candidate cost table
            # (--search-trace / FFS_SEARCH_TRACE; explain.py sets it too)
            emit_search_trace=bool(getattr(config, "search_trace", False)
                                   or os.environ.get("FFS_SEARCH_TRACE")),
        ),
        measured=measured or {},
    )
    # repeated-block pipeline metadata: lets the native search enumerate
    # 'pipe' meshes (GPipe cost model, native/ffs_sim.hpp)
    pipe_blocks = None
    if getattr(config, "enable_pipeline_parallel", True):
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks, pipeline_meta_json)
        pipe_blocks = detect_repeated_blocks(nodes)
        if pipe_blocks is not None:
            request["pipeline"] = pipeline_meta_json(nodes, pipe_blocks)
    if subst_rules is not None:
        request["subst_rules"] = subst_rules
    if final_ref is not None:
        request["final"] = [int(final_ref[0]), int(final_ref[1])]
    # search introspection (reference's RecursiveLogger around the DP —
    # graph.cc's get_logger() tree); on by --profiling or FF_LOG_SEARCH
    from flexflow_tpu.utils.logger import RecursiveLogger
    log = RecursiveLogger("unity", enabled=bool(
        getattr(config, "profiling", False)
        or os.environ.get("FF_LOG_SEARCH")))
    with log.enter(f"graph_optimize: {len(nodes)} ops on "
                   f"{num_devices} devices"):
        resp = native_optimize(request)
        stats = resp.get("stats", {})
        with log.enter(f"searched {stats.get('mesh_candidates')} meshes, "
                       f"{stats.get('states_explored')} DP states, "
                       f"{stats.get('rules_loaded')} rules"):
            for rw in resp.get("rewrites", []):
                log.info(f"rewrite {rw['rule']}: removed {rw['removed']}, "
                         f"added {[a['name'] for a in rw['added']]}")
        log.info(f"best mesh {resp.get('mesh')} predicted "
                 f"{resp.get('predicted_time', 0) * 1e3:.3f} ms "
                 f"({stats.get('rewrites_applied', 0)} rewrites)")
    new_nodes = nodes
    new_final = final_ref
    if resp.get("rewrites"):
        from flexflow_tpu.search.rewrite import apply_rewrites
        new_nodes, new_final = apply_rewrites(nodes, resp["rewrites"],
                                              final_ref)
    mesh_axes, strategy = decode_strategy(resp, new_nodes)
    # the search OBJECTIVE is part of the answer's provenance: TRAINING
    # minimizes simulated step time (fwd+bwd+update+sync), INFERENCE
    # minimizes simulated per-batch latency (forward only, no gradient
    # sync / '_wus' / opt-state terms) — the serving engine records it
    # per batch bucket and the strategy/search-trace artifacts carry it
    training_mode = request["config"]["training"]
    objective = "step_time" if training_mode else "latency"
    info = dict(predicted_time=resp.get("predicted_time"),
                predicted_memory=resp.get("predicted_memory"),
                memory_correction=mem_correction,
                objective=objective,
                # cost-model provenance: which pricing regime the search
                # ran under, and (when learned) which of this GRAPH's op
                # classes the trained table covered — fflint's staleness
                # lint and the strategy artifacts read this
                cost_model="learned" if learned_classes else "analytic",
                stats=resp.get("stats", {}),
                rewrites=resp.get("rewrites", []))
    if learned_classes:
        info["learned_cost_classes"] = learned_classes
    if resp.get("search_trace"):
        trace = dict(resp["search_trace"])
        trace.setdefault("objective", objective)
        info["search_trace"] = trace
    if resp.get("overlap"):
        # byte-weighted winning bucket size across the '_ovl' choices —
        # the searched value --overlap-bucket-mb 'auto' follows
        info["overlap"] = resp["overlap"]
    if resp.get("pipeline") and mesh_axes.get("pipe", 1) > 1:
        # the search picked a GPipe strategy: hand compile() what the
        # lowering onto pipeline_spmd needs (rewrites never fire together
        # with pipe meshes — block identity would break — so the detected
        # blocks are still valid for new_nodes == nodes)
        info["pipeline"] = dict(resp["pipeline"], blocks=pipe_blocks)
    if new_nodes is not nodes:
        info["rewritten_nodes"] = new_nodes
        info["final_ref"] = new_final
        # static rewrite verification (FFL213): the accepted rewrite's
        # post-rewrite edge-spec map must be collective-equivalent-or-
        # cheaper than the pre-rewrite map under the same strategy —
        # a substitution that wins on op-local simulated terms while
        # opening a reshard seam is caught here, before compile
        from flexflow_tpu.analysis.dataflow import verify_rewrite_dataflow
        try:
            info["rewrite_verification"] = verify_rewrite_dataflow(
                nodes, new_nodes, strategy, dict(mesh_axes),
                rewrites=resp.get("rewrites", []))
        except Exception as e:  # never let verification break the search
            info["rewrite_verification"] = dict(
                ok=True, findings=[], error=repr(e))
    return mesh_axes, strategy, info


def _memory_correction() -> float:
    """Median actual/predicted memory ratio from CALIBRATION.json's
    per-model `mem_ratio` rows (written by scripts/calibrate.py), 1.0
    when no calibration exists. FFS_CALIBRATION_FILE overrides the path
    (tests)."""
    path = os.environ.get("FFS_CALIBRATION_FILE") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "CALIBRATION.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return 1.0
    ratios = sorted(r["mem_ratio"] for r in data.get("results", [])
                    if isinstance(r.get("mem_ratio"), (int, float))
                    and r["mem_ratio"] > 0)
    if not ratios:
        return 1.0
    return float(ratios[len(ratios) // 2])


# ---- strategy files (--export-strategy / --import-strategy) ---------------

def strategy_json(mesh_axes: Dict[str, int], strategy: Strategy,
                  nodes, objective: Optional[str] = None) -> Dict[str, Any]:
    """Strategy keyed by op *name* (stable across runs, unlike guids —
    the reference keys by FFConfig::get_hash_id, strategy.cc:26) as a
    JSON-able dict: the body of a strategy file, also embedded verbatim
    in v2 checkpoint manifests (flexflow_tpu/ckpt) so a same-topology
    resume can reuse the searched strategy without re-searching."""
    by_guid = {n.op.guid: n.op.name for n in nodes}
    ops = {}
    for guid, st in strategy.items():
        name = by_guid.get(guid)
        if name is None:
            continue
        ops[name] = dict(
            choice=getattr(st, "choice", None),
            outputs=[list(s) if s is not None else None for s in st.output_specs],
            params={k: list(v) for k, v in st.param_specs.items()},
        )
    out = dict(version=1, mesh=dict(mesh_axes), ops=ops)
    if objective:
        # "step_time" (TRAINING) vs "latency" (INFERENCE serving): a
        # strategy file / checkpoint manifest records which objective
        # the recorded shardings were searched under
        out["objective"] = objective
    return out


def export_strategy_file(path: str, mesh_axes: Dict[str, int],
                         strategy: Strategy, nodes,
                         objective: Optional[str] = None) -> None:
    with open(path, "w") as f:
        json.dump(strategy_json(mesh_axes, strategy, nodes,
                                objective=objective), f, indent=1)


def import_strategy_file(path: str, nodes) -> Tuple[Dict[str, int], Strategy]:
    with open(path) as f:
        data = json.load(f)
    mesh_axes = {k: int(v) for k, v in data["mesh"].items()}
    strategy: Strategy = {}
    for node in nodes:
        oj = data["ops"].get(node.op.name)
        if oj is None:
            continue
        outs = [
            (P(*e) if e is not None else None)
            for e in oj["outputs"]
        ]
        params = {k: P(*v) for k, v in oj.get("params", {}).items()}
        st = OpStrategy(output_specs=outs, param_specs=params)
        st.choice = oj.get("choice")
        strategy[node.op.guid] = st
    return mesh_axes, strategy

"""Replay native graph-rewrite traces on the Python OpNode graph.

The native substitution engine (native/ffs_subst.hpp — analog of the
reference's GraphXfer, src/runtime/substitution.cc:596) rewrites the
search-side graph and reports a trace: per applied rule, the removed node
guids, descriptors of the added nodes, and an output remap. This module
replays that trace on the materialized OpNode list so the executor runs
the rewritten graph — the counterpart of the reference applying the
winning GraphXfer sequence to the PCG before execution
(Graph::graph_optimize_task, src/runtime/graph.cc:2047).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from flexflow_tpu.executor import OpNode
from flexflow_tpu.ffconst import ActiMode, DataType, OperatorType
from flexflow_tpu.layer import Layer
from flexflow_tpu.ops import OpRegistry


def external_input_ids(nodes) -> Dict[Tuple, int]:
    """Stable negative guid per distinct non-op input ref, in first-seen
    order — must match serialize_graph's numbering exactly."""
    neg_of: Dict[Tuple, int] = {}
    for node in nodes:
        for ref in node.input_refs:
            if ref[0] != "op" and tuple(ref) not in neg_of:
                neg_of[tuple(ref)] = -2 - len(neg_of)
    return neg_of


def _props_from_attrs(op_type: OperatorType, attrs) -> dict:
    """Map a native node descriptor's attrs to Layer properties."""
    a = dict(attrs or {})
    p: dict = {}
    if op_type == OperatorType.LINEAR:
        p["out_dim"] = int(a["out_dim"])
        p["activation"] = ActiMode(int(a.get("activation", 0)))
        p["use_bias"] = bool(a.get("use_bias", 1))
    elif op_type == OperatorType.SPLIT:
        p["sizes"] = tuple(int(s) for s in a["sizes"])
        p["axis"] = int(a.get("axis", -1))
    elif op_type == OperatorType.CONCAT:
        p["axis"] = int(a.get("axis", 0))
    elif op_type == OperatorType.REPARTITION:
        p["dim"] = int(a.get("dim", 0))
        p["degree"] = int(a.get("degree", 1))
        # default axis assignment mirrors FFModel.repartition
        p["axis"] = "data" if p["dim"] == 0 else "model"
    elif op_type in (OperatorType.COMBINE, OperatorType.REDUCTION):
        p["dim"] = int(a.get("dim", 0))
        p["degree"] = int(a.get("degree", 1))
    elif op_type == OperatorType.REPLICATE:
        p["degree"] = int(a.get("degree", 1))
    elif op_type == OperatorType.FUSED_PARALLEL:
        # step chain [[type, dim, degree], ...] -> (type, dim, degree,
        # axis) tuples; axis assignment mirrors FFModel.repartition
        p["ops"] = [
            (str(k), int(d), int(g), "data" if int(d) == 0 else "model")
            for (k, d, g) in a["ops"]
        ]
    elif op_type == OperatorType.CONV2D:
        p["out_channels"] = int(a["out_channels"])
        p["kernel_h"] = int(a.get("kernel_h", 1))
        p["kernel_w"] = int(a.get("kernel_w", 1))
        p["stride_h"] = int(a.get("stride_h", 1))
        p["stride_w"] = int(a.get("stride_w", 1))
        p["padding_h"] = int(a.get("padding_h", 0))
        p["padding_w"] = int(a.get("padding_w", 0))
        p["groups"] = int(a.get("groups", 1))
        p["activation"] = ActiMode(int(a.get("activation", 0)))
        p["use_bias"] = bool(a.get("use_bias", 1))
    else:
        # unary / elementwise / identity need nothing; pass through extras
        for k, v in a.items():
            p[k] = v
    return p


def apply_rewrites(nodes: List[OpNode], rewrites: List[dict],
                   final_ref: Optional[Tuple[int, int]] = None,
                   ) -> Tuple[List[OpNode], Optional[Tuple[int, int]]]:
    """Apply the native rewrite trace to ``nodes``; returns the new node
    list and the (guid, out_idx) the designated output moved to.

    The caller's nodes are never mutated: a failed replay (shape
    cross-check, malformed trace) leaves them intact so the data-parallel
    fallback in FFModel.compile runs on the original graph. All trace
    errors surface as RuntimeError — the fallback's catch type.
    """
    if not rewrites:
        return nodes, final_ref
    try:
        return _apply_rewrites(nodes, rewrites, final_ref)
    except RuntimeError:
        raise
    except Exception as e:  # malformed trace: KeyError, ValueError, ...
        raise RuntimeError(f"rewrite trace replay failed: {e!r}") from e


def _apply_rewrites(nodes, rewrites, final_ref):
    # work on wrapper copies so the caller's OpNodes stay untouched even
    # when a later trace entry fails mid-replay
    nodes = [OpNode(n.op, list(n.input_refs)) for n in nodes]
    neg_of = external_input_ids(nodes)
    ref_of_neg = {v: k for k, v in neg_of.items()}
    # shapes: external inputs learned from their current consumers,
    # op outputs from the producing op
    ext_shape: Dict[int, Tuple[int, ...]] = {}
    for node in nodes:
        for slot, ref in enumerate(node.input_refs):
            if ref[0] != "op":
                ext_shape.setdefault(neg_of[tuple(ref)],
                                     node.op.input_shapes[slot])
    out_shape: Dict[Tuple[int, int], Tuple[int, ...]] = {}
    for node in nodes:
        for i, s in enumerate(node.op.output_shapes):
            out_shape[(node.guid, i)] = tuple(s)

    fin = tuple(final_ref) if final_ref is not None else None
    for entry in rewrites:
        removed = {int(g) for g in entry["removed"]}
        remap = {(int(a), int(b)): (int(c), int(d))
                 for a, b, c, d in entry.get("output_remap", [])}
        new_nodes: List[OpNode] = []
        for desc in entry["added"]:
            op_type = OperatorType[desc["type"]]
            input_refs, in_shapes = [], []
            for sg, si in desc["inputs"]:
                sg, si = int(sg), int(si)
                if sg >= 0:
                    input_refs.append(("op", sg, si))
                    in_shapes.append(out_shape[(sg, si)])
                else:
                    input_refs.append(ref_of_neg[sg])
                    in_shapes.append(ext_shape[sg])
            layer = Layer(op_type, desc["name"], [],
                          data_type=DataType.FLOAT)
            # adopt the native-assigned guid: the returned strategy and
            # downstream edges are keyed by it
            layer.guid = int(desc["guid"])
            Layer._next_guid[0] = max(Layer._next_guid[0], layer.guid + 1)
            layer.properties.update(
                _props_from_attrs(op_type, desc.get("attrs")))
            op = OpRegistry.create(layer, in_shapes)
            got = [tuple(s) for s in op.output_shapes]
            want = [tuple(int(d) for d in s) for s in desc["output_shapes"]]
            if got != want:
                raise RuntimeError(
                    f"rewrite {entry['rule']}: node {desc['name']} shapes "
                    f"{got} != native {want}")
            for i, s in enumerate(got):
                out_shape[(op.guid, i)] = s
            new_nodes.append(OpNode(op, input_refs))

        insert_at = min((i for i, n in enumerate(nodes)
                         if n.guid in removed), default=len(nodes))
        spliced: List[OpNode] = []
        for i, n in enumerate(nodes):
            if i == insert_at:
                spliced.extend(new_nodes)
            if n.guid in removed:
                continue
            n.input_refs = [
                ("op",) + remap[(r[1], r[2])]
                if (r[0] == "op" and (r[1], r[2]) in remap) else r
                for r in n.input_refs
            ]
            spliced.append(n)
        if insert_at == len(nodes):
            spliced.extend(new_nodes)
        nodes = spliced
        if fin is not None and fin in remap:
            fin = remap[fin]
    return nodes, fin

"""FFModel: the user-facing model builder + training runtime.

TPU re-design of the reference FFModel (include/flexflow/model.h:326,
src/runtime/model.cc): the same deferred layer-building API (dense, conv2d,
multihead_attention, ..., model.h:380-520), a ``compile()`` that
materializes operators from layers (create_operators_from_layers,
model.cc:2784), picks a parallelization strategy, and builds the
executable — here a single jitted train-step over a device mesh rather
than Legion task launches. ``fit/eval`` mirror the Python frontend's loop
(flexflow_cffi.py:2073-2086) and print the same
``ELAPSED TIME / THROUGHPUT`` lines as the reference examples
(examples/cpp/Transformer/transformer.cc:209-211).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from flexflow_tpu.config import FFConfig
from flexflow_tpu.executor import GraphExecutor, OpNode
from flexflow_tpu.ffconst import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    PoolType,
)
from flexflow_tpu.layer import Layer
from flexflow_tpu.machine import MachineSpec, detect_machine_spec, make_mesh
from flexflow_tpu.metrics import Metrics, PerfMetrics
from flexflow_tpu.ops import OpRegistry
from flexflow_tpu.optimizers import Optimizer, SGDOptimizer
from flexflow_tpu.tensor import Tensor


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.layers: List[Layer] = []
        self.input_tensors: List[Tensor] = []
        self.label_tensor: Optional[Tensor] = None
        self.optimizer: Optional[Optimizer] = None
        self.executor: Optional[GraphExecutor] = None
        self.params: Dict[str, Dict[str, jax.Array]] = {}
        self.opt_state: Any = None
        self.state: Dict[str, Any] = {}
        self.machine_spec: Optional[MachineSpec] = None
        self.mesh = None
        self.strategy = None
        self._rng = jax.random.PRNGKey(self.config.seed)
        self._iter = 0
        self._metrics_acc = PerfMetrics()
        # parity loop state (forward/backward/update protocol)
        self._current_batch = None
        self._pending = None

    # ======================= tensor/layer construction =====================
    def create_tensor(self, dims: Sequence[int], dtype: DataType = DataType.FLOAT,
                      create_grad: bool = True, name: Optional[str] = None) -> Tensor:
        layer = Layer(OperatorType.INPUT, name or f"input_{len(self.input_tensors)}",
                      [], data_type=dtype)
        # input names key the feed dict — must be unique too
        if not hasattr(self, "_used_names"):
            self._used_names = set()
        if layer.name in self._used_names:
            layer.name = f"{layer.name}_{layer.guid}"
        self._used_names.add(layer.name)
        t = Tensor(dims, dtype, owner_layer=layer, name=layer.name)
        layer.outputs = [t]
        self.layers.append(layer)
        self.input_tensors.append(t)
        return t

    def _add_layer(self, op_type: OperatorType, inputs: List[Tensor],
                   props: Dict[str, Any], name: Optional[str] = None,
                   dtype: Optional[DataType] = None) -> Layer:
        layer = Layer(op_type, name, inputs,
                      data_type=dtype or (inputs[0].dtype if inputs else DataType.FLOAT))
        # parameters are keyed by layer name — names must be unique
        if not hasattr(self, "_used_names"):
            self._used_names = set()
        if layer.name in self._used_names:
            base = layer.name
            layer.name = f"{base}_{layer.guid}"
        self._used_names.add(layer.name)
        layer.properties.update(props)
        self.layers.append(layer)
        return layer

    def _finish(self, layer: Layer) -> Tensor:
        op = OpRegistry.create(layer, [t.shape for t in layer.inputs])
        outs = [
            Tensor(s, layer.data_type, owner_layer=layer, owner_idx=i,
                   name=f"{layer.name}_out{i}")
            for i, s in enumerate(op.output_shapes)
        ]
        layer.outputs = outs
        return outs[0] if len(outs) == 1 else tuple(outs)

    # ---- dense / conv stack (model.h:380-520 API parity) -------------------
    def dense(self, input: Tensor, out_dim: int,
              activation: ActiMode = ActiMode.AC_MODE_NONE, use_bias: bool = True,
              datatype: Optional[DataType] = None, kernel_initializer=None,
              bias_initializer=None, name: Optional[str] = None) -> Tensor:
        layer = self._add_layer(OperatorType.LINEAR, [input], dict(
            out_dim=out_dim, activation=activation, use_bias=use_bias,
            kernel_initializer=kernel_initializer, bias_initializer=bias_initializer,
        ), name, datatype)
        return self._finish(layer)

    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int, kernel_w: int,
               stride_h: int, stride_w: int, padding_h: int, padding_w: int,
               activation: ActiMode = ActiMode.AC_MODE_NONE, groups: int = 1,
               use_bias: bool = True, kernel_initializer=None,
               bias_initializer=None, name: Optional[str] = None) -> Tensor:
        layer = self._add_layer(OperatorType.CONV2D, [input], dict(
            out_channels=out_channels, kernel_h=kernel_h, kernel_w=kernel_w,
            stride_h=stride_h, stride_w=stride_w, padding_h=padding_h,
            padding_w=padding_w, activation=activation, groups=groups,
            use_bias=use_bias, kernel_initializer=kernel_initializer,
            bias_initializer=bias_initializer), name)
        return self._finish(layer)

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int, stride_h: int,
               stride_w: int, padding_h: int, padding_w: int,
               pool_type: PoolType = PoolType.POOL_MAX,
               activation: ActiMode = ActiMode.AC_MODE_NONE,
               name: Optional[str] = None) -> Tensor:
        layer = self._add_layer(OperatorType.POOL2D, [input], dict(
            kernel_h=kernel_h, kernel_w=kernel_w, stride_h=stride_h,
            stride_w=stride_w, padding_h=padding_h, padding_w=padding_w,
            pool_type=pool_type, activation=activation), name)
        return self._finish(layer)

    def batch_norm(self, input: Tensor, relu: bool = True,
                   name: Optional[str] = None) -> Tensor:
        layer = self._add_layer(OperatorType.BATCHNORM, [input],
                                dict(relu=relu), name)
        return self._finish(layer)

    def layer_norm(self, input: Tensor, axes: Sequence[int] = (-1,),
                   elementwise_affine: bool = True, eps: float = 1e-5,
                   name: Optional[str] = None) -> Tensor:
        layer = self._add_layer(OperatorType.LAYERNORM, [input], dict(
            axes=tuple(axes), elementwise_affine=elementwise_affine, eps=eps), name)
        return self._finish(layer)

    def group_norm(self, input: Tensor, groups: int, eps: float = 1e-5,
                   affine: bool = True, name: Optional[str] = None) -> Tensor:
        """nn.GroupNorm analog (r4): per-group channel normalization."""
        layer = self._add_layer(OperatorType.GROUPNORM, [input],
                                dict(groups=groups, eps=eps, affine=affine),
                                name)
        return self._finish(layer)

    def rms_norm(self, input: Tensor, eps: float = 1e-6,
                 name: Optional[str] = None) -> Tensor:
        """RMSNorm over the last dim (Llama/T5 family; new scope vs the
        reference)."""
        layer = self._add_layer(OperatorType.RMSNORM, [input],
                                dict(eps=eps), name)
        return self._finish(layer)

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: AggrMode = AggrMode.AGGR_MODE_NONE,
                  kernel_initializer=None, name: Optional[str] = None) -> Tensor:
        layer = self._add_layer(OperatorType.EMBEDDING, [input], dict(
            num_entries=num_entries, out_dim=out_dim, aggr=aggr,
            kernel_initializer=kernel_initializer), name, DataType.FLOAT)
        return self._finish(layer)

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0,
                            vdim: int = 0, dropout: float = 0.0, bias: bool = True,
                            qkv_bias: bool = False,
                            add_bias_kv: bool = False, add_zero_attn: bool = False,
                            causal: bool = False, num_kv_heads: int = 0,
                            rope: bool = False, rope_theta: float = 10000.0,
                            kernel_initializer=None,
                            seq_parallel: Optional[str] = None,
                            name: Optional[str] = None) -> Tensor:
        """``seq_parallel='seq'`` runs the attention core as ring attention
        over that mesh axis (context parallelism for long sequences)."""
        layer = self._add_layer(OperatorType.MULTIHEAD_ATTENTION,
                                [query, key, value], dict(
            embed_dim=embed_dim, num_heads=num_heads, kdim=kdim or embed_dim,
            vdim=vdim or embed_dim, dropout=dropout, bias=bias,
            qkv_bias=qkv_bias, causal=causal,
            num_kv_heads=num_kv_heads or num_heads, rope=rope,
            rope_theta=rope_theta,
            kernel_initializer=kernel_initializer, seq_parallel=seq_parallel), name)
        return self._finish(layer)

    # ---- elementwise -------------------------------------------------------
    def _unary(self, op_type, x, name=None, scalar=None, inplace=False):
        layer = self._add_layer(op_type, [x], dict(scalar=scalar, inplace=inplace), name)
        return self._finish(layer)

    def _binary(self, op_type, a, b, name=None):
        layer = self._add_layer(op_type, [a, b], {}, name)
        return self._finish(layer)

    def exp(self, x, name=None): return self._unary(OperatorType.EXP, x, name)
    def sin(self, x, name=None): return self._unary(OperatorType.SIN, x, name)
    def cos(self, x, name=None): return self._unary(OperatorType.COS, x, name)
    def relu(self, x, inplace=True, name=None): return self._unary(OperatorType.RELU, x, name, inplace=inplace)
    def gelu(self, x, name=None): return self._unary(OperatorType.GELU, x, name)
    def sigmoid(self, x, name=None): return self._unary(OperatorType.SIGMOID, x, name)
    def tanh(self, x, name=None): return self._unary(OperatorType.TANH, x, name)
    def elu(self, x, inplace=True, name=None): return self._unary(OperatorType.ELU, x, name, inplace=inplace)
    def rsqrt(self, x, name=None): return self._unary(OperatorType.RSQRT, x, name)
    def identity(self, x, name=None): return self._unary(OperatorType.IDENTITY, x, name)
    def pow(self, x, exponent, name=None): return self._unary(OperatorType.POW, x, name, scalar=exponent)
    def scalar_multiply(self, x, scalar, inplace=True, name=None):
        return self._unary(OperatorType.SCALAR_MULTIPLY, x, name, scalar=scalar, inplace=inplace)
    def scalar_add(self, x, scalar, inplace=True, name=None):
        return self._unary(OperatorType.SCALAR_ADD, x, name, scalar=scalar, inplace=inplace)
    def scalar_sub(self, x, scalar, inplace=True, name=None):
        return self._unary(OperatorType.SCALAR_SUB, x, name, scalar=scalar, inplace=inplace)
    def scalar_true_divide(self, x, scalar, inplace=True, name=None):
        return self._unary(OperatorType.SCALAR_TRUE_DIV, x, name, scalar=scalar, inplace=inplace)

    def add(self, a, b, name=None): return self._binary(OperatorType.EW_ADD, a, b, name)
    def subtract(self, a, b, name=None): return self._binary(OperatorType.EW_SUB, a, b, name)
    def multiply(self, a, b, name=None): return self._binary(OperatorType.EW_MUL, a, b, name)
    def divide(self, a, b, name=None): return self._binary(OperatorType.EW_DIV, a, b, name)
    def max(self, a, b, name=None): return self._binary(OperatorType.EW_MAX, a, b, name)
    def min(self, a, b, name=None): return self._binary(OperatorType.EW_MIN, a, b, name)

    # ---- shape / misc ------------------------------------------------------
    def concat(self, tensors: Sequence[Tensor], axis: int, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.CONCAT, list(tensors), dict(axis=axis), name)
        return self._finish(layer)

    def split(self, input: Tensor, sizes, axis: int, name=None):
        if isinstance(sizes, int):
            sizes = [input.shape[axis] // sizes] * sizes
        layer = self._add_layer(OperatorType.SPLIT, [input],
                                dict(sizes=tuple(sizes), axis=axis), name)
        return self._finish(layer)

    def reshape(self, input: Tensor, shape, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.RESHAPE, [input], dict(shape=tuple(shape)), name)
        return self._finish(layer)

    def transpose(self, input: Tensor, perm, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.TRANSPOSE, [input], dict(perm=tuple(perm)), name)
        return self._finish(layer)

    def flat(self, input: Tensor, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.FLAT, [input], {}, name)
        return self._finish(layer)

    def reverse(self, input: Tensor, axis: int, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.REVERSE, [input], dict(axis=axis), name)
        return self._finish(layer)

    def cast(self, input: Tensor, dtype: DataType, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.CAST, [input], dict(dtype=dtype), name, dtype)
        return self._finish(layer)

    def dropout(self, input: Tensor, rate: float = 0.5, seed: int = 0, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.DROPOUT, [input], dict(rate=rate, seed=seed), name)
        return self._finish(layer)

    def softmax(self, input: Tensor, axis: int = -1, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.SOFTMAX, [input], dict(axis=axis), name)
        return self._finish(layer)

    def gather(self, input: Tensor, index: Tensor, axis: int = 0, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.GATHER, [input, index], dict(axis=axis), name)
        return self._finish(layer)

    def batch_matmul(self, a: Tensor, b: Tensor, a_seq_length_dim: int = -1,
                     b_seq_length_dim: int = -1, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.BATCHMATMUL, [a, b], dict(
            a_seq_length_dim=a_seq_length_dim, b_seq_length_dim=b_seq_length_dim), name)
        return self._finish(layer)

    def reduce_sum(self, input: Tensor, axes, keepdims: bool = False, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.REDUCE_SUM, [input],
                                dict(axes=tuple(axes), keepdims=keepdims), name)
        return self._finish(layer)

    def reduce_max(self, input: Tensor, axes, keepdims: bool = False, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.REDUCE_MAX, [input],
                                dict(axes=tuple(axes), keepdims=keepdims), name)
        return self._finish(layer)

    def log(self, x, name=None):
        return self._unary(OperatorType.LOG, x, name)

    def constant(self, value, name=None, trainable=False) -> Tensor:
        """Embedded constant tensor (fx get_attr buffers, masks, tables).

        trainable=True makes it a leaf parameter (a bare learned tensor
        used directly in forward, e.g. a positional embedding) that the
        optimizer updates, with `value` as the initial value."""
        import numpy as _np
        layer = self._add_layer(OperatorType.CONST, [],
                                dict(value=_np.asarray(value),
                                     trainable=bool(trainable)), name)
        return self._finish(layer)

    def where(self, cond: Tensor, a: Tensor, b: Tensor, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.WHERE, [cond, a, b], {}, name)
        return self._finish(layer)

    def expand(self, input: Tensor, shape, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.EXPAND, [input],
                                dict(shape=tuple(shape)), name)
        return self._finish(layer)

    def einsum(self, equation: str, tensors: Sequence[Tensor], name=None) -> Tensor:
        layer = self._add_layer(OperatorType.EINSUM, list(tensors),
                                dict(equation=equation), name)
        return self._finish(layer)

    def mean(self, input: Tensor, dims, keepdims: bool = False, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.MEAN, [input],
                                dict(axes=tuple(dims), keepdims=keepdims), name)
        return self._finish(layer)

    def top_k(self, input: Tensor, k: int, sorted: bool = True, name=None):
        layer = self._add_layer(OperatorType.TOPK, [input], dict(k=k, sorted=sorted), name)
        return self._finish(layer)

    def arg_top_k(self, input: Tensor, k: int, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.ARG_TOPK, [input], dict(k=k), name)
        return self._finish(layer)

    # ---- MoE ---------------------------------------------------------------
    def group_by(self, input: Tensor, assign: Tensor, n: int, alpha: float = 1.0,
                 name=None):
        layer = self._add_layer(OperatorType.GROUP_BY, [input, assign],
                                dict(n=n, alpha=alpha), name)
        return self._finish(layer)

    def aggregate(self, inputs: Sequence[Tensor], n: int, lambda_bal: float = 0.0,
                  name=None) -> Tensor:
        layer = self._add_layer(OperatorType.AGGREGATE, list(inputs),
                                dict(n=n, lambda_bal=lambda_bal), name)
        return self._finish(layer)

    def aggregate_spec(self, inputs: Sequence[Tensor], n: int,
                       lambda_bal: float = 0.0, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.AGGREGATE_SPEC, list(inputs),
                                dict(n=n, lambda_bal=lambda_bal), name)
        return self._finish(layer)

    def cache(self, input: Tensor, num_batches: int = 1, score_fn=None, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.CACHE, [input],
                                dict(num_batches=num_batches, score_fn=score_fn), name)
        return self._finish(layer)

    def experts(self, input: Tensor, gate: Tensor, n: int, k: int,
                hidden_size: int, alpha: float = 2.0,
                lambda_bal: float = 0.0, expert_parallel=None,
                name=None) -> Tensor:
        """Fused MoE experts op: top-k dispatch -> stacked expert FFN ->
        gate-weighted combine. Stacked weights [E, ...] shard over an
        'expert' mesh axis (ops/experts.py; the TPU fusion of the
        reference's per-expert Linear placement, moe.cc:65-83)."""
        layer = self._add_layer(
            OperatorType.EXPERTS, [input, gate],
            dict(n=n, k=k, hidden_size=hidden_size, alpha=alpha,
                 lambda_bal=lambda_bal, expert_parallel=expert_parallel),
            name)
        return self._finish(layer)

    def moe(self, input: Tensor, num_exp: int, num_select: int,
            expert_hidden_size: int, alpha: float = 2.0,
            lambda_bal: float = 0.04, fused: bool = True, name=None) -> Tensor:
        """MoE sugar layer (model.h:507-512): softmax gate -> topk ->
        group_by -> per-expert dense -> aggregate. With ``fused=True``
        (default) the dispatch/experts/combine run as the single Experts op
        the search can expert-shard; ``fused=False`` builds the reference's
        literal subgraph. Note the two forms have different parameter trees
        (stacked [E, ...] weights vs per-expert dense layers), so
        checkpoints are not interchangeable between them."""
        gate = self.dense(input, num_exp, name=f"{name or 'moe'}_gate")
        gate = self.softmax(gate)
        if fused:
            return self.experts(input, gate, num_exp, num_select,
                                expert_hidden_size, alpha, lambda_bal,
                                name=f"{name or 'moe'}_experts")
        topk_out = self.top_k(gate, num_select)
        topk_values, topk_assign = topk_out
        grouped = self.group_by(input, topk_assign, num_exp, alpha,
                                name=f"{name or 'moe'}_group_by")
        if num_exp == 1:
            grouped = (grouped,)
        expert_outs = []
        for e in range(num_exp):
            h = self.dense(grouped[e], expert_hidden_size,
                           activation=ActiMode.AC_MODE_RELU,
                           name=f"{name or 'moe'}_expert{e}_h")
            o = self.dense(h, input.shape[-1], name=f"{name or 'moe'}_expert{e}_o")
            expert_outs.append(o)
        return self.aggregate(
            [topk_values, topk_assign, topk_assign, gate] + expert_outs,
            num_exp, lambda_bal, name=f"{name or 'moe'}_aggregate")

    # ---- parallel (resharding) ops — explicit PCG API ---------------------
    # (src/parallel_ops/*.cc; under XLA these become sharding-constraint
    # boundaries — see flexflow_tpu/ops/parallel_ops.py)
    def repartition(self, input: Tensor, dim: int, degree: int,
                    axis: Optional[str] = None, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.REPARTITION, [input], dict(
            dim=dim, degree=degree,
            axis=axis or ("data" if dim == 0 else "model")), name)
        return self._finish(layer)

    def combine(self, input: Tensor, dim: int, degree: int, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.COMBINE, [input],
                                dict(dim=dim, degree=degree), name)
        return self._finish(layer)

    def replicate(self, input: Tensor, degree: int, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.REPLICATE, [input],
                                dict(degree=degree), name)
        return self._finish(layer)

    def reduction(self, input: Tensor, dim: int, degree: int, name=None) -> Tensor:
        layer = self._add_layer(OperatorType.REDUCTION, [input],
                                dict(dim=dim, degree=degree), name)
        return self._finish(layer)

    # ======================= compile ========================================
    def _materialize_nodes(self, input_shape_overrides=None):
        """Layer -> Op materialization (create_operators_from_layers,
        model.cc:2784). With `input_shape_overrides` ({input layer name ->
        shape}) every intermediate shape is re-derived from the overridden
        INPUT shapes — the seq-length bucket path (FFIterationConfig
        analog, reference config.h:162-167) materializes the same layer
        graph at a shorter sequence this way.

        Returns (nodes, input_names, tensor_ref)."""
        nodes: List[OpNode] = []
        tensor_ref: Dict[int, Tuple] = {}  # Tensor.guid -> ref
        input_names: List[str] = []
        shape_of: Dict[int, Tuple[int, ...]] = {}
        for layer in self.layers:
            if layer.op_type == OperatorType.INPUT:
                t = layer.outputs[0]
                shape_of[t.guid] = tuple(
                    (input_shape_overrides or {}).get(layer.name, t.shape))
                tensor_ref[t.guid] = ("input", layer.name)
                input_names.append(layer.name)
                continue
            op = OpRegistry.create(
                layer, [shape_of.get(t.guid, t.shape) for t in layer.inputs])
            refs = [tensor_ref[t.guid] for t in layer.inputs]
            nodes.append(OpNode(op, refs))
            for i, t in enumerate(layer.outputs):
                tensor_ref[t.guid] = ("op", op.guid, i)
                shape_of[t.guid] = op.output_shapes[i]
        return nodes, input_names, tensor_ref

    def _select_final_ref(self, nodes, tensor_ref):
        """Output selection (get_final_operator, model.cc:2476): the
        user-designated tensor, else the sole unconsumed output of the
        final node."""
        out_t = getattr(self, "outputs", None)
        if out_t is not None:
            ref = tensor_ref.get(out_t.guid)
            if ref is None or ref[0] != "op":
                raise ValueError("outputs= must be a tensor produced by a layer")
            return (ref[1], ref[2])
        final_node = nodes[-1]
        consumed = {
            tensor_ref[t.guid][1:]
            for layer in self.layers
            for t in layer.inputs
            if tensor_ref.get(t.guid, ("x",))[0] == "op"
        }
        free = [i for i in range(len(final_node.op.output_shapes))
                if (final_node.guid, i) not in consumed]
        return (final_node.guid, free[0] if len(free) == 1 else 0)

    def compile(self, optimizer: Optional[Optimizer] = None,
                loss_type: LossType = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                metrics: Sequence[MetricsType] = (),
                comp_mode: CompMode = CompMode.TRAINING,
                machine_spec: Optional[MachineSpec] = None,
                mesh=None, outputs=None,
                lint: Optional[str] = None) -> None:
        """Materialize ops, choose a strategy, build jitted executables.

        Mirrors FFModel::compile (model.cc:2802): Layer->Op materialization,
        strategy search (or data-parallel default), then instead of Legion
        region allocation + NCCL bootstrap, mesh construction + sharding
        assignment + jit.

        ``lint`` runs the fflint static verifier (flexflow_tpu/analysis)
        over the materialized PCG + chosen strategy before parameters
        are allocated: "warn" prints the report, "error" raises on any
        ERROR-severity diagnostic. None defers to ``FFConfig.lint``
        (the ``--lint`` flag); the report lands in ``self.lint_report``.
        """
        cfg = self.config
        cfg.computation_mode = comp_mode
        self.optimizer = optimizer or SGDOptimizer(
            lr=cfg.learning_rate, weight_decay=cfg.weight_decay)
        self.loss_type = loss_type

        # --- create_operators_from_layers (model.cc:2784) ---
        nodes, input_names, tensor_ref = self._materialize_nodes()
        if not nodes:
            raise ValueError("model has no layers")
        # --- output selection (get_final_operator, model.cc:2476) ---
        # The model output is the user-designated tensor (compile(outputs=...)
        # or the Tensor marked via self.outputs), falling back to the sole
        # unconsumed output of the final node.
        out_t = outputs if outputs is not None else getattr(self, "outputs", None)
        if isinstance(out_t, (list, tuple)):
            if len(out_t) != 1:
                raise ValueError("exactly one output tensor is supported")
            out_t = out_t[0]
        # persist so recompile_on_condition's re-compile keeps the selection
        self.outputs = out_t
        final_ref = self._select_final_ref(nodes, tensor_ref)
        final_node = next(n for n in nodes if n.guid == final_ref[0])
        self._final_is_softmax = final_node.op.op_type == OperatorType.SOFTMAX
        self.metrics = Metrics(loss_type, list(metrics),
                               preds_are_probs=self._final_is_softmax)

        # --- machine + mesh + strategy -----------------------------------
        # Mirrors the GRAPH_OPTIMIZE task boundary (model.cc:2825): the
        # search owns the mesh factorization (MachineView enumeration
        # analog); without a search budget we take the data-parallel
        # default, optionally with tensor-parallel overrides.
        avail = len(jax.devices())
        # num_devices == 0 means "auto: use every visible device"
        n_dev = min(cfg.num_devices, avail) if cfg.num_devices > 0 else avail
        batch0 = self.input_tensors[0].shape[0] if self.input_tensors else 1
        if machine_spec is None and cfg.machine_model_file:
            # --machine-model-file / --machine-model-version (reference
            # model.cc:3640): version >= 1 selects the file-based model
            from flexflow_tpu.machine import MachineSpec
            machine_spec = MachineSpec.from_file(cfg.machine_model_file)
        elif cfg.machine_model_version > 0 and not cfg.machine_model_file:
            raise ValueError(
                "--machine-model-version > 0 requires --machine-model-file")
        self.machine_spec = machine_spec or detect_machine_spec(
            n_dev, slices=getattr(cfg, "slices", 1))
        self.search_info = None
        # search-objective provenance: "step_time" (TRAINING search),
        # "latency" (INFERENCE search), None (no search ran) — recorded
        # in exported strategy files and checkpoint manifests
        self.search_objective = None

        import math as _math
        from flexflow_tpu.parallel.strategy import (
            data_parallel_strategy, apply_strategy, tensor_parallel_overrides)
        from flexflow_tpu.search import unity as _unity

        def _heuristic_mesh():
            if cfg.enable_parameter_parallel and not cfg.only_data_parallel:
                mp = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
            else:
                mp = 1
            dp = n_dev // mp
            while dp > 1 and batch0 % dp != 0:
                dp //= 2
            axes = {"data": dp}
            if mp > 1:
                axes["model"] = mp
            return make_mesh(dp * mp, axes)

        def _heuristic_strategy():
            st = data_parallel_strategy(nodes, self.mesh)
            if cfg.enable_parameter_parallel:
                st = tensor_parallel_overrides(nodes, self.mesh, st)
            return st

        self.mesh = mesh
        self.strategy = None
        if cfg.import_strategy_file:
            mesh_axes, self.strategy = _unity.import_strategy_file(
                cfg.import_strategy_file, nodes)
            if self.mesh is None:
                need = _math.prod(mesh_axes.values())
                if need > avail:
                    raise ValueError(
                        f"strategy file {cfg.import_strategy_file} needs a "
                        f"{mesh_axes} mesh ({need} devices) but only {avail} "
                        f"are visible")
                self.mesh = make_mesh(need, mesh_axes)
            # drop spec axes the actual mesh doesn't carry (file may come
            # from a differently-shaped machine)
            valid = set(self.mesh.axis_names)
            for st in self.strategy.values():
                st.output_specs = [
                    (P(*(e if e in valid else None for e in s))
                     if s is not None else None)
                    for s in st.output_specs
                ]
                st.param_specs = {
                    k: P(*(e if e in valid else None for e in v))
                    for k, v in st.param_specs.items()
                }
        elif (cfg.search_budget > 0 and not cfg.only_data_parallel
              and mesh is None):
            try:
                # optimizer-state copies for the simulator's memory/update
                # model: 0 plain SGD, 1 momentum, 2 Adam-family
                from flexflow_tpu.optimizers import SGDOptimizer as _SGD
                if comp_mode == CompMode.INFERENCE:
                    cfg.opt_state_factor = 0.0  # no optimizer state at all
                elif isinstance(self.optimizer, _SGD):
                    cfg.opt_state_factor = (
                        1.0 if self.optimizer.momentum else 0.0)
                else:
                    cfg.opt_state_factor = 2.0
                measured = None
                if cfg.search_measure_ops:
                    # calibrate the cost model with real-device op timings
                    # (analog of the reference's measure_operator_cost pass)
                    from flexflow_tpu.search.profile import microbenchmark
                    measured = microbenchmark(
                        nodes, cache_file=cfg.measured_cache_file)
                mesh_axes, self.strategy, self.search_info = _unity.graph_optimize(
                    nodes, self.machine_spec, cfg, n_dev, batch=batch0,
                    measured=measured, final_ref=final_ref)
                self.search_objective = self.search_info.get("objective")
                self.mesh = make_mesh(_math.prod(mesh_axes.values()), mesh_axes)
                # the substitution engine may have rewritten the graph —
                # run the rewritten node list (strategy is keyed to it)
                if self.search_info.get("rewritten_nodes") is not None:
                    nodes = self.search_info["rewritten_nodes"]
                    if self.search_info.get("final_ref") is not None:
                        final_ref = tuple(self.search_info["final_ref"])
                    fnode = next(n for n in nodes if n.guid == final_ref[0])
                    was_softmax = self._final_is_softmax
                    self._final_is_softmax = (
                        fnode.op.op_type == OperatorType.SOFTMAX)
                    if was_softmax != self._final_is_softmax:
                        self.metrics = Metrics(
                            loss_type, list(metrics),
                            preds_are_probs=self._final_is_softmax)
            except (RuntimeError, ImportError, OSError) as e:
                # a requested search (--budget N) must never silently
                # degrade to data-parallel — a broken libffsearch.so on a
                # bench run would otherwise measure DP as "searched"
                # (VERDICT r4 Weak #6)
                raise RuntimeError(
                    f"auto-parallelization search was requested "
                    f"(search_budget={cfg.search_budget}) but failed: {e}. "
                    f"Rebuild native/libffsearch.so (cd native && make) or "
                    f"drop --budget to run data-parallel.") from e
        if self.mesh is None:
            self.mesh = _heuristic_mesh()
        if self.strategy is None:
            self.strategy = _heuristic_strategy()
        if cfg.export_strategy_file:
            axes_now = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            _unity.export_strategy_file(cfg.export_strategy_file, axes_now,
                                        self.strategy, nodes,
                                        objective=self.search_objective)
        # multi-slice runtime axis (flexflow_tpu/multislice): --slices N
        # splits the searched 'data' extent into an OUTER 'slice' axis
        # times the within-slice remainder, and extends every
        # 'data'-sharded PartitionSpec across both. The split happens
        # AFTER strategy export (strategy files stay flat/portable) and
        # before apply_strategy. The cross-slice axis carries data
        # parallelism only — matching the native search's
        # inner_axes_cross_slice mesh gate — so its gradient sync rides
        # the WUS bucketed-RS chaining like any other data axis.
        n_slices = max(1, int(getattr(cfg, "slices", 1) or 1))
        if n_slices > 1 and "slice" not in self.mesh.axis_names:
            axes_flat = dict(zip(self.mesh.axis_names,
                                 self.mesh.devices.shape))
            if axes_flat.get("pipe", 1) > 1:
                raise ValueError(
                    "--slices > 1 does not compose with a 'pipe' mesh: "
                    "the cross-slice axis must carry data parallelism only "
                    "(pass --disable-pipeline-parallel, or --slices 1)")
            from flexflow_tpu.multislice import (remap_strategy_for_slices,
                                                 slice_axes)
            sliced_axes = slice_axes(axes_flat, n_slices)
            self.mesh = make_mesh(_math.prod(sliced_axes.values()),
                                  sliced_axes)
            remap_strategy_for_slices(self.strategy)
        apply_strategy(nodes, self.strategy, self.mesh)
        self.op_profile = None
        if cfg.profiling:
            # --profiling (reference model.cc profiling mode wraps every
            # task with timers): microbenchmark each op on the device and
            # report the per-op fwd/bwd table through the RecursiveLogger
            from flexflow_tpu.search.profile import microbenchmark
            from flexflow_tpu.utils.logger import RecursiveLogger
            plog = RecursiveLogger("profiling")
            with plog.enter(f"per-op device microbenchmarks "
                            f"({len(nodes)} ops)"):
                prof = microbenchmark(nodes,
                                      cache_file=cfg.measured_cache_file)
                for node in nodes:
                    f_s = prof.get(f"{node.guid}:fwd")
                    b_s = prof.get(f"{node.guid}:bwd")
                    if f_s is not None:
                        plog.info(f"{node.op.name}: fwd {f_s * 1e6:9.1f}us  "
                                  f"bwd {b_s * 1e6:9.1f}us")
            self.op_profile = prof
        if cfg.export_strategy_computation_graph_file:
            from flexflow_tpu.utils.dot import export_strategy_dot
            export_strategy_dot(nodes, self.mesh,
                                cfg.export_strategy_computation_graph_file,
                                include_costs=cfg.include_costs_dot_graph,
                                search_info=self.search_info)

        compute_dtype = (
            jnp.bfloat16 if (cfg.allow_mixed_precision and
                             self.machine_spec.chip != "cpu-sim")
            else jnp.float32
        )
        # 'slice' is a data axis to the executor: batch sharding, the
        # WUS/optimizer-state sharding, and the bucketed-RS gradient sync
        # all extend across it (the cross-slice sync is the slow DCN leg
        # the '_ovl' pricing hides under backward compute)
        data_axes = tuple(a for a in self.mesh.axis_names
                          if a in ("slice", "data", "replica"))
        axes_now = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        # weight-update sharding (WUS): reduce-scatter gradient sync +
        # data-sharded master params / optimizer moments + fused all-gather
        # of the next step's compute params (flexflow_tpu/executor.py).
        # 'auto' defers to the native DP's per-mesh verdict when the
        # strategy was searched (WUS is a priced choice dimension — the
        # '_wus' choice suffix); heuristic strategies engage it at data
        # degree >= 4, where the optimizer-state HBM win dominates.
        import math as _math2
        data_deg = _math2.prod(axes_now.get(a, 1) for a in data_axes) or 1
        wus_mode = getattr(cfg, "weight_update_sharding", "auto")
        if wus_mode not in ("auto", "on", "off"):
            raise ValueError(f"weight_update_sharding expects auto|on|off, "
                             f"got {wus_mode!r}")
        searched = isinstance(self.search_info, dict)
        searched_wus = searched and any(
            "_wus" in (getattr(st, "choice", None) or "")
            for st in (self.strategy or {}).values())
        if comp_mode == CompMode.INFERENCE or wus_mode == "off":
            wus = False
        elif wus_mode == "on":
            wus = data_deg > 1
        else:
            wus = searched_wus if searched else data_deg >= 4
        self.wus_enabled = wus
        # per-op WUS granularity: a searched strategy picks '_wus' per
        # op; under 'auto' the executor honors each op's choice instead
        # of applying WUS globally — the ops the DP left on plain
        # all-reduce keep it, closing the priced-vs-emitted gap on mixed
        # strategies. Forced 'on' (and heuristic strategies) stay global.
        wus_ops = None
        if wus and wus_mode == "auto" and searched and searched_wus:
            wus_ops = {
                n.op.name for n in nodes
                if "_wus" in (getattr((self.strategy or {}).get(n.op.guid),
                                      "choice", None) or "")}
        # comms-compute overlap (ISSUE 9): bucketed async grad reduce-
        # scatter + prefetched compute-param all-gathers. 'auto' follows
        # the search: overlap engages when the DP picked '_ovl' choice
        # twins (with the searched bucket size), or whenever WUS engages
        # on heuristic strategies (4 MB default); explicit N forces
        # N-MB buckets; '0'/'off' disables.
        ovl_raw = str(getattr(cfg, "overlap_bucket_mb", "auto")).lower()
        searched_ovl = searched and any(
            "_ovl" in (getattr(st, "choice", None) or "")
            for st in (self.strategy or {}).values())
        searched_bucket = ((self.search_info or {}).get("overlap") or {}).get(
            "bucket_mb") if searched else None
        if ovl_raw in ("0", "off"):
            overlap, bucket_mb = False, 4.0
        elif ovl_raw == "auto":
            overlap = searched_ovl if searched else wus
            bucket_mb = float(searched_bucket or 4.0)
        else:
            bucket_mb = float(int(ovl_raw))
            overlap = bucket_mb > 0
        self.overlap_enabled = bool(overlap and wus)
        # kernel-implementation choices (ISSUE 15): the search prices
        # "_k:<impl>" twins per op; the executor honors each op's chosen
        # lowering through the same per-op plumbing as wus_ops. When the
        # kernel dimension ran, attention ops whose choice kept the
        # DEFAULT impl are pinned to it ("einsum") so the executor's
        # availability-based auto-pick cannot silently run a kernel the
        # DP priced AND rejected (the priced-vs-executed gap FFL209
        # watches). Off/not-searched leaves every op on auto — the
        # pre-kernel-search behavior, bit-identical.
        import os as _os
        from flexflow_tpu.search.unity import kernel_choice_of
        kernel_on = ((searched or any(
                         "_k:" in (getattr(st, "choice", None) or "")
                         for st in (self.strategy or {}).values()))
                     and str(getattr(cfg, "kernel_search", "auto")).lower()
                     != "off"
                     and not _os.environ.get("FFS_NO_KERNEL_SEARCH"))
        # pipe-mesh winners never enumerated the kernel dimension (the
        # native search gates "_k:" twins off pp>1 meshes) — pinning
        # attention to einsum there would disable the availability-based
        # flash auto-pick the DP never priced an alternative to
        if axes_now.get("pipe", 1) > 1:
            kernel_on = False
        kernel_choices: Optional[Dict[str, str]] = None
        if kernel_on:
            kernel_choices = {}
            for n in nodes:
                ch = getattr((self.strategy or {}).get(n.op.guid),
                             "choice", None) or ""
                impl = kernel_choice_of(ch)
                if impl is not None:
                    kernel_choices[n.op.name] = impl
                elif n.op.op_type == OperatorType.MULTIHEAD_ATTENTION:
                    kernel_choices[n.op.name] = ("ring" if "_ring" in ch
                                                 else "einsum")
            def _flash_was_enumerable(op):
                # mirror the native flash gate (ffs_strategy.hpp
                # kernel_gate): the "einsum" pin below asserts "the DP
                # priced flash AND rejected it" — which only holds when
                # a twin could exist for this op. Where the gate
                # excluded flash (dropout, tile divisibility,
                # cross-attention) the availability-based auto pick
                # must survive: eval/serve forwards may legally run
                # flash even though the TRAINING search never priced it.
                from flexflow_tpu.ops.pallas_kernels import BLK_Q
                try:
                    b, s, e = op.input_shapes[0]
                    sk = (op.input_shapes[1][1]
                          if len(op.input_shapes) > 1 else s)
                    return (sk == s and s % BLK_Q == 0
                            and op.head_dim % 8 == 0
                            and not (comp_mode == CompMode.TRAINING
                                     and op.dropout > 0))
                except Exception:
                    return False

            for n in nodes:
                impl = kernel_choices.get(n.op.name)
                if not hasattr(n.op, "seq_parallel"):
                    continue
                if impl == "flash":
                    n.op.kernel_impl = impl
                elif impl == "einsum" and _flash_was_enumerable(n.op):
                    n.op.kernel_impl = impl
                n.op._kernel_fallback = None  # fresh compile, fresh record
        else:
            # the off switch promises availability-based defaults
            # bit-identical to pre-kernel-search execution: clear any
            # kernel_impl apply_strategy pinned from an imported "_k:"
            # strategy under FFS_NO_KERNEL_SEARCH / --kernel-search off
            # (and any stale fallback record with it — FFL209 must not
            # keep firing for a fallback that can no longer occur)
            for n in nodes:
                if getattr(n.op, "kernel_impl", None) is not None:
                    n.op.kernel_impl = None
                if getattr(n.op, "_kernel_fallback", None) is not None:
                    n.op._kernel_fallback = None
        self.kernel_choices = kernel_choices
        # rematerialization (ISSUE 20): on flat meshes the search prices
        # per-op '_r' twins — ops whose twin won run under jax.checkpoint
        # (executor remat_ops); pipe meshes never enumerate '_r' twins and
        # instead carry a block-level 'remat' bit in the searched pipeline
        # object (body_remat below). The off switch (--remat-search off /
        # FFS_NO_REMAT) forces both off — bit-identical to pre-remat
        # execution.
        from flexflow_tpu.search.unity import executed_remat_ops
        remat_on = (str(getattr(cfg, "remat_search", "auto")).lower() != "off"
                    and not _os.environ.get("FFS_NO_REMAT"))
        remat_ops: Optional[set] = None
        if remat_on and axes_now.get("pipe", 1) == 1:
            remat_ops = executed_remat_ops(nodes, self.strategy) or None
        self.remat_ops = remat_ops
        exec_kwargs = dict(compute_dtype=compute_dtype, data_axes=data_axes,
                           final_is_softmax=self._final_is_softmax,
                           fold_conv_bn=cfg.fold_conv_bn,
                           weight_update_sharding=wus,
                           wus_ops=wus_ops,
                           overlap_grad_sync=overlap,
                           # MB (1e6), matching the native bucket sweep's
                           # wire-byte unit (ffs_strategy.hpp kOvlBucketMB)
                           overlap_bucket_bytes=int(bucket_mb * 1e6),
                           kernel_choices=kernel_choices,
                           remat_ops=remat_ops)
        # conv-family execution layout (flexflow_tpu/layout.py): NCHW stays
        # the API/PCG boundary, but on TPU the conv family computes
        # channels-last with boundary transposes hoisted to chain edges.
        # The pipeline executor keeps NCHW (its shard_map'd body stacks
        # block params; conv graphs don't pipeline today).
        from flexflow_tpu.layout import propagate_layouts
        self._layout_args = dict(
            mode=getattr(cfg, "conv_compute_layout", "auto"),
            on_tpu=self.machine_spec.chip != "cpu-sim")
        if axes_now.get("pipe", 1) > 1:
            self.layout_info = dict(enabled=False, nhwc_ops=0, transposes=0)
            # GPipe lowering: the search picked a pipe mesh (or the user
            # passed one explicitly) — the repeated-block body executes as
            # an SPMD pipeline (parallel/pipeline_exec.py)
            from flexflow_tpu.parallel.pipeline_exec import (
                PipelineGraphExecutor)
            pinfo = (self.search_info or {}).get("pipeline") \
                if isinstance(self.search_info, dict) else None
            if pinfo is None or pinfo.get("blocks") is None:
                from flexflow_tpu.parallel.pipeline_detect import (
                    detect_repeated_blocks)
                pb = detect_repeated_blocks(nodes)
                if pb is None:
                    raise ValueError(
                        "mesh has a 'pipe' axis but the graph has no "
                        "repeated-block body to pipeline")
                pinfo = dict(blocks=pb,
                             microbatches=cfg.pipeline_microbatches
                             or 2 * axes_now["pipe"])
            # precedence: explicit flags > searched values > auto
            schedule = getattr(cfg, "pipeline_schedule", "auto")
            if schedule == "auto" and pinfo.get("schedule"):
                schedule = pinfo["schedule"]
            microbatches = (cfg.pipeline_microbatches
                            or int(pinfo.get("microbatches") or 0))
            self.executor = PipelineGraphExecutor(
                nodes, input_names, final_ref, self.mesh, loss_type,
                self.metrics, self.optimizer,
                pipe_blocks=pinfo["blocks"],
                microbatches=microbatches,
                schedule=schedule,
                shard_queue=getattr(cfg, "pipeline_shard_queue", True),
                body_remat=bool(remat_on and pinfo.get("remat")),
                **exec_kwargs)
        else:
            self.layout_info = propagate_layouts(nodes, **self._layout_args)
            self.executor = GraphExecutor(
                nodes, input_names, final_ref, self.mesh, loss_type,
                self.metrics, self.optimizer, **exec_kwargs)
        self.executor.comp_mode = comp_mode
        # --- fflint static verification (flexflow_tpu/analysis) ----------
        # runs BEFORE parameter allocation so an illegal strategy fails
        # fast instead of deep inside jit
        self.lint_report = None
        lint_mode = (lint if lint is not None
                     else getattr(cfg, "lint", "off")) or "off"
        if lint_mode not in ("off", "warn", "error"):
            raise ValueError(
                f"lint expects off|warn|error, got {lint_mode!r}")
        if lint_mode != "off":
            from flexflow_tpu.analysis import lint_model
            self.lint_report = lint_model(self)
            if self.lint_report.diagnostics:
                print(self.lint_report.format_human())
            if lint_mode == "error" and self.lint_report.has_errors():
                raise ValueError(
                    f"fflint: {len(self.lint_report.errors)} error-"
                    f"severity diagnostic(s) — see report above "
                    f"(compile with lint='warn' to proceed anyway)")
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.state = self.executor.init_params_and_state(sub)
        # INFERENCE (ffconst.h:46 CompMode): forward-only executable — no
        # optimizer state is ever allocated
        self.opt_state = (None if comp_mode == CompMode.INFERENCE
                          else self.optimizer.init(self.params))
        self._iter = 0
        self._seq_execs: Dict[int, Any] = {}  # seq-length bucket executors
        self._declared_seq_cache = -1  # lazily derived (-1 = not yet)

    # ======================= data staging ==================================
    def _shard_batch(self, arr: np.ndarray, cast: bool = False,
                     inputs: bool = False) -> jax.Array:
        arr = jnp.asarray(arr)
        if cast and jnp.issubdtype(arr.dtype, jnp.floating):
            # activations flow in the compute dtype end-to-end (bf16 on
            # TPU): ops emit outputs in their input dtype, so casting once
            # at the graph boundary halves every activation's HBM traffic.
            # Labels are staged without cast (loss math is f32).
            arr = arr.astype(self.executor.compute_dtype)
        # inputs stage on the executor's batch layout (pipe-sharded under
        # the pipeline's sharded microbatch queue); labels stay on the
        # data-sharded loss layout
        sharding = (self.executor.batch_sharding() if inputs
                    else self.executor.label_sharding())
        if jax.process_count() > 1:
            # multi-controller SPMD: `arr` is the rows THIS host feeds;
            # assemble the global batch from per-process shards
            from flexflow_tpu import distributed as _dist
            return _dist.stage_local_batch(np.asarray(arr), sharding)
        return jax.device_put(arr, sharding)

    def _local_batch_size(self, global_bs: int) -> int:
        """Rows of a `global_bs` batch this process feeds (== global_bs
        single-process)."""
        if jax.process_count() <= 1:
            return global_bs
        from flexflow_tpu import distributed as _dist
        rows, _ = _dist.local_batch_rows(self.executor.batch_sharding(),
                                         global_bs)
        return rows

    def _stage_inputs(self, xs) -> Dict[str, jax.Array]:
        if not isinstance(xs, (list, tuple)):
            xs = [xs]
        names = self.executor.input_names
        if len(xs) != len(names):
            raise ValueError(f"model has {len(names)} inputs, got {len(xs)} arrays")
        return {n: self._shard_batch(x, cast=True, inputs=True)
                for n, x in zip(names, xs)}

    # ======================= train / eval loops ============================
    def _make_tracer(self, trace_dir, run_name: str):
        """Tracer for one fit/evaluate call: the explicit ``trace_dir``
        argument wins over ``Config --trace-dir``; both unset returns the
        shared no-op (flexflow_tpu/obs — zero overhead path)."""
        from flexflow_tpu.obs import make_tracer, model_context
        tracer = make_tracer(trace_dir or self.config.trace_dir,
                             run_name=run_name)
        if tracer.active:
            tracer.set_meta(**model_context(self))
        return tracer

    def _make_capture(self, tracer, profile_steps):
        """Windowed jax.profiler device-trace capture (obs/devtrace):
        the explicit ``profile_steps`` argument wins over ``Config
        --profile-steps``; both unset (or no active tracer) returns the
        shared no-op capture."""
        from flexflow_tpu.obs import make_capture
        return make_capture(tracer,
                            profile_steps or self.config.profile_steps)

    def _finalize_trace(self, tracer, success: bool = True,
                        devtrace=None) -> None:
        """Export the trace + the compiled-step summary (XLA cost/memory
        analysis, collective census) + the search-drift calibration
        report. Observability failures warn instead of killing the
        training run that produced the data.

        ``devtrace`` (an obs DeviceTraceCapture) is finalized FIRST so
        its device lanes and per-step attribution counters land in the
        exported Perfetto trace, and its measured per-collective times
        join the drift report's census-priced predictions.

        ``success=False`` (the run raised) flushes only the trace and
        counters: the summary/drift reports need a fresh lower+compile
        of the step (AOT inspection cannot reuse the executor's cached
        executable), which is minutes of XLA on TPU and — after an OOM
        — likely to fail again; the trace alone is the diagnosis."""
        if not tracer.active:
            return
        import os
        import sys
        from flexflow_tpu.obs import (drift_report, export_step_summary,
                                      get_registry, record_step_metrics,
                                      write_artifact)
        devrep = None
        if devtrace is not None and devtrace.active:
            try:
                devrep = devtrace.finalize(self, tracer)
            except Exception as e:
                print(f"[obs] device-trace attribution failed: {e!r}",
                      file=sys.stderr)
        step_metrics = None
        try:
            step_metrics = record_step_metrics(self, tracer)
        except Exception as e:
            print(f"[obs] step metrics failed: {e!r}", file=sys.stderr)
        if success:
            # predicted-schedule lanes (obs/simtrace): replay the
            # strategy through the native simulator and inject the
            # sim:compute / sim:comms Perfetto lanes BEFORE export so
            # the predicted step sits next to the measured device lanes
            try:
                from flexflow_tpu.obs import write_simtrace
                write_simtrace(self, tracer)
            except Exception as e:
                print(f"[obs] simulated-schedule trace failed: {e!r}",
                      file=sys.stderr)
        try:
            tracer.export()
        except Exception as e:
            print(f"[obs] trace export failed: {e!r}", file=sys.stderr)
        stem = os.path.join(tracer.trace_dir, tracer.file_stem)
        extra = dict(run_name=tracer.run_name, run_seq=tracer.run_seq)
        if (isinstance(self.search_info, dict)
                and self.search_info.get("search_trace")):
            # search provenance (--search-trace): the native trace rides
            # along as its own artifact so calibrate/explain tooling can
            # consume it without re-running the search
            try:
                write_artifact(stem + ".searchtrace.json",
                               dict(self.search_info["search_trace"]),
                               host_id=tracer.host_id, kind="searchtrace",
                               header_extra=extra)
            except Exception as e:
                print(f"[obs] search-trace artifact failed: {e!r}",
                      file=sys.stderr)
        if success:
            summary = None
            try:
                summary = export_step_summary(self, tracer)
            except Exception as e:
                print(f"[obs] step inspection failed: {e!r}",
                      file=sys.stderr)
            try:
                rep = drift_report(
                    self, tracer.step_time_s(),
                    census=(summary or {}).get("collectives"),
                    phase_summary=tracer.phase_summary(),
                    measured_collectives=(devrep or {}).get("collectives"),
                    step_metrics=step_metrics)
                write_artifact(stem + ".drift.json", rep,
                               host_id=tracer.host_id, kind="drift",
                               header_extra=extra)
            except Exception as e:
                print(f"[obs] drift report failed: {e!r}", file=sys.stderr)
        else:
            print(f"[obs] run failed: wrote trace/counters only "
                  f"({tracer.file_stem})", file=sys.stderr)
        try:
            get_registry().export(stem + ".counters.json",
                                  host_id=tracer.host_id)
        except Exception as e:
            print(f"[obs] counter export failed: {e!r}", file=sys.stderr)

    def _make_health(self, tracer, devtrace, run_name: str = "fit"):
        """RuntimeHealth for one fit call (None when supervision is
        off). ``--grace-window`` turns SIGTERM/SIGINT into a graceful
        stop the step loop honors (final checkpoint + trace flush +
        ``PREEMPTED_EXIT``); ``--watchdog-timeout`` starts the
        hung-collective watchdog, whose trip flushes this run's trace
        from the watchdog thread before ``HUNG_EXIT`` — the main
        thread is wedged and will never reach its own finalizer."""
        cfg = self.config
        if cfg.grace_window_s <= 0 and cfg.watchdog_timeout_s <= 0:
            return None
        from flexflow_tpu.runtime_health import RuntimeHealth

        def _flush_trace():
            self._finalize_trace(tracer, success=False, devtrace=devtrace)

        return RuntimeHealth(grace_window_s=cfg.grace_window_s,
                             watchdog_timeout_s=cfg.watchdog_timeout_s,
                             run_name=run_name, finalize_fn=_flush_trace)

    def _make_checkpointer(self, checkpoint_dir, checkpoint_every, resume,
                           run_name: str = "fit", heartbeat=None,
                           state_provider=None):
        """CheckpointManager for one fit call (None when checkpointing
        is off). Explicit arguments win over the ``--checkpoint-*`` /
        ``--resume`` config flags. With resume on, the newest COMPLETE
        checkpoint restores (fail-fast on every rank when the directory
        holds only partial ones) and the returned start step tells the
        epoch loop how many step slots to skip; an empty directory is a
        fresh launch — the same command line serves first start and
        every restart."""
        cfg = self.config
        cdir = checkpoint_dir or cfg.checkpoint_dir
        do_resume = resume if resume is not None else cfg.resume
        every = (checkpoint_every if checkpoint_every is not None
                 else cfg.checkpoint_every)
        if not cdir:
            if do_resume:
                raise ValueError(
                    "resume requested but no checkpoint directory — pass "
                    "fit(checkpoint_dir=...) or --checkpoint-dir")
            if every:
                # a cadence with nowhere to write would train for hours
                # saving nothing — the silent-data-loss launch typo
                raise ValueError(
                    f"checkpoint_every={every} requested but no checkpoint "
                    f"directory — pass fit(checkpoint_dir=...) or "
                    f"--checkpoint-dir")
            return None, 0
        from flexflow_tpu.ckpt import CheckpointManager
        mgr = CheckpointManager(self, cdir, every=every,
                                retain=cfg.checkpoint_retain,
                                async_write=cfg.checkpoint_async,
                                run_name=run_name, heartbeat=heartbeat,
                                state_provider=state_provider)
        start = mgr.resume() if do_resume else 0
        return mgr, start

    def _run_epochs(self, next_batch, num_batches: int, bs: int, epochs: int,
                    verbose: bool, on_epoch_start=None, tracer=None,
                    devtrace=None, ckpt_mgr=None, start_step: int = 0,
                    on_resume=None, health=None) -> float:
        """Shared epoch loop: per-batch jitted step, on-device metric
        accumulation (one host sync per epoch), ELAPSED TIME / THROUGHPUT
        report. ``next_batch(epoch, b)`` -> (inputs dict, labels).

        With an active tracer each step is a span with dispatch /
        device_wait phases (device_wait fences the step on the loss — an
        observer effect tracing accepts so per-step times mean device
        time, not async dispatch time) plus whatever phases the
        ``next_batch`` closure records (fit: sibling data_load /
        device_put spans — disjoint, so phase totals sum to step time
        instead of double-booking H2D under data_load), and each epoch
        ends with a metrics_sync span (the one host fetch of the
        accumulated metrics).

        ``ckpt_mgr`` (a flexflow_tpu.ckpt.CheckpointManager) saves every
        ``checkpoint_every`` iterations (blocking only for the local
        device→host shard snapshot; file writes and the manifest commit
        run on its writer thread) and once more at the end. A resumed
        run passes ``start_step``: the first ``start_step`` step slots
        of the epoch grid are skipped — the slots the checkpoint already
        covers — so epochs/batch indices line up with the uninterrupted
        schedule. Skipped slots cost NOTHING: loaders with positional
        state are repositioned by the one-shot ``on_resume(start_step)``
        callback (fit_loader seeks its loaders there) instead of
        fetching-and-discarding every covered batch.

        ``health`` (flexflow_tpu.runtime_health.RuntimeHealth) is fed
        once per finished step: the watchdog heartbeat, plus the
        preemption check — a pending SIGTERM/maintenance notice raises
        ``Preempted`` AFTER the in-flight step, at which point this
        loop cuts the grace-window checkpoint (``ckpt_mgr.finalize``)
        and lets the exception carry ``PREEMPTED_EXIT`` out."""
        from flexflow_tpu.ckpt import faults as _faults
        from flexflow_tpu.obs import NULL_CAPTURE, NULL_TRACER
        tracer = tracer or NULL_TRACER
        devtrace = devtrace or NULL_CAPTURE
        train_step = self.executor.make_train_step()
        self._refresh_compute_params()
        start = time.time()
        loss = None
        executed = 0
        step_idx = -1  # global step index, the --profile-steps coordinate
        for epoch in range(epochs):
            if on_epoch_start is not None:
                on_epoch_start()
            self._metrics_acc = PerfMetrics()
            mtotals = None
            epoch_executed = 0
            for b in range(num_batches):
                step_idx += 1
                if step_idx < start_step:
                    # this step slot is inside the restored checkpoint
                    continue
                if step_idx == start_step and start_step and on_resume:
                    # one-shot loader reposition: runs after this
                    # epoch's on_epoch_start reset, right before the
                    # first post-resume fetch
                    on_resume(start_step)
                # devtrace OUTSIDE tracer.step: the profiler session
                # start/stop at the window edges costs whole seconds on
                # some backends — observability overhead, not step time,
                # so it must not land in the step span the percentile
                # reservoir observes (ISSUE 8 satellite: the 17 s p99)
                with devtrace.step(step_idx), tracer.step():
                    inputs, labels = next_batch(epoch, b)
                    self._rng, sub = jax.random.split(self._rng)
                    with tracer.phase("dispatch"):
                        (self.params, self.opt_state, self.state, loss,
                         mvals) = train_step(
                            self.params, self.opt_state, self.state,
                            inputs, labels, sub)
                    self._iter += 1
                    mtotals = mvals if mtotals is None else jax.tree.map(
                        jnp.add, mtotals, mvals)
                    if tracer.active or devtrace.active:
                        with tracer.phase("device_wait"):
                            jax.block_until_ready(loss)
                executed += 1
                epoch_executed += 1
                # fault-injection seam (FFS_FAULT kill_host / sigterm /
                # hang); no-op when the env is unset
                _faults.step_hook(step_idx)
                if health is not None:
                    # watchdog heartbeat + preemption check. A pending
                    # notice surfaces HERE — after the in-flight step —
                    # so the grace checkpoint is a consistent post-step
                    # state the auto-resumed run continues bit-exactly.
                    try:
                        health.step_done(step_idx)
                    except BaseException:
                        if ckpt_mgr is not None:
                            t_grace = time.perf_counter()
                            with tracer.phase("grace_checkpoint"):
                                ckpt_mgr.finalize(
                                    elapsed_s=time.time() - start,
                                    steps=executed)
                            from flexflow_tpu.obs.registry import \
                                get_registry
                            get_registry().gauge(
                                f"{ckpt_mgr.run_name}/grace_checkpoint_s",
                                time.perf_counter() - t_grace)
                        raise
                if ckpt_mgr is not None:
                    if ckpt_mgr.should_save(self._iter):
                        with tracer.phase("checkpoint"):
                            ckpt_mgr.save(self._iter)
                    else:
                        ckpt_mgr.note_step(self._iter)
            with tracer.phase("metrics_sync", epoch=epoch):
                if epoch_executed:
                    # a resumed run's partial epoch accumulated only the
                    # EXECUTED steps' totals — average over those, not
                    # the full grid
                    self._metrics_acc.update(dict(mtotals or {}),
                                             bs * epoch_executed)
                    self._last_loss = float(loss)
            if verbose and epoch_executed:
                # fully-skipped epochs (inside the restored checkpoint)
                # have nothing to report
                rep = self._metrics_acc.report()
                print(f"epoch {epoch}: loss={self._last_loss:.4f} " +
                      " ".join(f"{k}={v:.4f}" for k, v in rep.items()))
        elapsed = time.time() - start
        if ckpt_mgr is not None:
            # final save + durability barrier + goodput gauge: the run
            # must not be reported done while a commit is still in flight
            ckpt_mgr.finalize(elapsed_s=elapsed, steps=executed)
        # throughput counts only the samples this run actually processed
        # (a resume skips the checkpoint-covered step slots in ~0 time)
        thr = bs * executed / elapsed
        if verbose:
            print(f"ELAPSED TIME = {elapsed:.4f}s, THROUGHPUT = {thr:.2f} samples/s")
        return thr

    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: Optional[int] = None, verbose: bool = True,
            trace_dir: Optional[str] = None,
            profile_steps: Optional[str] = None,
            checkpoint_dir: Optional[str] = None,
            checkpoint_every: Optional[int] = None,
            resume: Optional[bool] = None):
        """Keras-style whole-dataset training loop, streaming batches from
        host (base_model.py:376-430 / flexflow_cffi.py:2073-2086).

        ``trace_dir`` (or ``Config --trace-dir``) activates the runtime
        observability subsystem: per-step Chrome-trace/JSONL artifacts,
        a compiled-step summary (XLA FLOPs/bytes/peak memory +
        collective census), and a search-drift calibration report land
        in that directory when the loop finishes.

        ``profile_steps`` (or ``Config --profile-steps``, e.g. "2:4")
        additionally wraps that step window in a ``jax.profiler``
        capture: device compute/collective lanes and per-step
        compute/comms/exposed-comms attribution merge into the same
        trace dir (obs/devtrace).

        ``checkpoint_dir`` + ``checkpoint_every`` (or the
        ``--checkpoint-*`` flags) turn on v2 per-shard async
        checkpointing (flexflow_tpu/ckpt): every N iterations each host
        snapshots its addressable shards (the only blocking cost) and a
        writer thread commits them manifest-last, retaining the newest
        ``--checkpoint-retain`` checkpoints. ``resume`` (or
        ``--resume``) restores the newest complete checkpoint first and
        skips the step slots it covers, so ``epochs`` keeps meaning the
        TOTAL schedule — an interrupted and an uninterrupted run of the
        same command line end bit-identically."""
        epochs = epochs or self.config.epochs
        xs = x if isinstance(x, (list, tuple)) else [x]
        n = xs[0].shape[0]
        bs = batch_size or self.input_tensors[0].shape[0]
        # multi-host: x/y hold this process's rows; each batch takes the
        # local block of the global batch (multi-controller SPMD)
        lbs = self._local_batch_size(bs)
        num_batches = n // lbs
        if num_batches == 0:
            raise ValueError(
                f"dataset of {n} samples is smaller than batch size {lbs}")
        tracer = self._make_tracer(trace_dir, "fit")
        devtrace = self._make_capture(tracer, profile_steps)

        def next_batch(epoch, b):
            sl = slice(b * lbs, (b + 1) * lbs)
            with tracer.phase("data_load"):
                xs_np = [xx[sl] for xx in xs]
                y_np = y[sl]
            with tracer.phase("device_put"):
                return (self._stage_inputs(xs_np),
                        self._shard_batch(y_np))

        # a traced run that dies mid-training (OOM, NaN assert, ^C,
        # preemption) — or at resume, against a missing/corrupt
        # checkpoint — still flushes its trace: that trace is the
        # diagnosis
        run_name = tracer.run_name if tracer.active else "fit"
        health = self._make_health(tracer, devtrace, run_name=run_name)
        try:
            if health is not None:
                health.install()
            ckpt_mgr, start_step = self._make_checkpointer(
                checkpoint_dir, checkpoint_every, resume,
                run_name=run_name,
                heartbeat=health.heartbeat if health is not None else None)
            out = self._run_epochs(next_batch, num_batches, bs, epochs,
                                   verbose, tracer=tracer,
                                   devtrace=devtrace, ckpt_mgr=ckpt_mgr,
                                   start_step=start_step, health=health)
        except BaseException:
            self._finalize_trace(tracer, success=False, devtrace=devtrace)
            raise
        finally:
            if health is not None:
                health.close()
        self._finalize_trace(tracer, devtrace=devtrace)
        return out

    def fit_loader(self, loaders, epochs: Optional[int] = None,
                   verbose: bool = True, trace_dir: Optional[str] = None,
                   profile_steps: Optional[str] = None,
                   checkpoint_dir: Optional[str] = None,
                   checkpoint_every: Optional[int] = None,
                   resume: Optional[bool] = None):
        """Steady-state training from staged on-device loaders
        (flexflow_tpu.dataloader) — no host→device traffic per step."""
        epochs = epochs or self.config.epochs
        bs = loaders.input_loaders[0].batch_size
        tracer = self._make_tracer(trace_dir, "fit")
        devtrace = self._make_capture(tracer, profile_steps)

        def next_batch(e, b):
            with tracer.phase("data_load"):
                return loaders.next_batch()

        def cursor():
            # the dataloader position, recorded in every manifest: a
            # resume seeks straight here instead of fetching-and-
            # discarding every covered batch (ROADMAP elastic (c))
            nb = loaders.num_batches
            return dict(loader=dict(iteration=int(self._iter),
                                    epoch=int(self._iter // nb),
                                    batch=int(self._iter % nb),
                                    num_batches=int(nb)))

        run_name = tracer.run_name if tracer.active else "fit"
        health = self._make_health(tracer, devtrace, run_name=run_name)
        try:
            if health is not None:
                health.install()
            ckpt_mgr, start_step = self._make_checkpointer(
                checkpoint_dir, checkpoint_every, resume,
                run_name=run_name,
                heartbeat=health.heartbeat if health is not None else None,
                state_provider=cursor)
            # the staged loader advances positional state — a resumed
            # run repositions it once (seek) at the first post-resume
            # slot, paying zero fetches for the covered ones
            out = self._run_epochs(next_batch, loaders.num_batches, bs,
                                   epochs, verbose,
                                   on_epoch_start=loaders.reset,
                                   tracer=tracer, devtrace=devtrace,
                                   ckpt_mgr=ckpt_mgr,
                                   start_step=start_step,
                                   on_resume=lambda s: loaders.seek(
                                       s % loaders.num_batches),
                                   health=health)
        except BaseException:
            self._finalize_trace(tracer, success=False, devtrace=devtrace)
            raise
        finally:
            if health is not None:
                health.close()
        self._finalize_trace(tracer, devtrace=devtrace)
        return out

    # ---- checkpoint / resume (new scope vs reference — SURVEY §5.4) -------
    def save_checkpoint(self, path: str) -> None:
        from flexflow_tpu.checkpoint import save_checkpoint
        save_checkpoint(path, self)

    def load_checkpoint(self, path: str) -> int:
        from flexflow_tpu.checkpoint import load_checkpoint
        return load_checkpoint(path, self)

    def recompile_on_condition(self, recompile_state) -> bool:
        from flexflow_tpu.recompile import recompile_on_condition
        return recompile_on_condition(self, recompile_state)

    def evaluate(self, x=None, y=None, batch_size: Optional[int] = None,
                 trace_dir: Optional[str] = None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        n = xs[0].shape[0]
        bs_report = batch_size or self.input_tensors[0].shape[0]
        bs = self._local_batch_size(bs_report)  # multi-host: x/y are local rows
        if n // bs == 0:
            raise ValueError(
                f"dataset of {n} samples is smaller than batch size {bs}")
        eval_step = self.executor.make_eval_step()
        tracer = self._make_tracer(trace_dir, "evaluate")
        acc = PerfMetrics()
        loss_sum, batches = 0.0, 0
        try:
            for b in range(n // bs):
                with tracer.step():
                    sl = slice(b * bs, (b + 1) * bs)
                    with tracer.phase("device_put"):
                        inputs = self._stage_inputs([xx[sl] for xx in xs])
                        labels = self._shard_batch(y[sl])
                    with tracer.phase("dispatch"):
                        loss, logits, mvals = eval_step(
                            self.params, self.state, inputs, labels)
                    with tracer.phase("metrics_sync"):
                        loss_sum += float(loss)
                        batches += 1
                        acc.update({k: v for k, v in mvals.items()},
                                   bs_report)
        finally:
            if tracer.active:
                try:
                    tracer.export()
                except Exception as e:
                    import sys
                    print(f"[obs] trace export failed: {e!r}",
                          file=sys.stderr)
        rep = acc.report()
        rep["loss"] = loss_sum / max(batches, 1)
        return rep

    def serve(self, batch_buckets=None, max_wait_ms: float = 5.0,
              search_budget: Optional[int] = None, start: bool = False,
              verbose: bool = False):
        """Production inference serving over this compiled model
        (flexflow_tpu/serve): continuous/dynamic batching into per-
        batch-bucket executors, each with its OWN latency-objective
        searched sharding when ``search_budget`` (default: the
        compile-time ``--budget``) is nonzero and the native search is
        available. Returns a ``ServingEngine``; ``start=True`` also
        spins its background serving thread —

            engine = model.serve(start=True)
            out = engine.submit(sample).wait()

        p50/p99 request latency, queue depth, and batch occupancy land
        in the obs registry under ``serve/*``; ``scripts/serve_bench.py``
        drives the closed-loop benchmark."""
        if self.executor is None:
            raise ValueError("compile() the model before serve()")
        from flexflow_tpu.serve import ServingEngine
        engine = ServingEngine(self, batch_buckets=batch_buckets,
                               max_wait_ms=max_wait_ms,
                               search_budget=search_budget,
                               verbose=verbose)
        return engine.start() if start else engine

    def predict(self, x):
        fwd = self.executor.make_forward(training=False)
        inputs = self._stage_inputs(x if isinstance(x, (list, tuple)) else [x])
        self._rng, sub = jax.random.split(self._rng)
        out, _ = fwd(self.params, self.state, inputs, sub)
        if jax.process_count() > 1:
            from flexflow_tpu import distributed as _dist
            return _dist.all_gather_host(out)
        return np.asarray(out)

    # ---- reference-parity iteration protocol ------------------------------
    # (forward / backward / update with FFIterationConfig.seq_length —
    # model.cc:2415-2475 + config.h:162-167. Under XLA these are phases of
    # one fused jitted step; we keep the API by staging the batch in
    # set_batch and running the fused step in update(). A seq_length
    # shorter than the model's declared sequence dispatches to a BUCKET
    # executor: the same layer graph re-materialized at the next
    # power-of-two length, so every op — not just BatchMatmul — skips the
    # compute beyond the active length while jit sees only a bounded set
    # of static shapes. begin/end_trace are no-ops: jit IS the trace.)
    def set_batch(self, x, y):
        self._current_batch = (self._stage_inputs(x if isinstance(x, (list, tuple)) else [x]),
                               self._shard_batch(y))

    def forward(self, seq_length: Optional[int] = None):
        if self._current_batch is None:
            raise ValueError("call set_batch(x, y) before forward()")
        self._iter_seq = seq_length
        self._pending = "forward"

    def zero_gradients(self):
        pass

    def backward(self, seq_length: Optional[int] = None):
        if seq_length is not None:
            self._iter_seq = seq_length
        self._pending = "backward"

    def _declared_seq(self) -> Optional[int]:
        """The model's sequence extent: the dim any op marks with the SEQ
        role (attention and friends). None = no sequence dim (MLP/conv),
        in which case seq_length iteration args are ignored — matching
        the reference, where only seq ops consume FFIterationConfig."""
        if self._declared_seq_cache != -1:
            return self._declared_seq_cache
        from flexflow_tpu.ops.base import DimRole
        # collect EVERY SEQ-role extent: a graph whose ops disagree on the
        # sequence length (e.g. encoder/decoder cross-attention) has no
        # single bucketable extent — run full-length rather than slicing
        # against whichever op happened to iterate last (ADVICE r5)
        found = {
            shp[d]
            for node in self.executor.nodes
            for shp, roles in zip(node.op.output_shapes,
                                  node.op.output_dim_roles())
            for d, r in enumerate(roles)
            if r == DimRole.SEQ
        }
        self._declared_seq_cache = found.pop() if len(found) == 1 else None
        return self._declared_seq_cache

    def _seq_bucket(self, seq_length: Optional[int]) -> Optional[int]:
        """Bucketed static length for an iteration's seq_length: the next
        power of two (>=16), None when the full-length step applies."""
        declared = self._declared_seq()
        if not seq_length or declared is None or seq_length >= declared:
            return None
        if isinstance(self.search_info, dict) \
                and self.search_info.get("rewritten_nodes") is not None:
            return None  # strategy is keyed to the rewritten graph
        from flexflow_tpu.executor import GraphExecutor
        if type(self.executor) is not GraphExecutor:
            return None  # pipeline bodies are stacked at full length
        # at least one INPUT must carry the sequence at dim 1, or the
        # bucket graph would equal the full graph while update() slices —
        # degrade to the full-length step instead
        if not any(len(layer.outputs[0].shape) >= 2
                   and layer.outputs[0].shape[1] == declared
                   for layer in self.layers
                   if layer.op_type == OperatorType.INPUT):
            return None
        b = 16
        while b < seq_length:
            b *= 2
        return b if b < declared else None

    def _bucket_executor(self, bucket: int):
        """GraphExecutor for the layer graph re-materialized at `bucket`
        sequence length; params/opt state/op state are shared with the
        full-length executor (layer guids are stable, and no parameter
        shape depends on the sequence extent)."""
        ex = self._seq_execs.get(bucket)
        if ex is not None:
            return ex
        from flexflow_tpu.executor import GraphExecutor
        from flexflow_tpu.parallel.strategy import apply_strategy
        declared = self._declared_seq()
        overrides = {}
        for layer in self.layers:
            if layer.op_type != OperatorType.INPUT:
                continue
            shp = list(layer.outputs[0].shape)
            if sum(1 for e in shp[1:] if e == declared) > 1:
                raise NotImplementedError(
                    f"seq_length buckets: input '{layer.name}' shape "
                    f"{tuple(shp)} carries the sequence extent on more "
                    f"than one dim (e.g. an [B,S,S] mask) — ambiguous "
                    f"to slice")
            if len(shp) >= 2 and shp[1] == declared:
                shp[1] = bucket
                overrides[layer.name] = tuple(shp)
        nodes, input_names, tensor_ref = self._materialize_nodes(overrides)
        final_ref = self._select_final_ref(nodes, tensor_ref)
        # parameter SHAPES must be sequence-independent; a mismatch means
        # dim 1 of some input was NOT the sequence (e.g. an auxiliary
        # (B, S)-shaped feature input whose extent coincides) and slicing
        # it would silently corrupt training — refuse instead. Shapes via
        # eval_shape, not element counts: a parameter that reshapes at the
        # bucketed length while keeping its element count must still trip
        # the guard (ADVICE r5).
        def _shapes(op):
            # None (not {}) when init_params cannot be abstractly
            # evaluated, so an eval_shape failure falls back to the
            # element-count guard instead of silently comparing {} == {}
            try:
                tree = jax.eval_shape(op.init_params, jax.random.PRNGKey(0))
            except Exception:
                return None
            return {k: tuple(v.shape) for k, v in tree.items()}

        full_shapes = {n.op.guid: _shapes(n.op)
                       for n in self.executor.nodes}
        for n in nodes:
            mine = _shapes(n.op)
            ref = full_shapes.get(n.op.guid, mine)
            if ref is None or mine is None:
                full_node = self.executor.by_guid.get(n.op.guid)
                mismatch = (full_node is not None and
                            full_node.op.params_elems()
                            != n.op.params_elems())
            else:
                mismatch = ref != mine
            if mismatch:
                raise NotImplementedError(
                    f"seq_length buckets: op '{n.op.name}' changes "
                    f"parameter shape at the bucketed length — an input "
                    f"whose dim 1 coincides with the sequence extent is "
                    f"not actually a sequence; run full-length instead")
        apply_strategy(nodes, self.strategy, self.mesh)
        from flexflow_tpu.layout import propagate_layouts
        propagate_layouts(nodes, **getattr(
            self, "_layout_args", dict(mode="nchw", on_tpu=False)))
        full = self.executor
        ex = GraphExecutor(nodes, input_names, final_ref, self.mesh,
                           self.loss_type, self.metrics, self.optimizer,
                           compute_dtype=full.compute_dtype,
                           data_axes=full.data_axes,
                           final_is_softmax=self._final_is_softmax,
                           fold_conv_bn=full.fold_conv_bn,
                           weight_update_sharding=full.weight_update_sharding,
                           wus_ops=full.wus_ops,
                           overlap_grad_sync=full.grad_overlap,
                           overlap_bucket_bytes=full.overlap_bucket_bytes,
                           kernel_choices=full.kernel_choices)
        ex.comp_mode = full.comp_mode
        self._seq_execs[bucket] = ex
        return ex

    def _slice_seq(self, arr, bucket: int):
        declared = self._declared_seq()
        if arr.ndim >= 2 and arr.shape[1] == declared:
            return arr[:, :bucket]
        return arr

    def _final_output_has_seq(self) -> bool:
        """Token-level model (output carries a SEQ dim) => labels slice
        with the sequence; pooled heads (e.g. an S-class classifier whose
        label dim coincidentally equals S) keep full labels."""
        from flexflow_tpu.ops.base import DimRole
        guid, idx = self.executor.final_ref
        node = next(n for n in self.executor.nodes if n.op.guid == guid)
        return DimRole.SEQ in node.op.output_dim_roles()[idx]

    def update(self):
        inputs, labels = self._current_batch
        ex = self.executor
        bucket = self._seq_bucket(getattr(self, "_iter_seq", None))
        if bucket is not None:
            ex = self._bucket_executor(bucket)
            inputs = {k: self._slice_seq(v, bucket)
                      for k, v in inputs.items()}
            if self._final_output_has_seq():
                labels = self._slice_seq(labels, bucket)
        train_step = ex.make_train_step()
        self._refresh_compute_params()
        self._rng, sub = jax.random.split(self._rng)
        (self.params, self.opt_state, self.state, self._last_loss, self._last_metrics) = \
            train_step(self.params, self.opt_state, self.state, inputs, labels, sub)
        self._iter += 1
        self._pending = None

    def begin_trace(self, trace_id: int = 0):
        pass

    def end_trace(self, trace_id: int = 0):
        pass

    # ---- weight I/O (parallel_tensor.h:164-169 set_tensor/get_tensor) -----
    def _body_ref(self, layer_name: str):
        """(template_key, block_idx) when layer_name is a pipelined body op."""
        m = getattr(self.executor, "body_param_map", None)
        return m.get(layer_name) if m else None

    def get_parameter(self, layer_name: str, param_name: str = "kernel") -> np.ndarray:
        ref = self._body_ref(layer_name)
        if ref is not None:
            from flexflow_tpu.parallel.pipeline_exec import BODY_KEY
            key, b = ref
            return np.asarray(self.params[BODY_KEY][key][param_name][b])
        return np.asarray(self.params[layer_name][param_name])

    def set_parameter(self, layer_name: str, value: np.ndarray,
                      param_name: str = "kernel") -> None:
        ref = self._body_ref(layer_name)
        if ref is not None:
            from flexflow_tpu.parallel.pipeline_exec import BODY_KEY
            key, b = ref
            old = self.params[BODY_KEY][key][param_name]
            if tuple(old.shape[1:]) != tuple(value.shape):
                raise ValueError(
                    f"shape mismatch {old.shape[1:]} vs {value.shape}")
            # device-side slice update: keeps the pipe sharding and avoids
            # a full host round-trip of the stacked [R, ...] array per call
            self.params[BODY_KEY][key][param_name] = old.at[b].set(
                jnp.asarray(value, old.dtype))
            self._compute_params_dirty = True
            return
        old = self.params[layer_name][param_name]
        if tuple(old.shape) != tuple(value.shape):
            raise ValueError(f"shape mismatch {old.shape} vs {value.shape}")
        self.params[layer_name][param_name] = jax.device_put(
            jnp.asarray(value, old.dtype), old.sharding)
        # defer the bf16 working-copy re-cast: per-weight import loops
        # (torch/onnx/keras frontends) would otherwise cast the whole tree
        # once per weight
        self._compute_params_dirty = True

    def _refresh_compute_params(self) -> None:
        """Re-derive the bf16 working copy after direct params mutations
        (set_parameter / checkpoint load / recompile carry-over) so the
        next jitted step sees the new weights. Lazy: runs once before the
        next use, however many mutations happened."""
        from flexflow_tpu.executor import COMPUTE_PARAMS_KEY
        if not getattr(self, "_compute_params_dirty", False):
            return
        self._compute_params_dirty = False
        if self.executor is not None and self.executor.use_master_copy:
            self.state[COMPUTE_PARAMS_KEY] = \
                self.executor.cast_compute_copy(self.params)

    def get_layer_names(self) -> List[str]:
        return [n.op.name for n in (self.executor.nodes if self.executor else [])]

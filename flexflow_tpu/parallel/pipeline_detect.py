"""Repeated-block detection for pipeline parallelism.

Finds the maximal run of structurally-identical, shape-preserving,
single-tensor-boundary blocks in a compiled op graph — the "repeated
blocks" a GPipe pipeline distributes over the 'pipe' mesh axis
(parallel/pipeline.py). A block may span several single-cut segments
(e.g. a transformer layer = attention half + FFN half), so detection
looks for the longest *periodic* run of segment signatures. The
reference only reserves an enum for this capability (OP_PIPELINE,
/root/reference/include/flexflow/ffconst.h:153); here the detection
feeds both the native search's GPipe cost model and FFModel.compile's
lowering onto pipeline_spmd.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class PipelineBlocks:
    """head / blocks / tail partition of a node list (indices into it)."""
    head: List[int]
    blocks: List[List[int]]          # each: node indices of one block
    tail: List[int]
    # ref of the tensor entering block 0: ("op", guid, out_idx) or
    # ("input", name); and ("op", guid, out_idx) leaving the last block
    body_in: Tuple
    body_out: Tuple

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)


def _analyze(nodes):
    produced_at = {}
    for i, node in enumerate(nodes):
        for oi in range(len(node.op.output_shapes)):
            produced_at[(node.op.guid, oi)] = i
    last_use: Dict[Tuple, int] = {}
    input_last: Dict[str, int] = {}
    for j, node in enumerate(nodes):
        for ref in node.input_refs:
            if ref[0] == "input":
                input_last[ref[1]] = j
            else:
                last_use[(ref[1], ref[2])] = j
    return produced_at, last_use, input_last


def _cut_points(nodes, produced_at, last_use, input_last) -> List[int]:
    """Positions p where exactly ONE op-produced tensor crosses between
    nodes[:p] and nodes[p:] and no graph input is consumed at/after p."""
    n = len(nodes)
    in_last = max(input_last.values()) if input_last else -1
    cuts = []
    for p in range(1, n):
        if in_last >= p:
            continue
        crossing = sum(1 for t, lu in last_use.items()
                       if produced_at.get(t, 1 << 30) < p <= lu)
        if crossing == 1:
            cuts.append(p)
    return cuts


def _boundary_tensor(nodes, produced_at, p) -> Optional[Tuple]:
    """The single op tensor crossing cut position p (as an ('op',g,i) ref),
    or for p == 0 the sole graph input ref, else None."""
    if p == 0:
        names = {ref[1] for node in nodes for ref in node.input_refs
                 if ref[0] == "input"}
        return ("input", names.pop()) if len(names) == 1 else None
    found = None
    for j in range(p, len(nodes)):
        for ref in nodes[j].input_refs:
            if ref[0] == "op" and produced_at.get((ref[1], ref[2]),
                                                  1 << 30) < p:
                if found is not None and found != ref:
                    return None
                found = ("op", ref[1], ref[2])
    return found


def _block_signature(nodes, seg: List[int], boundary_in) -> Tuple:
    """Structural signature: op types, attrs, shapes, relative wiring.
    External refs must all equal the block's boundary-in ref."""
    local = {}
    for rel, i in enumerate(seg):
        for oi in range(len(nodes[i].op.output_shapes)):
            local[(nodes[i].op.guid, oi)] = (rel, oi)
    from flexflow_tpu.search.unity import _node_attrs, _param_shapes
    sig = []
    for i in seg:
        op = nodes[i].op
        wiring = []
        for ref in nodes[i].input_refs:
            key = (ref[1], ref[2]) if ref[0] == "op" else None
            if key is not None and key in local:
                wiring.append(("l",) + local[key])
            elif boundary_in is not None and tuple(ref) == tuple(boundary_in):
                wiring.append(("in",))
            else:
                return ()  # reaches past the block boundary
        sig.append((
            op.op_type.name,
            tuple(wiring),
            tuple(map(tuple, op.output_shapes)),
            tuple(sorted((k, tuple(v))
                         for k, v in _param_shapes(op).items())),
            tuple(sorted((k, tuple(v) if isinstance(v, list) else v)
                         for k, v in _node_attrs(op).items())),
        ))
    return tuple(sig)


def detect_repeated_blocks(nodes, min_blocks: int = 2,
                           allow_stateful: bool = False
                           ) -> Optional[PipelineBlocks]:
    """Longest run of >= min_blocks consecutive identical blocks, where a
    block is a periodic group of single-cut segments. Blocks must be
    shape-preserving (boundary-in shape == boundary-out shape) and
    stateless (no op with init_state — BN running stats cannot ride the
    pipeline's shard_map in the current lowering). ``allow_stateful``
    drops the statelessness requirement — fflint's FFL107 rule uses it to
    tell "repeated but unpipelineable (stateful/dropout body)" apart from
    "no repeated structure at all"; the runtime never sets it."""
    if len(nodes) < 2:
        return None
    produced_at, last_use, input_last = _analyze(nodes)
    cuts = _cut_points(nodes, produced_at, last_use, input_last)
    bounds = [0] + cuts + [len(nodes)]
    nseg = len(bounds) - 1
    if nseg < min_blocks:
        return None
    segments = [list(range(bounds[s], bounds[s + 1])) for s in range(nseg)]

    def stateless(seg):
        # the pipeline lowering cannot carry op state (BN running stats),
        # per-op rng (dropout), or auxiliary losses (MoE load balancing)
        # through the shard_map body — such blocks are not pipelineable
        if allow_stateful:
            return True
        from flexflow_tpu.ffconst import OperatorType
        aux_types = {OperatorType.EXPERTS, OperatorType.AGGREGATE,
                     OperatorType.AGGREGATE_SPEC, OperatorType.GROUP_BY,
                     OperatorType.DROPOUT}
        for i in seg:
            op = nodes[i].op
            if hasattr(op, "init_state"):
                return False
            if op.op_type in aux_types:
                return False
            if getattr(op, "dropout", 0.0):
                return False
        return True

    def block_of(s, P):
        return [i for seg in segments[s:s + P] for i in seg]

    best = None  # (num_blocks, covered_nodes, s0, P)
    for P in range(1, nseg // min_blocks + 1):
        for s0 in range(0, nseg - min_blocks * P + 1):
            bin0 = _boundary_tensor(nodes, produced_at, bounds[s0])
            if bin0 is None:
                continue
            blk0 = block_of(s0, P)
            sig0 = _block_signature(nodes, blk0, bin0)
            if not sig0 or not stateless(blk0):
                continue
            m = 1
            while s0 + (m + 1) * P <= nseg:
                s = s0 + m * P
                b_in = _boundary_tensor(nodes, produced_at, bounds[s])
                blk = block_of(s, P)
                if (b_in is None or not stateless(blk)
                        or _block_signature(nodes, blk, b_in) != sig0):
                    break
                m += 1
            if m < min_blocks:
                continue
            covered = sum(len(segments[s0 + i]) for i in range(m * P))
            cand = (m, covered, -s0, P)
            if best is None or cand > best:
                best = cand
    if best is None:
        return None
    m, _, neg_s0, P = best
    s0 = -neg_s0
    blocks = [block_of(s0 + i * P, P) for i in range(m)]
    body_in = _boundary_tensor(nodes, produced_at, bounds[s0])
    last = blocks[-1][-1]
    out_ref = _boundary_tensor(nodes, produced_at, bounds[s0 + m * P]) \
        if s0 + m * P < nseg else None
    body_out = out_ref if (out_ref and out_ref[0] == "op"
                           and out_ref[1] == nodes[last].op.guid) \
        else ("op", nodes[last].op.guid, 0)
    # shape preservation: in == out shape
    if body_in[0] == "op":
        in_pos = produced_at.get((body_in[1], body_in[2]))
        if in_pos is None:
            return None
        in_shape = nodes[in_pos].op.output_shapes[body_in[2]]
    else:
        first = blocks[0][0]
        slot = next((k for k, r in enumerate(nodes[first].input_refs)
                     if tuple(r) == tuple(body_in)), None)
        if slot is None:
            return None
        in_shape = nodes[first].op.input_shapes[slot]
    out_shape = nodes[last].op.output_shapes[body_out[2]]
    if tuple(in_shape) != tuple(out_shape):
        return None
    head = [i for seg in segments[:s0] for i in seg]
    tail = [i for seg in segments[s0 + m * P:] for i in seg]
    return PipelineBlocks(head=head, blocks=blocks, tail=tail,
                          body_in=tuple(body_in), body_out=tuple(body_out))


def pipeline_meta_json(nodes, blocks: PipelineBlocks) -> Dict:
    """Request payload for the native search's GPipe cost model."""
    import numpy as np
    body = [nodes[i].op.guid for blk in blocks.blocks for i in blk]
    last = blocks.blocks[-1][-1]
    shp = nodes[last].op.output_shapes[blocks.body_out[2]]
    out_bytes = int(np.prod(shp)) * nodes[last].op.dtype.size
    return dict(
        num_blocks=blocks.num_blocks,
        body=body,
        head=[nodes[i].op.guid for i in blocks.head],
        tail=[nodes[i].op.guid for i in blocks.tail],
        block_out_bytes=out_bytes,
        batch=int(shp[0]) if shp else 0,
    )

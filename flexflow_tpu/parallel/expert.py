"""Expert parallelism: MoE dispatch/combine over an 'expert' mesh axis.

The reference achieves expert parallelism by making each expert a separate
Linear op the search places on a different GPU
(examples/cpp/mixture_of_experts/moe.cc:65-83 rebalances that placement at
runtime). Under SPMD/jit that per-op placement doesn't exist; the TPU-native
design stacks expert weights on a leading E dim sharded over an 'expert'
mesh axis and exchanges tokens with explicit collectives inside shard_map:

  dispatch:  local partial-group einsum, then reduce-scatter over the
             expert axis (the all_to_all+sum that moves every token to its
             expert's shard) and psum over remaining batch shards.
  experts:   batched einsum over the *local* expert block [E/ep, C, D].
  combine:   all_gather expert outputs over the expert axis, then the local
             gate-weighted combine einsum.

Numerics are exactly the dense path's: the dispatch/combine tensors are
built from the replicated gate/assign (tiny [B,K] ints), so capacity
positions are global — no per-shard cumsum divergence.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _mesh_axes(mesh: Mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def expert_parallel_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o,
                        mesh: Mesh, expert_axis: str = "expert",
                        data_axes: Sequence[str] = ("data",),
                        activation=jax.nn.relu):
    """Run the MoE FFN with experts sharded over ``expert_axis``.

    x:        [B, D]   tokens; B is sharded over data_axes AND the expert
                       axis jointly (the expert axis doubles as a batch
                       axis on the token side, so the reduce-scatter sums
                       true partials, GShard-style)
    dispatch: [B, K, E, C] one-hot routing (same sharding as x on B)
    combine:  [B, K, E, C] gate-weighted routing
    w_h/b_h:  [E, D, H] / [E, H]   stacked expert weights, E sharded over
    w_o/b_o:  [E, H, D] / [E, D]   the expert axis
    returns:  [B, D] combined expert outputs, B sharded like x.
    """
    axes = _mesh_axes(mesh)
    ep = axes.get(expert_axis, 1)
    e_total = w_h.shape[0]
    data_axes = tuple(a for a in data_axes if axes.get(a, 1) > 1)
    tok_shards = ep
    for a in data_axes:
        tok_shards *= axes[a]
    if (ep <= 1 or e_total % ep != 0 or x.shape[0] % tok_shards != 0):
        if ep > 1:
            import warnings

            warnings.warn(
                f"expert_parallel_ffn: cannot shard {e_total} experts / "
                f"{x.shape[0]} tokens over expert axis of {ep} (tokens must "
                f"divide {tok_shards}); falling back to the replicated dense "
                f"path", stacklevel=2)
        return dense_moe_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o,
                             activation=activation)

    def local(x_l, disp_l, comb_l, w_h_l, b_h_l, w_o_l, b_o_l):
        # partial groups over local tokens, all experts: [E, C, D]
        part = jnp.einsum("bd,bkec->ecd", x_l.astype(jnp.float32),
                          disp_l.astype(jnp.float32))
        # move each expert's rows home: sum over expert-axis peers while
        # scattering the E dim (reduce-scatter == all_to_all + local sum)
        grouped = jax.lax.psum_scatter(part, expert_axis,
                                       scatter_dimension=0, tiled=True)
        for a in data_axes:  # finish the token sum over batch shards
            grouped = jax.lax.psum(grouped, a)
        # local expert block FFN: [E/ep, C, D] -> [E/ep, C, D]
        h = jnp.einsum("ecd,edh->ech", grouped, w_h_l.astype(jnp.float32))
        h = activation(h + b_h_l[:, None, :])
        o = jnp.einsum("ech,ehd->ecd", h, w_o_l.astype(jnp.float32))
        o = o + b_o_l[:, None, :]
        # bring every expert's output to every token shard
        full = jax.lax.all_gather(o, expert_axis, axis=0, tiled=True)
        y = jnp.einsum("bkec,ecd->bd", comb_l.astype(jnp.float32), full)
        return y.astype(x_l.dtype)

    tok_axes = (*data_axes, expert_axis)
    tok2 = P(tok_axes, None)
    tok4 = P(tok_axes, None, None, None)
    wspec3 = P(expert_axis, None, None)
    wspec2 = P(expert_axis, None)
    from flexflow_tpu.utils.shard_map_compat import shard_map
    return shard_map(
        local, mesh=mesh,
        in_specs=(tok2, tok4, tok4, wspec3, wspec2, wspec3, wspec2),
        out_specs=tok2, check_rep=False,
    )(x, dispatch, combine, w_h, b_h, w_o, b_o)


def dense_moe_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o,
                  activation=jax.nn.relu):
    """Single-device / replicated reference path (identical numerics)."""
    grouped = jnp.einsum("bd,bkec->ecd", x.astype(jnp.float32),
                         dispatch.astype(jnp.float32))
    h = jnp.einsum("ecd,edh->ech", grouped, w_h.astype(jnp.float32))
    h = activation(h + b_h[:, None, :])
    o = jnp.einsum("ech,ehd->ecd", h, w_o.astype(jnp.float32))
    o = o + b_o[:, None, :]
    y = jnp.einsum("bkec,ecd->bd", combine.astype(jnp.float32), o)
    return y.astype(x.dtype)

"""Ring attention: sequence/context parallelism over the ICI ring.

First-class long-context support — new scope the reference lacks entirely
(SURVEY §5.7: FlexFlow's only sequence handling is seq_length iteration
config; no ring attention / Ulysses / context parallelism exists there).

Design: the sequence dim of Q/K/V is sharded over a 'seq' mesh axis. Each
device holds its local Q block permanently and its K/V block initially;
K/V blocks rotate around the ring via ``jax.lax.ppermute`` (pure ICI
neighbor traffic, no all-gather), and each step's partial attention is
merged with the running result using the numerically-stable streaming
log-sum-exp accumulation of blockwise/flash attention:

    m_new = max(m, m_blk);  l = l*e^{m-m_new} + l_blk*e^{m_blk-m_new}
    o = (o*l*e^{m-m_new} + o_blk*l_blk*e^{m_blk-m_new}) / l_new

Causal masking is exact: a rotating K/V block is fully visible when its
ring index < the local index, fully masked when greater, and
triangle-masked when equal — so later steps skip no compute but contribute
zero probability (XLA's static schedule cannot skip iterations; the
*communication* is what sequence parallelism saves).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _attn_block(q, k, v, scale, mask):
    """One Q-block × KV-block partial attention.

    q: [B,H,Sq,D], k/v: [B,H,Sk,D]; mask broadcastable to [B,H,Sq,Sk] or
    None. Returns (o_blk [B,H,Sq,D] *unnormalized*, m_blk [B,H,Sq],
    l_blk [B,H,Sq]).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    # fully-masked rows: keep m finite so exp() underflows to 0, not NaN
    m_safe = jnp.where(jnp.isfinite(m_blk), m_blk, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    l_blk = jnp.sum(p, axis=-1)
    o_blk = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                       preferred_element_type=jnp.float32)
    return o_blk, m_safe, l_blk


# large-negative stand-in for -inf in the streaming lse accumulation:
# keeps every exp()/logaddexp() finite so gradients through the merge
# weights never see inf - inf (NaN) while still underflowing to exactly 0
_NEG = -1e30


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-shard body under shard_map. q,k,v: [B,H,S_loc,D] local blocks.

    When the Pallas flash kernel is available for the local block shape,
    each Q-block x KV-block partial runs inside it — the S_loc x S_loc
    score tile lives in VMEM only, in BOTH forward and backward (the
    K-blocked backward kernel covers shard lengths up to
    MAX_BWD_BLOCKED_SEQ; only beyond that does the backward fall back to
    the HBM-materializing einsum recompute). Fixes VERDICT r3 Weak #7:
    the einsum inner body materialized per-shard scores in HBM, quadratic
    in the shard length at exactly the long contexts ring attention
    exists for. The merge accumulates (o_normalized, lse) blockwise:
        lse' = logaddexp(lse, lse_blk)
        o'   = o * e^{lse - lse'} + o_blk * e^{lse_blk - lse'}
    """
    from flexflow_tpu.ops.pallas_kernels import (flash_attention_available,
                                                 flash_attention_lse,
                                                 pallas_mode)

    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    b, h, sq, d = q.shape
    if flash_attention_available(sq, d) and sq == k.shape[2]:
        interpret = pallas_mode() == "interpret"
        fold = lambda x: x.reshape(b * h, x.shape[2], x.shape[3])

        def _run(q_, k_, v_, blk_causal):
            o, lse = flash_attention_lse(fold(q_), fold(k_), fold(v_),
                                         blk_causal, interpret)
            return (o.astype(jnp.float32).reshape(b, h, sq, d),
                    lse.reshape(b, h, sq))

        def block(k_cur, v_cur, kv_idx):
            if not causal:
                return _run(q, k_cur, v_cur, False)
            mode = jnp.where(kv_idx < my_idx, 0,
                             jnp.where(kv_idx == my_idx, 1, 2))
            return jax.lax.switch(mode, [
                lambda _: _run(q, k_cur, v_cur, False),   # fully visible
                lambda _: _run(q, k_cur, v_cur, True),    # diagonal: tri
                lambda _: (jnp.zeros((b, h, sq, d), jnp.float32),  # masked
                           jnp.full((b, h, sq), _NEG, jnp.float32)),
            ], None)

        def fstep(carry, _):
            o, lse, k_cur, v_cur, kv_idx = carry
            o_blk, lse_blk = block(k_cur, v_cur, kv_idx)
            lse_blk = jnp.maximum(lse_blk, _NEG)  # finite always
            lse_new = jnp.logaddexp(lse, lse_blk)
            w1 = jnp.exp(lse - lse_new)
            w2 = jnp.exp(lse_blk - lse_new)
            o = o * w1[..., None] + o_blk * w2[..., None]
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return (o, lse_new, k_nxt, v_nxt, (kv_idx - 1) % n), None

        o0 = jnp.zeros((b, h, sq, d), jnp.float32)
        lse0 = jnp.full((b, h, sq), _NEG, jnp.float32)
        (o, _, _, _, _), _ = jax.lax.scan(
            fstep, (o0, lse0, k, v, my_idx), None, length=n)
        return o.astype(q.dtype)

    qf = q.astype(jnp.float32)

    def mask_for(kv_idx):
        if not causal:
            return None
        # kv block strictly earlier: visible; strictly later: masked;
        # same block: lower triangle
        tri = (jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :])
        full = kv_idx < my_idx
        none = kv_idx > my_idx
        blk = jnp.where(none, False, jnp.where(full, True, tri))
        return blk[None, None, :, :]

    def step(carry, _):
        o, m, l, k_cur, v_cur, kv_idx = carry
        o_blk, m_blk, l_blk = _attn_block(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            scale, mask_for(kv_idx))
        m_new = jnp.maximum(m, m_blk)
        c1 = jnp.exp(m - m_new)
        c2 = jnp.exp(m_blk - m_new)
        o = o * c1[..., None] + o_blk * c2[..., None]
        l = l * c1 + l_blk * c2
        # rotate K/V to the next device on the ring (ICI neighbor hop)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_nxt = (kv_idx - 1) % n
        return (o, m_new, l, k_nxt, v_nxt, kv_nxt), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, m, l, _, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v, my_idx), None, length=n)
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                   batch_axis: Optional[str] = "data",
                   head_axis: Optional[str] = None,
                   causal: bool = False):
    """Sequence-parallel attention. q,k,v: [B, H, S, D] global arrays whose
    S dim is (to be) sharded over ``seq_axis``; B over ``batch_axis`` and
    H over ``head_axis`` if those axes exist in the mesh (heads are
    independent, so keeping them sharded composes head parallelism with the
    seq ring instead of gathering heads at the shard_map boundary).

    Runs under shard_map: all mesh axes manual, ppermute over the seq ring.
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    ba = batch_axis if batch_axis in axes else None
    ha = (head_axis if head_axis in axes and q.shape[1] % axes[head_axis] == 0
          else None)
    spec = P(ba, ha, seq_axis, None)
    fn = functools.partial(_ring_attention_local, axis_name=seq_axis,
                          causal=causal)
    # axes not named in the specs replicate, which is the intended layout
    # for dp x sp attention
    from flexflow_tpu.utils.shard_map_compat import shard_map
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)

"""PCG sharding round-trips: degree form ↔ PartitionSpec form.

The canonical ``ParallelDim``/``ParallelTensorShape`` classes live in
``flexflow_tpu.tensor`` (re-exported here): every tensor dimension carries
a parallel *degree* plus the mesh axes it is sharded on, replica dims model
weight replication (reference include/flexflow/parallel_tensor.h:36-163).
Where the reference maps dims onto Legion index-space partitions, we map
them onto ``jax.sharding.PartitionSpec`` entries over a named ``Mesh`` —
the degrees ARE the mesh-axis extents, and GSPMD materializes the data
movement Legion partitions performed.

This module adds the conversions the search/strategy layers need:

* ``shape_from_partition_spec(shape, spec, mesh)`` — spec form → degree
  form (degrees read off the mesh-axis extents);
* ``spec_to_degrees`` — shorthand returning just the degree vector;
* ``replicated_shape`` — an unsharded degree-form shape.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import DataType
from flexflow_tpu.tensor import ParallelDim, ParallelTensorShape

__all__ = [
    "ParallelDim",
    "ParallelTensorShape",
    "MAX_TENSOR_DIM",
    "replicated_shape",
    "shape_from_partition_spec",
    "spec_to_degrees",
]

MAX_TENSOR_DIM = 8  # reference MAX_TENSOR_DIM (include/flexflow/config.h)


def replicated_shape(shape: Sequence[int],
                     dtype: DataType = DataType.FLOAT) -> ParallelTensorShape:
    return ParallelTensorShape.make(list(shape), dtype)


def shape_from_partition_spec(shape: Sequence[int], spec: Optional[P], mesh,
                              dtype: DataType = DataType.FLOAT
                              ) -> ParallelTensorShape:
    """Spec form → degree form, reading degrees off the mesh-axis extents."""
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    entries = list(spec) if spec is not None else []
    entries += [None] * (len(shape) - len(entries))
    dims = []
    for s, a in zip(shape, entries):
        if a is None:
            dims.append(ParallelDim(s))
        else:
            axes = a if isinstance(a, tuple) else (a,)
            deg = 1
            for ax in axes:
                deg *= axis_sizes[ax]
            dims.append(ParallelDim(s, deg, tuple(axes)))
    return ParallelTensorShape(tuple(dims), dtype)


def spec_to_degrees(shape: Sequence[int], spec: Optional[P], mesh) -> List[int]:
    return list(shape_from_partition_spec(shape, spec, mesh).degrees)

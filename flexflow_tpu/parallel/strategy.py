"""Parallelization strategies: per-op sharding assignment over the mesh.

The reference expresses a strategy as per-op MachineViews + the four
resharding ops inserted in the PCG (SURVEY §2.3); on TPU a strategy is a
map op-guid -> OpStrategy{output PartitionSpecs, param PartitionSpecs}.
GSPMD then inserts the collectives that the reference's
Repartition/Combine/Replicate/Reduction ops perform explicitly.

``data_parallel_strategy`` is the analog of
``--only-data-parallel`` (graph.cc:1939-1964): batch dim of every
activation sharded over the 'data' axis, parameters replicated (their
gradient psum is the NCCL allreduce analog).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from jax.sharding import PartitionSpec as P

from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import DimRole


@dataclasses.dataclass
class OpStrategy:
    output_specs: List[Optional[P]]
    param_specs: Dict[str, P] = dataclasses.field(default_factory=dict)


Strategy = Dict[int, OpStrategy]


def _axis_size(mesh, name: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)


def data_parallel_strategy(nodes, mesh) -> Strategy:
    """Batch dim over 'data'; if the mesh carries a 'seq' axis, SEQ-role
    dims shard over it too (context parallelism: activations stay
    seq-sharded between ring-attention ops)."""
    dp = _axis_size(mesh, "data")
    sp = _axis_size(mesh, "seq")
    strategy: Strategy = {}
    for node in nodes:
        specs = []
        for shp, roles in zip(node.op.output_shapes, node.op.output_dim_roles()):
            entries = [None] * len(shp)
            if (dp > 1 and shp and roles and roles[0] == DimRole.SAMPLE
                    and shp[0] % dp == 0):
                entries[0] = "data"
            if sp > 1:
                for d, role in enumerate(roles):
                    if role == DimRole.SEQ and shp[d] % sp == 0:
                        entries[d] = "seq"
                        break
            specs.append(P(*entries) if any(e for e in entries) else None)
        strategy[node.op.guid] = OpStrategy(output_specs=specs)
    return strategy


def tensor_parallel_overrides(nodes, mesh, strategy: Strategy) -> Strategy:
    """Shard weight-heavy ops on the 'model' axis: Linear column-parallel
    (kernel [in, out] -> out sharded), attention head-parallel, embedding
    vocab-parallel. Analog of the parameter/attribute-parallel
    substitutions (substitution.cc:1756-1809)."""
    mp = _axis_size(mesh, "model")
    if mp <= 1:
        return strategy
    for node in nodes:
        op = node.op
        st = strategy[op.guid]
        if op.op_type == OperatorType.LINEAR and op.out_dim % mp == 0:
            st.param_specs["kernel"] = P(None, "model")
            st.param_specs["bias"] = P("model")
            shp = op.output_shapes[0]
            base = st.output_specs[0] or P(*([None] * len(shp)))
            st.output_specs[0] = P(*list(base)[:-1], "model")
        elif op.op_type == OperatorType.MULTIHEAD_ATTENTION and op.num_heads % mp == 0:
            st.param_specs.update({
                "wq": P("model", None, None),
                "wo": P("model", None, None),
            })
            # GQA: wk/wv carry num_kv_heads (< num_heads) on dim 0 — only
            # shard them when the kv-head count divides the axis too
            if getattr(op, "num_kv_heads", op.num_heads) % mp == 0:
                st.param_specs.update({
                    "wk": P("model", None, None),
                    "wv": P("model", None, None),
                })
        elif op.op_type == OperatorType.EMBEDDING and op.out_dim % mp == 0:
            st.param_specs["kernel"] = P(None, "model")
    return strategy


# ops that preserve shape and follow their input's sharding: a manual
# parallel op's layout propagates through these until the next layout- or
# value-changing op (matches the reference, where a Repartition changes the
# ParallelTensor layout every consumer then sees)
_FOLLOW_OPS = frozenset({
    OperatorType.RELU, OperatorType.GELU, OperatorType.SIGMOID,
    OperatorType.TANH, OperatorType.ELU, OperatorType.EXP, OperatorType.SIN,
    OperatorType.COS, OperatorType.POW, OperatorType.RSQRT,
    OperatorType.IDENTITY, OperatorType.SCALAR_MULTIPLY,
    OperatorType.SCALAR_ADD, OperatorType.SCALAR_SUB,
    OperatorType.SCALAR_TRUE_DIV, OperatorType.DROPOUT, OperatorType.CAST,
    OperatorType.SOFTMAX, OperatorType.LAYERNORM, OperatorType.RMSNORM,
})


def _axis_entry_valid(entry, valid_axes) -> bool:
    if entry is None:
        return True
    axes = entry if isinstance(entry, tuple) else (entry,)
    return all(a in valid_axes for a in axes)


def apply_strategy(nodes, strategy: Strategy, mesh) -> None:
    by_guid = {n.op.guid: n for n in nodes}
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    # guid -> spec entries forced by an upstream manual parallel op
    forced: Dict[int, List] = {}
    for node in nodes:
        st = strategy.get(node.op.guid)
        if st is not None:
            node.output_specs = list(st.output_specs)
            node.param_specs = dict(st.param_specs)
            # a searched "ring" choice switches the attention op onto the
            # ring-attention execution path over the mesh's 'seq' axis (the
            # analog of a substitution rewrite changing the op's task
            # implementation); "head" choices record the head-sharded axis
            # so ring attention keeps heads distributed under shard_map
            # ("_wus" may trail any choice name — weight-update sharding
            # composes with every base choice, so match by substring)
            choice = getattr(st, "choice", None) or ""
            # a searched "_k:<impl>" kernel suffix records WHICH KERNEL
            # runs the op (ISSUE 15): attention ops carry it as
            # kernel_impl (forward honors it — "flash" forces the Pallas
            # kernel where available, "einsum" pins the reference path);
            # "fused"/"conv_bn_fused" are executor-level choices routed
            # via GraphExecutor.kernel_choices
            if "_k:" in choice and hasattr(node.op, "seq_parallel"):
                from flexflow_tpu.search.unity import kernel_choice_of
                impl = kernel_choice_of(choice)
                if impl in ("flash", "einsum"):
                    # model.compile clears this again when the kernel
                    # dimension is switched off (--kernel-search off /
                    # FFS_NO_KERNEL_SEARCH): the off switch promises
                    # availability-based defaults
                    node.op.kernel_impl = impl
            if hasattr(node.op, "seq_parallel"):
                if "_ring" in choice and axis_sizes.get("seq", 1) > 1:
                    node.op.seq_parallel = "seq"
                if "head" in choice and axis_sizes.get("model", 1) > 1:
                    node.op.head_parallel = "model"
                # record the batch-dim sharding (may be a tuple under the
                # sample2 'data+model' 2-D partition) so the flash-attention
                # shard_map keeps the joint sharding instead of forcing an
                # all-gather over the model axis (advisor r3 finding)
                spec0 = st.output_specs[0] if st.output_specs else None
                if spec0:
                    entries = list(spec0)
                    node.op.batch_parallel = entries[0] if entries else None
            if (hasattr(node.op, "expert_parallel")
                    and "_ep" in choice
                    and axis_sizes.get("expert", 1) > 1):
                node.op.expert_parallel = "expert"
        op = node.op
        is_par = getattr(op, "is_parallel_op", False)
        if (is_par and hasattr(op, "preferred_spec_update")) or (
            op.op_type in _FOLLOW_OPS and node.input_refs
            and node.input_refs[0][0] == "op"
            and node.input_refs[0][1] in forced
        ):
            ref = node.input_refs[0]
            nd = len(op.output_shapes[0])
            if ref[0] == "op" and ref[1] in forced:
                src = forced[ref[1]]
            elif ref[0] == "op" and ref[1] in by_guid:
                src = by_guid[ref[1]].output_specs[ref[2]]
            else:
                src = None
            entries = (list(src) + [None] * nd)[:nd] if src else [None] * nd
            if is_par:
                if (op.op_type == OperatorType.REPARTITION
                        and op.axis in axis_sizes
                        and op.repartition_degree != axis_sizes[op.axis]):
                    raise ValueError(
                        f"repartition degree {op.repartition_degree} != mesh "
                        f"axis '{op.axis}' size {axis_sizes[op.axis]} — under "
                        f"GSPMD the degree must equal the axis extent")
                entries = op.preferred_spec_update(entries)
            entries = [e if _axis_entry_valid(e, axis_sizes) else None
                       for e in entries]
            used = [e for e in entries if e is not None]
            if len(used) != len(set(used)):
                raise ValueError(
                    f"parallel op '{op.name}' would shard two dims over the "
                    f"same mesh axis ({entries}); repartition a dim that is "
                    f"not already sharded on that axis")
            node.output_specs = [P(*entries)] + node.output_specs[1:]
            forced[op.guid] = entries



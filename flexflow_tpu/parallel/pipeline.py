"""Pipeline parallelism: SPMD GPipe over a 'pipe' mesh axis.

New executing scope vs the reference, where pipeline parallelism exists
only as an enum value (`/root/reference/include/flexflow/ffconst.h:153`
OP_PIPELINE, with no runtime behind it).

TPU-native design (the MaxText/praxis recipe): a model whose body is S
identical repeated stages stacks each stage's parameters on a leading
[S, ...] axis sharded over the 'pipe' mesh axis. Under ``shard_map``
every device holds one stage's weights; microbatch activations flow
stage-to-stage with ``jax.lax.ppermute`` over the pipe ring. The GPipe
schedule runs T = M + S - 1 ticks for M microbatches (bubble fraction
(S-1)/T); each device computes on the microbatch that has reached its
stage and forwards the result one hop. Backward is ordinary JAX autodiff
through the shard_map — the transpose of ppermute is the reverse-ring
ppermute, so the returning gradient pipeline falls out of jax.grad.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from flexflow_tpu.utils.shard_map_compat import shard_map


def pipeline_spmd(stage_fn, stacked_params, x, mesh, *, num_microbatches,
                  axis: str = "pipe", data_axis: str = "data",
                  stage_leading_dim: bool = False):
    """Run ``stage_fn`` as an S-stage GPipe pipeline.

    stage_fn(params_slice, x) -> y: one stage's computation; input and
        output must share shape/dtype (repeated-block models).
    stacked_params: pytree with leading dim R (a multiple of the ``axis``
        mesh size S), sharded over ``axis``. With R == S each stage holds
        one slice; ``stage_leading_dim=True`` keeps the local [R/S, ...]
        leading dim and hands the whole local tree to stage_fn (a stage
        running R/S blocks); False (default) squeezes it (R must equal S).
    x: [B, ...] global batch; B % num_microbatches == 0, and the
        microbatch size is the unit each stage processes per tick. When
        ``data_axis`` names a mesh axis, each microbatch additionally
        shards over it (pipeline x data composition).
    Returns y of x's shape: the last stage's outputs, gathered.

    Memory note: the microbatch queue (and the output buffer) replicate
    over the pipe axis — each stage device holds the full (data-sharded)
    batch although it only computes on one in-flight microbatch. For
    memory-bound deployments the queue should stream from stage 0 only;
    that variant trades this implementation's simple SPMD schedule for a
    sharded-queue one and is left as the optimization path.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = sizes[axis]
    for leaf in jax.tree.leaves(stacked_params):
        bad = (leaf.shape[0] % S != 0) if stage_leading_dim \
            else (leaf.shape[0] != S)
        if bad:
            raise ValueError(
                f"stacked param dim 0 is {leaf.shape[0]} but the '{axis}' "
                f"mesh axis has {S} stages — a mismatch would silently "
                f"drop stages")
    M = num_microbatches
    if x.shape[0] % M:
        raise ValueError(f"batch {x.shape[0]} % microbatches {M} != 0")
    data_axis = data_axis if sizes.get(data_axis, 1) > 1 else None
    if data_axis and (x.shape[0] // M) % sizes[data_axis]:
        raise ValueError(
            f"microbatch size {x.shape[0] // M} % '{data_axis}' axis "
            f"({sizes[data_axis]}) != 0")

    def body(params, xs):
        # params: [R/S, ...] this device's stage; xs: [M, B/M, ...]
        # (replicated over pipe)
        idx = jax.lax.axis_index(axis)
        p = params if stage_leading_dim \
            else jax.tree.map(lambda w: w[0], params)
        mb = xs.shape[1]
        state = jnp.zeros((mb,) + xs.shape[2:], xs.dtype)  # in-flight act
        outs = jnp.zeros_like(xs)

        def tick(t, carry):
            state, outs = carry
            # stage 0 ingests microbatch t (while it exists); others take
            # the activation ppermuted from the previous stage
            feed = jnp.where(t < M, t, M - 1)
            inject = jax.lax.dynamic_index_in_dim(xs, feed, 0,
                                                  keepdims=False)
            cur = jnp.where(idx == 0, inject, state)
            y = stage_fn(p, cur)
            # the microbatch leaving the last stage this tick is t-(S-1)
            done = t - (S - 1)
            valid = jnp.logical_and(idx == S - 1,
                                    jnp.logical_and(done >= 0, done < M))
            slot = jnp.clip(done, 0, M - 1)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(valid, y,
                          jax.lax.dynamic_index_in_dim(outs, slot, 0,
                                                       keepdims=False)),
                slot, 0)
            # forward the activation one hop around the pipe ring
            state = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % S) for i in range(S)])
            return state, outs

        _, outs = jax.lax.fori_loop(0, M + S - 1, tick, (state, outs))
        # every device returns outs; only the last stage's is real — psum
        # after zeroing the others yields the replicated result
        outs = jnp.where(idx == S - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    pipe_spec = P(axis)
    # microbatch dim replicated; the batch-within-microbatch dim shards
    # over the data axis so pipeline x data composes (each data shard
    # pipelines its slice of every microbatch)
    x_spec = P(None, data_axis) if data_axis else P()
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: pipe_spec, stacked_params), x_spec),
        out_specs=x_spec, check_rep=False)
    mb = x.shape[0] // M
    xs = x.reshape((M, mb) + x.shape[1:])
    return fn(stacked_params, xs).reshape(x.shape)


def transformer_block_stage(embed_dim: int, num_heads: int, seq_length: int,
                            batch_per_microbatch: int, ffn_mult: int = 4):
    """(init_fn, stage_fn) for one pre-norm transformer block built from
    the framework's own op implementations — the repeated stage a
    pipelined transformer runs on each 'pipe' shard.

    init_fn(rng) -> params pytree for one stage;
    stage_fn(params, x[Bmb, S, E]) -> same shape.

    ``seq_length``/``batch_per_microbatch`` are construction-time shape
    metadata only (Op instances are built against concrete shapes); the
    returned stage_fn itself is shape-polymorphic, so running it on a
    differently-sized (e.g. data-sharded) block is fine.
    """
    from flexflow_tpu.ffconst import ActiMode, DataType, OperatorType
    from flexflow_tpu.layer import Layer
    from flexflow_tpu.ops import OpRegistry
    from flexflow_tpu.ops.base import OpContext

    b, s, e = batch_per_microbatch, seq_length, embed_dim

    def make(op_type, props, shapes):
        lyr = Layer(op_type, None, [], data_type=DataType.FLOAT)
        lyr.properties.update(props)
        return OpRegistry.create(lyr, shapes)

    ln1 = make(OperatorType.LAYERNORM, dict(axes=(-1,)), [(b, s, e)])
    attn = make(OperatorType.MULTIHEAD_ATTENTION,
                dict(embed_dim=e, num_heads=num_heads, dropout=0.0),
                [(b, s, e)] * 3)
    ln2 = make(OperatorType.LAYERNORM, dict(axes=(-1,)), [(b, s, e)])
    ff1 = make(OperatorType.LINEAR,
               dict(out_dim=e * ffn_mult,
                    activation=ActiMode.AC_MODE_RELU), [(b, s, e)])
    ff2 = make(OperatorType.LINEAR, dict(out_dim=e), [(b, s, e * ffn_mult)])

    def init_fn(rng):
        ks = jax.random.split(rng, 5)
        return {"ln1": ln1.init_params(ks[0]),
                "attn": attn.init_params(ks[1]),
                "ln2": ln2.init_params(ks[2]),
                "ff1": ff1.init_params(ks[3]),
                "ff2": ff2.init_params(ks[4])}

    def stage_fn(p, x):
        ctx = OpContext(training=True, compute_dtype=jnp.float32)
        h = ln1.forward(p["ln1"], [x], ctx)[0]
        a = attn.forward(p["attn"], [h, h, h], ctx)[0]
        x = x + a
        h = ln2.forward(p["ln2"], [x], ctx)[0]
        h = ff1.forward(p["ff1"], [h], ctx)[0]
        h = ff2.forward(p["ff2"], [h], ctx)[0]
        return x + h

    return init_fn, stage_fn


def stack_stage_params(per_stage_params):
    """[params_stage0, ..., params_stageS-1] (identical trees) -> one tree
    with a leading [S, ...] axis, ready to shard over 'pipe'."""
    return jax.tree.map(lambda *ws: jnp.stack(ws), *per_stage_params)


def shard_stacked(stacked_params, mesh, axis: str = "pipe"):
    """Place the stacked tree with dim 0 sharded over the pipe axis."""
    def put(w):
        spec = P(axis, *([None] * (w.ndim - 1)))
        return jax.device_put(w, NamedSharding(mesh, spec))

    return jax.tree.map(put, stacked_params)

"""Pipeline parallelism: SPMD GPipe / circular pipelines over a 'pipe' axis.

New executing scope vs the reference, where pipeline parallelism exists
only as an enum value (`/root/reference/include/flexflow/ffconst.h:153`
OP_PIPELINE, with no runtime behind it).

TPU-native design (the MaxText/praxis recipe): a model whose body is R
identical repeated blocks stacks each block's parameters on a leading
[R, ...] axis sharded over the 'pipe' mesh axis. Under ``shard_map``
every device holds R/S blocks' weights; microbatch activations flow
stage-to-stage with ``jax.lax.ppermute`` over the pipe ring.

Two schedules:

* ``gpipe`` — each stage holds k = R/S *consecutive* blocks and runs all
  of them per tick. T = M + S - 1 ticks for M microbatches; bubble
  fraction (S-1)/T.
* ``circular`` — blocks are assigned round-robin (stage s holds blocks
  s, s+S, s+2S, ...) and each stage runs ONE block per tick; a
  microbatch circulates the ring k times, re-entering stage 0 from a
  recirculation buffer. T = kM + S - 1 ticks, shrinking the bubble to
  (S-1)/(kM+S-1) — the MaxText circular-pipeline schedule.

The microbatch queue and output buffer shard over the pipe axis
(``shard_queue``): stage s holds only its M/S microbatches, and two
single-microbatch ppermute streams carry inputs down to stage 0 and
finished outputs back to their owning stage — per-device queue memory
drops by ~S vs the replicated-queue lowering (kept as the fallback when
S does not divide M).

Backward is ordinary JAX autodiff through the shard_map — the transpose
of ppermute is the reverse-ring ppermute, so the returning gradient
pipeline falls out of jax.grad.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from flexflow_tpu.utils.shard_map_compat import shard_map

SCHEDULES = ("gpipe", "circular")


def circular_block_order(num_blocks: int, num_stages: int):
    """Storage-row order for ``schedule='circular'``: returns the list
    ``order`` with ``order[row] = block index stored at that row``, such
    that sharding the leading dim over S stages gives stage s the
    round-robin blocks {s, s+S, s+2S, ...} with local slice r = round r's
    block. Row s*k + r holds block r*S + s."""
    k = num_blocks // num_stages
    return [r * num_stages + s for s in range(num_stages) for r in range(k)]


def pipeline_spmd(stage_fn, stacked_params, x, mesh, *, num_microbatches,
                  axis: str = "pipe", data_axis: str = "data",
                  stage_leading_dim: bool = False,
                  schedule: str = "gpipe", shard_queue: bool = True):
    """Run ``stage_fn`` as an S-stage SPMD pipeline.

    stage_fn(params_slice, x) -> y: one stage's computation; input and
        output must share shape/dtype (repeated-block models).
    stacked_params: pytree with leading dim R (a multiple of the ``axis``
        mesh size S), sharded over ``axis``. With R == S each stage holds
        one slice; ``stage_leading_dim=True`` keeps the local [R/S, ...]
        leading dim. Under ``schedule='gpipe'`` stage_fn then receives
        the whole local tree (a stage running R/S consecutive blocks);
        under ``schedule='circular'`` the rows must be in
        ``circular_block_order`` and stage_fn receives ONE block's
        squeezed slice per call (the round's block).
    x: [B, ...] global batch; B % num_microbatches == 0, and the
        microbatch size is the unit each stage processes per tick. When
        ``data_axis`` names a mesh axis, each microbatch additionally
        shards over it (pipeline x data composition).
    shard_queue: shard the microbatch queue and output buffer over the
        pipe axis (each stage holds M/S microbatches; per-tick ppermute
        streams feed stage 0 and scatter finished outputs back). Falls
        back to the replicated queue when S does not divide M.
    Returns y of x's shape: the last stage's outputs, gathered.
    """
    if schedule not in SCHEDULES:
        raise ValueError(f"schedule must be one of {SCHEDULES}, "
                         f"got {schedule!r}")
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = sizes[axis]
    R = None
    for leaf in jax.tree.leaves(stacked_params):
        bad = (leaf.shape[0] % S != 0) if stage_leading_dim \
            else (leaf.shape[0] != S)
        if bad:
            raise ValueError(
                f"stacked param dim 0 is {leaf.shape[0]} but the '{axis}' "
                f"mesh axis has {S} stages — a mismatch would silently "
                f"drop stages")
        R = leaf.shape[0] if R is None else R
    M = num_microbatches
    if x.shape[0] % M:
        raise ValueError(f"batch {x.shape[0]} % microbatches {M} != 0")
    data_axis = data_axis if sizes.get(data_axis, 1) > 1 else None
    if data_axis and (x.shape[0] // M) % sizes[data_axis]:
        raise ValueError(
            f"microbatch size {x.shape[0] // M} % '{data_axis}' axis "
            f"({sizes[data_axis]}) != 0")
    # circular: one block per tick, k rounds around the ring; without a
    # stage-leading dim there is exactly one round and the schedules
    # coincide
    circular = schedule == "circular" and stage_leading_dim
    rounds = (R // S) if circular else 1
    use_circ = rounds > 1  # recirculation buffer needed
    if use_circ and M < S:
        raise ValueError(
            f"circular schedule needs microbatches >= stages "
            f"({M} < {S}): a returning microbatch would overtake the "
            f"recirculation buffer")
    qsharded = shard_queue and M % S == 0
    q = M // S if qsharded else M
    ticks = rounds * M + S - 1
    # the sharded output stream needs S-1 more hops to land the last
    # microbatches on their owners — a separate compute-free drain loop
    # (running stage_fn on garbage there would cost real backward
    # residual memory for nothing)

    down = [(i, (i - 1) % S) for i in range(S)]  # toward stage 0
    up = [(i, (i + 1) % S) for i in range(S)]    # the pipeline direction

    def body(params, xs):
        # params: this device's block slices; xs: [q, mb, ...] local
        # queue slice (the full [M, ...] queue when replicated)
        idx = jax.lax.axis_index(axis)
        outs = jnp.zeros_like(xs)
        z = jnp.zeros(xs.shape[1:], xs.dtype)

        def block_params(r):
            if circular:
                return jax.tree.map(
                    lambda w: jax.lax.dynamic_index_in_dim(
                        w, r, 0, keepdims=False), params)
            return params if stage_leading_dim \
                else jax.tree.map(lambda w: w[0], params)

        def tick(t, carry):
            state, outs, circ, in_stream, out_stream = carry
            # ---- input side: the microbatch entering stage 0 ----------
            if qsharded:
                # double-buffered input stream (ISSUE 9): consume the
                # value staged at the END of the previous tick, then
                # advance the stream for tick t+1 — the hop's ppermute
                # has no consumer inside this tick, so XLA's async
                # collective scheduling overlaps it with the block
                # compute instead of gating stage 0's feed on it (the
                # simulator already priced the streams as bandwidth-only
                # prefetch traffic; this makes the runtime match).
                # Protocol: owner h(m) = m // q injects m at the end of
                # tick m - h - 1 (h == 0 and m == 0 come from the
                # pre-loop staging); stage 0 reads microbatch t at tick
                # t, exactly as the synchronous stream delivered.
                queue_feed = in_stream
                nxt = jax.lax.ppermute(in_stream, axis, down)
                m_in = t + 1 + idx
                owned = jnp.logical_and(m_in >= idx * q,
                                        m_in < (idx + 1) * q)
                li = jnp.clip(m_in - idx * q, 0, q - 1)
                mine = jax.lax.dynamic_index_in_dim(xs, li, 0,
                                                    keepdims=False)
                in_stream = jnp.where(owned, mine, nxt)
            else:
                feed = jnp.clip(t, 0, M - 1)
                queue_feed = jax.lax.dynamic_index_in_dim(
                    xs, feed, 0, keepdims=False)
            if use_circ:
                # rounds >= 1 re-enter from the recirculation buffer —
                # a W = M-S+1 slot ring in BOTH queue lowerings (a value
                # u lives from its bank tick u+S-1 to its consume tick
                # u+M, so at most W slots are ever live); the value fed
                # at global step u0 is microbatch u0-M of the previous
                # round, parked in slot (u0-M) % W. Round 0 feeds from
                # the queue directly (ISSUE 20 satellite: the replicated
                # fallback no longer keeps a full M-slot ring).
                u0 = jnp.clip(t, 0, rounds * M - 1)
                cslot = (u0 - M) % (M - S + 1)
                circ_feed = jax.lax.dynamic_index_in_dim(
                    circ, cslot, 0, keepdims=False)
                feed_val = jnp.where(t < M, queue_feed, circ_feed)
            else:
                feed_val = queue_feed
            cur = jnp.where(idx == 0, feed_val, state)
            # ---- compute: this stage's block for the current round ----
            u = t - idx  # global step of the microbatch at this stage
            r = jnp.clip(u, 0, rounds * M - 1) // M
            y = stage_fn(block_params(r), cur)
            # ---- output side: microbatch leaving its final round ------
            u_last = t - (S - 1)                 # last stage's step
            fin = u_last - (rounds - 1) * M      # finished microbatch
            finished = jnp.logical_and(fin >= 0, fin < M)
            if qsharded:
                # out stream rides the ring away from the last stage;
                # each stage captures the finished microbatches it owns
                out_stream = jax.lax.ppermute(out_stream, axis, up)
                out_stream = jnp.where(
                    jnp.logical_and(idx == S - 1, finished), y, out_stream)
                m_out = fin - ((idx + 1) % S)
                owned_out = jnp.logical_and(m_out >= idx * q,
                                            m_out < (idx + 1) * q)
                lo = jnp.clip(m_out - idx * q, 0, q - 1)
                prev = jax.lax.dynamic_index_in_dim(outs, lo, 0,
                                                    keepdims=False)
                outs = jax.lax.dynamic_update_index_in_dim(
                    outs, jnp.where(owned_out, out_stream, prev), lo, 0)
            else:
                slot = jnp.clip(fin, 0, M - 1)
                valid = jnp.logical_and(idx == S - 1, finished)
                prev = jax.lax.dynamic_index_in_dim(outs, slot, 0,
                                                    keepdims=False)
                outs = jax.lax.dynamic_update_index_in_dim(
                    outs, jnp.where(valid, y, prev), slot, 0)
            # ---- forward the activation one hop around the pipe ring --
            state = jax.lax.ppermute(y, axis, up)
            if use_circ:
                # stage 0 banks the returning activation for its next
                # round (consumed M-S+1 ticks later — safe: M >= S)
                u_arr = jnp.clip(t - (S - 1), 0, rounds * M - 1)
                ok = jnp.logical_and(
                    jnp.logical_and(t - (S - 1) >= 0,
                                    u_arr // M < rounds - 1),
                    idx == 0)
                # the ring write at tick t lands on the slot whose value
                # was consumed THIS tick ((t-S+1) - (t-M) = W) — safe
                # because circ_feed above read the pre-update buffer
                s_arr = u_arr % (M - S + 1)
                prevc = jax.lax.dynamic_index_in_dim(circ, s_arr, 0,
                                                     keepdims=False)
                circ = jax.lax.dynamic_update_index_in_dim(
                    circ, jnp.where(ok, state, prevc), s_arr, 0)
            return state, outs, circ, in_stream, out_stream

        if use_circ:
            # windowed to the M-S+1 in-flight slots in BOTH queue
            # lowerings: the HBM win the simulator's queue-memory term
            # prices with the same (M-pp+1)/M factor
            circ0 = jnp.zeros((M - S + 1,) + xs.shape[1:], xs.dtype)
        else:
            circ0 = jnp.zeros((1,) + xs.shape[1:], xs.dtype)  # unused
        if qsharded:
            # pre-loop staging of the double-buffered input stream: the
            # "end of tick -1" injection — stage 0 stages microbatch 0
            # (and with q == 1, stage h stages its own microbatch h,
            # which then rides h hops to arrive at tick h)
            m0 = idx
            owned0 = jnp.logical_and(m0 >= idx * q, m0 < (idx + 1) * q)
            li0 = jnp.clip(m0 - idx * q, 0, q - 1)
            in0 = jnp.where(owned0,
                            jax.lax.dynamic_index_in_dim(xs, li0, 0,
                                                         keepdims=False), z)
        else:
            in0 = z
        carry = (z, outs, circ0, in0, z)
        _, outs, _, _, out_stream = jax.lax.fori_loop(0, ticks, tick, carry)
        if qsharded:
            def drain_tick(j, carry):
                outs, out_stream = carry
                t = ticks + j
                out_stream = jax.lax.ppermute(out_stream, axis, up)
                fin = t - (S - 1) - (rounds - 1) * M
                m_out = fin - ((idx + 1) % S)
                owned_out = jnp.logical_and(m_out >= idx * q,
                                            m_out < (idx + 1) * q)
                lo = jnp.clip(m_out - idx * q, 0, q - 1)
                prev = jax.lax.dynamic_index_in_dim(outs, lo, 0,
                                                    keepdims=False)
                outs = jax.lax.dynamic_update_index_in_dim(
                    outs, jnp.where(owned_out, out_stream, prev), lo, 0)
                return outs, out_stream

            outs, _ = jax.lax.fori_loop(0, S - 1, drain_tick,
                                        (outs, out_stream))
            # each stage returns the finished microbatches it owns — the
            # out_specs sharding assembles the global [M, ...] result
            return outs
        # every device returns outs; only the last stage's is real — psum
        # after zeroing the others yields the replicated result
        outs = jnp.where(idx == S - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    pipe_spec = P(axis)
    # queue layout: microbatch dim sharded over pipe (or replicated in
    # the fallback); the batch-within-microbatch dim shards over the data
    # axis so pipeline x data composes (each data shard pipelines its
    # slice of every microbatch)
    x_spec = P(axis if qsharded else None, data_axis) if data_axis \
        else (P(axis) if qsharded else P())
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: pipe_spec, stacked_params), x_spec),
        out_specs=x_spec, check_rep=False)
    mb = x.shape[0] // M
    xs = x.reshape((M, mb) + x.shape[1:])
    return fn(stacked_params, xs).reshape(x.shape)


def transformer_block_stage(embed_dim: int, num_heads: int, seq_length: int,
                            batch_per_microbatch: int, ffn_mult: int = 4):
    """(init_fn, stage_fn) for one pre-norm transformer block built from
    the framework's own op implementations — the repeated stage a
    pipelined transformer runs on each 'pipe' shard.

    init_fn(rng) -> params pytree for one stage;
    stage_fn(params, x[Bmb, S, E]) -> same shape.

    ``seq_length``/``batch_per_microbatch`` are construction-time shape
    metadata only (Op instances are built against concrete shapes); the
    returned stage_fn itself is shape-polymorphic, so running it on a
    differently-sized (e.g. data-sharded) block is fine.
    """
    from flexflow_tpu.ffconst import ActiMode, DataType, OperatorType
    from flexflow_tpu.layer import Layer
    from flexflow_tpu.ops import OpRegistry
    from flexflow_tpu.ops.base import OpContext

    b, s, e = batch_per_microbatch, seq_length, embed_dim

    def make(op_type, props, shapes):
        lyr = Layer(op_type, None, [], data_type=DataType.FLOAT)
        lyr.properties.update(props)
        return OpRegistry.create(lyr, shapes)

    ln1 = make(OperatorType.LAYERNORM, dict(axes=(-1,)), [(b, s, e)])
    attn = make(OperatorType.MULTIHEAD_ATTENTION,
                dict(embed_dim=e, num_heads=num_heads, dropout=0.0),
                [(b, s, e)] * 3)
    ln2 = make(OperatorType.LAYERNORM, dict(axes=(-1,)), [(b, s, e)])
    ff1 = make(OperatorType.LINEAR,
               dict(out_dim=e * ffn_mult,
                    activation=ActiMode.AC_MODE_RELU), [(b, s, e)])
    ff2 = make(OperatorType.LINEAR, dict(out_dim=e), [(b, s, e * ffn_mult)])

    def init_fn(rng):
        ks = jax.random.split(rng, 5)
        return {"ln1": ln1.init_params(ks[0]),
                "attn": attn.init_params(ks[1]),
                "ln2": ln2.init_params(ks[2]),
                "ff1": ff1.init_params(ks[3]),
                "ff2": ff2.init_params(ks[4])}

    def stage_fn(p, x):
        ctx = OpContext(training=True, compute_dtype=jnp.float32)
        h = ln1.forward(p["ln1"], [x], ctx)[0]
        a = attn.forward(p["attn"], [h, h, h], ctx)[0]
        x = x + a
        h = ln2.forward(p["ln2"], [x], ctx)[0]
        h = ff1.forward(p["ff1"], [h], ctx)[0]
        h = ff2.forward(p["ff2"], [h], ctx)[0]
        return x + h

    return init_fn, stage_fn


def stack_stage_params(per_stage_params, order=None):
    """[params_block0, ..., params_blockR-1] (identical trees) -> one tree
    with a leading [R, ...] axis, ready to shard over 'pipe'. ``order``
    permutes the storage rows (``circular_block_order`` for the circular
    schedule: row i holds block order[i])."""
    if order is not None:
        per_stage_params = [per_stage_params[b] for b in order]
    return jax.tree.map(lambda *ws: jnp.stack(ws), *per_stage_params)


def shard_stacked(stacked_params, mesh, axis: str = "pipe"):
    """Place the stacked tree with dim 0 sharded over the pipe axis."""
    def put(w):
        spec = P(axis, *([None] * (w.ndim - 1)))
        return jax.device_put(w, NamedSharding(mesh, spec))

    return jax.tree.map(put, stacked_params)

"""Parallelism layer: PCG, parallel (resharding) ops, strategies, collectives."""
